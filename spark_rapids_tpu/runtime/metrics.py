"""Operator metrics — the GpuMetric analog.

Reference: GpuExec.scala:32-140: GpuMetric wraps SQLMetric with levels
ESSENTIAL/MODERATE/DEBUG gated by spark.rapids.sql.metrics.level; ~25 standard names
(NUM_OUTPUT_ROWS, OP_TIME, SEMAPHORE_WAIT_TIME, SPILL bytes per tier, …) and
makeSpillCallback feeding spill bytes back into the running operator's metrics."""

from __future__ import annotations

import bisect
import itertools
import os
import threading
import time
import uuid
from contextlib import contextmanager

ESSENTIAL = 0
MODERATE = 1
DEBUG = 2

_LEVELS = {"ESSENTIAL": ESSENTIAL, "MODERATE": MODERATE, "DEBUG": DEBUG}

# standard metric names (reference GpuExec.scala:42-67)
NUM_OUTPUT_ROWS = "numOutputRows"
NUM_OUTPUT_BATCHES = "numOutputBatches"
NUM_INPUT_ROWS = "numInputRows"
NUM_INPUT_BATCHES = "numInputBatches"
OP_TIME = "opTime"
TOTAL_TIME = "totalTime"
SEMAPHORE_WAIT_TIME = "semaphoreWaitTime"
PEAK_DEVICE_MEMORY = "peakDevMemory"
SPILL_AMOUNT = "spillData"
SPILL_AMOUNT_DISK = "spillDisk"
SPILL_AMOUNT_HOST = "spillHost"
BUILD_TIME = "buildTime"
JOIN_TIME = "joinTime"
SORT_TIME = "sortTime"
AGG_TIME = "computeAggTime"
CONCAT_TIME = "concatTime"
READ_FS_TIME = "readFsTime"
WRITE_TIME = "writeTime"
PARTITION_TIME = "partitionTime"
COLLECT_TIME = "collectTime"
NUM_PARTITIONS = "partitions"
# derived wall-clock attribution: time spent producing this node's output
# batches minus time spent inside child nodes on the same thread (the SQL
# UI's "op time" self-time column; maintained by TpuExec.wrap_output frames)
SELF_TIME = "selfTime"
# the build region's own self time (a nested node_frame inside the join's
# output frame: charged here, subtracted from the join's selfTime) — the
# profiler renders it as the "(build)" line item
BUILD_SELF_TIME = "buildSelfTime"
READAHEAD_STALL_TIME = "readaheadStallTime"
# pipeline queue edges (runtime/pipeline.py): per-edge metric names are
# suffixed "<name>:<edge>" (e.g. "queueWaitTime:scan.decode") so one exec
# can own several edges and the profiler can attribute stalls per edge
QUEUE_WAIT_TIME = "queueWaitTime"      # consumer blocked on an empty queue
QUEUE_FULL_TIME = "queueFullTime"      # producer blocked on a full queue
QUEUE_DEPTH_PEAK = "queueDepthPeak"    # high-water mark of queued batches

# resilience counters (reference: RmmRapidsRetryIterator retry/split counts
# surfaced through GpuMetric, RapidsShuffleIterator fetch-failure accounting)
NUM_OOM_RETRIES = "numOomRetries"
NUM_OOM_SPLIT_RETRIES = "numOomSplitRetries"
OOM_SPILL_BYTES = "oomRetrySpillBytes"
FETCH_RETRIES = "fetchRetries"
FETCH_FAILOVERS = "fetchFailovers"
FETCH_RECOMPUTES = "fetchRecomputes"
# cluster-scheduler recovery (cluster/minicluster.py): task re-attempts,
# executor deaths and blacklistings, lineage-scoped partial stage
# recomputes (map tasks re-run counted separately so chaos tests can prove
# recovery cost was proportional to the loss), and speculation outcomes
TASK_ATTEMPTS = "taskAttempts"
EXECUTORS_LOST = "executorsLost"
EXECUTORS_BLACKLISTED = "executorsBlacklisted"
STAGE_PARTIAL_RECOMPUTES = "stagePartialRecomputes"
MAP_TASKS_RECOMPUTED = "mapTasksRecomputed"
SPECULATION_WON = "speculationWon"
SPECULATION_LOST = "speculationLost"
# unified mesh-cluster plane (cluster/minicluster.py + distributed/mesh.py):
# a mesh map task that could not run (or finish) on its executor's local
# mesh and was transparently re-planned onto the per-split TCP-shuffle path
# under a bumped epoch. Zero in every healthy run — rides the no-faults
# all-zero gates like the rest of the recovery ladder
MESH_DEGRADED_FALLBACKS = "meshDegradedFallbacks"
# multi-tenant query lifecycle (runtime/scheduler.py): shed submissions,
# cancelled/deadlined queries and fair-share demotions of a victim query's
# device buffers during a peer's OOM recovery
QUERIES_SHED = "queriesShed"
QUERIES_CANCELLED = "queriesCancelled"
QUERY_DEMOTIONS = "queryDemotions"
# serving endpoint (runtime/endpoint.py): a client connection lost while its
# query was in flight (half-close, RST, or idle-timeout expiry) — the query
# was cancelled by the disconnect path
CLIENT_DISCONNECTS = "clientDisconnects"
# memory observability plane (runtime/memory.py): catalog buffers a finished
# query left behind, caught + reclaimed by the end-of-query leak detector.
# Riding the resilience registry makes leak-freedom a standing CI invariant:
# the no-faults bench gates already assert every counter here is zero
MEMORY_LEAKS = "memoryLeakedBuffers"
# serving fleet (runtime/fleet.py): a survivor's sweeper adopted a dead
# replica's expired lease — unlinked the membership record and reclaimed its
# orphaned shared-store write intents
FLEET_ADOPTIONS = "fleetAdoptions"
# fleet client (runtime/endpoint.py EndpointClient): a retryable failure
# rotated the client to the next replica in its address list
REPLICA_FAILOVERS = "replicaFailovers"
# streaming epochs (streaming/coordinator.py): a pending (begun,
# uncommitted) epoch re-run after a crash/kill, and a committed state
# snapshot that failed its journal checksum and was rebuilt from the
# consumed batch log. Both zero in every clean run — a no-faults stream
# never replays and never rebuilds
STREAM_EPOCH_REPLAYS = "streamEpochReplays"
STREAM_STATE_REBUILDS = "streamStateRebuilds"

RESILIENCE_METRICS = (NUM_OOM_RETRIES, NUM_OOM_SPLIT_RETRIES, OOM_SPILL_BYTES,
                      FETCH_RETRIES, FETCH_FAILOVERS, FETCH_RECOMPUTES,
                      TASK_ATTEMPTS, EXECUTORS_LOST, EXECUTORS_BLACKLISTED,
                      STAGE_PARTIAL_RECOMPUTES, MAP_TASKS_RECOMPUTED,
                      SPECULATION_WON, SPECULATION_LOST,
                      MESH_DEGRADED_FALLBACKS,
                      QUERIES_SHED, QUERIES_CANCELLED, QUERY_DEMOTIONS,
                      CLIENT_DISCONNECTS, MEMORY_LEAKS,
                      FLEET_ADOPTIONS, REPLICA_FAILOVERS,
                      STREAM_EPOCH_REPLAYS, STREAM_STATE_REBUILDS)


class GpuMetric:
    __slots__ = ("name", "level", "_value", "_lock", "_pending")

    def __init__(self, name: str, level: int = MODERATE):
        self.name = name
        self.level = level
        self._value = 0
        self._lock = threading.Lock()
        self._pending = []

    def add(self, v):
        with self._lock:
            self._value += int(v)

    def add_lazy(self, v):
        """Accumulate a possibly-device scalar WITHOUT forcing a host sync;
        pending scalars are folded into the value at read time (value())."""
        if isinstance(v, int):
            self.add(v)
            return
        with self._lock:
            self._pending.append(v)

    def set(self, v):
        with self._lock:
            self._value = int(v)

    @property
    def value(self):
        with self._lock:
            if self._pending:
                for v in self._pending:
                    self._value += int(v)
                self._pending = []
            return self._value

    @contextmanager
    def timed(self):
        """Time a region in nanoseconds (reference NvtxWithMetrics couples a trace
        range with a timing metric — see runtime/tracing.py for the range side)."""
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            self.add(time.perf_counter_ns() - t0)

    def __repr__(self):
        return f"GpuMetric({self.name}={self._value})"


class _NoopMetric(GpuMetric):
    """Stand-in for metrics above the configured level: all updates are dropped."""

    def add(self, v):
        pass

    def add_lazy(self, v):
        # must drop like add/set: appending device scalars to _pending on a
        # metric whose value is never read would pin them forever
        pass

    def set(self, v):
        pass


class MetricsRegistry:
    """Per-operator metric set filtered by the configured level."""

    def __init__(self, level_name: str = "MODERATE"):
        self.level = _LEVELS.get(level_name.upper(), MODERATE)
        self._metrics: dict[str, GpuMetric] = {}

    def metric(self, name: str, level: int = MODERATE) -> GpuMetric:
        if name not in self._metrics:
            cls = _NoopMetric if level > self.level else GpuMetric
            self._metrics[name] = cls(name, level)
        return self._metrics[name]

    def snapshot(self):
        return {n: m.value for n, m in self._metrics.items() if m.level <= self.level}


# -- process-wide resilience registry ----------------------------------------
# Retry/split/fetch-failover counts outlive any one operator's registry (a
# retry may span operator teardown), so they accumulate here; chaos tests
# (tests/test_retry_faults.py) and bench.py's `resilience` JSON field read
# whole-query totals from this registry.

_global_registry: "MetricsRegistry | None" = None
_global_lock = threading.Lock()


def global_registry() -> MetricsRegistry:
    global _global_registry
    with _global_lock:
        if _global_registry is None:
            _global_registry = MetricsRegistry("DEBUG")
        return _global_registry


def reset_global_registry() -> None:
    global _global_registry
    with _global_lock:
        _global_registry = None


def resilience_snapshot() -> dict:
    """All resilience counters (zeros included) — the shape bench.py records."""
    g = global_registry()
    return {name: g.metric(name).value for name in RESILIENCE_METRICS}


def resilience_add(name: str, v: int = 1) -> None:
    """Increment one resilience counter in the process-wide registry AND in
    the ambient query's own scoped registry. Concurrent queries made the old
    start/finish DELTA attribution wrong — a peer's retry landing inside
    another query's window leaked across query scopes; routing every
    increment through here pins it to the query whose thread did the work
    (worker threads re-enter their query's collector scope, so the ambient
    collector is the right owner even off the driving thread)."""
    global_registry().metric(name).add(v)
    c = current_collector()
    if c is not None:
        c._resilience_local.metric(name).add(v)


# -- process-wide gauges / counters / histograms ------------------------------
# The live serving-metrics plane (endpoint STATS frames, executor.health
# samples): gauges are last-write-wins instantaneous values (endpoint
# connection count, pipeline queue occupancy), counters are monotonic
# (deadline kills), and histograms are fixed-bucket distributions cheap
# enough to observe on every query completion.

_gauge_lock = threading.Lock()
_gauges: dict[str, float] = {}
_counters: dict[str, int] = {}


def set_gauge(name: str, value) -> None:
    with _gauge_lock:
        _gauges[name] = value


def add_gauge(name: str, delta) -> None:
    with _gauge_lock:
        _gauges[name] = _gauges.get(name, 0) + delta


def gauges_snapshot() -> dict:
    with _gauge_lock:
        return dict(_gauges)


def counter_add(name: str, v: int = 1) -> None:
    with _gauge_lock:
        _counters[name] = _counters.get(name, 0) + v


def counters_snapshot() -> dict:
    with _gauge_lock:
        return dict(_counters)


def reset_observability() -> None:
    """Test hook: clear gauges, counters, histograms and the movement
    ledger."""
    global _histograms
    with _gauge_lock:
        _gauges.clear()
        _counters.clear()
    with _hist_lock:
        _histograms = {}
    from spark_rapids_tpu.runtime import movement
    movement.reset()


# latency-shaped default bounds: 1ms .. 5min, roughly x2.5 per step —
# fine enough for p99 interpolation at interactive scales, coarse enough
# that one histogram is 18 ints
DEFAULT_HISTOGRAM_BOUNDS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)


class Histogram:
    """Lock-cheap fixed-bucket histogram: observe() is one bisect over a
    static bound tuple plus four guarded int/float updates — cheap enough
    for per-query (not per-batch) call sites. Bucket i counts values
    v <= bounds[i]; the last bucket is the +inf overflow. min/max are
    tracked so percentile() can clamp interpolation to observed reality."""

    __slots__ = ("name", "bounds", "_counts", "_sum", "_count",
                 "_min", "_max", "_lock")

    def __init__(self, name: str, bounds=None):
        self.name = name
        self.bounds = tuple(sorted(bounds)) if bounds \
            else DEFAULT_HISTOGRAM_BOUNDS
        self._counts = [0] * (len(self.bounds) + 1)
        self._sum = 0.0
        self._count = 0
        self._min = None
        self._max = None
        self._lock = threading.Lock()

    def observe(self, v) -> None:
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v

    def snapshot(self) -> dict:
        with self._lock:
            return {"bounds": list(self.bounds),
                    "counts": list(self._counts),
                    "sum": self._sum, "count": self._count,
                    "min": self._min, "max": self._max}

    def percentile(self, q: float) -> float | None:
        """Linear-interpolated q-quantile (q in [0,1]) from the bucket
        cumulative counts, clamped to the observed [min, max]; None before
        any observation."""
        with self._lock:
            if not self._count:
                return None
            counts = list(self._counts)
            total, lo, hi = self._count, self._min, self._max
        target = q * total
        cum = 0.0
        for i, c in enumerate(counts):
            if cum + c >= target and c:
                b_lo = self.bounds[i - 1] if i > 0 else 0.0
                b_hi = self.bounds[i] if i < len(self.bounds) else hi
                frac = (target - cum) / c
                v = b_lo + (b_hi - b_lo) * frac
                return min(max(v, lo), hi)
            cum += c
        return hi


_hist_lock = threading.Lock()
_histograms: dict[str, Histogram] = {}


def histogram(name: str, bounds=None) -> Histogram:
    """Fetch-or-create the process-wide histogram `name` (shared across
    sessions, like the resilience registry)."""
    with _hist_lock:
        h = _histograms.get(name)
        if h is None:
            h = _histograms[name] = Histogram(name, bounds)
        return h


def histograms_snapshot() -> dict:
    with _hist_lock:
        items = list(_histograms.items())
    return {name: h.snapshot() for name, h in items}


def histogram_percentiles(name: str, qs=(0.5, 0.95, 0.99)) -> dict | None:
    with _hist_lock:
        h = _histograms.get(name)
    if h is None or not h._count:
        return None
    out = {f"p{int(q * 100)}": round(h.percentile(q), 6) for q in qs}
    out["count"] = h._count
    return out


# -- per-query compile/retrace accounting --------------------------------------
# runtime/fuse.py mirrors every XLA trace (compile) and program replay
# (dispatch) into the ambient query's collector, the same pattern as
# resilience_add: the process-global fuse counters stay authoritative for
# whole-process telemetry, while the per-query deltas establish the
# retrace denominator (ROADMAP item 1's zero-retrace gate reads these from
# last_query_metrics()).

def compile_add(kind: str, v: int = 1) -> None:
    c = current_collector()
    if c is not None:
        nid = current_node()
        with c._compile_lock:
            c._compile_local[kind] = c._compile_local.get(kind, 0) + v
            # per-node mirror: the innermost attribution frame on this thread
            # is the operator whose kernel compiled/dispatched, which makes
            # the fusion gate (dispatches per batch on a chain) measurable
            # per chain instead of per process
            if nid is not None:
                d = c._node_stats.setdefault(nid, {})
                d[kind] = d.get(kind, 0) + v


def stats_add(key: str, v, node: int | None = None) -> None:
    """Accumulate one observed-statistics counter into the ambient query's
    stats ledger, attributed to `node` (default: the innermost node_frame on
    this thread; no frame -> query-level). Always on — a dict update under a
    lock, the same cost class as the memory accounting — so the stats plane
    does not depend on the metrics level."""
    c = current_collector()
    if c is None:
        return
    nid = node if node is not None else current_node()
    with c._compile_lock:
        d = (c._node_stats.setdefault(nid, {}) if nid is not None
             else c._query_stats)
        d[key] = d.get(key, 0) + v


# -- query-scoped collection ---------------------------------------------------
# The SQL-UI analog: every exec node registers its MetricsRegistry with the
# query's collector at construction (TpuExec.__init__), so a finished query
# can render its plan tree annotated per node and attribute events
# (spill/oom/fetch) to plan-node ids. The collector is carried in a
# thread-local; pool-based schedulers re-enter it on worker threads via
# collector_context().

_collector_tls = threading.local()
_query_counter = itertools.count(1)


def current_collector() -> "QueryMetricsCollector | None":
    return getattr(_collector_tls, "collector", None)


def current_query_id() -> str | None:
    c = current_collector()
    return c.query_id if c is not None else None


@contextmanager
def collector_context(collector: "QueryMetricsCollector | None"):
    """Make `collector` the thread's current query scope (None allowed: a
    worker thread spawned outside any query keeps a clean scope)."""
    prev = getattr(_collector_tls, "collector", None)
    _collector_tls.collector = collector
    try:
        yield collector
    finally:
        _collector_tls.collector = prev


class _Frame:
    __slots__ = ("node_id", "child_ns")

    def __init__(self, node_id):
        self.node_id = node_id
        self.child_ns = 0


_frame_tls = threading.local()


def current_node() -> int | None:
    """Plan-node id of the innermost operator computing on this thread (the
    node-attribution stack maintained by node_frame) — events emitted while
    an operator runs land on its plan node."""
    stack = getattr(_frame_tls, "stack", None)
    return stack[-1].node_id if stack else None


@contextmanager
def node_frame(node_id, self_time_metric):
    """One attribution frame: wall time inside the frame, minus time spent in
    nested frames on the same thread, accumulates into `self_time_metric`
    (pass None to attribute events without charging time — e.g. while
    blocking on another thread's work that charges itself)."""
    stack = getattr(_frame_tls, "stack", None)
    if stack is None:
        stack = _frame_tls.stack = []
    f = _Frame(node_id)
    stack.append(f)
    t0 = time.perf_counter_ns()
    try:
        yield
    finally:
        dt = time.perf_counter_ns() - t0
        stack.pop()
        if self_time_metric is not None:
            self_time_metric.add(max(dt - f.child_ns, 0))
        if stack:
            stack[-1].child_ns += dt


class QueryMetricsCollector:
    """Per-query registry of plan-node metric sets (the SQLExecution /
    SQL-UI metrics-aggregation analog). Created by a DataFrame action,
    populated during plan conversion (exec construction) and execution,
    finished when the action returns; session.last_query_metrics() and
    DataFrame.explain(metrics=True) read it afterwards."""

    def __init__(self, description: str = ""):
        self.query_id = f"q{next(_query_counter):04d}-{os.getpid():x}-" \
                        f"{uuid.uuid4().hex[:8]}"
        self.description = description
        # cross-process trace id: defaults to the query id; the serving
        # endpoint/session may override it from the client's SUBMIT frame
        # (runtime/tracing.current_trace_id reads it through the ambient
        # collector so every worker thread inherits it)
        self.trace_id = self.query_id
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._nodes: dict[int, object] = {}   # node_id -> exec node
        self.root = None
        self._t0 = time.perf_counter()
        # query-scoped resilience counters: resilience_add() mirrors every
        # process-wide increment here, keyed by the worker thread's ambient
        # collector — correct under concurrent queries where the old
        # start/finish delta would count a peer's retries as this query's
        self._resilience_local = MetricsRegistry("DEBUG")
        # query-scoped compile/dispatch counters, mirrored by compile_add()
        # from runtime/fuse.py — the retrace denominator (a healthy repeat
        # query shows compiles == 0 here while dispatches == O(batches))
        self._compile_lock = threading.Lock()
        self._compile_local = {"compiles": 0, "dispatches": 0}
        # observed-statistics ledger (runtime/stats.py reads it): per-node
        # counters fed by stats_add/compile_add (output bytes, h2d/d2h
        # transfer bytes, per-node compiles/dispatches, input rows) plus
        # query-level counters for increments with no ambient node frame
        self._node_stats: dict[int, dict] = {}
        self._query_stats: dict = {}
        # per-shuffle reduce-partition byte sizes recorded by the map stage
        # (exchange/mesh), independent of the event log being enabled
        self._shuffle_stats: list[dict] = []
        # per-query mirror of the movement ledger (runtime/movement.py):
        # (edge, link) -> [bytes, payload_bytes, transfers] — the query.end
        # movement section and bench.py's movement summary read this
        self._movement: dict = {}
        # admission footprint info ({estimate, static, history_hit,
        # fingerprint, ...}) set at submit; plan.stats payload set at finish
        self.footprint: dict | None = None
        self.stats: dict | None = None
        # cooperative cancellation (runtime/scheduler.py): the session's
        # action sets the query's CancelToken here so every thread that
        # re-enters this collector's scope can reach it
        self.cancel_token = None
        self.wall_s: float | None = None
        self._resilience: dict | None = None
        # per-query memory summary (peak device bytes + top allocation
        # sites), set by the action's memory epilogue
        # (session._finish_query_memory); None for host-only queries
        self.memory: dict | None = None

    # -- population (plan conversion + execution) -----------------------------
    def register(self, exec_node) -> int:
        with self._lock:
            nid = next(self._ids)
            self._nodes[nid] = exec_node
            return nid

    def set_root(self, root) -> None:
        self.root = root

    def finish(self) -> None:
        if self.wall_s is None:
            self.wall_s = time.perf_counter() - self._t0
            self._resilience = self.query_resilience()

    # -- read-out -------------------------------------------------------------
    def query_resilience(self) -> dict:
        """Resilience counters attributable to THIS query (zeros included).
        Accumulated directly in the query's scoped registry by
        resilience_add() — not a delta of the process-wide registry, which
        concurrent peers mutate inside this query's window."""
        if self._resilience is not None:
            return dict(self._resilience)
        return {name: self._resilience_local.metric(name).value
                for name in RESILIENCE_METRICS}

    def compile_metrics(self) -> dict:
        """XLA compiles (traces) and program dispatches attributable to THIS
        query (runtime/fuse.py mirrors them here via compile_add)."""
        with self._compile_lock:
            return dict(self._compile_local)

    def node_stats(self) -> dict:
        """{node_id: {stat: value}} snapshot of the observed-stats ledger."""
        with self._compile_lock:
            return {nid: dict(d) for nid, d in self._node_stats.items()}

    def query_stats(self) -> dict:
        with self._compile_lock:
            return dict(self._query_stats)

    def record_shuffle_sizes(self, node_id, shuffle_id, sizes) -> None:
        """Per-reduce-partition byte sizes observed at map-stage completion
        (the MapOutputTracker read-out); one entry per completed map stage."""
        with self._compile_lock:
            self._shuffle_stats.append({
                "node": node_id, "shuffle": int(shuffle_id),
                "partition_sizes": [int(s) for s in sizes]})

    def shuffle_stats(self) -> list:
        with self._compile_lock:
            return [dict(e) for e in self._shuffle_stats]

    def movement_stats(self) -> dict:
        """{(edge, link): {bytes, payload_bytes, transfers}} snapshot of
        this query's movement mirror (runtime/movement.py)."""
        with self._compile_lock:
            return {k: {"bytes": v[0], "payload_bytes": v[1],
                        "transfers": v[2]}
                    for k, v in self._movement.items()}

    def _walk(self, node, parent_id, depth, visit):
        """Duck-typed hybrid-tree walk (no imports of exec/plan here): device
        execs carry _node_id/metrics, HostBridgeNode carries tpu_exec, host
        PlanNodes carry children; DeviceBridgeExec's host subtree is walked
        as unregistered host nodes."""
        nid = getattr(node, "_node_id", None)
        if nid is not None or hasattr(node, "metrics"):
            visit(node, nid, parent_id, depth)
            parent_id = nid
        elif hasattr(node, "tpu_exec"):          # HostBridgeNode
            visit(node, None, parent_id, depth)
            self._walk(node.tpu_exec, parent_id, depth + 1, visit)
            return
        else:                                     # host PlanNode
            visit(node, None, parent_id, depth)
        for c in getattr(node, "children", []) or []:
            self._walk(c, parent_id, depth + 1, visit)
        host_node = getattr(node, "host_node", None)   # DeviceBridgeExec
        if host_node is not None:
            self._walk(host_node, parent_id, depth + 1, visit)

    def node_summaries(self) -> list:
        """[{id, name, args, parent, depth, metrics}] in plan-tree preorder
        (registered nodes that never made the executed tree are appended with
        parent None so nothing silently disappears)."""
        out, seen = [], set()

        def visit(node, nid, parent_id, depth):
            entry = {
                "id": nid,
                "name": type(node).__name__,
                "args": (node.args_string()
                         if hasattr(node, "args_string") else ""),
                "parent": parent_id,
                "depth": depth,
                "metrics": (node.metrics.snapshot()
                            if hasattr(node, "metrics") else {}),
            }
            out.append(entry)
            if nid is not None:
                seen.add(nid)

        if self.root is not None:
            self._walk(self.root, None, 0, visit)
        with self._lock:
            stragglers = [(nid, n) for nid, n in self._nodes.items()
                          if nid not in seen]
        for nid, n in sorted(stragglers):
            visit(n, nid, None, 0)
        return out

    def node_metrics(self) -> dict:
        """{node_id: metrics snapshot} for every registered node."""
        with self._lock:
            items = list(self._nodes.items())
        return {nid: n.metrics.snapshot() for nid, n in items
                if hasattr(n, "metrics")}

    def annotated_plan(self) -> str:
        """The explain tree annotated per node with its metric snapshot —
        the SQL-UI plan-with-metrics analog."""
        cm = self.compile_metrics()
        lines = [f"Query {self.query_id}"
                 + (f" [{self.description}]" if self.description else "")
                 + (f" wall={self.wall_s:.4f}s" if self.wall_s is not None
                    else " (running)")
                 + f" compiles={cm['compiles']} dispatches={cm['dispatches']}"]

        def fmt(mname, v):
            if mname.endswith(("Time", "time")) or mname == SELF_TIME:
                return f"{mname}={v / 1e6:.1f}ms"
            return f"{mname}={v}"

        def visit(node, nid, parent_id, depth):
            head = "  " * depth + "*" + type(node).__name__
            args = (node.args_string()
                    if hasattr(node, "args_string") else "")
            if args:
                head += " " + args
            if nid is not None:
                snap = node.metrics.snapshot()
                # zero metrics are noise — except the row count, which is
                # load-bearing even (especially) when it is zero
                ann = ", ".join(fmt(k, v) for k, v in sorted(snap.items())
                                if v or k == NUM_OUTPUT_ROWS)
                head += f"  [id={nid}" + (f", {ann}" if ann else "") + "]"
            lines.append(head)

        if self.root is not None:
            self._walk(self.root, None, 0, visit)
        else:
            lines.append("  (no executed plan recorded)")
        return "\n".join(lines)
