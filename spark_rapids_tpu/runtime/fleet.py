"""Serving-fleet replica plane: on-disk membership with lease adoption.

N `QueryEndpoint` replicas (ROADMAP item 3: the millions-of-users serving
deployment) register into one shared fleet directory so replicas and clients
discover live peers without a coordinator process:

  - **Membership record**: one `replica-<id>.json` per replica (id =
    host-port-pid), written atomically via a pid-unique tmp + os.replace,
    carrying the replica's address, pid, and the shared-store directories it
    writes (stage cache, plan history) — the state a survivor must reclaim.
  - **Lease**: the record file's mtime. A daemon heartbeat thread renews it
    every `fleet.heartbeat.intervalSeconds`; a record older than
    `fleet.lease.timeoutSeconds` is expired — the replica is dead (SIGKILL),
    wedged, or partitioned, and is dropped from `members(live_only=True)`.
    Each renewal rewrites the record embedding the registered health
    provider's compact summary (active queries, HBM watermark, cache hit
    rates, resilience counters, SLO snapshot), so the fleet directory is
    also the fleet-wide health roster (`profiler.py fleet`).
  - **Adoption**: every heartbeat also runs `sweep_expired()` under a
    cross-process advisory lock (runtime/locks.py), so exactly one survivor
    adopts each expired lease: it unlinks the membership record and reclaims
    the dead replica's shared-store WRITE INTENTS — orphaned
    `*.tmp.<pid>...` files a mid-write crash left in the store directories
    (completed entries are already durable via os.replace and stay). Each
    adoption emits a `fleet.adopt` event and counts `fleetAdoptions` in the
    resilience registry, which the no-faults gates assert stays zero.

Failure posture mirrors the other shared stores: every filesystem race
(record vanishing mid-read, peer sweeping concurrently) degrades to a skip,
never an error — fleet membership can cost a stale member list for one
heartbeat, never a query.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

from spark_rapids_tpu.runtime.locks import advisory_lock

log = logging.getLogger("spark_rapids_tpu.fleet")

_PREFIX = "replica-"
_DEPARTED_PREFIX = "departed-"
_SUFFIX = ".json"
_LOCK_FILE = "fleet.lock"


def _record_name(replica_id: str) -> str:
    return _PREFIX + replica_id + _SUFFIX


def _is_write_intent(name: str, pid: int) -> bool:
    """True for an orphaned tmp file written by `pid` — the `.tmp.<pid>` /
    `.tmp.<pid>-<seq>` suffixes of stage_cache.save and history._store."""
    marker = ".tmp."
    idx = name.rfind(marker)
    if idx < 0:
        return False
    tail = name[idx + len(marker):]
    owner = tail.split("-", 1)[0]
    return owner == str(pid)


class FleetDirectory:
    """One replica's view of the shared fleet directory. `register()` makes
    this process a member (with heartbeat + sweeper); an unregistered
    instance is a read-only observer clients use for discovery."""

    def __init__(self, directory: str, *, lease_timeout_s: float = 10.0,
                 heartbeat_interval_s: float = 2.0):
        self.directory = directory
        self.lease_timeout_s = max(float(lease_timeout_s), 0.1)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.replica_id: str | None = None
        self._record_path: str | None = None
        self._record: dict | None = None
        self._health_provider = None
        self._hb_thread: threading.Thread | None = None
        self._hb_stop = threading.Event()
        self._lock = threading.Lock()
        # observability counters (tests + STATS read these)
        self.heartbeats = 0
        self.sweeps = 0
        self.adoptions = 0
        self.reclaimed_intents = 0
        os.makedirs(directory, exist_ok=True)

    # -- membership -----------------------------------------------------------

    def register(self, host: str, port: int, *, stores=(), extra=None) -> str:
        """Write this replica's lease-stamped membership record and start the
        heartbeat thread. Returns the replica id."""
        rid = f"{host}-{port}-{os.getpid()}"
        record = {
            "replica": rid,
            "host": host,
            "port": int(port),
            "pid": os.getpid(),
            "stores": [s for s in stores if s],
            "registered": time.time(),
        }
        if extra:
            record.update(extra)
        with self._lock:
            self.replica_id = rid
            self._record = record
            self._record_path = os.path.join(self.directory, _record_name(rid))
            self._write_record()
        self._emit("fleet.register", replica=rid, host=host, port=int(port))
        if self.heartbeat_interval_s > 0:
            self._hb_stop.clear()
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, name=f"srt-fleet-hb-{port}",
                daemon=True)
            self._hb_thread.start()
        return rid

    def deregister(self) -> None:
        """Stop the heartbeat and drop this replica's membership record (the
        clean-shutdown path; a SIGKILLed replica instead expires and is
        adopted)."""
        self._hb_stop.set()
        t = self._hb_thread
        if t is not None:
            t.join(timeout=self.heartbeat_interval_s + 5)
            self._hb_thread = None
        with self._lock:
            rid, path = self.replica_id, self._record_path
            self.replica_id = None
            self._record_path = None
            self._record = None
        if path is not None:
            try:
                os.unlink(path)
            except OSError:
                pass
            self._emit("fleet.deregister", replica=rid)

    def set_health_provider(self, fn) -> None:
        """Register a callable returning a compact JSON-serializable health
        summary; every lease renewal embeds its latest result in the
        membership record, so the fleet directory doubles as the roster of
        last-known replica state — a dead replica's final record (preserved
        as a `departed-` tombstone on adoption) still names what it was
        doing. None unregisters."""
        with self._lock:
            self._health_provider = fn

    def renew(self) -> None:
        """Renew this replica's lease by rewriting the record (the atomic
        os.replace stamps a fresh mtime), embedding the health provider's
        current summary. The provider runs OUTSIDE the fleet lock — it may
        take the endpoint's own locks — and its failure degrades to a
        health-less renewal, never a lost lease."""
        with self._lock:
            if self._record_path is None:
                return
            prov = self._health_provider
        health = None
        if prov is not None:
            try:
                health = prov()
            except Exception as e:  # noqa: BLE001 — health is best-effort
                log.warning("fleet health provider failed: %s", e)
        with self._lock:
            if self._record_path is None:
                return   # deregistered while the provider ran
            if health is not None:
                self._record["health"] = health
            try:
                self._write_record()
                self.heartbeats += 1
            except OSError as e:
                log.warning("fleet lease renewal failed (%s); peers may "
                            "adopt this replica's lease", e)

    def _write_record(self) -> None:
        tmp = f"{self._record_path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self._record, f, separators=(",", ":"), default=str)
        os.replace(tmp, self._record_path)

    def _heartbeat_loop(self) -> None:
        while not self._hb_stop.wait(self.heartbeat_interval_s):
            self.renew()
            try:
                self.sweep_expired()
            except Exception as e:  # noqa: BLE001 — sweeping must not kill hb
                log.warning("fleet sweep failed: %s", e)

    # -- discovery ------------------------------------------------------------

    def members(self, live_only: bool = True) -> list[dict]:
        """All membership records, each with an `age_s` field; `live_only`
        drops records whose lease (mtime) has expired."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        now = time.time()
        out = []
        for n in sorted(names):
            if not (n.startswith(_PREFIX) and n.endswith(_SUFFIX)):
                continue
            p = os.path.join(self.directory, n)
            try:
                age = now - os.stat(p).st_mtime
                with open(p, "r", encoding="utf-8") as f:
                    rec = json.load(f)
            except (OSError, ValueError):
                continue  # swept/torn by a peer mid-read
            if live_only and age > self.lease_timeout_s:
                continue
            rec["age_s"] = age
            out.append(rec)
        return out

    def addresses(self) -> list[tuple]:
        """(host, port) of every live member — the client discovery view."""
        return [(m["host"], int(m["port"])) for m in self.members()
                if m.get("host") and m.get("port")]

    # -- adoption -------------------------------------------------------------

    def sweep_expired(self) -> list[str]:
        """Adopt every expired lease: unlink the membership record and
        reclaim the dead replica's orphaned shared-store write intents.
        Serialized across replicas by the fleet advisory lock, so each dead
        replica is adopted exactly once. Returns adopted replica ids."""
        try:
            names = [n for n in os.listdir(self.directory)
                     if n.startswith(_PREFIX) and n.endswith(_SUFFIX)]
        except OSError:
            return []
        own = _record_name(self.replica_id) if self.replica_id else None
        stale = []
        now = time.time()
        for n in names:
            if n == own:
                continue
            try:
                age = now - os.stat(os.path.join(self.directory, n)).st_mtime
            except OSError:
                continue
            if age > self.lease_timeout_s:
                stale.append(n)
        if not stale:
            return []
        adopted = []
        with advisory_lock(os.path.join(self.directory, _LOCK_FILE)):
            with self._lock:
                self.sweeps += 1
            for n in stale:
                p = os.path.join(self.directory, n)
                try:
                    # re-check under the lock: the replica may have renewed,
                    # or a peer may have adopted it while we waited
                    if time.time() - os.stat(p).st_mtime <= self.lease_timeout_s:
                        continue
                    with open(p, "r", encoding="utf-8") as f:
                        rec = json.load(f)
                    os.unlink(p)
                except (OSError, ValueError):
                    continue
                reclaimed = self._reclaim_intents(rec)
                rid = rec.get("replica", n)
                adopted.append(rid)
                with self._lock:
                    self.adoptions += 1
                    self.reclaimed_intents += reclaimed
                # preserve the victim's final record (last-known health,
                # blackbox path) as a departed- tombstone: the roster
                # (profiler.py fleet) can still explain a dead replica
                self._write_tombstone(rec, adopted_by=self.replica_id)
                from spark_rapids_tpu.runtime import metrics as M
                M.resilience_add(M.FLEET_ADOPTIONS)
                self._emit("fleet.adopt", replica=rid,
                           by=self.replica_id, dead_pid=rec.get("pid"),
                           reclaimed_intents=reclaimed,
                           blackbox=rec.get("blackbox"))
                log.info("fleet: adopted expired lease of %s "
                         "(%d write intents reclaimed)", rid, reclaimed)
        return adopted

    def _write_tombstone(self, rec: dict, adopted_by: str | None) -> None:
        rec = dict(rec)
        rec["departed"] = time.time()
        rec["adopted_by"] = adopted_by
        name = _DEPARTED_PREFIX + str(rec.get("replica", "unknown")) + _SUFFIX
        path = os.path.join(self.directory, name)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(rec, f, separators=(",", ":"), default=str)
            os.replace(tmp, path)
        except OSError:
            pass   # the tombstone is observability, never load-bearing

    def departed(self) -> list[dict]:
        """Tombstones of adopted (dead) replicas: each is the victim's final
        membership record — last-known health included — plus `departed`
        (adoption wall-clock) and `adopted_by`."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        out = []
        for n in sorted(names):
            if not (n.startswith(_DEPARTED_PREFIX) and n.endswith(_SUFFIX)):
                continue
            try:
                with open(os.path.join(self.directory, n), "r",
                          encoding="utf-8") as f:
                    out.append(json.load(f))
            except (OSError, ValueError):
                continue
        return out

    def _reclaim_intents(self, rec: dict) -> int:
        """Unlink orphaned `*.tmp.<pid>...` files the dead replica left in
        its recorded store directories. Completed entries landed via
        os.replace and are untouched — only half-written intents go."""
        pid = rec.get("pid")
        if not isinstance(pid, int):
            return 0
        n = 0
        for d in rec.get("stores") or []:
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for name in names:
                if _is_write_intent(name, pid):
                    try:
                        os.unlink(os.path.join(d, name))
                        n += 1
                    except OSError:
                        pass
        return n

    # -- observability --------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {"replica": self.replica_id,
                    "heartbeats": self.heartbeats,
                    "sweeps": self.sweeps,
                    "adoptions": self.adoptions,
                    "reclaimed_intents": self.reclaimed_intents,
                    "live_members": len(self.members())}

    def _emit(self, event: str, **fields) -> None:
        try:
            from spark_rapids_tpu.runtime import eventlog as EL
            if EL.enabled():
                EL.emit(event, **fields)
        except Exception:  # noqa: BLE001 — observability must not fail serving
            pass


# -- shared catalog epoch -----------------------------------------------------
# One monotonic counter file per fleet directory. A catalog-changing event on
# ANY replica (a streaming APPEND landing through one endpoint) must
# invalidate EVERY replica's result cache, including replicas that never saw
# the append — the cache keys on the session's catalog epoch, and the
# session folds this shared counter in (session.catalog_epoch) whenever
# fleet.dir is configured. Same write discipline as the lease records:
# read-modify-replace via a pid-unique intent under the advisory lock, so
# two replicas bumping concurrently lose neither bump.

_EPOCH_FILE = "catalog_epoch.json"


def shared_catalog_epoch(directory: str) -> int:
    """The fleet-wide catalog epoch; 0 for a fresh/unreadable counter (an
    unreadable counter can cost a stale cache MISS path only after a bump
    lands, and bumps rewrite the file whole)."""
    try:
        with open(os.path.join(directory, _EPOCH_FILE),
                  "r", encoding="utf-8") as f:
            return int(json.load(f).get("epoch", 0))
    except (OSError, ValueError, TypeError):
        return 0


def bump_shared_catalog_epoch(directory: str) -> int:
    """Atomically advance the fleet-wide catalog epoch; returns the new
    value. Never raises — a bump that cannot land degrades to a warning
    (serving keeps working; at worst a peer replica can serve one stale
    cached frame until its own catalog changes)."""
    path = os.path.join(directory, _EPOCH_FILE)
    try:
        os.makedirs(directory, exist_ok=True)
        with advisory_lock(path + ".lock"):
            epoch = shared_catalog_epoch(directory) + 1
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"epoch": epoch}, f)
            os.replace(tmp, path)
        return epoch
    except OSError as e:
        log.warning("shared catalog epoch bump failed under %s: %s",
                    directory, e)
        return 0
