"""Arrow-over-TCP query-serving endpoint — the network front door.

The reference plugin is reachable because Spark itself is: external clients
hand SQL to a Thrift/Connect server and stream columnar results back. This
engine's multi-tenant core stopped at the Python API — PR 6 built admission
control, deadlines, cooperative cancellation and overload shedding
(runtime/scheduler.py) and made ``QueryRejectedError`` pickle-round-trippable
*for exactly this boundary*. This module is the remaining half of ROADMAP
item 2: a driver-side TCP server that accepts SQL submissions, routes them
through the scheduler, and streams Arrow-IPC result batches back, speaking
the shuffle transport's length-prefixed frame protocol
(shuffle/transport.py ``send_frame``/``recv_frame``) with CRC32C-stamped
payloads (runtime/checksum.py).

The robustness core is the failure surface, not the happy path:

- **Disconnect-driven cancellation.** Every active connection is watched
  for half-close/RST/idle-timeout while its query runs; a lost client fires
  the query's ``CancelToken`` (reason ``client_disconnect``) so the PR-6
  drain path frees buffers, semaphore permits and shuffle map outputs —
  a killed client costs the engine nothing beyond the work already done.
- **Backpressure.** Result batches flow through a byte-bounded
  :class:`_ResultStream` whose budget is capped by the shared host-prefetch
  budget (``endpoint.maxStreamBufferBytes`` ∧ free host spill headroom): a
  slow client stalls its own producer, never the heap or its neighbours.
- **Graceful drain.** :meth:`QueryEndpoint.shutdown` (the SIGTERM path via
  :meth:`install_signal_handlers`) stops accepting, sheds new submissions
  with retryable backoff-hinted ``QueryRejectedError``, gives in-flight
  queries ``endpoint.drain.graceSeconds`` to finish, then flips their
  tokens (reason ``drain``) — the hard-kill escalation — before closing.
- **Typed errors over the wire.** Server-side failures are pickled and
  re-raised typed at the client: ``QueryRejectedError`` (with its
  ``backoff_hint_s``), ``QueryCancelledError``/``QueryDeadlineError``,
  ``DeviceOomError``, ``TransportError``, ``SpillCorruptionError`` — so
  :meth:`EndpointClient.submit_with_retry` can honor the scheduler's own
  backoff hints instead of guessing.
- **Fleet membership + failover.** With ``fleet.dir`` set, the endpoint
  registers a lease-stamped membership record (runtime/fleet.py) naming its
  address and shared-store directories; its heartbeat doubles as the
  standby sweeper that adopts dead peers' leases. A fleet-registered
  replica converts a ``request_timeout`` kill into a retryable
  ``QueryRejectedError`` (reason ``replica_timeout``) — on a fleet, a
  wedged replica's queries belong on a surviving peer, so
  :class:`EndpointClient` (which accepts a comma-separated replica list)
  rotates instead of failing. Without a fleet the timeout stays a
  non-retryable typed cancellation, exactly as before.
- **Result cache.** With ``endpoint.resultCache.enabled``, fully-streamed
  results are recorded (runtime/result_cache.py) keyed by catalog epoch +
  plan signature + SQL digest; an identical re-submission replays the
  recorded CRC-stamped frames bit-identically WITHOUT touching scheduler
  admission — the hot set survives overload.
- **Chaos surface.** Fault sites ``endpoint.accept`` / ``endpoint.recv`` /
  ``endpoint.send`` (any armed kind fires, runtime/faults.py) and the
  ``endpoint.corrupt`` payload site (byte flip AFTER the CRC is stamped,
  so the client's verification must catch it) drive tools/endpoint_chaos.py
  and tests/test_endpoint.py.

Every transition is visible in the event log: ``endpoint.start`` /
``endpoint.stop``, ``client.connected`` / ``client.disconnected``,
``server.drain`` — alongside the scheduler's query lifecycle events.

Trust model: the error channel carries pickled exceptions, so the endpoint
binds loopback by default (``endpoint.host``) and belongs behind the same
trust boundary as the shuffle data plane — it is the driver's front door,
not an internet-facing gateway.
"""

from __future__ import annotations

import collections
import copy
import json
import pickle
import random
import select
import socket
import socketserver
import struct
import threading
import time
import uuid

import pyarrow as pa

from spark_rapids_tpu import config as CFG
from spark_rapids_tpu.runtime import blackbox as BB
from spark_rapids_tpu.runtime import faults as F
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import scheduler as SCHED
from spark_rapids_tpu.runtime.checksum import block_checksum
from spark_rapids_tpu.shuffle.transport import (TransportError,
                                                configure_socket,
                                                max_frame_bytes as
                                                _default_max_frame,
                                                recv_frame, send_frame)

# endpoint message ids — disjoint from the shuffle control plane's 1..5 so a
# client pointed at the wrong port fails loudly instead of half-parsing
MSG_SUBMIT = 16         # client→server: JSON request (sql + per-query knobs)
MSG_RESULT_BATCH = 17   # server→client: <Q crc> + Arrow-IPC stream payload
MSG_RESULT_END = 18     # server→client: JSON summary (query id, rows, ...)
MSG_QUERY_ERROR = 19    # server→client: pickled typed exception
MSG_PING = 20           # client→server: liveness probe
MSG_PONG = 21           # server→client: liveness reply
MSG_STATS = 22          # client→server: live serving-metrics snapshot probe
MSG_STATS_RESP = 23     # server→client: Prometheus-style text exposition
MSG_APPEND = 24         # client→server: <I hlen> + JSON header (source,
#                         batch, crc) + Arrow-IPC stream payload — one
#                         durable streaming-source batch (streaming/)
MSG_APPEND_ACK = 25     # server→client: JSON ack (duplicate flag, rows,
#                         catalog epoch, replica) — sent only after the
#                         batch is durable on disk

_CRC = struct.Struct("<Q")
_HDR = struct.Struct("<I")

# request knobs a client may set per submission — mapped onto the session
# conf keys the scheduler reads at submit time; everything else in the
# request JSON is rejected (the wire must not become a generic conf setter).
# 'trace' is NOT a conf key: it is the client's distributed trace id, handed
# to the query's collector so server-side spans merge with the client's own.
# 'journey'/'attempt' are likewise pure observability: the client-stamped
# journey id survives submit_with_retry's replica rotation, so each
# replica's query.journey record joins into one cross-replica timeline
_REQUEST_KNOBS = {
    "priority": (CFG.SCHEDULER_PRIORITY.key, int),
    "deadline_s": (CFG.SCHEDULER_QUERY_DEADLINE.key, float),
    "queue_timeout_s": (CFG.SCHEDULER_QUEUE_TIMEOUT.key, float),
}

_META_FIELDS = {"sql", "description", "trace", "journey", "attempt"}


def _table_to_ipc(tbl: pa.Table) -> bytes:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, tbl.schema) as w:
        w.write_table(tbl)
    return sink.getvalue().to_pybytes()


def _ipc_to_table(data: bytes) -> pa.Table:
    return pa.ipc.open_stream(pa.BufferReader(data)).read_all()


def _pickle_error(exc: BaseException) -> bytes:
    try:
        return pickle.dumps(exc)
    except Exception:   # noqa: BLE001 — an unpicklable error still travels
        return pickle.dumps(RuntimeError(
            f"{type(exc).__name__}: {exc!r}"[:500]))


# ---------------------------------------------------------------------------
# live serving metrics (STATS frames)
# ---------------------------------------------------------------------------

def _hist_family(name: str):
    """Map a runtime/metrics histogram name to its Prometheus family +
    label string."""
    if name.startswith("query.latency.priority"):
        p = name[len("query.latency.priority"):]
        return "srt_query_latency_seconds", f'priority="{p}"'
    if name == "admission.wait":
        return "srt_admission_wait_seconds", ""
    # movement plane: per-transfer size / latency distributions
    if name == "movement.transfer.bytes":
        return "srt_movement_transfer_bytes", ""
    if name == "movement.transfer.latency":
        return "srt_movement_transfer_latency_seconds", ""
    safe = "".join(c if c.isalnum() else "_" for c in name)
    return f"srt_{safe}", ""


def render_stats(include_histograms: bool = True, endpoint=None) -> str:
    """Prometheus-style text snapshot of the live serving metrics: query
    lifecycle counters (admitted / shed / cancelled / deadline), the whole
    resilience registry, memory + queue gauges (HBM in use, spill tiers,
    admission queue depth, active queries, pipeline queue occupancy,
    endpoint connections) and the fixed-bucket latency histograms. An
    `endpoint` adds its fleet-membership and result-cache families."""
    from spark_rapids_tpu.runtime import eventlog as EL
    lines = []

    def fam(name, mtype):
        lines.append(f"# TYPE {name} {mtype}")

    sched = SCHED.QueryScheduler.get().stats()
    for key, metric in (("admitted", "srt_queries_admitted_total"),
                        ("shed", "srt_queries_shed_total"),
                        ("demotions", "srt_query_demotions_total")):
        fam(metric, "counter")
        lines.append(f"{metric} {sched[key]}")
    counters = M.counters_snapshot()
    fam("srt_queries_deadline_total", "counter")
    lines.append("srt_queries_deadline_total "
                 f"{counters.get('queries.deadline', 0)}")
    fam("srt_resilience_total", "counter")
    for k, v in sorted(M.resilience_snapshot().items()):
        lines.append(f'srt_resilience_total{{counter="{k}"}} {v}')

    fam("srt_scheduler_running", "gauge")
    lines.append(f"srt_scheduler_running {sched['running']}")
    fam("srt_scheduler_queue_depth", "gauge")
    lines.append(f"srt_scheduler_queue_depth {sched['queued']}")
    health = EL.health_payload()
    if health.get("device_initialized"):
        fam("srt_hbm_bytes", "gauge")
        for kind in ("budget", "used", "free"):
            lines.append(f'srt_hbm_bytes{{kind="{kind}"}} '
                         f'{health[f"hbm_{kind}_bytes"]}')
        fam("srt_spill_tier_bytes", "gauge")
        for tier, d in sorted(health["tiers"].items()):
            lines.append(f'srt_spill_tier_bytes{{tier="{tier}"}} '
                         f'{d["bytes"]}')
        # memory observability plane: process device high-water mark + live
        # device bytes per allocation site (who holds the HBM right now)
        fam("srt_hbm_watermark_bytes", "gauge")
        lines.append("srt_hbm_watermark_bytes "
                     f"{health.get('hbm_watermark_bytes', 0)}")
        mem_sites = health.get("memory_sites") or {}
        if mem_sites:
            fam("srt_memory_site_bytes", "gauge")
            for site, v in sorted(mem_sites.items()):
                lines.append(f'srt_memory_site_bytes{{site="{site}"}} {v}')
    fuse = health.get("fuse", {})
    fam("srt_fuse_total", "counter")
    for k in ("traces", "dispatches"):
        lines.append(f'srt_fuse_total{{kind="{k}"}} {fuse.get(k, 0)}')
    # stats plane: plan-shape history occupancy + submit-time hit counter
    gauges = M.gauges_snapshot()
    fam("srt_history_shapes", "gauge")
    lines.append(f"srt_history_shapes {gauges.get('history.shapes', 0)}")
    fam("srt_history_hit_total", "counter")
    lines.append(f"srt_history_hit_total {counters.get('history.hit', 0)}")
    fam("srt_gauge", "gauge")
    for k, v in sorted(gauges.items()):
        if k == "history.shapes":   # already exposed as its own family
            continue
        lines.append(f'srt_gauge{{name="{k}"}} {v}')
    # movement plane: cumulative bytes per (edge, link) from the ledger
    from spark_rapids_tpu.runtime import movement as MV
    flows = MV.edge_link_totals()
    if flows:
        fam("srt_movement_bytes", "gauge")
        for (edge, link), v in sorted(flows.items()):
            lines.append(f'srt_movement_bytes{{edge="{edge}",link="{link}"}} '
                         f'{v["bytes"]}')

    if endpoint is not None and endpoint.fleet is not None:
        fstats = endpoint.fleet.stats()
        fam("srt_fleet_live_members", "gauge")
        lines.append(f"srt_fleet_live_members {fstats['live_members']}")
        fam("srt_fleet_total", "counter")
        for k in ("heartbeats", "sweeps", "adoptions", "reclaimed_intents"):
            lines.append(f'srt_fleet_total{{event="{k}"}} {fstats[k]}')
    if endpoint is not None and endpoint.result_cache is not None:
        rstats = endpoint.result_cache.stats()
        fam("srt_result_cache_total", "counter")
        for k in ("hits", "misses", "inserts", "evictions", "stale_drops"):
            lines.append(f'srt_result_cache_total{{event="{k}"}} {rstats[k]}')
        fam("srt_result_cache_bytes", "gauge")
        lines.append(f"srt_result_cache_bytes {rstats['bytes']}")
        fam("srt_result_cache_entries", "gauge")
        lines.append(f"srt_result_cache_entries {rstats['entries']}")
    if endpoint is not None and endpoint.slo.target_s > 0:
        sstats = endpoint.slo.snapshot()
        fam("srt_slo_latency_target_seconds", "gauge")
        lines.append(f"srt_slo_latency_target_seconds {sstats['target_s']}")
        fam("srt_slo_total", "counter")
        for k in ("served", "breaches", "errors"):
            lines.append(f'srt_slo_total{{event="{k}"}} {sstats[k]}')

    if include_histograms:
        for name, snap in sorted(M.histograms_snapshot().items()):
            family, label = _hist_family(name)
            fam(family, "histogram")
            cum = 0
            for bound, count in zip(snap["bounds"], snap["counts"]):
                cum += count
                sep = "," if label else ""
                lines.append(f'{family}_bucket{{{label}{sep}le="{bound}"}} '
                             f"{cum}")
            sep = "," if label else ""
            lines.append(f'{family}_bucket{{{label}{sep}le="+Inf"}} '
                         f'{snap["count"]}')
            lab = f"{{{label}}}" if label else ""
            lines.append(f"{family}_sum{lab} {round(snap['sum'], 6)}")
            lines.append(f"{family}_count{lab} {snap['count']}")
    return "\n".join(lines) + "\n"


def parse_stats_text(text: str) -> dict:
    """Parse a render_stats() exposition back into
    ``{"counters": {series: value}, "gauges": {series: value}}`` keyed by
    the full series string (``name{labels}``). Histogram families are
    skipped — bucket counts do not sum meaningfully across label sets.
    The inverse half of the fleet-stats rollup: aggregate counters are the
    per-series SUM across replicas (gauges do not sum; they stay
    per-replica)."""
    out = {"counters": {}, "gauges": {}}
    types: dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) >= 4:
                types[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue
        series, _, value = line.rpartition(" ")
        if not series:
            continue
        name = series.split("{", 1)[0]
        kind = types.get(name)
        if kind not in ("counter", "gauge"):
            continue
        try:
            v = float(value)
        except ValueError:
            continue
        out["counters" if kind == "counter" else "gauges"][series] = v
    return out


def merge_fleet_stats(per_replica: dict) -> dict:
    """Merge ``{address: stats_text | Exception}`` into the fleet rollup:
    per-replica parsed counters/gauges (or the dial error) plus the
    fleet-aggregate counter families, where every aggregate counter equals
    the sum of the per-replica values — the invariant the ci fleet gate
    asserts."""
    replicas = {}
    aggregate: dict[str, float] = {}
    live = 0
    for addr, text in per_replica.items():
        if isinstance(text, BaseException):
            replicas[addr] = {"ok": False,
                              "error": f"{type(text).__name__}: {text}"}
            continue
        parsed = parse_stats_text(text)
        replicas[addr] = {"ok": True, "raw": text, **parsed}
        live += 1
        for series, v in parsed["counters"].items():
            aggregate[series] = aggregate.get(series, 0.0) + v
    return {"replicas": replicas, "aggregate": {"counters": aggregate},
            "live": live, "total": len(per_replica)}


def render_fleet_stats(fs: dict) -> str:
    """Human/CI-facing text of a merge_fleet_stats() rollup: one raw
    per-replica section per address, then the aggregate counter families
    (tpu_client.py fleet-stats prints this)."""
    lines = []
    for addr, rep in fs["replicas"].items():
        lines.append(f"== replica {addr} ==")
        if not rep["ok"]:
            lines.append(f"UNREACHABLE {rep['error']}")
        else:
            lines.append(rep["raw"].rstrip("\n"))
        lines.append("")
    lines.append(f"== fleet aggregate ({fs['live']}/{fs['total']} "
                 f"replicas) ==")
    for series, v in sorted(fs["aggregate"]["counters"].items()):
        out = int(v) if float(v).is_integer() else v
        lines.append(f"{series} {out}")
    return "\n".join(lines) + "\n"


class _SloTracker:
    """Per-replica serving-latency/availability accounting against
    ``endpoint.slo.latencyTargetSeconds``. A served/cached submission over
    the target is a breach; a failed submission (error/timeout/disconnect)
    counts against availability. Inert (every observe a no-op) when the
    target is <= 0."""

    def __init__(self, target_s: float):
        self.target_s = float(target_s)
        self._lock = threading.Lock()
        self.served = 0
        self.breaches = 0
        self.errors = 0

    def observe(self, wall_s: float | None, ok: bool) -> bool:
        """Record one finished submission; True when it breached the
        latency target (the caller emits the slo.breach event)."""
        if self.target_s <= 0:
            return False
        with self._lock:
            if not ok:
                self.errors += 1
                return False
            self.served += 1
            if wall_s is not None and wall_s > self.target_s:
                self.breaches += 1
                return True
            return False

    def snapshot(self) -> dict:
        with self._lock:
            finished = self.served + self.errors
            return {
                "target_s": self.target_s,
                "served": self.served,
                "breaches": self.breaches,
                "errors": self.errors,
                "availability": round(self.served / finished, 6)
                if finished else 1.0,
            }


def _unpickle_error(payload: bytes) -> BaseException:
    try:
        exc = pickle.loads(payload)
    except Exception as e:   # noqa: BLE001
        return TransportError(f"undecodable server error frame: {e!r}")
    if isinstance(exc, BaseException):
        return exc
    return TransportError(f"server error frame was not an exception: {exc!r}")


class _ResultStream:
    """Byte-bounded handoff between a query's executor thread and its client
    connection — the endpoint's backpressure edge. Same progress guarantee
    as the pipeline queues: one item is always accepted when empty, so a
    single result batch larger than the budget cannot deadlock the query.
    The producer's full-wait runs :func:`scheduler.check_cancel`, so a
    cancelled query (disconnect, drain, deadline) unblocks immediately."""

    def __init__(self, max_bytes: int):
        self._cond = threading.Condition()
        self._items: collections.deque = collections.deque()
        self._bytes = 0
        self.max_bytes = max(1, int(max_bytes))
        self._done = False
        self._summary = None
        self._error: BaseException | None = None
        self._closed = False

    def put(self, payload: bytes) -> bool:
        """Producer side; blocks while over budget. False = consumer gone
        (connection closed) — the producer must stop, not retry."""
        with self._cond:
            while (not self._closed and self._items
                   and self._bytes + len(payload) > self.max_bytes):
                SCHED.check_cancel()
                self._cond.wait(0.05)
            if self._closed:
                return False
            self._items.append(payload)
            self._bytes += len(payload)
            self._cond.notify_all()
            return True

    def finish(self, summary: dict) -> None:
        with self._cond:
            self._summary = summary
            self._done = True
            self._cond.notify_all()

    def fail(self, exc: BaseException) -> None:
        with self._cond:
            self._error = exc
            self._done = True
            self._cond.notify_all()

    def get(self, timeout: float):
        """Consumer side: ("batch", bytes) | ("error", exc) |
        ("end", summary) | None on timeout. Queued batches drain before a
        terminal item is surfaced (results already produced still ship)."""
        with self._cond:
            if not self._items and not self._done:
                self._cond.wait(timeout)
            if self._items:
                p = self._items.popleft()
                self._bytes -= len(p)
                self._cond.notify_all()
                return ("batch", p)
            if self._done:
                if self._error is not None:
                    return ("error", self._error)
                return ("end", self._summary)
            return None

    def close(self) -> None:
        """Consumer-side cancel: unblocks and stops the producer."""
        with self._cond:
            self._closed = True
            self._items.clear()
            self._bytes = 0
            self._cond.notify_all()


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        endpoint: QueryEndpoint = self.server.owner   # type: ignore
        endpoint._handle_connection(self.request, self.client_address)


class QueryEndpoint:
    """The serving endpoint bound to one :class:`TpuSession` (whose temp
    views are the queryable catalog). Listening starts at construction;
    ``with QueryEndpoint(session) as ep: ...`` drains on exit."""

    def __init__(self, session, host: str | None = None,
                 port: int | None = None):
        from spark_rapids_tpu.runtime import eventlog as EL
        from spark_rapids_tpu.shuffle import transport as TR
        self.session = session
        conf = session.conf
        self.idle_timeout = conf.get(CFG.ENDPOINT_IDLE_TIMEOUT)
        self.request_timeout = conf.get(CFG.ENDPOINT_REQUEST_TIMEOUT)
        self.drain_grace = conf.get(CFG.ENDPOINT_DRAIN_GRACE)
        self.stream_buffer = conf.get(CFG.ENDPOINT_STREAM_BUFFER)
        self.stats_enabled = conf.get(CFG.ENDPOINT_STATS_ENABLED)
        self.stats_histograms = conf.get(CFG.ENDPOINT_STATS_HISTOGRAMS)
        self.slo = _SloTracker(conf.get(CFG.ENDPOINT_SLO_LATENCY_TARGET))
        TR.set_max_frame_bytes(conf.get(CFG.TRANSPORT_MAX_FRAME_BYTES))
        self._draining = False
        self._drain_deadline = None
        self._closing = False
        self._lock = threading.Lock()
        self._conns: set = set()
        self._active: dict = {}        # id(stream) -> {df, stream, query}
        self._next_worker = 0
        self.result_cache = None
        if conf.get(CFG.ENDPOINT_RESULT_CACHE_ENABLED):
            from spark_rapids_tpu.runtime.result_cache import ResultCache
            self.result_cache = ResultCache(
                conf.get(CFG.ENDPOINT_RESULT_CACHE_MAX_BYTES),
                conf.get(CFG.ENDPOINT_RESULT_CACHE_MAX_ENTRIES))

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True
        self._srv = _Server((host or conf.get(CFG.ENDPOINT_HOST),
                             port if port is not None
                             else conf.get(CFG.ENDPOINT_PORT)), _Handler)
        self._srv.owner = self
        self.host, self.port = self._srv.server_address[:2]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True, name="srt-endpoint")
        self._thread.start()
        # fleet membership: register this replica's lease once the port is
        # bound, recording the shared-store dirs a survivor must reclaim
        self.fleet = None
        fleet_dir = conf.get(CFG.FLEET_DIR)
        if fleet_dir:
            from spark_rapids_tpu.runtime.fleet import FleetDirectory
            stores = [conf.get(CFG.STAGE_CACHE_DIR)
                      if conf.stage_cache_enabled else None,
                      conf.get(CFG.STATS_HISTORY_DIR)]
            self.fleet = FleetDirectory(
                fleet_dir,
                lease_timeout_s=conf.get(CFG.FLEET_LEASE_TIMEOUT),
                heartbeat_interval_s=conf.get(CFG.FLEET_HEARTBEAT_INTERVAL))
            # the membership record names this replica's blackbox dump path
            # and lease timeout, so a survivor's fleet.adopt can point at
            # the victim's post-mortem and an observer (profiler.py fleet)
            # can judge liveness without knowing the fleet's config
            extra = {"lease_timeout_s": self.fleet.lease_timeout_s}
            if BB.dump_path():
                extra["blackbox"] = BB.dump_path()
            self.fleet.register(self.host, self.port, stores=stores,
                                extra=extra)
            # every heartbeat embeds this endpoint's health in the lease
            # record AND runs the stuck-query watchdog — the heartbeat
            # thread outlives a wedged connection thread, so deadline
            # enforcement and the blackbox dump survive a hung send
            self.fleet.set_health_provider(self._fleet_health)
        BB.set_inflight_provider(self._inflight_snapshot)
        EL.emit("endpoint.start", query=None, host=self.host, port=self.port)

    # -- connection lifecycle ------------------------------------------------
    def _handle_connection(self, sock, peer):
        from spark_rapids_tpu.runtime import eventlog as EL
        try:
            # chaos: an armed endpoint.accept fault kills the connection at
            # admission — the client observes connect-then-close and retries
            F.maybe_inject_any("endpoint.accept")
        except BaseException:   # noqa: BLE001 — any fault kind drops the conn
            return
        configure_socket(
            sock, timeout_s=self.idle_timeout if self.idle_timeout > 0
            else None)
        with self._lock:
            if self._closing:
                return
            self._conns.add(sock)
            M.set_gauge("endpoint.connections", len(self._conns))
        EL.emit("client.connected", query=None, peer=f"{peer[0]}:{peer[1]}")
        try:
            while not self._closing:
                try:
                    F.maybe_inject_any("endpoint.recv")
                    msg, payload = recv_frame(sock)
                except (TransportError, OSError, RuntimeError):
                    return   # idle timeout, client close, or any fault kind
                if msg == MSG_PING:
                    send_frame(sock, MSG_PONG, b"")
                    continue
                if msg == MSG_STATS:
                    if not self.stats_enabled:
                        self._send_error(sock, RuntimeError(
                            "endpoint.stats.enabled=false on this endpoint"))
                        return
                    send_frame(sock, MSG_STATS_RESP, render_stats(
                        self.stats_histograms, endpoint=self).encode("utf-8"))
                    continue
                if msg == MSG_APPEND:
                    if not self._serve_append(sock, payload):
                        return
                    continue
                if msg != MSG_SUBMIT:
                    self._send_error(sock, TransportError(
                        f"unexpected message {msg} (want SUBMIT)"))
                    return
                if not self._serve_query(sock, payload):
                    return
        except (OSError, RuntimeError):
            return   # connection-level failure: the conn dies, not the server
        finally:
            with self._lock:
                self._conns.discard(sock)
                M.set_gauge("endpoint.connections", len(self._conns))

    def _send_error(self, sock, exc) -> bool:
        try:
            send_frame(sock, MSG_QUERY_ERROR, _pickle_error(exc))
            return True
        except OSError:
            return False

    def _shed_draining(self, sock) -> bool:
        remaining = 0.0
        if self._drain_deadline is not None:
            remaining = max(0.0, self._drain_deadline - time.monotonic())
        hint = round(remaining + 1.0, 3)
        return self._send_error(sock, SCHED.QueryRejectedError(
            f"endpoint draining (shutdown in progress); retry another "
            f"replica after ~{hint}s", backoff_hint_s=hint,
            reason="draining"))

    def _serve_append(self, sock, payload) -> bool:
        """One streaming APPEND: CRC-verify, persist durably, bump the
        catalog epoch (local + fleet-shared), THEN ack — the ack is the
        durability receipt, so a client that saw it can stop retrying and
        a client that didn't can retry blindly (idempotent by (source,
        batch_id)). Returns False when the connection is dead."""
        try:
            (hlen,) = _HDR.unpack_from(payload, 0)
            hdr = json.loads(payload[_HDR.size:_HDR.size + hlen]
                             .decode("utf-8"))
            source, batch = hdr["source"], hdr["batch"]
            crc = int(hdr["crc"])
            body = payload[_HDR.size + hlen:]
        except BaseException as e:   # noqa: BLE001 — parse errors travel
            return self._send_error(sock, e)
        if self._draining:
            return self._shed_draining(sock)
        try:
            # on the SERVER session, never a request copy: the epoch bump
            # must land on the session the result-cache key reads
            ack = self.session.streaming_append(source, batch,
                                                ipc_body=body, crc=crc)
        except BaseException as e:   # noqa: BLE001 — typed errors travel
            return self._send_error(sock, e)
        ack["replica"] = self.replica_name
        try:
            send_frame(sock, MSG_APPEND_ACK,
                       json.dumps(ack).encode("utf-8"))
            return True
        except OSError:
            # the batch IS durable; the client that missed this ack will
            # retry into the duplicate path and get its receipt there
            return False

    def _request_session(self, req: dict):
        """Per-request session view: shares the server session's temp views
        and process switches, but carries its own conf with the request's
        scheduler knobs — concurrent requests must not mutate shared conf."""
        overrides = {}
        for field, (key, conv) in _REQUEST_KNOBS.items():
            if req.get(field) is not None:
                overrides[key] = conv(req[field])
        sess = copy.copy(self.session)
        if overrides:
            sess.conf = self.session.conf.copy_with(**overrides)
        return sess

    # -- one submission ------------------------------------------------------
    def _serve_query(self, sock, payload) -> bool:
        """Run one submission and stream its results; returns False when the
        connection is dead and the handler loop should exit."""
        try:
            req = json.loads(payload.decode("utf-8"))
            sql = req["sql"]
            unknown = set(req) - set(_REQUEST_KNOBS) - _META_FIELDS
            if unknown:
                raise ValueError(f"unknown request fields {sorted(unknown)}")
            # the journey context exists from the first parsed byte, so
            # even a shed or plan-error submission leaves its timeline
            # record; an unstamped (legacy) client gets a server-minted id
            jctx = {"journey": str(req.get("journey") or
                                   "j-" + uuid.uuid4().hex[:12]),
                    "attempt": max(1, int(req.get("attempt") or 1)),
                    "t0": time.monotonic(), "done": False}
        except BaseException as e:   # noqa: BLE001 — parse errors travel
            return self._send_error(sock, e)
        if self._draining:
            self._journey_finish(jctx, "shed", reason="draining")
            return self._shed_draining(sock)
        try:
            sess = self._request_session(req)
            df = sess.sql(sql)
        except BaseException as e:   # noqa: BLE001 — plan errors travel
            self._journey_finish(jctx, "error", error=type(e).__name__)
            return self._send_error(sock, e)

        # result cache: a hit replays the recorded frames bit-identically
        # WITHOUT entering the scheduler — admission-exempt by design
        record = None
        if self.result_cache is not None:
            ckey = self._result_cache_key(sql, df)
            if ckey is not None:
                hit = self.result_cache.get(ckey)
                if hit is not None:
                    return self._stream_cached(sock, hit, jctx)
                record = {"key": ckey, "frames": [], "bytes": 0,
                          "over": False}

        from spark_rapids_tpu.runtime.memory import host_prefetch_budget
        stream = _ResultStream(host_prefetch_budget(self.stream_buffer))
        entry = {"df": df, "stream": stream, "sql": sql[:500],
                 "description": req.get("description", ""),
                 "jny": jctx, "t0": jctx["t0"], "timed_out": False}
        key = id(stream)
        with self._lock:
            raced_drain = self._draining   # raced shutdown(): shed, don't run
            if not raced_drain:
                self._active[key] = entry
                self._next_worker += 1
                wname = f"srt-endpoint-w{self._next_worker}"
        if raced_drain:
            self._journey_finish(jctx, "shed", reason="draining")
            return self._shed_draining(sock)
        worker = threading.Thread(target=self._run_query,
                                  args=(df, stream, req.get("trace"), record),
                                  daemon=True, name=wname)
        worker.start()
        try:
            return self._pump(sock, entry)
        finally:
            # leak guard on EVERY exit path (including a pump bug or an
            # unexpected fault class): the stream must be closed and a
            # still-running worker cancelled, or it would block forever on a
            # full stream nobody drains
            stream.close()
            if worker.is_alive():
                self._cancel_query(df, "connection_closed", wait_s=1.0)
            worker.join(timeout=60)
            with self._lock:
                self._active.pop(key, None)

    def _run_query(self, df, stream: _ResultStream, trace: str | None = None,
                   record: dict | None = None):
        """Worker thread: execute the action, pushing each result batch into
        the stream as a CRC-stamped Arrow-IPC payload. Partitions run in
        order on this one thread (batch order must be deterministic for the
        bit-identity contract); the pipelined executor still overlaps
        decode/compute/exchange inside each partition, and the stream's
        byte budget overlaps compute with the network send. A client-supplied
        `trace` id is handed to the query's collector so server-side spans
        land in the client's distributed trace. `record` collects the clean
        wire frames for the result cache (admitted only on success)."""
        from spark_rapids_tpu.exec.base import TaskContext, TpuExec
        from spark_rapids_tpu.runtime import pipeline as P
        from spark_rapids_tpu.runtime import tracing
        if trace:
            tracing.set_pending_trace(str(trace))
        counts = {"rows": 0, "batches": 0}

        def sink(tbl: pa.Table):
            body = _table_to_ipc(tbl)
            crc = block_checksum(body)
            if record is not None and not record["over"]:
                # record BEFORE fault corruption — a chaos byte flip must
                # reach exactly one client, never be replayed from cache
                clean = _CRC.pack(crc) + body
                record["frames"].append(clean)
                record["bytes"] += len(clean)
                if record["bytes"] > self.result_cache.max_bytes:
                    record["over"] = True
                    record["frames"].clear()
            # chaos: flip a byte AFTER the CRC is stamped — the client's
            # verification must catch it and raise typed TransportError
            body = F.maybe_corrupt("endpoint.corrupt", body)
            if not stream.put(_CRC.pack(crc) + body):
                SCHED.check_cancel()   # raises the token's typed error
                raise SCHED.QueryCancelledError(
                    "result stream closed by the connection")
            counts["rows"] += tbl.num_rows
            counts["batches"] += 1

        def run(hybrid):
            if isinstance(hybrid, TpuExec):
                pipe_on = P.enabled(hybrid.conf)
                for split in range(hybrid.num_partitions):
                    with TaskContext():
                        it = hybrid.execute_partition(split)
                        if pipe_on:
                            it = P.stage_iterator(
                                it, edge="collect", conf=hybrid.conf,
                                registry=hybrid.metrics,
                                node_id=hybrid._node_id, spillable=True)
                        for b in it:
                            sink(b.to_arrow())
                if counts["batches"] == 0:
                    sink(hybrid.output.to_arrow().empty_table())
            else:
                sink(hybrid.collect_host())
            return None

        try:
            df._run_action(df._plan, run)
            qm = df._last_collector
            summary = {
                "query": qm.query_id, "trace": qm.trace_id,
                "rows": counts["rows"],
                "batches": counts["batches"],
                "wall_s": round(qm.wall_s, 4),
                # XLA compiles attributable to THIS attempt: the journey
                # plane's retrace count (a warm replica serves with 0)
                "traces": qm.compile_metrics().get("compiles", 0),
                "resilience": {k: v for k, v in
                               qm.query_resilience().items() if v},
            }
            stream.finish(summary)
            if record is not None and not record["over"]:
                self.result_cache.put(record["key"], record["frames"],
                                      summary)
        except BaseException as e:   # noqa: BLE001 — marshalled to the client
            stream.fail(e)

    def _result_cache_key(self, sql: str, df):
        """(catalog epoch, plan signature, sql digest) — or None for a plan
        the signature can't cover (never cache what can't be keyed)."""
        from spark_rapids_tpu.plan.fingerprint import plan_signature
        from spark_rapids_tpu.runtime.result_cache import ResultCache
        try:
            sig = plan_signature(df._plan)
        except Exception:   # noqa: BLE001 — unkeyable plan: run it, skip cache
            return None
        return ResultCache.key(self.session.catalog_epoch, sig, sql)

    def _stream_cached(self, sock, hit: dict, jctx: dict | None = None) -> bool:
        """Replay a cached result: the recorded frames bit-identically, then
        the recorded summary marked ``cached`` and re-stamped with THIS
        submission's journey (the recorded journey belongs to the
        submission that populated the cache)."""
        from spark_rapids_tpu.runtime import movement as MV
        try:
            egress_link = MV.classify_peer(sock.getpeername())
        except OSError:
            egress_link = "client"
        try:
            for frame in hit["frames"]:
                t0 = time.perf_counter()
                send_frame(sock, MSG_RESULT_BATCH, frame)
                MV.record("endpoint.egress", len(frame), link=egress_link,
                          site="endpoint.result",
                          seconds=time.perf_counter() - t0)
            summary = dict(hit["summary"])
            summary["cached"] = True
            if jctx is not None:
                summary["journey"] = jctx["journey"]
                summary["attempt"] = jctx["attempt"]
                summary["replica"] = self.replica_name
            send_frame(sock, MSG_RESULT_END,
                       json.dumps(summary).encode("utf-8"))
            self._journey_finish(jctx, "cached",
                                 query=hit["summary"].get("query"), traces=0)
            return True
        except OSError:
            self._journey_finish(jctx, "disconnect",
                                 query=hit["summary"].get("query"))
            return False

    def _cancel_query(self, df, reason: str, wait_s: float = 5.0) -> str | None:
        """Flip the query's CancelToken (waiting briefly for the collector to
        exist — the submit/disconnect race is microseconds wide); returns the
        query id when known."""
        deadline = time.monotonic() + wait_s
        while time.monotonic() < deadline:
            c = df._last_collector
            tok = getattr(c, "cancel_token", None) if c is not None else None
            if tok is not None:
                tok.cancel(reason)
                return c.query_id
            time.sleep(0.01)
        return None

    def _pump(self, sock, entry: dict) -> bool:
        """Connection-thread loop: watch the socket for disconnect while
        relaying stream items as frames. Returns False when the connection
        died (the handler loop must exit)."""
        df, stream, jctx = entry["df"], entry["stream"], entry["jny"]
        deadline = (entry["t0"] + self.request_timeout
                    if self.request_timeout > 0 else None)
        from spark_rapids_tpu.runtime import movement as MV
        try:
            egress_link = MV.classify_peer(sock.getpeername())
        except OSError:
            egress_link = "client"
        while True:
            # disconnect probe: the client sends nothing mid-query, so any
            # readability is a half-close (b""), an RST (OSError), or a
            # protocol violation — all treated as a lost client
            try:
                readable, _, _ = select.select([sock], [], [], 0)
            except (OSError, ValueError):
                readable = [sock]
            if readable:
                try:
                    data = sock.recv(1 << 16)
                except OSError:
                    data = b""
                # half-close (b""), RST (OSError) and mid-query traffic (a
                # protocol violation) all end the connection the same way
                return self._disconnected(df, stream, jctx,
                                          half_close=not data)
            if deadline is not None and not entry["timed_out"] \
                    and time.monotonic() > deadline:
                # entry-shared flag: the heartbeat watchdog (_sweep_stuck)
                # enforces the same deadline when THIS thread is wedged
                entry["timed_out"] = True
                self._cancel_query(df, "request_timeout")
                # deadline hard-kill: flush the flight recorder while the
                # in-flight registry still names the killed query
                BB.dump("deadline_kill")
            item = stream.get(timeout=0.05)
            if item is None:
                continue
            kind, val = item
            try:
                if kind == "batch":
                    F.maybe_inject_any("endpoint.send")
                    t0 = time.perf_counter()
                    send_frame(sock, MSG_RESULT_BATCH, val)
                    # movement ledger: Arrow IPC bytes leaving to the client
                    MV.record("endpoint.egress", len(val), link=egress_link,
                              site="endpoint.result",
                              seconds=time.perf_counter() - t0)
                elif kind == "end":
                    # echo the journey in the summary frame (a copy: the
                    # result cache must record the journey-free original)
                    val = dict(val)
                    if jctx is not None:
                        val["journey"] = jctx["journey"]
                        val["attempt"] = jctx["attempt"]
                        val["replica"] = self.replica_name
                    send_frame(sock, MSG_RESULT_END,
                               json.dumps(val).encode("utf-8"))
                    self._journey_finish(jctx, "served",
                                         query=val.get("query"),
                                         wall_s=val.get("wall_s"),
                                         traces=val.get("traces", 0))
                    return True
                else:   # error
                    exc = self._fleet_retryable(val, entry["timed_out"])
                    self._journey_error(jctx, exc, entry)
                    return self._send_error(sock, exc)
            except (OSError, RuntimeError) as e:
                # a dead client socket, or an injected endpoint.send fault
                # of any kind: the server-side write path died —
                # indistinguishable from a lost client
                return self._disconnected(
                    df, stream, jctx, send_fault=isinstance(e, RuntimeError))

    def _fleet_retryable(self, exc: BaseException,
                         timed_out: bool) -> BaseException:
        """On a fleet, a ``request_timeout`` kill means THIS replica wedged —
        the query belongs on a surviving peer, so the client gets a
        retryable rejection (reason ``replica_timeout``) its rotation
        re-routes. Without a fleet the non-retryable typed cancellation is
        unchanged (there is nowhere else to go)."""
        if (self.fleet is not None and timed_out
                and isinstance(exc, SCHED.QueryCancelledError)
                and getattr(exc, "reason", "") == "request_timeout"):
            return SCHED.QueryRejectedError(
                f"replica {self.fleet.replica_id} exceeded "
                f"requestTimeoutSeconds ({self.request_timeout}s); retry a "
                f"surviving replica", backoff_hint_s=0.05,
                query_id=getattr(exc, "query_id", None),
                reason="replica_timeout", replica=self.fleet.replica_id)
        return exc

    def _disconnected(self, df, stream: _ResultStream, jctx=None,
                      **detail) -> bool:
        from spark_rapids_tpu.runtime import eventlog as EL
        qid = self._cancel_query(df, "client_disconnect")
        M.resilience_add(M.CLIENT_DISCONNECTS)
        EL.emit("client.disconnected", query=qid, **detail)
        self._journey_finish(jctx, "disconnect", query=qid)
        stream.close()
        return False

    # -- journey plane -------------------------------------------------------
    @property
    def replica_name(self) -> str:
        """This replica's identity in journey records and summary frames:
        the fleet replica id when registered, host:port otherwise."""
        if self.fleet is not None and self.fleet.replica_id:
            return self.fleet.replica_id
        return f"{self.host}:{self.port}"

    def _journey_finish(self, jctx, outcome: str, *, query=None,
                        wall_s=None, **fields) -> None:
        """Emit the submission's terminal query.journey record exactly once
        — the connection thread and the heartbeat watchdog can race to
        close the same submission — and feed the SLO accounting (a shed is
        a redirect, not an availability loss)."""
        from spark_rapids_tpu.runtime import eventlog as EL
        if jctx is None:
            return
        with self._lock:
            if jctx["done"]:
                return
            jctx["done"] = True
        if wall_s is None:
            wall_s = time.monotonic() - jctx["t0"]
        wall_s = round(float(wall_s), 4)
        breach = False
        if outcome in ("served", "cached"):
            breach = self.slo.observe(wall_s, ok=True)
        elif outcome != "shed":
            self.slo.observe(wall_s, ok=False)
        extra = {k: v for k, v in fields.items() if v is not None}
        EL.emit("query.journey", query=query, journey=jctx["journey"],
                attempt=jctx["attempt"], replica=self.replica_name,
                outcome=outcome, wall_s=wall_s, **extra)
        if breach:
            EL.emit("slo.breach", query=query, journey=jctx["journey"],
                    attempt=jctx["attempt"], replica=self.replica_name,
                    wall_s=wall_s, target_s=self.slo.target_s)

    def _journey_error(self, jctx, exc: BaseException, entry: dict) -> None:
        """Close a submission's journey from its error path, classifying
        the outcome, and flush the flight recorder when the exception class
        is one the serving contract does not expect."""
        if isinstance(exc, SCHED.QueryRejectedError):
            outcome = ("replica_timeout"
                       if getattr(exc, "reason", "") == "replica_timeout"
                       else "shed")
        elif entry["timed_out"]:
            outcome = "timeout"
        else:
            outcome = "error"
        self._journey_finish(jctx, outcome,
                             query=getattr(exc, "query_id", None),
                             error=type(exc).__name__,
                             reason=getattr(exc, "reason", None))
        if outcome == "error" and not isinstance(
                exc, (SCHED.QueryCancelledError, TransportError)):
            BB.dump("endpoint_error")

    def _inflight_snapshot(self) -> list:
        """Blackbox dump detail: what this endpoint is serving right now —
        the record a survivor reads to explain a dead replica."""
        now = time.monotonic()
        with self._lock:
            entries = list(self._active.values())
        out = []
        for e in entries:
            c = e["df"]._last_collector
            jctx = e.get("jny") or {}
            out.append({
                "query": c.query_id if c is not None else None,
                "journey": jctx.get("journey"),
                "attempt": jctx.get("attempt"),
                "sql": e.get("sql", ""),
                "description": e.get("description", ""),
                "age_s": round(now - e.get("t0", now), 4),
                "timed_out": bool(e.get("timed_out")),
            })
        return out

    def _sweep_stuck(self) -> None:
        """Heartbeat-side deadline enforcement: the connection thread that
        normally enforces requestTimeoutSeconds can itself be wedged (a
        hung send), so every fleet heartbeat re-checks the age of each
        in-flight submission. A stuck one is cancelled, its journey closed
        (``replica_timeout`` on a fleet — the client re-routes), and the
        flight recorder dumped while this process can still write — the
        post-mortem a SIGKILL would otherwise erase."""
        limit = self.request_timeout
        if limit <= 0:
            return
        now = time.monotonic()
        with self._lock:
            stuck = [e for e in self._active.values()
                     if now - e["t0"] > limit and not e["timed_out"]]
            for e in stuck:
                e["timed_out"] = True
        for e in stuck:
            qid = self._cancel_query(e["df"], "request_timeout", wait_s=0.1)
            outcome = ("replica_timeout" if self.fleet is not None
                       else "timeout")
            self._journey_finish(e["jny"], outcome, query=qid, stuck=True)
        if stuck:
            BB.dump("stuck_query", min_interval_s=min(1.0, limit))

    def _fleet_health(self) -> dict:
        """Compact health summary embedded in this replica's lease record
        on every heartbeat — the per-replica row of the fleet roster
        (profiler.py fleet), preserved in the departed tombstone when a
        survivor adopts the lease. Doubles as the stuck-query watchdog's
        clock: the heartbeat thread outlives a wedged connection thread."""
        from spark_rapids_tpu.runtime import eventlog as EL
        self._sweep_stuck()
        h = EL.health_payload()
        out = {
            "active_queries": self.active_queries(),
            "hbm_watermark_bytes": int(h.get("hbm_watermark_bytes") or 0),
            "fuse": h.get("fuse", {}),
            "resilience": {k: v for k, v in
                           M.resilience_snapshot().items() if v},
        }
        if self.result_cache is not None:
            rs = self.result_cache.stats()
            out["result_cache"] = {"hits": rs["hits"],
                                   "misses": rs["misses"]}
        if self.slo.target_s > 0:
            out["slo"] = self.slo.snapshot()
        return out

    # -- drain / shutdown ----------------------------------------------------
    def active_queries(self) -> int:
        with self._lock:
            return len(self._active)

    def shutdown(self, grace_s: float | None = None) -> dict:
        """Graceful drain: stop accepting, shed new submissions (retryable,
        backoff-hinted), let in-flight queries finish within ``grace_s``
        (default ``endpoint.drain.graceSeconds``), then deadline-kill the
        stragglers via their CancelTokens — the hard-kill escalation — and
        close every connection. Idempotent; returns drain statistics."""
        from spark_rapids_tpu.runtime import eventlog as EL
        grace = self.drain_grace if grace_s is None else grace_s
        with self._lock:
            first = not self._draining
            self._draining = True
            if first:
                self._drain_deadline = time.monotonic() + max(0.0, grace)
            in_flight = len(self._active)
        if not first:
            return {"in_flight": in_flight, "cancelled": 0, "repeat": True}
        EL.emit("server.drain", query=None, phase="begin",
                in_flight=in_flight, grace_s=grace)
        # the listener stays up through the grace window: a client arriving
        # mid-drain gets the typed QueryRejectedError with a backoff hint
        # (retry another replica / later) instead of a blind refused connect
        while time.monotonic() < self._drain_deadline and self.active_queries():
            time.sleep(0.05)
        cancelled = 0
        with self._lock:
            stragglers = list(self._active.values())
        for entry in stragglers:
            if self._cancel_query(entry["df"], "drain", wait_s=0.5):
                cancelled += 1
        if cancelled:
            # drain hard-kill: in-flight queries are being force-cancelled;
            # leave the post-mortem before their state drains away
            BB.dump("drain_kill")
        # bounded wait for the cancelled queries to drain through their
        # cooperative checkpoints, then stop accepting and force the
        # remaining connections closed
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and self.active_queries():
            time.sleep(0.05)
        self._srv.shutdown()
        self._srv.server_close()
        self._closing = True
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        self._thread.join(timeout=5)
        if self.fleet is not None:
            self.fleet.deregister()
        stats = {"in_flight": in_flight, "cancelled": cancelled,
                 "leaked": self.active_queries()}
        EL.emit("server.drain", query=None, phase="end", **stats)
        EL.emit("endpoint.stop", query=None, port=self.port)
        return stats

    def install_signal_handlers(self, grace_s: float | None = None) -> None:
        """SIGTERM → graceful drain (main thread only). The handler runs
        shutdown() on a helper thread so the signal frame returns
        immediately; the process exits once the drain completes and the
        caller's main loop observes ``draining``."""
        import signal

        def _on_term(signum, frame):
            threading.Thread(target=self.shutdown, args=(grace_s,),
                             daemon=True, name="srt-endpoint-drain").start()
        signal.signal(signal.SIGTERM, _on_term)

    @property
    def draining(self) -> bool:
        return self._draining

    def __enter__(self) -> "QueryEndpoint":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

def _parse_addresses(address) -> list:
    """Normalize every accepted address spec to [(host, port), ...]:
    one (host, port) tuple, one "host:port" string, a comma-separated
    "host:port,host:port" replica list, or a sequence of either."""
    def one(a):
        if isinstance(a, str):
            host, _, port = a.strip().rpartition(":")
            if not host:
                raise ValueError(f"address {a!r} needs host:port")
            return (host, int(port))
        return (a[0], int(a[1]))

    if isinstance(address, str):
        parts = [p for p in (s.strip() for s in address.split(",")) if p]
        if not parts:
            raise ValueError("empty endpoint address list")
        return [one(p) for p in parts]
    seq = list(address)
    if len(seq) == 2 and isinstance(seq[0], str) and isinstance(seq[1], int):
        return [(seq[0], seq[1])]   # the classic single (host, port) tuple
    if not seq:
        raise ValueError("empty endpoint address list")
    return [one(a) for a in seq]


class EndpointClient:
    """Remote submitter (tools/tpu_client.py is the CLI front). One
    connection per submission; closing the connection mid-stream is the
    cancellation protocol — the server cancels the query on disconnect.

    `address` may name a whole replica fleet — a comma-separated
    "host:port,host:port" list (or a sequence of addresses): plain submits
    use the current replica, and :meth:`submit_with_retry` rotates to the
    next one with jitter on any retryable failure (connection refused, a
    replica dying mid-stream, shed/drain/replica_timeout rejections), so
    failover needs no client code changes."""

    def __init__(self, address, *, timeout_s: float = 60.0,
                 max_frame_bytes: int | None = None):
        self.addresses = _parse_addresses(address)
        self._addr_idx = 0
        self.timeout_s = timeout_s
        self.max_frame = max_frame_bytes or _default_max_frame()
        self.last_summary: dict | None = None
        self.last_journey: str | None = None

    @property
    def address(self) -> tuple:
        """The replica currently targeted (rotation advances it)."""
        return self.addresses[self._addr_idx]

    def rotate(self) -> tuple:
        """Advance to the next replica in the list; returns the new target.
        Counts a replicaFailovers resilience event when there is more than
        one replica (rotation on a fleet IS the failover)."""
        if len(self.addresses) > 1:
            self._addr_idx = (self._addr_idx + 1) % len(self.addresses)
            M.resilience_add(M.REPLICA_FAILOVERS)
        return self.address

    def connect(self, address=None):
        addr = address if address is not None else self.address
        try:
            sock = socket.create_connection(addr, timeout=self.timeout_s)
        except OSError as e:
            # connection refused/reset IS retryable: the replica is gone,
            # the fleet may not be — rotation finds out
            raise TransportError(
                f"endpoint {addr} unreachable: {e}") from e
        configure_socket(sock, timeout_s=self.timeout_s)
        return sock

    def ping(self) -> bool:
        sock = self.connect()
        try:
            send_frame(sock, MSG_PING, b"")
            msg, _ = recv_frame(sock, max_bytes=self.max_frame)
            return msg == MSG_PONG
        except (TransportError, OSError):
            return False
        finally:
            sock.close()

    def stats(self, address=None) -> str:
        """Live serving-metrics snapshot (Prometheus-style text): admission
        counters, resilience registry, HBM/spill/queue gauges and latency
        histograms. `address` targets a specific replica (default: the
        currently-targeted one). Raises the server's typed error when STATS
        is disabled (endpoint.stats.enabled=false)."""
        addr = address if address is not None else self.address
        sock = self.connect(addr)
        try:
            send_frame(sock, MSG_STATS, b"")
            msg, payload = recv_frame(sock, max_bytes=self.max_frame)
            if msg == MSG_QUERY_ERROR:
                raise _unpickle_error(payload)
            if msg != MSG_STATS_RESP:
                raise TransportError(f"unexpected endpoint message {msg}")
            return payload.decode("utf-8")
        except OSError as e:
            raise TransportError(
                f"endpoint {addr} stats failed: {e}") from e
        finally:
            sock.close()

    def stats_all(self) -> dict:
        """Per-replica stats across the WHOLE replica list — never just the
        one replica the client happens to target. ``{"host:port": text |
        Exception}``; a dial failure is recorded, not raised, so one dead
        replica cannot hide the rest of the fleet."""
        out = {}
        for addr in self.addresses:
            key = f"{addr[0]}:{addr[1]}"
            try:
                out[key] = self.stats(addr)
            except Exception as e:   # noqa: BLE001 — typed server errors
                out[key] = e         # (stats disabled) report per-replica
        return out

    def fleet_stats(self) -> dict:
        """Fleet-wide stats rollup: dial every replica in the list, parse
        each Prometheus snapshot, and merge — per-replica counters/gauges
        (or the dial error) plus fleet-aggregate counter families where
        every aggregate equals the sum of per-replica values
        (tools/tpu_client.py fleet-stats renders this)."""
        return merge_fleet_stats(self.stats_all())

    def submit_iter(self, sql: str, *, priority: int | None = None,
                    deadline_s: float | None = None,
                    queue_timeout_s: float | None = None,
                    description: str = "", trace: str | None = None,
                    journey: str | None = None, attempt: int | None = None):
        """Generator of result tables, one per streamed Arrow-IPC batch;
        ``self.last_summary`` carries the MSG_RESULT_END stats afterwards.
        Abandoning the generator closes the connection, which cancels the
        query server-side. Raises the server's typed exception on failure
        and TransportError on any wire-level fault (CRC mismatch, short
        read, reset). Every submission is stamped with a journey id +
        attempt number (minted here when the caller has none):
        submit_with_retry reuses one journey across its replica rotation,
        so each replica's query.journey record joins one timeline."""
        if journey is None:
            journey = "j-" + uuid.uuid4().hex[:12]
        self.last_journey = journey
        req = {"sql": sql, "description": description,
               "priority": priority, "deadline_s": deadline_s,
               "queue_timeout_s": queue_timeout_s, "trace": trace,
               "journey": journey, "attempt": max(1, int(attempt or 1))}
        sock = self.connect()
        try:
            try:
                send_frame(sock, MSG_SUBMIT, json.dumps(
                    {k: v for k, v in req.items() if v is not None}
                ).encode("utf-8"))
                while True:
                    msg, payload = recv_frame(sock, max_bytes=self.max_frame)
                    if msg == MSG_RESULT_BATCH:
                        (crc,) = _CRC.unpack_from(payload, 0)
                        body = payload[_CRC.size:]
                        got = block_checksum(body)
                        if got != crc:
                            raise TransportError(
                                f"result batch checksum mismatch (sent "
                                f"{crc:#x}, got {got:#x}, {len(body)}B)")
                        yield _ipc_to_table(body)
                    elif msg == MSG_RESULT_END:
                        self.last_summary = json.loads(payload)
                        return
                    elif msg == MSG_QUERY_ERROR:
                        raise _unpickle_error(payload)
                    else:
                        raise TransportError(
                            f"unexpected endpoint message {msg}")
            except TransportError:
                raise
            except OSError as e:
                raise TransportError(
                    f"endpoint {self.address} connection failed: {e}") from e
        finally:
            sock.close()

    def submit(self, sql: str, **kw) -> pa.Table:
        """Submit and collect the whole result (a schema-bearing empty table
        for empty results)."""
        tables = list(self.submit_iter(sql, **kw))
        return pa.concat_tables(tables)

    def append(self, source: str, batch_id: str, tbl: pa.Table) -> dict:
        """Ship one streaming batch as a CRC-stamped Arrow-IPC APPEND
        frame; returns the server's ack (duplicate flag, rows, catalog
        epoch, replica). The ack means DURABLE — the server persisted the
        batch before replying. Raises the server's typed error, or a
        retryable TransportError on any wire-level fault."""
        from spark_rapids_tpu.streaming.source import table_to_ipc
        body = table_to_ipc(tbl)
        hdr = json.dumps({"source": source, "batch": batch_id,
                          "crc": block_checksum(body)}).encode("utf-8")
        sock = self.connect()
        try:
            try:
                send_frame(sock, MSG_APPEND,
                           _HDR.pack(len(hdr)) + hdr + body)
                msg, payload = recv_frame(sock, max_bytes=self.max_frame)
                if msg == MSG_QUERY_ERROR:
                    raise _unpickle_error(payload)
                if msg != MSG_APPEND_ACK:
                    raise TransportError(
                        f"unexpected endpoint message {msg} "
                        f"(want APPEND_ACK)")
                return json.loads(payload)
            except TransportError:
                raise
            except OSError as e:
                raise TransportError(
                    f"endpoint {self.address} append failed: {e}") from e
        finally:
            sock.close()

    def append_with_retry(self, source: str, batch_id: str, tbl: pa.Table,
                          *, max_attempts: int = 5,
                          backoff_cap_s: float = 10.0,
                          on_retry=None) -> dict:
        """APPEND under the same fleet rotation contract as
        submit_with_retry — safe to retry blindly because APPEND is
        idempotent by (source, batch_id): a replica that died AFTER
        persisting but BEFORE acking turns the retry into a ``duplicate``
        ack, never a double ingest. Retryable rejections (shed/drain)
        honor their backoff hint; transport faults back off exponentially;
        with a replica list every retryable failure rotates first."""
        attempt = 0
        while True:
            attempt += 1
            try:
                return self.append(source, batch_id, tbl)
            except SCHED.QueryRejectedError as e:
                if attempt >= max_attempts:
                    raise
                delay = min(max(0.05, e.backoff_hint_s), backoff_cap_s)
            except TransportError as e:
                if attempt >= max_attempts or not getattr(
                        e, "retryable", False):
                    raise
                delay = min(0.1 * (2 ** (attempt - 1)), backoff_cap_s)
            if len(self.addresses) > 1:
                self.rotate()
                delay *= 0.5 + random.random() * 0.5   # jittered rotation
            if on_retry is not None:
                on_retry(attempt, delay)
            time.sleep(delay)

    def submit_with_retry(self, sql: str, *, max_attempts: int = 5,
                          backoff_cap_s: float = 10.0, on_retry=None,
                          **kw) -> pa.Table:
        """Submit, honoring the serving contract: a retryable rejection
        (shed/drain/replica_timeout) sleeps its ``backoff_hint_s``; a
        transport fault (endpoint died mid-handshake or mid-stream, reset,
        connection refused) retries with jittered exponential backoff;
        non-retryable typed errors propagate immediately. With a replica
        list, every retryable failure first rotates to the next replica
        (jittered, so a killed replica's clients don't stampede one
        survivor) — failover is this loop, not new client code.

        One journey id spans every attempt, and when the caller passed no
        trace id the journey doubles as the trace — so a failed-over
        submission's server-side spans land in ONE distributed trace
        instead of orphaning attempt 1's spans under a per-attempt id."""
        journey = kw.pop("journey", None) or "j-" + uuid.uuid4().hex[:12]
        if kw.get("trace") is None:
            kw["trace"] = journey
        attempt = 0
        while True:
            attempt += 1
            try:
                return self.submit(sql, journey=journey, attempt=attempt,
                                   **kw)
            except SCHED.QueryRejectedError as e:
                if attempt >= max_attempts:
                    raise
                delay = min(max(0.05, e.backoff_hint_s), backoff_cap_s)
            except TransportError as e:
                if attempt >= max_attempts or not getattr(
                        e, "retryable", False):
                    raise
                delay = min(0.1 * (2 ** (attempt - 1)), backoff_cap_s)
            if len(self.addresses) > 1:
                self.rotate()
                delay *= 0.5 + random.random() * 0.5   # jittered rotation
            if on_retry is not None:
                on_retry(attempt, delay)
            time.sleep(delay)
