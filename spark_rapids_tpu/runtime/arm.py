"""RAII helpers and buffer accounting — the Arm / leak-tracking analog.

Reference: Arm.scala:23-100 (withResource/closeOnExcept) and the refcounted
RapidsBuffer catalog. XLA arrays are immutable and garbage-collected, so RAII here
shrinks to (a) context helpers for things that DO need closing (files, host buffers,
spill handles) and (b) a leak-tracking registry asserting that tracked resources are
closed — used by tests the way the reference uses cudf's leak detection."""

from __future__ import annotations

import threading
import warnings
from contextlib import contextmanager


@contextmanager
def with_resource(resource):
    """withResource: close on scope exit (Arm.scala:30)."""
    try:
        yield resource
    finally:
        resource.close()


@contextmanager
def close_on_except(resource):
    """closeOnExcept: close only if the body throws (Arm.scala:63)."""
    try:
        yield resource
    except BaseException:
        resource.close()
        raise


class LeakTracker:
    """Registry of live tracked resources; tests call assert_no_leaks()."""

    _lock = threading.Lock()
    _live: dict[int, str] = {}
    _next = 0

    @classmethod
    def track(cls, what: str) -> int:
        with cls._lock:
            cls._next += 1
            cls._live[cls._next] = what
            return cls._next

    @classmethod
    def release(cls, token: int):
        with cls._lock:
            cls._live.pop(token, None)

    @classmethod
    def live_count(cls) -> int:
        with cls._lock:
            return len(cls._live)

    @classmethod
    def assert_no_leaks(cls):
        with cls._lock:
            if cls._live:
                leaked = list(cls._live.values())
                cls._live.clear()
                raise AssertionError(f"leaked resources: {leaked}")

    @classmethod
    def warn_leaks(cls):
        with cls._lock:
            for what in cls._live.values():
                warnings.warn(f"resource leak: {what}")
            cls._live.clear()
