"""Shim layer — version-gated Spark semantics behind one stable interface.

Reference: ShimLoader.scala + the per-version sql-plugin shim source sets
(SURVEY.md component #2/#43): the reference compiles one shim jar per Spark
release and picks one at runtime. A standalone engine has no Spark classpath
to shim against, so the analog is SEMANTIC shims: one `SparkShim` object per
supported Spark behavior-generation, chosen by `spark.rapids.tpu.spark.version`,
gating the places where Spark releases genuinely disagree:

- string→date casting: 3.0 parses lenient variants ("2021-1-5"), 3.2+ accepts
  only the ANSI subset (yyyy[-M[-d]]).
- element_at(arr, 0): error pre-3.4 semantics vs null under later ANSI-off
  behavior — the engine always nulls, the 3.0 shim documents the divergence.
- parquet datetime rebase for files written by legacy (hybrid-calendar)
  writers: mode per spark.rapids.tpu.sql.parquet.datetimeRebaseModeInRead
  (EXCEPTION | CORRECTED | LEGACY), with a real Julian→proleptic-Gregorian
  day rebase (`rebase_julian_to_gregorian_days`) like Spark's
  RebaseDateTime.
- string→timestamp casting: the device/host parser implements the 3.2+
  ANSI subset; 3.0/3.1 lenient forms pin the cast to host
  (`lenient_string_to_timestamp`).
- special datetime strings (SPARK-35581): `cast('epoch'|'now'|'today'|
  'yesterday'|'tomorrow' as date/timestamp)` resolves at plan time on
  3.0/3.1 generations (`special_datetime_strings`) and yields null on
  3.2+, matching the removal; DATE/TIMESTAMP typed literals keep them on
  every generation, as Spark does.
- AQE post-shuffle coalescing default (3.2 flip, SPARK-33679) incl. the
  Databricks 3.0/3.1 early default-on.

Explicit NON-GOALS (version divergences the engine's surface does not
model; listed so the 6-generation facade is honest about its resolution —
reference SparkShims.scala:73-210 gates dozens more):
- spark.sql.legacy.timeParserPolicy=LEGACY (SimpleDateFormat quirks and
  week-based tokens; the engine's device subset rejects unsupported
  tokens on every generation and pins those expressions to host),
- ANSI mode everywhere (ANSI interval types from 3.2, try_* functions,
  error-on-overflow arithmetic; the engine is ANSI-off only),
- char/varchar padding semantics (3.1+, SPARK-33480) — no char types,
- regexp engine deltas across JDK releases (RLike rides Python `re` with
  documented divergences in docs/compatibility.md),
- CSV/JSON malformed-record policy changes across 3.x (PERMISSIVE only).
"""

from __future__ import annotations

import numpy as np


class SparkShim:
    version_prefix = "3.5"
    #: "" = OSS Apache Spark; platform variants ("databricks", "emr") mirror
    #: the reference's spark301db/spark301emr/spark310db shim modules
    platform = ""
    #: accept lenient date strings ("2021-1-5", "2021/01/05") in cast
    lenient_string_to_date = False
    #: AQE (and with it post-shuffle partition coalescing) is default-ON
    #: only since Spark 3.2 (SPARK-33679); earlier generations must opt in
    adaptive_coalesce_default = True
    #: element_at(arr, 0): pre-3.4 generations RAISE ("SQL array indices
    #: start at 1"); 3.4+ ANSI-off returns null
    element_at_zero_errors = False
    #: accept lenient timestamp strings in cast (3.0/3.1); the device
    #: parser implements the 3.2+ ANSI subset, so lenient generations pin
    #: the cast to host
    lenient_string_to_timestamp = False
    #: cast('epoch'/'now'/'today'/'yesterday'/'tomorrow' as date/timestamp)
    #: resolves on 3.0/3.1; REMOVED from casts in 3.2 (SPARK-35581) —
    #: typed literals keep them on every generation
    special_datetime_strings = False

    def __repr__(self):
        return f"SparkShim({self.version_prefix}.x)"


class Spark30Shim(SparkShim):
    version_prefix = "3.0"
    lenient_string_to_date = True
    lenient_string_to_timestamp = True
    special_datetime_strings = True
    adaptive_coalesce_default = False
    element_at_zero_errors = True


class Spark31Shim(Spark30Shim):
    """3.1 keeps 3.0's date parsing and opt-in AQE."""
    version_prefix = "3.1"


class Spark32Shim(SparkShim):
    version_prefix = "3.2"
    element_at_zero_errors = True


class Spark33Shim(Spark32Shim):
    version_prefix = "3.3"


class Spark34Shim(SparkShim):
    """3.4 flips element_at(arr, 0) from error to null (ANSI off)."""
    version_prefix = "3.4"


class Spark35Shim(SparkShim):
    version_prefix = "3.5"


# -- platform-variant shims ---------------------------------------------------
# The reference ships per-platform shim modules alongside the OSS ones
# (shims/spark301db, shims/spark301emr, shims/spark310db — Databricks and
# Amazon EMR builds of the same Spark release). The semantic deltas an engine
# must honor:
#  - Databricks Runtime enabled AQE by default from DBR 7.x (Spark 3.0),
#    two releases before OSS flipped it in 3.2 (SPARK-33679), so the
#    post-shuffle coalescing default differs from the same-numbered OSS shim.
#  - EMR tracks OSS semantics; the reference's spark301emr module exists for
#    packaging/classpath reasons, so its semantic shim is the OSS one with a
#    distinct identity (tooling that logs the shim must see the platform).


class Spark30DatabricksShim(Spark30Shim):
    version_prefix = "3.0"
    platform = "databricks"
    adaptive_coalesce_default = True   # DBR 7.x default-on AQE


class Spark31DatabricksShim(Spark31Shim):
    version_prefix = "3.1"
    platform = "databricks"
    adaptive_coalesce_default = True


class Spark30EmrShim(Spark30Shim):
    version_prefix = "3.0"
    platform = "emr"


class Spark31EmrShim(Spark31Shim):
    version_prefix = "3.1"
    platform = "emr"


_SHIMS = [Spark30Shim, Spark31Shim, Spark32Shim, Spark33Shim, Spark34Shim,
          Spark35Shim]

#: platform -> ordered shim list; the ShimServiceProvider-discovery analog.
#: register_shim() lets a deployment plug in its own platform the way the
#: reference discovers shims through java.util.ServiceLoader
#: (ShimLoader.scala:26-68).
_PLATFORM_SHIMS = {
    "": list(_SHIMS),
    "databricks": [Spark30DatabricksShim, Spark31DatabricksShim],
    "emr": [Spark30EmrShim, Spark31EmrShim],
}


def register_shim(shim_cls, platform: str = "") -> None:
    """Add a shim to the selection table (ServiceLoader-registration analog).
    Later registrations win ties on version_prefix."""
    _PLATFORM_SHIMS.setdefault(platform, []).append(shim_cls)


def load_shim(version: str) -> SparkShim:
    """Latest shim whose version_prefix <= requested version (ShimLoader's
    getShimVersion selection). A `-<platform>` suffix ("3.0.1-databricks",
    the spark.rapids.shims-provider-override analog) selects that platform's
    shim set, falling back to OSS for generations the platform doesn't
    specialize."""
    version, _, platform = version.partition("-")

    def key(p):
        a, b = p.split(".")
        return (int(a), int(b))
    want = key(".".join(version.split(".")[:2]))
    candidates = list(_PLATFORM_SHIMS[""])
    if platform:
        if platform not in _PLATFORM_SHIMS:
            raise ValueError(
                f"unknown shim platform {platform!r}; registered: "
                f"{sorted(p for p in _PLATFORM_SHIMS if p)}")
        candidates += _PLATFORM_SHIMS[platform]
    best, best_key = None, None
    for s in candidates:
        k = key(s.version_prefix)
        if k <= want:
            platform_match = getattr(s, "platform", "") == platform
            rank = (k, platform_match)
            if best_key is None or rank >= best_key:
                best, best_key = s, rank
    chosen = best or _SHIMS[0]
    if platform and getattr(chosen, "platform", "") != platform:
        # e.g. load_shim("3.5.0-databricks") when the databricks set only
        # specializes 3.0/3.1: the OSS generation serves the request, but
        # newer platform semantic deltas are unmodeled — say so once.
        import warnings
        warnings.warn(
            f"shim {version}-{platform}: no {platform} shim specializes "
            f"{version}; using OSS {chosen.version_prefix} semantics")
    return chosen()


def shim_for(conf) -> SparkShim:
    from spark_rapids_tpu import config as C
    return load_shim(conf.get(C.SPARK_VERSION))


# -- legacy (hybrid-calendar) datetime rebase --------------------------------
# Spark RebaseDateTime: files written by Spark 2.x / Hive used the hybrid
# Julian+Gregorian calendar; days before the 1582-10-15 switch must be
# reinterpreted. JDN arithmetic, vectorized on host at scan time (the decode
# stage is host-side; the rebase never touches the device path).

GREGORIAN_SWITCH_DAY = -141427  # 1582-10-15 as days since 1970-01-01


def _julian_jdn_to_ymd(jdn):
    c = jdn + 32082
    d = (4 * c + 3) // 1461
    e = c - (1461 * d) // 4
    m = (5 * e + 2) // 153
    day = e - (153 * m + 2) // 5 + 1
    month = m + 3 - 12 * (m // 10)
    year = d - 4800 + m // 10
    return year, month, day


def _gregorian_ymd_to_jdn(y, m, d):
    a = (14 - m) // 12
    y2 = y + 4800 - a
    m2 = m + 12 * a - 3
    return (d + (153 * m2 + 2) // 5 + 365 * y2 + y2 // 4 - y2 // 100
            + y2 // 400 - 32045)


def rebase_julian_to_gregorian_days(days: np.ndarray) -> np.ndarray:
    """Hybrid-calendar epoch days → proleptic Gregorian epoch days (read
    rebase). Identity at/after the 1582-10-15 switch."""
    days = np.asarray(days, dtype=np.int64)
    old = days < GREGORIAN_SWITCH_DAY
    if not old.any():
        return days
    jdn = days[old] + 2440588  # JDN of 1970-01-01
    y, m, d = _julian_jdn_to_ymd(jdn)
    out = days.copy()
    out[old] = _gregorian_ymd_to_jdn(y, m, d) - 2440588
    return out


def rebase_gregorian_to_julian_days(days: np.ndarray) -> np.ndarray:
    """Inverse (write rebase, LEGACY writer mode)."""
    days = np.asarray(days, dtype=np.int64)
    old = days < GREGORIAN_SWITCH_DAY
    if not old.any():
        return days
    jdn = days[old] + 2440588
    # invert gregorian jdn → ymd
    a = jdn + 32044
    b = (4 * a + 3) // 146097
    c = a - (146097 * b) // 4
    d_ = (4 * c + 3) // 1461
    e = c - (1461 * d_) // 4
    m = (5 * e + 2) // 153
    day = e - (153 * m + 2) // 5 + 1
    month = m + 3 - 12 * (m // 10)
    year = 100 * b + d_ - 4800 + m // 10
    # julian ymd → jdn
    a2 = (14 - month) // 12
    y2 = year + 4800 - a2
    m2 = month + 12 * a2 - 3
    jdn_j = day + (153 * m2 + 2) // 5 + 365 * y2 + y2 // 4 - 32083
    out = days.copy()
    out[old] = jdn_j - 2440588
    return out
