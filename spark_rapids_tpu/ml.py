"""ML integration: zero-copy export of query output to JAX arrays.

Reference (SURVEY.md #41): ColumnarRdd.scala:49 + InternalColumnarRddConverter
export a DataFrame as RDD[cudf.Table] without copies so XGBoost4J-Spark trains
directly on GPU data; GpuBringBackToHost gates the device→host hop. TPU analog:
the query's device batches stay jax arrays — `columnar_partitions` hands them to
ML code with no host round-trip, and `to_feature_matrix` builds the (n, d)
design matrix ON DEVICE (cast + stack, one XLA program), the row-matrix
conversion XGBoost needs."""

from __future__ import annotations

import typing

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import TaskContext, TpuExec
from spark_rapids_tpu.expr.core import Col
from spark_rapids_tpu.ops.concat import concat_batches
from spark_rapids_tpu.plan.overrides import TpuOverrides
from spark_rapids_tpu.plan.transitions import DeviceBridgeExec


def _device_plan(df) -> TpuExec:
    from spark_rapids_tpu.plan.transitions import to_device_plan
    return to_device_plan(df._plan, df.session.conf)


def columnar_partitions(df) -> typing.Iterator[ColumnarBatch]:
    """Yield each partition's data as ONE device ColumnarBatch (the
    RDD[cudf.Table] analog: no host materialization)."""
    plan = _device_plan(df)
    for split in range(plan.num_partitions):
        with TaskContext():
            batches = list(plan.execute_partition(split))
        if batches:
            yield concat_batches(batches)


def to_feature_matrix(df, feature_cols: list, label_col: str | None = None,
                      dtype=jnp.float32):
    """Collect a DataFrame into a dense on-device design matrix.

    Returns (X, y, mask): X is (n, d) `dtype`, y is (n,) or None, mask is (n,)
    bool marking rows where every feature (and label) is non-null — ML callers
    filter or weight by it (the reference leaves null handling to XGBoost).
    Padding rows are trimmed using the synced row count."""
    plan = _device_plan(df)
    names = [f.name for f in plan.output]
    fidx = [names.index(c) for c in feature_cols]
    lidx = names.index(label_col) if label_col is not None else None

    xs, ys, ms = [], [], []
    for split in range(plan.num_partitions):
        with TaskContext():
            batches = list(plan.execute_partition(split))
        if not batches:
            continue
        b = concat_batches(batches)
        n = b.num_rows                      # sync once per partition
        cols = [Col.from_vector(b.column(i)) for i in fidx]
        for c in cols:
            if isinstance(c.dtype, T.StringType):
                raise TypeError("string feature columns need encoding before "
                                "to_feature_matrix")
        feat = jnp.stack([c.values.astype(dtype) for c in cols], axis=1)[:n]
        valid = jnp.stack([c.validity for c in cols], axis=1).all(axis=1)[:n]
        if lidx is not None:
            lc = Col.from_vector(b.column(lidx))
            ys.append(lc.values.astype(dtype)[:n])
            valid = valid & lc.validity[:n]
        xs.append(feat)
        ms.append(valid)
    if not xs:
        d = len(feature_cols)
        return (jnp.zeros((0, d), dtype),
                jnp.zeros((0,), dtype) if label_col else None,
                jnp.zeros((0,), bool))
    X = jnp.concatenate(xs, axis=0)
    y = jnp.concatenate(ys, axis=0) if ys else None
    mask = jnp.concatenate(ms, axis=0)
    return X, y, mask
