"""Native ORC encode: device computes, host frames (VERDICT r4 next #3).

Reference: GpuOrcFileFormat.scala (178 LoC) / ColumnarOutputWriter.scala:182
write ORC straight from device buffers (libcudf's writer); the previous path
here round-tripped every batch device -> host arrow -> pyarrow re-encode.
Same split as io/parquet_write_native.py (and the mirror image of
io/orc_native.py's reader): the device runs one jitted kernel per column —
null compaction (ORC DATA streams carry only non-null values), null count,
min/max — and transfers each column ONCE; the host does byte framing only:

- PRESENT: bits MSB-first + byte-RLE (the reader's decode_boolean_rle
  inverse)
- SHORT/INT/LONG/DATE: RLEv2 DIRECT runs (zigzag, MSB-first bit packing)
- FLOAT/DOUBLE: raw little-endian IEEE
- STRING: DICTIONARY_V2 — the engine's sorted dictionary maps 1:1 onto
  ORC's sorted dictionary (codes = DATA, lengths = LENGTH, utf8 =
  DICTIONARY_DATA); per-row bytes never materialize on device
- BOOLEAN: bit + byte-RLE; TIMESTAMP: seconds-from-2015 + nanos streams;
  DECIMAL(<=18): unbounded zigzag varints + constant scale stream
- protobuf StripeFooter / Footer / PostScript writers (inverse of
  orc_native._ProtoReader)

Compression: NONE, ZLIB (raw DEFLATE) and SNAPPY, chunked with the 3-byte
`(len << 1) | isOriginal` headers the spec defines — streams, stripe
footers and the file footer all ride the codec (inverse of
orc_native._decompress_chunked). Schemas outside the list above fall back
to the arrow writer (io/writer.py routes).
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.io.parquet_write_native import _prep_column

MAGIC = b"ORC"

# Type.Kind enum
K_BOOLEAN, K_BYTE, K_SHORT, K_INT, K_LONG = 0, 1, 2, 3, 4
K_FLOAT, K_DOUBLE, K_STRING, K_TIMESTAMP = 5, 6, 7, 9
K_STRUCT, K_DECIMAL, K_DATE = 12, 14, 15
# Stream.Kind
S_PRESENT, S_DATA, S_LENGTH, S_DICT_DATA, S_SECONDARY = 0, 1, 2, 3, 5
# ColumnEncoding.Kind
E_DIRECT, E_DIRECT_V2, E_DICTIONARY_V2 = 0, 2, 3

_TS_BASE_MICROS = 1420070400 * 1000000      # 2015-01-01 00:00:00 UTC

# CompressionKind enum + writer.py codec-name mapping
C_NONE, C_ZLIB, C_SNAPPY = 0, 1, 2
CODECS = {"none": C_NONE, "uncompressed": C_NONE, "zlib": C_ZLIB,
          "gzip": C_ZLIB, "snappy": C_SNAPPY}
_BLOCK = 262144


def _compress_chunked(blob: bytes, codec: int) -> bytes:
    """One ORC compression stream: 3-byte little-endian
    `(chunkLength << 1) | isOriginal` headers; incompressible chunks store
    original bytes (isOriginal=1)."""
    if codec == C_NONE or not blob:
        return blob
    out = bytearray()
    for s in range(0, len(blob), _BLOCK):
        chunk = blob[s:s + _BLOCK]
        if codec == C_ZLIB:
            c = zlib.compressobj(wbits=-15)
            body = c.compress(chunk) + c.flush()
        else:
            import pyarrow as pa
            body = bytes(pa.Codec("snappy").compress(chunk))
        orig = 1 if len(body) >= len(chunk) else 0
        if orig:
            body = chunk
        hdr = (len(body) << 1) | orig
        out += bytes([hdr & 0xFF, (hdr >> 8) & 0xFF, (hdr >> 16) & 0xFF])
        out += body
    return bytes(out)


def _kind_of(dt: T.DataType) -> int:
    if isinstance(dt, T.BooleanType):
        return K_BOOLEAN
    if isinstance(dt, T.ByteType):
        return K_BYTE
    if isinstance(dt, T.ShortType):
        return K_SHORT
    if isinstance(dt, T.IntegerType):
        return K_INT
    if isinstance(dt, T.LongType):
        return K_LONG
    if isinstance(dt, T.FloatType):
        return K_FLOAT
    if isinstance(dt, T.DoubleType):
        return K_DOUBLE
    if isinstance(dt, T.StringType):
        return K_STRING
    if isinstance(dt, T.TimestampType):
        return K_TIMESTAMP
    if isinstance(dt, T.DateType):
        return K_DATE
    if isinstance(dt, T.DecimalType):
        if dt.precision > 18:
            raise TypeError(f"native orc writer: decimal {dt.precision}")
        return K_DECIMAL
    raise TypeError(f"native orc writer: unsupported type {dt}")


def supports_schema(schema: T.StructType) -> bool:
    try:
        for f in schema.fields:
            _kind_of(f.data_type)
    except TypeError:
        return False
    return True


# --- protobuf writer (inverse of orc_native._ProtoReader) -------------------

def _pvarint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class _Proto:
    def __init__(self):
        self.buf = bytearray()

    def uint(self, fid: int, v: int):
        self.buf += _pvarint(fid << 3)
        self.buf += _pvarint(v)

    def bytes_(self, fid: int, v: bytes):
        self.buf += _pvarint((fid << 3) | 2)
        self.buf += _pvarint(len(v))
        self.buf += v

    def packed(self, fid: int, vals):
        body = b"".join(_pvarint(v) for v in vals)
        self.bytes_(fid, body)

    def done(self) -> bytes:
        return bytes(self.buf)


# --- byte-RLE / boolean-RLE (inverse of orc_native.decode_boolean_rle) ------

def byte_rle(data: bytes) -> bytes:
    """ORC Byte-RLE: [0..127, b] = run of n+3 copies of b;
    [-n as 256-n, b0..b{n-1}] = n literal bytes (1 <= n <= 128)."""
    out = bytearray()
    i, n = 0, len(data)
    while i < n:
        run = 1
        while i + run < n and run < 130 and data[i + run] == data[i]:
            run += 1
        if run >= 3:
            out.append(run - 3)
            out.append(data[i])
            i += run
            continue
        lit_start = i
        while i < n and i - lit_start < 128:
            if (i + 2 < n and data[i + 1] == data[i]
                    and data[i + 2] == data[i]):
                break               # a >=3 run starts here; end the literals
            i += 1
        cnt = i - lit_start         # 1..128 by the loop bound
        out.append(256 - cnt)
        out += data[lit_start:i]
    return bytes(out)


def bool_rle(bits: np.ndarray) -> bytes:
    """Boolean stream: bits MSB-first into bytes, then Byte-RLE."""
    return byte_rle(np.packbits(bits.astype(np.uint8)).tobytes())


# --- RLEv2 DIRECT writer ----------------------------------------------------

# closest allowed direct widths and their 5-bit codes
_WIDTHS = list(range(1, 25)) + [26, 28, 30, 32, 40, 48, 56, 64]
_WIDTH_CODE = {w: (w - 1 if w <= 24 else 24 + [26, 28, 30, 32, 40, 48, 56,
                                              64].index(w)) for w in _WIDTHS}


def _fit_width(maxbits: int) -> int:
    for w in _WIDTHS:
        if w >= maxbits:
            return w
    return 64


def _pack_msb(vals: np.ndarray, width: int) -> bytes:
    """Bit-pack uint64 values MSB-first at `width` bits."""
    n = len(vals)
    shifts = np.arange(width - 1, -1, -1, dtype=np.uint64)
    bits = ((vals[:, None] >> shifts[None, :]) & np.uint64(1)).astype(np.uint8)
    return np.packbits(bits.ravel()).tobytes()


def rlev2_direct(vals: np.ndarray, signed: bool) -> bytes:
    """Encode values as a sequence of RLEv2 DIRECT runs (<=512 values each).
    DIRECT is valid for any data; the reader (orc_native.scan_rlev2) handles
    all four sub-encodings, so the writer only needs one."""
    v = vals.astype(np.int64)
    if signed:
        u = ((v << 1) ^ (v >> 63)).astype(np.uint64)     # zigzag
    else:
        u = v.astype(np.uint64)
    out = bytearray()
    for s in range(0, len(u), 512):
        chunk = u[s:s + 512]
        m = int(chunk.max()) if len(chunk) else 0
        width = _fit_width(max(m.bit_length(), 1))
        code = _WIDTH_CODE[width]
        ln = len(chunk) - 1
        out.append(0x40 | (code << 1) | (ln >> 8))
        out.append(ln & 0xFF)
        out += _pack_msb(chunk, width)
    return bytes(out)


# --- column encoders --------------------------------------------------------

class _Streams:
    """Accumulates one stripe's streams in file-layout order."""

    def __init__(self):
        self.entries = []        # (kind, column, bytes)

    def add(self, kind: int, col: int, blob: bytes):
        self.entries.append((kind, col, blob))


def _encode_column(streams: _Streams, col_id: int, col, dt: T.DataType,
                   num_rows: int):
    """Encode one column's stripe streams; returns (encoding_kind,
    dict_size, n_valid, has_null)."""
    kind = _kind_of(dt)
    vals, n_valid, null_count, _vmin, _vmax, valid = _prep_column(
        col, num_rows)
    if null_count:
        streams.add(S_PRESENT, col_id, bool_rle(valid))

    if kind == K_STRING:
        entries = ([] if col.dictionary is None
                   else [s.as_py().encode("utf-8") for s in col.dictionary])
        streams.add(S_DATA, col_id, rlev2_direct(vals, signed=False))
        streams.add(S_DICT_DATA, col_id, b"".join(entries))
        streams.add(S_LENGTH, col_id,
                    rlev2_direct(np.array([len(e) for e in entries],
                                          np.int64), signed=False))
        return E_DICTIONARY_V2, len(entries), n_valid, bool(null_count)
    if kind in (K_SHORT, K_INT, K_LONG, K_DATE):
        streams.add(S_DATA, col_id, rlev2_direct(vals, signed=True))
        return E_DIRECT_V2, 0, n_valid, bool(null_count)
    if kind in (K_FLOAT, K_DOUBLE):
        streams.add(S_DATA, col_id, vals.astype(
            "<f4" if kind == K_FLOAT else "<f8").tobytes())
        return E_DIRECT, 0, n_valid, bool(null_count)
    if kind == K_BOOLEAN:
        streams.add(S_DATA, col_id, bool_rle(vals.astype(np.uint8)))
        return E_DIRECT, 0, n_valid, bool(null_count)
    if kind == K_BYTE:
        streams.add(S_DATA, col_id,
                    byte_rle(vals.astype(np.int8).tobytes()))
        return E_DIRECT, 0, n_valid, bool(null_count)
    if kind == K_TIMESTAMP:
        rel = vals.astype(np.int64) - _TS_BASE_MICROS
        secs = np.floor_divide(rel, 1_000_000)
        nanos = (rel - secs * 1_000_000) * 1000      # always >= 0
        streams.add(S_DATA, col_id, rlev2_direct(secs, signed=True))
        # low 3 bits 0 = no trailing-zero compression (spec-valid)
        streams.add(S_SECONDARY, col_id,
                    rlev2_direct(nanos << 3, signed=False))
        return E_DIRECT_V2, 0, n_valid, bool(null_count)
    if kind == K_DECIMAL:
        body = bytearray()
        for x in vals.astype(np.int64).tolist():     # unbounded zigzag varint
            body += _pvarint((x << 1) ^ (x >> 63))
        streams.add(S_DATA, col_id, bytes(body))
        streams.add(S_SECONDARY, col_id,
                    rlev2_direct(np.full(n_valid, dt.scale, np.int64),
                                 signed=True))
        return E_DIRECT_V2, 0, n_valid, bool(null_count)
    raise TypeError(f"native orc writer: {dt}")


# --- file writer ------------------------------------------------------------

class NativeOrcFile:
    """Streaming writer: one stripe per append_batch(). Mirrors the task
    writer lifecycle (open -> append* -> close) of ColumnarOutputWriter."""

    def __init__(self, path: str, schema: T.StructType,
                 compression: str = "zlib"):
        if not supports_schema(schema):
            raise TypeError("schema unsupported by native orc writer")
        codec = compression.lower()
        if codec not in CODECS:
            raise ValueError(f"native orc writer: codec {compression}")
        self.codec = CODECS[codec]
        self.path = path
        self.schema = schema
        self._f = open(path, "wb")
        self._f.write(MAGIC)
        self._offset = len(MAGIC)
        self._stripes = []       # StripeInformation fields
        self._num_rows = 0
        # footer stats: per column (incl. root): [n_values, has_null]
        self._stats = [[0, False] for _ in range(len(schema.fields) + 1)]

    def append_batch(self, batch) -> int:
        n = batch.num_rows
        streams = _Streams()
        encodings = [(E_DIRECT, 0)]             # root struct
        for i, (field, col) in enumerate(zip(self.schema.fields,
                                             batch.columns)):
            enc, dsize, n_valid, has_null = _encode_column(
                streams, i + 1, col, field.data_type, n)
            encodings.append((enc, dsize))
            self._stats[i + 1][0] += n_valid
            self._stats[i + 1][1] |= has_null
        self._stats[0][0] += n

        comp = [(kind, col, _compress_chunked(blob, self.codec))
                for kind, col, blob in streams.entries]
        data = b"".join(blob for _, _, blob in comp)
        sf = _Proto()
        for kind, col, blob in comp:
            s = _Proto()
            s.uint(1, kind)
            s.uint(2, col)
            s.uint(3, len(blob))
            sf.bytes_(1, s.done())
        for enc, dsize in encodings:
            e = _Proto()
            e.uint(1, enc)
            if dsize:
                e.uint(2, dsize)
            sf.bytes_(2, e.done())
        footer = _compress_chunked(sf.done(), self.codec)

        start = self._offset
        self._f.write(data)
        self._f.write(footer)
        self._offset += len(data) + len(footer)
        self._stripes.append((start, 0, len(data), len(footer), n))
        self._num_rows += n
        return len(data) + len(footer)

    def close(self):
        if self._f is None:
            return
        ft = _Proto()
        ft.uint(1, len(MAGIC))                  # headerLength
        ft.uint(2, self._offset)                # contentLength
        for (off, ilen, dlen, flen, rows) in self._stripes:
            s = _Proto()
            s.uint(1, off)
            s.uint(2, ilen)
            s.uint(3, dlen)
            s.uint(4, flen)
            s.uint(5, rows)
            ft.bytes_(3, s.done())
        root = _Proto()
        root.uint(1, K_STRUCT)
        root.packed(2, range(1, len(self.schema.fields) + 1))
        for f in self.schema.fields:
            root.bytes_(3, f.name.encode("utf-8"))
        ft.bytes_(4, root.done())
        for f in self.schema.fields:
            t = _Proto()
            t.uint(1, _kind_of(f.data_type))
            if isinstance(f.data_type, T.DecimalType):
                t.uint(5, f.data_type.precision)
                t.uint(6, f.data_type.scale)
            ft.bytes_(4, t.done())
        ft.uint(6, self._num_rows)
        for n_values, has_null in self._stats:
            st = _Proto()
            st.uint(1, n_values)
            st.uint(10, 1 if has_null else 0)
            ft.bytes_(7, st.done())
        footer = _compress_chunked(ft.done(), self.codec)
        self._f.write(footer)

        ps = _Proto()
        ps.uint(1, len(footer))
        ps.uint(2, self.codec)                  # CompressionKind
        if self.codec != C_NONE:
            ps.uint(3, _BLOCK)                  # compressionBlockSize
        ps.packed(4, [0, 12])                   # file version 0.12
        ps.uint(5, 0)                           # no metadata section
        ps.uint(6, 1)                           # writerVersion
        ps.bytes_(8000, MAGIC)
        psb = ps.done()
        self._f.write(psb)
        self._f.write(struct.pack("B", len(psb)))
        self._f.close()
        self._f = None

    def abort(self):
        if self._f is not None:
            self._f.close()
            self._f = None


def write_batch_file(path: str, batch, schema: T.StructType,
                     compression: str = "zlib") -> int:
    """One batch -> one single-stripe file (the per-batch shape io/writer.py
    uses)."""
    f = NativeOrcFile(path, schema, compression)
    try:
        f.append_batch(batch)
        f.close()
    except BaseException:
        f.abort()
        raise
    import os
    return os.path.getsize(path)
