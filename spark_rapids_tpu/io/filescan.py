"""File scan: plan node + device exec with partition-values handling.

Reference: GpuFileSourceScanExec.scala:59 (DSv1), GpuBatchScanExec (DSv2),
GpuMultiFileReader.scala plumbing, ColumnarPartitionReaderWithPartitionValues
(partition-directory values concatenated as constant columns). Files are grouped
into FilePartitions by target size like Spark's FilePartition packing."""

from __future__ import annotations

import dataclasses
import datetime
import os
import typing

import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu import config as CFG
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import TpuExec, acquire_semaphore
from spark_rapids_tpu.io import readers as R
from spark_rapids_tpu.plan.nodes import PlanNode
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime.tracing import trace_range


@dataclasses.dataclass(frozen=True)
class FilePartition:
    """Files + constant partition-column values (from dir names a/b=1/...)."""
    paths: tuple
    partition_values: tuple = ()   # ((name, value), ...) applied to every row


def discover_partitions(root: str, fmt: str) -> list[FilePartition]:
    """Walk a (possibly hive-partitioned) directory into per-directory partitions."""
    exts = {"parquet": (".parquet", ".pq"), "orc": (".orc",), "csv": (".csv",)}
    out = []
    for dirpath, dirnames, files in os.walk(root):
        # prune hidden/metadata dirs (uncommitted _temporary-* output, _SUCCESS
        # siblings…) the way Spark's file index skips '_'/'.' paths. NB: os.walk
        # must not be wrapped in sorted() — that would drain the generator before
        # this in-place prune is seen.
        dirnames[:] = sorted(d for d in dirnames if not d.startswith(("_", ".")))
        paths = tuple(sorted(
            os.path.join(dirpath, f) for f in files
            if f.endswith(exts[fmt]) and not f.startswith(("_", "."))))
        if not paths:
            continue
        rel = os.path.relpath(dirpath, root)
        pvals = []
        if rel != ".":
            for seg in rel.split(os.sep):
                if "=" in seg:
                    k, v = seg.split("=", 1)
                    pvals.append((k, v))
        out.append(FilePartition(paths, tuple(pvals)))
    out.sort(key=lambda p: p.paths)
    return out




# proleptic-Gregorian vs hybrid-Julian calendars agree on every date from the
# 1582-10-15 Gregorian cutover onward, so the legacy datetime rebase
# (readers.py _rebase) is the identity there in EVERY rebase mode
_GREGORIAN_CUTOVER = datetime.date(1582, 10, 15)


def _dates_post_cutover(md, date_cols: list) -> bool:
    """True when every row group's footer statistics PROVE all values of the
    named date columns are on/after the Gregorian cutover — the condition
    under which device decode (which never rebases) is bit-identical to the
    arrow path's rebase handling. Missing stats fail closed."""
    leaf = {}
    for i in range(md.num_columns):
        p = md.schema.column(i).path
        if "." not in p:
            leaf[p] = i
    for name in date_cols:
        i = leaf.get(name)
        if i is None:
            return False
        for g in range(md.num_row_groups):
            st = md.row_group(g).column(i).statistics
            if st is None or not st.has_min_max:
                return False
            mn = st.min
            if not isinstance(mn, datetime.date) or \
                    isinstance(mn, datetime.datetime) or \
                    mn < _GREGORIAN_CUTOVER:
                return False
    return True


def _scan_meta(path: str) -> dict:
    """Scan provenance for the input_file_name expression family; whole-file
    reads expose the file as one block (Spark: split start/length)."""
    return {"input_file": path, "block_start": 0,
            "block_length": os.path.getsize(path)}


def _infer_partition_type(values: list) -> T.DataType:
    try:
        for v in values:
            int(v)
        return T.INT if all(-2**31 <= int(v) < 2**31 for v in values) else T.LONG
    except ValueError:
        return T.STRING


def rewrite_scan_path(path, conf):
    """Alluxio-style path-prefix replacement (reference
    spark.rapids.alluxio.pathsToReplace, RapidsConf.scala:1031): rewrite
    'from->to' prefixes on every scan path so a caching filesystem mount
    transparently fronts direct storage."""
    from spark_rapids_tpu import config as CFG
    spec = conf.get(CFG.ALLUXIO_PATHS_REPLACE) if conf is not None else None
    if not spec or not isinstance(path, (str, list, tuple)):
        return path
    rules = []
    for rule in spec.split(";"):
        rule = rule.strip()
        if not rule:
            continue
        if "->" not in rule:
            raise ValueError(
                f"bad {CFG.ALLUXIO_PATHS_REPLACE.key} rule {rule!r}: "
                "expected 'from->to'")
        frm, to = rule.split("->", 1)
        rules.append((frm.strip(), to.strip()))

    def one(p):
        for frm, to in rules:
            if p.startswith(frm):
                return to + p[len(frm):]
        return p
    return one(path) if isinstance(path, str) else [one(p) for p in path]


class FileScanNode(PlanNode):
    """CPU plan node for a file scan; the override layer converts it to
    FileSourceScanExec. Host execution = the same readers without the device
    upload (the CPU-Spark oracle path)."""

    def __init__(self, paths_or_dir, fmt: str = "parquet",
                 schema: T.StructType | None = None,
                 pushed_filter=None, options: dict | None = None,
                 files_per_partition: int = 1):
        super().__init__()
        self.fmt = fmt
        self.options = options or {}
        if isinstance(paths_or_dir, str) and os.path.isdir(paths_or_dir):
            parts = discover_partitions(paths_or_dir, fmt)
        else:
            paths = ([paths_or_dir] if isinstance(paths_or_dir, str)
                     else list(paths_or_dir))
            parts = [FilePartition(tuple(paths[i:i + files_per_partition]))
                     for i in range(0, len(paths), files_per_partition)]
        if not parts:
            raise ValueError(f"no {fmt} files under {paths_or_dir}")
        keys0 = tuple(k for k, _ in parts[0].partition_values)
        for p in parts[1:]:
            if tuple(k for k, _ in p.partition_values) != keys0:
                raise ValueError(
                    "inconsistent partition directory layout: "
                    f"{keys0} vs {tuple(k for k, _ in p.partition_values)} "
                    f"under {p.paths[0]}")
        self.partitions = parts
        self.pushed_filter = pushed_filter  # Expression; converted per-read
        self.reader = R.reader_for(fmt, **self.options)
        if schema is None:
            file_schema = T.StructType.from_arrow(
                self.reader.schema_of(parts[0].paths[0]))
            pfields = []
            if parts[0].partition_values:
                for i, (k, _) in enumerate(parts[0].partition_values):
                    vals = [p.partition_values[i][1] for p in parts]
                    pfields.append(T.StructField(
                        k, _infer_partition_type(vals), False))
            schema = T.StructType(list(file_schema.fields) + pfields)
        self._schema = schema
        self._n_partition_cols = (len(parts[0].partition_values)
                                  if parts[0].partition_values else 0)

    @property
    def output(self):
        return self._schema

    @property
    def num_partitions(self):
        return len(self.partitions)

    def _data_columns(self) -> list:
        n = len(self._schema.fields) - self._n_partition_cols
        return [f.name for f in self._schema.fields[:n]]

    def _arrow_filter(self):
        if self.pushed_filter is None:
            return None
        return R.spark_filter_to_arrow(self.pushed_filter)

    def _append_partition_values(self, tbl: pa.Table, part: FilePartition):
        """Constant partition columns for every row (reference
        ColumnarPartitionReaderWithPartitionValues)."""
        if not part.partition_values:
            return tbl
        n = len(self._schema.fields) - self._n_partition_cols
        for (k, v), f in zip(part.partition_values, self._schema.fields[n:]):
            val = int(v) if isinstance(f.data_type, T.IntegralType) else v
            tbl = tbl.append_column(
                pa.field(k, T.to_arrow_type(f.data_type)),
                pa.array([val] * tbl.num_rows, T.to_arrow_type(f.data_type)))
        return tbl

    def _residual_filter(self, tbl: pa.Table) -> pa.Table:
        """Exact Spark-semantics filter on the host for predicates the arrow
        scanner cannot express (float comparisons with NaN ordering, etc.)."""
        from spark_rapids_tpu.plan.host_eval import eval_host
        from spark_rapids_tpu.expr.core import bind_references
        if tbl.num_rows == 0:
            return tbl
        cond = bind_references(self.pushed_filter, self._schema)
        pred = eval_host(cond, tbl)
        return tbl.filter(pa.array([v is True for v in pred.data]))

    def tables_for(self, split: int, batch_rows: int,
                   strategy: str = "PERFILE", num_threads: int = 4,
                   target_rows: int = 1 << 20, rebase_mode: str | None = None):
        reader = self.reader
        if rebase_mode is not None and hasattr(reader, "rebase_mode") and \
                reader.rebase_mode != rebase_mode.upper():
            # fresh reader per divergent call: never mutate the shared one
            # (concurrent host/device scans of this node must not interleave)
            opts = {k: v for k, v in self.options.items()
                    if k != "rebase_mode"}
            reader = R.reader_for(self.fmt, rebase_mode=rebase_mode, **opts)
        part = self.partitions[split]
        filt = self._arrow_filter()
        residual = self.pushed_filter is not None and filt is None
        cols = self._data_columns()
        if strategy == "MULTITHREADED":
            gen = R.multithreaded_tables(reader, list(part.paths), cols,
                                         filt, batch_rows, num_threads)
        elif strategy == "COALESCING":
            gen = R.coalescing_tables(reader, list(part.paths), cols, filt,
                                      batch_rows, target_rows)
        else:
            gen = R.perfile_tables(reader, list(part.paths), cols, filt,
                                   batch_rows)
        for tbl in gen:
            tbl = self._append_partition_values(tbl, part)
            if residual:
                tbl = self._residual_filter(tbl)
            yield tbl

    def execute_host(self, split):
        tables = list(self.tables_for(split, batch_rows=1 << 20))
        if not tables:
            return self._empty()
        return pa.concat_tables(tables, promote_options="permissive")

    def args_string(self):
        return (f"{self.fmt} {len(self.partitions)} partitions"
                + (f" filter={self.pushed_filter!r}" if self.pushed_filter is not None
                   else ""))


class FileSourceScanExec(TpuExec):
    """Leaf device exec: host decode (strategy-selected) → one H2D per batch
    (reference GpuFileSourceScanExec.doExecuteColumnar:376)."""

    def __init__(self, node: FileScanNode, conf=None):
        from spark_rapids_tpu.config import RapidsConf
        super().__init__(conf=conf or RapidsConf())
        self.node = node
        self._scan_time = self.metrics.metric(M.READ_FS_TIME, M.MODERATE)

    @property
    def output(self):
        return self.node.output

    @property
    def num_partitions(self):
        return self.node.num_partitions

    def _device_decode_batches(self, split, batch_rows: int,
                               batch_bytes: int):
        """Row-group-at-a-time device decode (no arrow materialization).
        Returns None when the partition is out of the device path's scope
        (pushed filters, partition-dir values, temporal columns needing the
        rebase, or row groups larger than the reader batch caps)."""
        import pyarrow.parquet as pq
        from spark_rapids_tpu.io import parquet_native as PN
        node = self.node
        if node.fmt != "parquet" or node.pushed_filter is not None:
            return None
        part = node.partitions[split]
        if part.partition_values:
            return None
        # timestamps stay on the arrow path (it owns the legacy datetime
        # rebase, readers.py _rebase); nested columns need the arrow
        # list/struct conversion. DATE columns are admitted when footer
        # statistics prove every value post-dates the Gregorian cutover
        # (rebase is the identity there) — without this, scan-heavy TPC-H
        # queries like q1 (l_shipdate filter) never reach device decode.
        if any(isinstance(f.data_type, (T.TimestampType,
                                        T.ArrayType, T.StructDataType))
               for f in self.output):
            return None
        date_cols = [f.name for f in self.output
                     if isinstance(f.data_type, T.DateType)]
        files = []
        for path in part.paths:
            pf = pq.ParquetFile(path)
            md = pf.metadata
            # honor BOTH reader caps: the arrow path re-chunks oversized
            # groups, this path emits one batch per row group
            if any(md.row_group(g).num_rows > batch_rows
                   or md.row_group(g).total_byte_size > batch_bytes
                   for g in range(md.num_row_groups)):
                return None
            if date_cols and not _dates_post_cutover(md, date_cols):
                return None
            files.append((path, pf, md.num_row_groups))
        encoded = self.conf.get(CFG.PARQUET_ENCODED_UPLOAD)

        def it():
            cols = node._data_columns()
            for path, pf, n_groups in files:
                meta = _scan_meta(path)
                for rg in range(n_groups):
                    acquire_semaphore(self.metrics)
                    with trace_range("FileScan.devdecode", self._scan_time):
                        batch = PN.read_row_group_device(
                            path, rg, self.output, cols, pf=pf,
                            encoded=encoded)
                    batch.metadata = meta
                    yield batch
        return it()

    def _csv_device_decode_batches(self, split):
        """Whole-file device CSV parse for in-scope files (io/csv_native.py).
        ALL scope checks run up front in one host pass per file — if any
        file is out of scope the whole partition takes the host arrow
        reader (reference gates per type the same way); the committed
        device iterator can always finish."""
        from spark_rapids_tpu.io import csv_native as CN
        node = self.node
        if node.fmt != "csv" or node.pushed_filter is not None:
            return None
        part = node.partitions[split]
        if part.partition_values:
            return None
        allow_f = self.conf.get(CFG.CSV_READ_FLOATS)
        schema = self.output
        rdr = node.reader
        shapes = []
        for path in part.paths:
            shape = CN.try_scan_for_device(path, schema, rdr.delimiter,
                                           rdr.header, allow_f)
            if shape is None:
                return None
            shapes.append(shape)
        from spark_rapids_tpu.columnar.vector import bucket_capacity

        def it():
            for path, shape in zip(part.paths, shapes):
                acquire_semaphore(self.metrics)
                with trace_range("FileScan.csvdevdecode", self._scan_time):
                    batch = CN.decode_shape_device(shape, schema,
                                                   bucket_capacity)
                batch.metadata = _scan_meta(path)
                yield batch
        return it()

    def _orc_device_decode_batches(self, split, batch_rows, batch_bytes):
        """Stripe-at-a-time device ORC decode (io/orc_native.py); None →
        host arrow reader. Scope gates (compression, stripe caps) run up
        front; unsupported COLUMNS fall back per column inside the stripe
        read, mirroring the parquet path's granularity."""
        from spark_rapids_tpu.io import orc_native as ON
        node = self.node
        if node.fmt != "orc" or node.pushed_filter is not None:
            return None
        part = node.partitions[split]
        if part.partition_values:
            return None
        metas = []
        for path in part.paths:
            try:
                meta = ON.read_meta(path)
            except (NotImplementedError, OSError, IndexError):
                return None
            if any(si.num_rows > batch_rows
                   or si.data_length > batch_bytes
                   for si in meta.stripes):
                return None  # arrow path re-chunks oversized stripes
            metas.append(meta)
        schema = self.output

        def it():
            import pyarrow.orc as orc
            for path, meta in zip(part.paths, metas):
                pf = None
                fmeta = _scan_meta(path)
                for si_ in range(len(meta.stripes)):
                    acquire_semaphore(self.metrics)
                    with trace_range("FileScan.orcdevdecode",
                                     self._scan_time):
                        if pf is None:
                            pf = orc.ORCFile(path)
                        batch = ON.read_stripe_device(path, meta, si_,
                                                      schema, pf=pf)
                    batch.metadata = fmeta
                    yield batch
        return it()

    def _maybe_pipeline(self, it, edge, depth=None):
        """Detach a device-batch iterator onto its own pipeline segment:
        decode/upload work runs on the stage's worker thread (charged to
        this scan's selfTime there), queued batches sit spillable in the
        catalog, and the downstream consumer overlaps its compute."""
        from spark_rapids_tpu.runtime import pipeline as P
        if not P.enabled(self.conf):
            return it
        return P.stage_iterator(
            it, edge=edge, conf=self.conf, registry=self.metrics,
            node_id=self._node_id, self_time_metric=self._self_time,
            spillable=True, depth=depth)

    def execute_partition(self, split):
        conf = self.conf
        strategy = conf.get(CFG.PARQUET_READER_TYPE).upper()
        batch_rows = min(conf.get(CFG.MAX_READER_BATCH_SIZE_ROWS), 1 << 20)
        threads = conf.get(CFG.MULTITHREADED_READ_NUM_THREADS)

        def decode_engaged(entry):
            """Device decode pays only when a real accelerator is attached:
            on the CPU backend the 'device' IS the host, so arrow decode is
            strictly cheaper. An explicitly-set conf always wins (tests force
            the device path on the CPU platform)."""
            if entry.key in conf.settings:
                return conf.get(entry)
            if not conf.get(entry):
                return False
            import jax
            return jax.default_backend() != "cpu"

        if decode_engaged(CFG.PARQUET_DEVICE_DECODE):
            dev_it = self._device_decode_batches(
                split, batch_rows, conf.get(CFG.MAX_READER_BATCH_SIZE_BYTES))
            if dev_it is not None:
                return self.wrap_output(
                    self._maybe_pipeline(dev_it, "scan.device"))

        if decode_engaged(CFG.CSV_DEVICE_DECODE):
            dev_it = self._csv_device_decode_batches(split)
            if dev_it is not None:
                return self.wrap_output(
                    self._maybe_pipeline(dev_it, "scan.device"))

        if decode_engaged(CFG.ORC_DEVICE_DECODE):
            dev_it = self._orc_device_decode_batches(
                split, batch_rows, conf.get(CFG.MAX_READER_BATCH_SIZE_BYTES))
            if dev_it is not None:
                return self.wrap_output(
                    self._maybe_pipeline(dev_it, "scan.device"))

        part = self.node.partitions[split]
        # 1:1 provenance is provable only for single-file partitions on the
        # host reader path (multi-file strategies may stitch files)
        host_meta = _scan_meta(part.paths[0]) if len(part.paths) == 1 else None
        from spark_rapids_tpu.runtime import pipeline as P
        pipe_on = P.enabled(conf)

        def it():
            gen = self.node.tables_for(
                split, batch_rows, strategy, threads,
                rebase_mode=conf.get(CFG.PARQUET_REBASE_MODE))
            depth = conf.get(CFG.SCAN_READAHEAD_DEPTH)
            if pipe_on and depth <= 0:
                depth = conf.get(CFG.PIPELINE_QUEUE_DEPTH)
            if depth > 0:
                # decode readahead stays BEFORE the semaphore: it buffers
                # host arrow tables only, so admission control still gates
                # every device upload. One mechanism, one byte budget: the
                # scan's decode edge is a pipeline stage whose cap is the
                # tighter of the readahead and pipeline byte knobs
                from spark_rapids_tpu.runtime.memory import (
                    host_prefetch_budget)
                budget = host_prefetch_budget(min(
                    conf.get(CFG.SCAN_READAHEAD_MAX_BUFFER),
                    conf.get(CFG.PIPELINE_MAX_QUEUE_BYTES)))
                gen = P.stage_iterator(
                    gen, edge="scan.decode", conf=conf,
                    registry=self.metrics, node_id=self._node_id,
                    self_time_metric=self._self_time,
                    depth=depth, max_bytes=budget,
                    stall_metric=self.metrics.metric(
                        M.READAHEAD_STALL_TIME, M.MODERATE))
            for tbl in gen:
                acquire_semaphore(self.metrics)
                with trace_range("FileScan.h2d", self._scan_time):
                    batch = ColumnarBatch.from_arrow(tbl, self.output)
                batch.metadata = host_meta
                yield batch

        # double-buffered host→device transfer: the upload stage's worker
        # converts batch N+1 (and its decode edge prefetches N+2) while the
        # consumer computes on batch N
        return self.wrap_output(self._maybe_pipeline(it(), "scan.upload"))

    def args_string(self):
        return self.node.args_string()


# self-registration with the override engine (kept here, not in overrides.py, so
# plan/ never imports io/ — mirrors the reference's per-format ScanRule modules)
def _register_scan_rule():
    from spark_rapids_tpu.plan.overrides import REGISTRY, ExecRule
    from spark_rapids_tpu.plan.typesig import ExecChecks, ORDERABLE

    def conv_filescan(meta, kids):
        return FileSourceScanExec(meta.node, conf=meta.conf)

    def tag_filescan(meta):
        fmt = meta.node.fmt
        if fmt == "csv" and not meta.conf.get(CFG.CSV_ENABLED):
            meta.will_not_work("CSV scan disabled by conf")
        if fmt == "orc" and not meta.conf.get(CFG.ORC_ENABLED):
            meta.will_not_work("ORC scan disabled by conf")

    REGISTRY.exec_rule(FileScanNode, ExecRule(
        "accelerated parquet/orc/csv scan", conv_filescan,
        ExecChecks(ORDERABLE), None, tag_filescan))


_register_scan_rule()
