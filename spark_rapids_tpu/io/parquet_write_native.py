"""Native Parquet encode: device computes, host frames (VERDICT r3 weak #7).

Reference: ColumnarOutputWriter.scala / GpuParquetFileFormat.scala:348 write
Parquet straight from device buffers (libcudf's writer); the previous path
here round-tripped every batch device -> host arrow -> pyarrow re-encode.
This module keeps the WORK on the device and leaves only byte FRAMING to the
host — the same split io/parquet_native.py uses for reads (metadata on host,
bulk bits on device):

- device (one jitted kernel per column dtype/capacity): null-compaction of
  the value stream (Parquet PLAIN stores only non-null values), null_count,
  and min/max statistics (masked reductions). String columns never
  materialize bytes on device — their int32 dictionary codes ARE the
  dictionary-page indices (the engine's order-preserving sorted dictionary
  maps 1:1 onto a Parquet dictionary page, so string min/max = code min/max).
- host: definition-level RLE/bit-pack hybrid, thrift compact metadata
  (PageHeader / ColumnMetaData / FileMetaData — mirror image of
  parquet_native._CompactReader), page compression, file assembly.

Codecs: UNCOMPRESSED, GZIP (zlib, real compression), SNAPPY (real
compression via pyarrow's bundled codec — the same `pa.Codec` the ORC
native writer uses, io/orc_write_native.py:_compress_chunked; spec-valid
all-literal framing remains as the fallback if the codec is unavailable).
Schemas with list columns or decimals beyond DECIMAL64 fall back to the
arrow writer (io/writer.py routes).
"""

from __future__ import annotations

import functools
import struct
import zlib

import numpy as np
import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T

MAGIC = b"PAR1"

# --- thrift compact protocol writer (inverse of parquet_native._CompactReader)

_CT_BOOL_TRUE, _CT_BOOL_FALSE = 1, 2
_CT_I16, _CT_I32, _CT_I64 = 4, 5, 6
_CT_BINARY, _CT_LIST, _CT_STRUCT = 8, 9, 12


def _varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zigzag(v: int) -> bytes:
    return _varint((v << 1) ^ (v >> 63))


class _CompactWriter:
    """Emit one thrift-compact struct. Fields must be written in ascending
    field-id order (the compact protocol encodes the id as a delta)."""

    def __init__(self):
        self.buf = bytearray()
        self._last_fid = [0]

    def _field_header(self, fid: int, ftype: int):
        delta = fid - self._last_fid[-1]
        if 0 < delta <= 15:
            self.buf.append((delta << 4) | ftype)
        else:
            self.buf.append(ftype)
            self.buf += _zigzag(fid)
        self._last_fid[-1] = fid

    def field_bool(self, fid: int, v: bool):
        self._field_header(fid, _CT_BOOL_TRUE if v else _CT_BOOL_FALSE)

    def field_i32(self, fid: int, v: int, *, wide: int = _CT_I32):
        self._field_header(fid, wide)
        self.buf += _zigzag(v)

    def field_i64(self, fid: int, v: int):
        self.field_i32(fid, v, wide=_CT_I64)

    def field_binary(self, fid: int, v: bytes):
        self._field_header(fid, _CT_BINARY)
        self.buf += _varint(len(v))
        self.buf += v

    def begin_struct(self, fid: int):
        self._field_header(fid, _CT_STRUCT)
        self._last_fid.append(0)

    def end_struct(self):
        self.buf.append(0)
        self._last_fid.pop()

    def begin_list(self, fid: int, elem_type: int, size: int):
        self._field_header(fid, _CT_LIST)
        if size < 15:
            self.buf.append((size << 4) | elem_type)
        else:
            self.buf.append(0xF0 | elem_type)
            self.buf += _varint(size)

    def list_i32(self, v: int):
        self.buf += _zigzag(v)

    def list_binary(self, v: bytes):
        self.buf += _varint(len(v))
        self.buf += v

    def end_top(self) -> bytes:
        self.buf.append(0)
        return bytes(self.buf)


# --- physical-type mapping -------------------------------------------------

# parquet Type enum
_PT_BOOLEAN, _PT_INT32, _PT_INT64 = 0, 1, 2
_PT_FLOAT, _PT_DOUBLE, _PT_BYTE_ARRAY = 4, 5, 6
# ConvertedType enum values actually used
_CV_UTF8, _CV_DECIMAL, _CV_DATE, _CV_TS_MICROS = 0, 5, 6, 10
_CV_INT8, _CV_INT16 = 15, 16
# CompressionCodec enum
CODECS = {"uncompressed": 0, "none": 0, "snappy": 1, "gzip": 2}
# Encoding enum
_ENC_PLAIN, _ENC_PLAIN_DICTIONARY, _ENC_RLE = 0, 2, 3


def _physical(dt: T.DataType):
    """(parquet Type, converted_type|None, value numpy dtype for the PLAIN
    byte image). Raises TypeError for schemas the native writer can't frame —
    the caller falls back to arrow."""
    if isinstance(dt, T.BooleanType):
        return _PT_BOOLEAN, None, np.bool_
    if isinstance(dt, T.ByteType):
        return _PT_INT32, _CV_INT8, np.int32
    if isinstance(dt, T.ShortType):
        return _PT_INT32, _CV_INT16, np.int32
    if isinstance(dt, T.IntegerType):
        return _PT_INT32, None, np.int32
    if isinstance(dt, T.LongType):
        return _PT_INT64, None, np.int64
    if isinstance(dt, T.FloatType):
        return _PT_FLOAT, None, np.float32
    if isinstance(dt, T.DoubleType):
        return _PT_DOUBLE, None, np.float64
    if isinstance(dt, T.StringType):
        return _PT_BYTE_ARRAY, _CV_UTF8, np.int32
    if isinstance(dt, T.DateType):
        return _PT_INT32, _CV_DATE, np.int32
    if isinstance(dt, T.TimestampType):
        return _PT_INT64, _CV_TS_MICROS, np.int64
    if isinstance(dt, T.DecimalType):
        if dt.precision > 18:
            raise TypeError(f"native writer: decimal precision {dt.precision}")
        return _PT_INT64, _CV_DECIMAL, np.int64
    raise TypeError(f"native parquet writer: unsupported type {dt}")


def supports_schema(schema: T.StructType) -> bool:
    try:
        for f in schema.fields:
            _physical(f.data_type)
    except TypeError:
        return False
    return True


# --- device kernel: compact + stats ---------------------------------------

@functools.lru_cache(maxsize=256)
def _prep_kernel(cap: int, dt_name: str):
    """Per (capacity, dtype) jitted column prep: stable-compact non-null
    values to the front (cumsum + searchsorted, same trick as
    ops/filtering.compact_cols) and reduce min/max/null_count in one program."""
    dt = jnp.dtype(dt_name)
    if jnp.issubdtype(dt, jnp.floating):
        lo, hi = -jnp.inf, jnp.inf
    elif dt == jnp.bool_:
        lo, hi = False, True
    else:
        info = jnp.iinfo(dt)
        lo, hi = info.min, info.max

    @jax.jit
    def k(vals, valid, n):
        live = jnp.arange(cap) < n
        vl = valid & live
        running = jnp.cumsum(vl.astype(jnp.int32))
        cnt = running[-1]
        j = jnp.arange(cap, dtype=jnp.int32)
        perm = jnp.clip(jnp.searchsorted(running, j + 1, side="left"),
                        0, cap - 1).astype(jnp.int32)
        comp = vals[perm]
        if dt == jnp.bool_:
            vmin = jnp.where(vl, vals, True).all()
            vmax = jnp.where(vl, vals, False).any()
        else:
            vmin = jnp.where(vl, vals, hi).min()
            vmax = jnp.where(vl, vals, lo).max()
        return comp, cnt, n - cnt, vmin, vmax, vl

    return k


def _prep_column(col, num_rows: int):
    """Run the device prep; returns host-side (values[:n_valid], n_valid,
    null_count, vmin, vmax, valid[:num_rows]) — one device->host transfer
    for the stream (the validity rides along so _encode_column doesn't pay
    a second per-column transfer for definition levels)."""
    k = _prep_kernel(col.capacity, np.dtype(col.data.dtype).name)
    comp, cnt, nulls, vmin, vmax, vl = k(col.data, col.validity,
                                         jnp.int32(num_rows))
    cnt, nulls = int(cnt), int(nulls)
    # static device-side slice before transfer: capacities are power-of-two
    # bucketed, so the padded tail can dwarf the live rows (to_host pattern).
    # All-valid columns (the common case) skip the validity transfer.
    valid = np.asarray(vl[:num_rows]) if nulls else None
    return (np.asarray(comp[:num_rows])[:cnt], cnt, nulls,
            np.asarray(vmin)[()], np.asarray(vmax)[()], valid)


# --- host framing ----------------------------------------------------------

def _rle_bitpacked(values: np.ndarray, bit_width: int) -> bytes:
    """RLE/bit-packed hybrid, bit-packed branch only (groups of 8 values,
    LSB-first within each byte — Parquet's layout matches numpy's
    bitorder='little')."""
    n = len(values)
    if n == 0:
        return b""
    groups = (n + 7) // 8
    padded = np.zeros(groups * 8, dtype=np.uint32)
    padded[:n] = values.astype(np.uint32)
    bits = ((padded[:, None] >> np.arange(bit_width, dtype=np.uint32)) & 1)
    packed = np.packbits(bits.astype(np.uint8).ravel(), bitorder="little")
    return _varint((groups << 1) | 1) + packed.tobytes()


def _def_levels_v1(valid: np.ndarray) -> bytes:
    """Definition levels for one optional flat column, v1 framing: 4-byte LE
    length prefix + RLE/bit-packed hybrid of 1-bit levels."""
    n = len(valid)
    if n and valid.all():
        body = _varint(n << 1) + b"\x01"      # one RLE run of 1s
    elif n and not valid.any():
        body = _varint(n << 1) + b"\x00"
    else:
        body = _rle_bitpacked(valid.astype(np.uint8), 1)
    return struct.pack("<I", len(body)) + body


def _snappy(raw: bytes) -> bytes:
    """Real SNAPPY page compression via pyarrow's bundled codec (ported
    from the ORC writer, io/orc_write_native.py:77 — parquet compresses the
    whole page body as one raw snappy block, no chunk headers needed).
    Falls back to the spec-valid all-literal framing when the codec is
    missing from the arrow build."""
    try:
        import pyarrow as pa
        return bytes(pa.Codec("snappy").compress(raw))
    except (ImportError, NotImplementedError, OSError):
        return _snappy_literal(raw)


def _snappy_literal(raw: bytes) -> bytes:
    """Spec-valid snappy framing of one all-literal chunk (no compression —
    the _snappy fallback)."""
    n = len(raw)
    out = bytearray(_varint(n))
    if n == 0:
        return bytes(out)
    if n <= 60:
        out.append((n - 1) << 2)
    else:
        length = n - 1
        nbytes = (length.bit_length() + 7) // 8
        out.append((59 + nbytes) << 2)
        out += length.to_bytes(nbytes, "little")
    out += raw
    return bytes(out)


def _compress(raw: bytes, codec: str) -> bytes:
    if codec in ("uncompressed", "none"):
        return raw
    if codec == "gzip":
        co = zlib.compressobj(6, zlib.DEFLATED, 31)
        return co.compress(raw) + co.flush()
    if codec == "snappy":
        return _snappy(raw)
    raise ValueError(f"native parquet writer: codec {codec}")


def _plain_stat_bytes(dt: T.DataType, v, dictionary=None) -> bytes | None:
    """PLAIN byte image of one statistics value; None suppresses the stat."""
    if isinstance(dt, T.StringType):
        if dictionary is None or len(dictionary) == 0:
            return None
        return dictionary[int(v)].as_py().encode("utf-8")
    pt, _, np_dt = _physical(dt)
    if pt == _PT_BOOLEAN:
        return b"\x01" if bool(v) else b"\x00"
    a = np.asarray(v).astype(np_dt)
    if np.issubdtype(a.dtype, np.floating) and np.isnan(a):
        return None
    return a.tobytes()


class _ColumnResult(object):
    __slots__ = ("pages", "meta_fields", "dict_page_len")

    def __init__(self, pages, meta_fields, dict_page_len):
        self.pages = pages                # list[bytes] ready to append
        self.meta_fields = meta_fields    # dict for ColumnMetaData
        self.dict_page_len = dict_page_len


def _page_header(page_type: int, unc: int, comp: int, body_writer) -> bytes:
    w = _CompactWriter()
    w.field_i32(1, page_type)
    w.field_i32(2, unc)
    w.field_i32(3, comp)
    body_writer(w)
    return w.end_top()


def _stats_struct(w: _CompactWriter, fid: int, null_count: int,
                  min_b: bytes | None, max_b: bytes | None):
    w.begin_struct(fid)
    w.field_i64(3, null_count)
    if max_b is not None:
        w.field_binary(5, max_b)
    if min_b is not None:
        w.field_binary(6, min_b)
    w.end_struct()


def _encode_column(col, dt: T.DataType, num_rows: int, codec: str):
    """Encode one column chunk: optional dictionary page + one v1 data page."""
    vals, n_valid, null_count, vmin, vmax, valid = _prep_column(col, num_rows)
    if valid is None:
        valid = np.ones(num_rows, dtype=bool)

    pt, _, np_dt = _physical(dt)
    is_string = isinstance(dt, T.StringType)
    pages = []
    dict_page_len = 0
    raw_bytes = 0   # spec: total_uncompressed_size = headers + RAW page bodies
    encodings = [_ENC_RLE, _ENC_PLAIN]

    if is_string:
        # dictionary page: PLAIN byte arrays of the engine's sorted dictionary
        entries = ([] if col.dictionary is None
                   else [s.as_py().encode("utf-8") for s in col.dictionary])
        raw = b"".join(struct.pack("<I", len(e)) + e for e in entries)
        comp = _compress(raw, codec)
        hdr = _page_header(2, len(raw), len(comp), lambda w: (
            w.begin_struct(7),
            w.field_i32(1, len(entries)),
            w.field_i32(2, _ENC_PLAIN_DICTIONARY),
            w.end_struct()))
        pages.append(hdr + comp)
        dict_page_len = len(hdr) + len(comp)
        raw_bytes += len(hdr) + len(raw)
        # data page payload: bit width byte + RLE/bit-packed dictionary codes
        bw = max(1, (max(1, len(entries)) - 1).bit_length())
        payload = bytes([bw]) + _rle_bitpacked(vals.astype(np.uint32), bw)
        encodings = [_ENC_RLE, _ENC_PLAIN_DICTIONARY]
    elif pt == _PT_BOOLEAN:
        payload = np.packbits(vals.astype(np.uint8),
                              bitorder="little").tobytes()
    else:
        payload = vals.astype(np_dt).tobytes()

    raw_page = _def_levels_v1(valid) + payload
    comp_page = _compress(raw_page, codec)
    min_b = _plain_stat_bytes(dt, vmin, col.dictionary) if n_valid else None
    max_b = _plain_stat_bytes(dt, vmax, col.dictionary) if n_valid else None
    enc = _ENC_PLAIN_DICTIONARY if is_string else _ENC_PLAIN
    hdr = _page_header(0, len(raw_page), len(comp_page), lambda w: (
        w.begin_struct(5),
        w.field_i32(1, num_rows),
        w.field_i32(2, enc),
        w.field_i32(3, _ENC_RLE),
        w.field_i32(4, _ENC_RLE),
        _stats_struct(w, 5, null_count, min_b, max_b),
        w.end_struct()))
    pages.append(hdr + comp_page)
    raw_bytes += len(hdr) + len(raw_page)

    meta = {
        "type": pt,
        "encodings": encodings,
        "codec": CODECS[codec],
        "num_values": num_rows,
        "total_uncompressed_size": raw_bytes,
        "null_count": null_count,
        "min_b": min_b,
        "max_b": max_b,
    }
    return _ColumnResult(pages, meta, dict_page_len)


def _schema_elements(w: _CompactWriter, schema: T.StructType):
    w.begin_list(2, _CT_STRUCT, len(schema.fields) + 1)
    # root
    r = _CompactWriter()
    r.field_binary(4, b"schema")
    r.field_i32(5, len(schema.fields))
    w.buf += r.end_top()
    for f in schema.fields:
        pt, cv, _ = _physical(f.data_type)
        e = _CompactWriter()
        e.field_i32(1, pt)
        e.field_i32(3, 1)                      # OPTIONAL
        e.field_binary(4, f.name.encode("utf-8"))
        if cv is not None:
            e.field_i32(6, cv)
        if isinstance(f.data_type, T.DecimalType):
            e.field_i32(7, f.data_type.scale)
            e.field_i32(8, f.data_type.precision)
        if isinstance(f.data_type, T.TimestampType):
            # LogicalType TIMESTAMP(isAdjustedToUTC=true, MICROS) — readers
            # reconstruct timestamp[us, UTC] (converted_type alone is naive)
            e.begin_struct(10)
            e.begin_struct(8)
            e.field_bool(1, True)
            e.begin_struct(2)
            e.begin_struct(2)                  # TimeUnit.MICROS (empty)
            e.end_struct()
            e.end_struct()
            e.end_struct()
            e.end_struct()
        w.buf += e.end_top()


class NativeParquetFile:
    """Streaming writer: one row group per append_batch(). Mirrors the task
    writer lifecycle (open -> append* -> close) of ColumnarOutputWriter."""

    def __init__(self, path: str, schema: T.StructType,
                 compression: str = "snappy"):
        codec = compression.lower()
        if codec not in CODECS:
            raise ValueError(f"native parquet writer: codec {compression}")
        if not supports_schema(schema):
            raise TypeError("schema unsupported by native writer")
        self.path = path
        self.schema = schema
        self.codec = codec
        self._f = open(path, "wb")
        self._f.write(MAGIC)
        self._offset = len(MAGIC)
        self._row_groups = []   # (columns_meta, num_rows, total_bytes)
        self._num_rows = 0

    def append_batch(self, batch) -> int:
        """Encode one ColumnarBatch as a row group; returns bytes written."""
        n = batch.num_rows
        cols_meta = []
        group_bytes = 0
        for field, col in zip(self.schema.fields, batch.columns):
            res = _encode_column(col, field.data_type, n, self.codec)
            first_off = self._offset
            for p in res.pages:
                self._f.write(p)
                self._offset += len(p)
            m = dict(res.meta_fields)
            m["path"] = field.name
            if res.dict_page_len:
                m["dictionary_page_offset"] = first_off
                m["data_page_offset"] = first_off + res.dict_page_len
            else:
                m["data_page_offset"] = first_off
            m["file_offset"] = first_off
            m["total_compressed_size"] = self._offset - first_off
            cols_meta.append(m)
            group_bytes += m["total_uncompressed_size"]
        self._row_groups.append((cols_meta, n, group_bytes))
        self._num_rows += n
        return sum(m["total_compressed_size"] for m in cols_meta)

    def close(self):
        if self._f is None:
            return
        w = _CompactWriter()
        w.field_i32(1, 1)                       # version
        _schema_elements(w, self.schema)
        w.field_i64(3, self._num_rows)
        w.begin_list(4, _CT_STRUCT, len(self._row_groups))
        for cols_meta, n, group_bytes in self._row_groups:
            g = _CompactWriter()
            g.begin_list(1, _CT_STRUCT, len(cols_meta))
            for m in cols_meta:
                c = _CompactWriter()
                c.field_i64(2, m["file_offset"])
                c.begin_struct(3)               # ColumnMetaData
                c.field_i32(1, m["type"])
                c.begin_list(2, _CT_I32, len(m["encodings"]))
                for e in m["encodings"]:
                    c.list_i32(e)
                c.begin_list(3, _CT_BINARY, 1)
                c.list_binary(m["path"].encode("utf-8"))
                c.field_i32(4, m["codec"])
                c.field_i64(5, m["num_values"])
                c.field_i64(6, m["total_uncompressed_size"])
                c.field_i64(7, m["total_compressed_size"])
                c.field_i64(9, m["data_page_offset"])
                if "dictionary_page_offset" in m:
                    c.field_i64(11, m["dictionary_page_offset"])
                _stats_struct(c, 12, m["null_count"], m["min_b"], m["max_b"])
                c.end_struct()
                g.buf += c.end_top()
            g.field_i64(2, group_bytes)
            g.field_i64(3, n)
            w.buf += g.end_top()
        w.field_binary(6, b"spark-rapids-tpu native writer")
        # ColumnOrder TYPE_ORDER per column — without this readers must treat
        # min_value/max_value statistics as having undefined ordering
        w.begin_list(7, _CT_STRUCT, len(self.schema.fields))
        for _ in self.schema.fields:
            o = _CompactWriter()
            o.begin_struct(1)      # TypeDefinedOrder (empty struct)
            o.end_struct()
            w.buf += o.end_top()
        footer = w.end_top()
        self._f.write(footer)
        self._f.write(struct.pack("<I", len(footer)))
        self._f.write(MAGIC)
        self._f.close()
        self._f = None

    def abort(self):
        if self._f is not None:
            self._f.close()
            self._f = None


def write_batch_file(path: str, batch, schema: T.StructType,
                     compression: str = "snappy") -> int:
    """One batch -> one file with one row group (the per-batch shape
    io/writer.py's task writer uses). Returns bytes written."""
    f = NativeParquetFile(path, schema, compression)
    try:
        f.append_batch(batch)
        f.close()
    except BaseException:
        f.abort()
        raise
    import os
    return os.path.getsize(path)
