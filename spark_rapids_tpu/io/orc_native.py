"""Native ORC stripe access: protobuf metadata + RLEv2 run scan on host,
bulk bit-unpack on device (stage-one ORC device decode, SURVEY.md §7).

Reference: GpuOrcScan.scala:375 (GpuOrcPartitionReader copies stripe bytes
to the GPU where libcudf decodes). Same split as io/parquet_native.py: the
PROTOBUF footers and RLEv2 run HEADERS are metadata — bytes to kilobytes,
parsed here with a minimal proto-wire reader — while the packed payload
bits go to the device (ops/orc_decode.py: MSB bit-unpack + zigzag).

Scope: flat schemas; UNCOMPRESSED or block-compressed streams
(ZLIB/SNAPPY/LZ4/ZSTD — see _stream_bytes below); INT/LONG columns with
DIRECT_V2 encoding (all four RLEv2 sub-encodings: SHORT_REPEAT, DIRECT,
DELTA, PATCHED_BASE), FLOAT/DOUBLE raw-IEEE streams,
DICTIONARY_V2 strings (the ORC dictionary maps 1:1 onto the engine's
sorted string dictionary — per-row bytes never materialize), PRESENT
(boolean-RLE) null streams. Anything else falls back to the pyarrow ORC
reader PER COLUMN, the same granularity as the parquet path."""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T

MAGIC = b"ORC"

# ORC "closest fixed bit width" table: 5-bit code → bit width
_WIDTH_TABLE = list(range(1, 25)) + [26, 28, 30, 32, 40, 48, 56, 64]


def _closest_fixed_bits(n: int) -> int:
    """ORC getClosestFixedBits: the smallest encodable width ≥ n."""
    for w in _WIDTH_TABLE:
        if w >= n:
            return w
    return 64


class _ProtoReader:
    """Just enough protobuf wire format for ORC footers."""

    def __init__(self, buf: bytes, pos: int = 0, end: int | None = None):
        self.buf = buf
        self.pos = pos
        self.end = len(buf) if end is None else end

    def varint(self) -> int:
        out = shift = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def fields(self):
        """Yield (field_number, wire_type, value_or_bytes)."""
        while self.pos < self.end:
            tag = self.varint()
            fnum, wt = tag >> 3, tag & 7
            if wt == 0:
                yield fnum, wt, self.varint()
            elif wt == 2:
                ln = self.varint()
                data = self.buf[self.pos:self.pos + ln]
                self.pos += ln
                yield fnum, wt, data
            elif wt == 5:
                data = self.buf[self.pos:self.pos + 4]
                self.pos += 4
                yield fnum, wt, data
            elif wt == 1:
                data = self.buf[self.pos:self.pos + 8]
                self.pos += 8
                yield fnum, wt, data
            else:
                raise NotImplementedError(f"proto wire type {wt}")


class StripeInfo:
    __slots__ = ("offset", "index_length", "data_length", "footer_length",
                 "num_rows")

    def __init__(self):
        self.offset = self.index_length = self.data_length = 0
        self.footer_length = self.num_rows = 0


class OrcMeta:
    __slots__ = ("stripes", "column_kinds", "column_names", "compression")

    def __init__(self):
        self.stripes: list[StripeInfo] = []
        self.column_kinds: list[int] = []   # leaf type kind per column
        self.column_names: list[str] = []
        self.compression = 0


# type kinds
C_NONE, C_ZLIB, C_SNAPPY, C_LZO, C_LZ4, C_ZSTD = 0, 1, 2, 3, 4, 5


def _decompress_chunked(buf: bytes, codec: int) -> bytes:
    """Decompress one ORC stream: a sequence of chunks, each with a 3-byte
    little-endian header `(chunkLength << 1) | isOriginal` (ORC spec
    'Compression'). ZLIB is raw DEFLATE; SNAPPY's uncompressed length rides
    as the snappy-format leading varint (pyarrow's codec needs it
    explicitly)."""
    import zlib
    out = []
    pos = 0
    n = len(buf)
    while pos + 3 <= n:
        hdr = buf[pos] | (buf[pos + 1] << 8) | (buf[pos + 2] << 16)
        pos += 3
        length = hdr >> 1
        chunk = buf[pos:pos + length]
        pos += length
        if hdr & 1:                       # isOriginal: stored uncompressed
            out.append(chunk)
        elif codec == C_ZLIB:
            out.append(zlib.decompressobj(wbits=-15).decompress(chunk))
        elif codec == C_SNAPPY:
            import pyarrow as pa
            size = shift = 0
            i = 0
            while True:
                b = chunk[i]
                size |= (b & 0x7F) << shift
                i += 1
                if not b & 0x80:
                    break
                shift += 7
            dec = pa.Codec("snappy").decompress(chunk, size)
            out.append(dec.to_pybytes() if hasattr(dec, "to_pybytes")
                       else bytes(dec))
        else:
            raise NotImplementedError(f"ORC compression codec {codec}")
    return b"".join(out)


K_SHORT, K_INT, K_LONG = 2, 3, 4
K_FLOAT, K_DOUBLE = 5, 6
K_STRING = 7
# stream kinds
S_PRESENT, S_DATA = 0, 1
S_LENGTH, S_DICT_DATA = 2, 3
# column encodings
E_DIRECT, E_DICTIONARY, E_DIRECT_V2, E_DICTIONARY_V2 = 0, 1, 2, 3


def read_meta(path: str) -> OrcMeta:
    with open(path, "rb") as f:
        f.seek(0, 2)
        size = f.tell()
        tail_len = min(size, 16 * 1024)
        f.seek(size - tail_len)
        tail = f.read(tail_len)
        # layout: ...stripes | metadata | footer | postscript | psLen(1).
        # The "ORC" magic rides at the end of the postscript (its writers
        # encode it as a trailing length-delimited proto field), so the
        # last 4 bytes are b"ORC" + psLen.
        if tail[-4:-1] != MAGIC:
            raise NotImplementedError("not an ORC file")
        ps_len = tail[-1]
        meta = OrcMeta()
        footer_len = 0
        for fnum, wt, val in _ProtoReader(tail[-1 - ps_len:-1]).fields():
            if fnum == 1:
                footer_len = val
            elif fnum == 2:
                meta.compression = val
        if meta.compression not in (C_NONE, C_ZLIB, C_SNAPPY):
            raise NotImplementedError(
                f"ORC compression codec {meta.compression}: host path")
        need = 1 + ps_len + footer_len
        if need > tail_len:            # giant footer: re-read exactly enough
            f.seek(size - need)
            tail = f.read(need)
    footer = tail[-1 - ps_len - footer_len:-1 - ps_len]
    if meta.compression != C_NONE:
        footer = _decompress_chunked(footer, meta.compression)
    types: list[tuple[int, list, list]] = []   # (kind, subtypes, names)
    for fnum, wt, val in _ProtoReader(footer).fields():
        if fnum == 3:          # StripeInformation
            si = StripeInfo()
            for f2, _w, v in _ProtoReader(val).fields():
                if f2 == 1:
                    si.offset = v
                elif f2 == 2:
                    si.index_length = v
                elif f2 == 3:
                    si.data_length = v
                elif f2 == 4:
                    si.footer_length = v
                elif f2 == 5:
                    si.num_rows = v
            meta.stripes.append(si)
        elif fnum == 4:        # Type
            kind, subtypes, names = 0, [], []
            for f2, w2, v in _ProtoReader(val).fields():
                if f2 == 1:
                    kind = v
                elif f2 == 2:
                    if w2 == 0:
                        subtypes.append(v)
                    else:           # packed repeated uint32
                        pr = _ProtoReader(v)
                        while pr.pos < pr.end:
                            subtypes.append(pr.varint())
                elif f2 == 3:
                    names.append(v.decode("utf-8"))
            types.append((kind, subtypes, names))
    if not types or types[0][0] != 12:          # root must be a struct
        raise NotImplementedError("non-struct root type")
    root_kind, subtypes, names = types[0]
    for tid, name in zip(subtypes, names):
        kind, sub, _n = types[tid]
        if sub:
            raise NotImplementedError(f"nested column {name}")
        meta.column_kinds.append(kind)
        meta.column_names.append(name)
    return meta


def _read_stripe_footer(raw: bytes, si: StripeInfo, compression: int = 0):
    """(streams [(kind, column, length)], encodings [kind])."""
    foot_off = si.offset + si.index_length + si.data_length
    footer = raw[foot_off:foot_off + si.footer_length]
    if compression != C_NONE:
        footer = _decompress_chunked(footer, compression)
    streams, encodings = [], []
    for fnum, _w, val in _ProtoReader(footer).fields():
        if fnum == 1:
            kind = col = length = 0
            for f2, _w2, v in _ProtoReader(val).fields():
                if f2 == 1:
                    kind = v
                elif f2 == 2:
                    col = v
                elif f2 == 3:
                    length = v
            streams.append((kind, col, length))
        elif fnum == 2:
            enc = dict_size = 0
            for f2, _w2, v in _ProtoReader(val).fields():
                if f2 == 1:
                    enc = v
                elif f2 == 2:
                    dict_size = v
            encodings.append((enc, dict_size))
    return streams, encodings


def decode_boolean_rle(buf: bytes, n_bits: int) -> np.ndarray:
    """PRESENT stream: byte-RLE over bit-bytes, bits MSB-first."""
    out_bytes = bytearray()
    pos = 0
    need = (n_bits + 7) // 8
    while len(out_bytes) < need and pos < len(buf):
        h = buf[pos]
        pos += 1
        if h < 128:                      # run of h+3 copies of next byte
            out_bytes.extend(buf[pos:pos + 1] * (h + 3))
            pos += 1
        else:                            # 256-h literal bytes
            lit = 256 - h
            out_bytes.extend(buf[pos:pos + lit])
            pos += lit
    bits = np.unpackbits(np.frombuffer(bytes(out_bytes[:need]), np.uint8),
                         bitorder="big")
    return bits[:n_bits].astype(np.int32)


def _zz(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


class _ByteReader:
    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def byte(self) -> int:
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def varint(self) -> int:
        out = shift = 0
        while True:
            b = self.byte()
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7


def _unpack_msb_host(buf: bytes, byte_off: int, width: int,
                     count: int) -> np.ndarray:
    """Host MSB-first unpack for small runs (delta payloads). Expands only
    the run's own bytes — runs always start byte-aligned."""
    if width == 0 or count == 0:
        return np.zeros(count, np.int64)
    nbytes = (width * count + 7) // 8
    bits = np.unpackbits(np.frombuffer(buf, np.uint8, nbytes, byte_off),
                         bitorder="big")[:width * count]
    mat = bits.reshape(count, width).astype(np.int64)
    pw = (1 << np.arange(width - 1, -1, -1, dtype=np.int64))
    return (mat * pw).sum(axis=1)


def scan_rlev2(buf: bytes, start: int, end: int, n_values: int,
               signed: bool):
    """Split an RLEv2 stream into runs. Returns a list of
    ('direct', count, width, payload_bit_offset) — device-unpacked — and
    ('const', count, ndarray) — host-materialized (short-repeat, delta,
    patched-base, and 57-64-bit direct; only patched-base widths > 56
    still raise for the per-column fallback)."""
    r = _ByteReader(buf, start)
    runs = []
    got = 0
    while got < n_values and r.pos < end:
        h = r.byte()
        enc = h >> 6
        if enc == 0:                    # SHORT_REPEAT
            nbytes = ((h >> 3) & 7) + 1
            cnt = (h & 7) + 3
            v = int.from_bytes(buf[r.pos:r.pos + nbytes], "big")
            r.pos += nbytes
            if signed:
                v = _zz(v)
            runs.append(("const", cnt, np.full(cnt, v, np.int64)))
            got += cnt
        elif enc == 1:                  # DIRECT
            w = _WIDTH_TABLE[(h >> 1) & 31]
            cnt = (((h & 1) << 8) | r.byte()) + 1
            if w > 56:
                # full-width values overflow the int64 device unpack;
                # materialize on host with uint64 arithmetic (wraps mod
                # 2^64, which IS two's-complement int64)
                nbytes = (w * cnt + 7) // 8
                bits = np.unpackbits(
                    np.frombuffer(buf, np.uint8, nbytes, r.pos),
                    bitorder="big")[:w * cnt]
                mat = bits.reshape(cnt, w).astype(np.uint64)
                pw = (np.uint64(1)
                      << np.arange(w - 1, -1, -1, dtype=np.uint64))
                u = (mat * pw).sum(axis=1, dtype=np.uint64)
                if signed:
                    vals = ((u >> np.uint64(1)).astype(np.int64)
                            ^ -((u & np.uint64(1)).astype(np.int64)))
                else:
                    vals = u.astype(np.int64)
                r.pos += nbytes
                runs.append(("const", cnt, vals))
                got += cnt
                continue
            runs.append(("direct", cnt, w, r.pos * 8))
            r.pos += (cnt * w + 7) // 8
            got += cnt
        elif enc == 3:                  # DELTA
            wcode = (h >> 1) & 31
            w = 0 if wcode == 0 else _WIDTH_TABLE[wcode]
            cnt = (((h & 1) << 8) | r.byte()) + 1
            base = r.varint()
            base = _zz(base) if signed else base
            delta0 = _zz(r.varint())
            vals = np.zeros(cnt, np.int64)
            vals[0] = base
            if cnt > 1:
                vals[1] = base + delta0
            if cnt > 2:
                if w == 0:              # fixed-delta run
                    deltas = np.full(cnt - 2, abs(delta0), np.int64)
                else:
                    deltas = _unpack_msb_host(buf, r.pos, w, cnt - 2)
                    r.pos += (w * (cnt - 2) + 7) // 8
                sign = 1 if delta0 >= 0 else -1
                vals[2:] = vals[1] + sign * np.cumsum(deltas)
            runs.append(("const", cnt, vals))
            got += cnt
        else:                           # PATCHED_BASE (host-materialized)
            w = _WIDTH_TABLE[(h >> 1) & 31]
            cnt = (((h & 1) << 8) | r.byte()) + 1
            b3 = r.byte()
            bw = ((b3 >> 5) & 7) + 1          # base width, bytes
            pw = _WIDTH_TABLE[b3 & 31]        # patch width, bits
            b4 = r.byte()
            pgw = ((b4 >> 5) & 7) + 1         # patch gap width, bits
            pll = b4 & 31                     # patch list length
            if w > 56 or _closest_fixed_bits(pgw + pw) > 56:
                raise NotImplementedError("patched-base width > 56")
            base = int.from_bytes(buf[r.pos:r.pos + bw], "big")
            r.pos += bw
            sign_bit = 1 << (bw * 8 - 1)      # sign-magnitude base
            if base & sign_bit:
                base = -(base & (sign_bit - 1))
            vals = _unpack_msb_host(buf, r.pos, w, cnt)
            r.pos += (w * cnt + 7) // 8
            # writers pack patch entries at getClosestFixedBits(pgw+pw);
            # the gap stays in bits [pw, pw+pgw) (top padding is zero)
            cw = _closest_fixed_bits(pgw + pw)
            entries = _unpack_msb_host(buf, r.pos, cw, pll)
            r.pos += (cw * pll + 7) // 8
            at = 0
            for e in entries:
                at += int(e) >> pw
                patch = int(e) & ((1 << pw) - 1)
                vals[at] |= patch << w
            runs.append(("const", cnt, base + vals))
            got += cnt
    if got < n_values:
        raise NotImplementedError("short RLEv2 stream")
    return runs


def intv2_column_to_device(raw: bytes, data_off: int, data_len: int,
                           present: np.ndarray | None, n_rows: int,
                           spark_type, capacity: int, raw_dev=None,
                           signed: bool = True, return_raw: bool = False):
    """One INT/LONG DIRECT_V2 column chunk → TpuColumnVector: run headers
    host-side, DIRECT payload bits unpacked on device, const runs merged.
    `raw_dev` is the stripe's device-resident byte array (uploaded ONCE per
    stripe by read_stripe_device and shared across its columns)."""
    import jax.numpy as jnp
    from spark_rapids_tpu.columnar.vector import TpuColumnVector, bucket_capacity
    from spark_rapids_tpu.ops import orc_decode as OD
    from spark_rapids_tpu.ops import parquet_decode as PD

    n_present = n_rows if present is None else int(present.sum())
    runs = scan_rlev2(raw, data_off, data_off + data_len, n_present, signed)
    pcap = max(bucket_capacity(max(n_present, 1)), 8)
    bit_offsets = np.zeros(pcap, np.int64)
    widths = np.zeros(pcap, np.int64)
    const_mask = np.zeros(pcap, bool)
    const_vals = np.zeros(pcap, np.int64)
    at = 0
    for run in runs:
        if run[0] == "direct":
            _k, cnt, w, bit0 = run
            bit_offsets[at:at + cnt] = bit0 + w * np.arange(cnt)
            widths[at:at + cnt] = w
        else:
            _k, cnt, vals = run
            const_mask[at:at + cnt] = True
            const_vals[at:at + cnt] = vals
        at += cnt
    packed_d = (raw_dev if raw_dev is not None
                else jnp.asarray(np.frombuffer(raw, np.uint8)))
    present_vals = OD.decode_intv2_device(
        packed_d, jnp.asarray(bit_offsets), jnp.asarray(widths),
        jnp.asarray(const_mask), jnp.asarray(const_vals), signed, pcap)
    if return_raw:
        return present_vals, n_present, pcap
    if present is None:
        vals = jnp.zeros((capacity,), jnp.int64).at[:pcap].set(
            present_vals)[:capacity]
        valid = (jnp.arange(capacity) < n_rows)
    else:
        pres = jnp.zeros((capacity,), jnp.bool_).at[:n_rows].set(
            jnp.asarray(present.astype(bool)))
        padded = jnp.zeros((capacity,), jnp.int64).at[:pcap].set(present_vals)
        vals, valid = PD.expand_present_to_rows(padded, pres, capacity)
    st = spark_type
    out = vals.astype(st.jnp_dtype)
    default = jnp.asarray(st.default_value(), out.dtype)
    out = jnp.where(valid, out, default)
    return TpuColumnVector(st, out, valid)


def float_column_to_device(raw: bytes, data_off: int, data_len: int,
                           present: np.ndarray | None, n_rows: int,
                           spark_type, capacity: int):
    """FLOAT/DOUBLE: the DATA stream is raw little-endian IEEE — one host
    view + H2D, then the null-layout expand on device."""
    import jax.numpy as jnp
    from spark_rapids_tpu.columnar.vector import TpuColumnVector
    from spark_rapids_tpu.ops import parquet_decode as PD

    isf32 = isinstance(spark_type, T.FloatType)
    np_dt = "<f4" if isf32 else "<f8"
    width = 4 if isf32 else 8
    n_present = n_rows if present is None else int(present.sum())
    vals_np = np.frombuffer(raw, np_dt, n_present, data_off).astype(
        np.float32 if isf32 else np.float64)
    del width
    padded = np.zeros(capacity, vals_np.dtype)
    padded[:n_present] = vals_np
    if present is None:
        vals = jnp.asarray(padded)
        valid = jnp.arange(capacity) < n_rows
    else:
        pres = jnp.zeros((capacity,), jnp.bool_).at[:n_rows].set(
            jnp.asarray(present.astype(bool)))
        vals, valid = PD.expand_present_to_rows(jnp.asarray(padded), pres,
                                                capacity)
    default = jnp.asarray(spark_type.default_value(), vals.dtype)
    vals = jnp.where(valid, vals, default)
    return TpuColumnVector(spark_type, vals, valid)


_KIND_TO_TYPE = {K_SHORT: T.INT, K_INT: T.INT, K_LONG: T.LONG,
                 K_FLOAT: T.FLOAT, K_DOUBLE: T.DOUBLE,
                 K_STRING: T.STRING}


def read_stripe_device(path: str, meta: OrcMeta, stripe_idx: int, schema,
                       pf=None):
    """Read one stripe via the device path; out-of-scope columns fall back
    to the pyarrow ORC reader PER COLUMN. Returns a ColumnarBatch."""
    from spark_rapids_tpu.columnar.arrow import array_to_device
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.vector import bucket_capacity

    si = meta.stripes[stripe_idx]
    with open(path, "rb") as f:
        f.seek(si.offset)
        raw = f.read(si.index_length + si.data_length + si.footer_length)
    # make offsets stripe-relative: footer stream lengths are laid out from
    # the stripe start (index region first, then data region)
    si_rel = StripeInfo()
    si_rel.offset = 0
    si_rel.index_length = si.index_length
    si_rel.data_length = si.data_length
    si_rel.footer_length = si.footer_length
    streams, encodings = _read_stripe_footer(raw, si_rel, meta.compression)
    n_rows = si.num_rows
    cap = bucket_capacity(max(n_rows, 1))

    # absolute offset of each stream within `raw` (file layout order). For
    # compressed files, every stream decompresses on host and `raw` becomes
    # the concatenation of the DECOMPRESSED streams — offsets, the device
    # upload, and every decoder below then work unchanged (the reference
    # decompresses on device, GpuOrcScan.scala:375; host inflate is this
    # engine's stage-1.5, same as the parquet path).
    offsets = {}
    if meta.compression == C_NONE:
        off = 0
        for kind, col, length in streams:
            offsets[(kind, col)] = (off, length)
            off += length
    else:
        pieces = []
        src_off = new_off = 0
        for kind, col, length in streams:
            blob = _decompress_chunked(raw[src_off:src_off + length],
                                       meta.compression)
            src_off += length
            pieces.append(blob)
            offsets[(kind, col)] = (new_off, len(blob))
            new_off += len(blob)
        raw = b"".join(pieces)

    name_to_col = {n: i for i, n in enumerate(meta.column_names)}
    raw_dev = None  # uploaded lazily, ONCE, shared by every int column
    cols, fields = [], []
    for f_ in schema.fields:
        sf_type = f_.data_type
        try:
            ci = name_to_col.get(f_.name)
            if ci is None:
                raise NotImplementedError(f"unknown column {f_.name}")
            col_id = ci + 1                     # root struct is column 0
            kind = meta.column_kinds[ci]
            want = _KIND_TO_TYPE.get(kind)
            if want is None or type(want) is not type(sf_type):
                raise NotImplementedError(f"kind {kind} vs {sf_type}")
            enc, dict_size = (encodings[col_id]
                              if col_id < len(encodings) else (0, 0))
            present = None
            if (S_PRESENT, col_id) in offsets:
                poff, plen = offsets[(S_PRESENT, col_id)]
                present = decode_boolean_rle(raw[poff:poff + plen], n_rows)
            doff, dlen = offsets[(S_DATA, col_id)]
            if kind in (K_SHORT, K_INT, K_LONG):
                if enc != E_DIRECT_V2:
                    raise NotImplementedError(f"int encoding {enc}")
                if raw_dev is None:
                    import jax.numpy as jnp
                    raw_dev = jnp.asarray(np.frombuffer(raw, np.uint8))
                cols.append(intv2_column_to_device(
                    raw, doff, dlen, present, n_rows, sf_type, cap,
                    raw_dev=raw_dev))
            elif kind == K_STRING:
                if enc == E_DICTIONARY_V2:
                    if raw_dev is None:
                        import jax.numpy as jnp
                        raw_dev = jnp.asarray(np.frombuffer(raw, np.uint8))
                    cols.append(string_column_to_device(
                        raw, offsets, col_id, present, n_rows, cap,
                        raw_dev=raw_dev, n_dict=dict_size))
                elif enc == E_DIRECT_V2:
                    cols.append(direct_string_column_to_device(
                        raw, offsets, col_id, present, n_rows, cap))
                else:
                    raise NotImplementedError(f"string encoding {enc}")
            else:
                cols.append(float_column_to_device(
                    raw, doff, dlen, present, n_rows, sf_type, cap))
        except NotImplementedError:
            import pyarrow.orc as orc
            pfile = pf if pf is not None else orc.ORCFile(path)
            tbl = pfile.read_stripe(stripe_idx, columns=[f_.name])
            arr = (tbl.column(0) if hasattr(tbl, "column")
                   else tbl[0])
            cols.append(array_to_device(arr, sf_type, cap))
        fields.append(f_)
    return ColumnarBatch(cols, n_rows, T.StructType(fields))


def rlev2_decode_host(raw: bytes, off: int, length: int, n: int,
                      signed: bool) -> np.ndarray:
    """Fully host-materialized RLEv2 decode (small streams: LENGTH etc.)."""
    out = np.zeros(n, np.int64)
    at = 0
    for run in scan_rlev2(raw, off, off + length, n, signed):
        if run[0] == "direct":
            _k, cnt, w, bit0 = run
            vals = _unpack_msb_host(raw, bit0 // 8, w, cnt)
            if bit0 % 8:
                raise NotImplementedError("unaligned direct run")
            if signed:
                vals = (vals >> 1) ^ -(vals & 1)
            out[at:at + cnt] = vals
        else:
            out[at:at + run[1]] = run[2]
        at += run[1]
    return out


def string_column_to_device(raw: bytes, offsets: dict, col_id: int,
                            present: np.ndarray | None, n_rows: int,
                            capacity: int, raw_dev=None,
                            n_dict: int = 0):
    """DICTIONARY_V2 string column → engine string vector. The ORC
    dictionary (DICTIONARY_DATA + LENGTH streams, entry count from the
    stripe footer's ColumnEncoding.dictionarySize) maps 1:1 onto the
    engine's sorted string dictionary — per-row bytes never materialize,
    exactly like the parquet path (io/parquet_native.py chunk_to_device).
    Indices (DATA stream, unsigned RLEv2) decode on device."""
    import jax.numpy as jnp
    from spark_rapids_tpu.columnar.vector import TpuColumnVector
    from spark_rapids_tpu.ops import parquet_decode as PD

    if (S_DICT_DATA, col_id) not in offsets or \
            (S_LENGTH, col_id) not in offsets or n_dict <= 0:
        raise NotImplementedError("direct-encoded strings: host path")
    ddoff, ddlen = offsets[(S_DICT_DATA, col_id)]
    loff, llen = offsets[(S_LENGTH, col_id)]
    doff, dlen = offsets[(S_DATA, col_id)]
    lens = rlev2_decode_host(raw, loff, llen, n_dict, signed=False)
    ends = np.cumsum(lens)
    starts = ends - lens
    blob = raw[ddoff:ddoff + ddlen]
    entries = [blob[s:e].decode("utf-8") for s, e in zip(starts, ends)]
    from spark_rapids_tpu.ops.strings import sorted_dict_and_rank
    sorted_dict, rank = sorted_dict_and_rank(entries)

    idx, n_present, pcap = intv2_column_to_device(
        raw, doff, dlen, present, n_rows, T.LONG, capacity,
        raw_dev=raw_dev, signed=False, return_raw=True)
    safe = jnp.clip(idx.astype(jnp.int32), 0, max(n_dict - 1, 0))
    codes_present = jnp.asarray(rank)[safe]
    if present is None:
        codes = jnp.zeros((capacity,), jnp.int32).at[:pcap].set(
            codes_present)[:capacity]
        valid = jnp.arange(capacity) < n_rows
    else:
        pres = jnp.zeros((capacity,), jnp.bool_).at[:n_rows].set(
            jnp.asarray(present.astype(bool)))
        padded = jnp.zeros((capacity,), jnp.int32).at[:pcap].set(
            codes_present)
        codes, valid = PD.expand_present_to_rows(padded, pres, capacity)
    codes = jnp.where(valid, codes, 0)   # canonical-null invariant
    cv = TpuColumnVector(T.STRING, codes, valid)
    return cv.with_dictionary(sorted_dict)


def direct_string_column_to_device(raw: bytes, offsets: dict, col_id: int,
                                   present: np.ndarray | None, n_rows: int,
                                   capacity: int):
    """DIRECT_V2 string column (no dictionary): the DATA stream is the
    concatenated UTF-8 bytes, LENGTH the per-present-row byte lengths
    (unsigned RLEv2). A zero-copy arrow StringArray over (offsets, blob)
    rides the engine's normal dictionary-encoding ingestion — same endpoint
    as the reference's device byte columns (GpuOrcScan.scala:375), reached
    via the engine's sorted-dictionary representation."""
    import jax.numpy as jnp
    import pyarrow as pa
    from spark_rapids_tpu.columnar import arrow as ai
    from spark_rapids_tpu.columnar.vector import TpuColumnVector
    from spark_rapids_tpu.ops import parquet_decode as PD

    doff, dlen = offsets[(S_DATA, col_id)]
    loff, llen = offsets[(S_LENGTH, col_id)]
    n_present = n_rows if present is None else int(present.sum())
    if n_present == 0:
        codes = jnp.zeros((capacity,), jnp.int32)
        valid = jnp.zeros((capacity,), jnp.bool_)
        cv = TpuColumnVector(T.STRING, codes, valid)
        return cv.with_dictionary(pa.array([], pa.string()))
    lens = rlev2_decode_host(raw, loff, llen, n_present, signed=False)
    off_arr = np.zeros(n_present + 1, np.int32)
    np.cumsum(lens, out=off_arr[1:])
    blob = raw[doff:doff + dlen]
    arr = pa.StringArray.from_buffers(
        n_present, pa.py_buffer(off_arr.tobytes()), pa.py_buffer(blob))
    cv = ai.string_array_to_device(arr)
    codes_present = cv.data
    k = min(codes_present.shape[0], capacity)
    if present is None:
        codes = jnp.zeros((capacity,), jnp.int32).at[:k].set(
            codes_present[:k])
        valid = jnp.arange(capacity) < n_rows
    else:
        pres = jnp.zeros((capacity,), jnp.bool_).at[:n_rows].set(
            jnp.asarray(present.astype(bool)))
        padded = jnp.zeros((capacity,), jnp.int32).at[:k].set(
            codes_present[:k])
        codes, valid = PD.expand_present_to_rows(padded, pres, capacity)
    codes = jnp.where(valid, codes, 0)   # canonical-null invariant
    return TpuColumnVector(T.STRING, codes, valid).with_dictionary(
        cv.dictionary)
