"""L5 I/O layer: accelerated file formats (SURVEY.md §1 L5).

Reference: GpuParquetScan.scala (PERFILE/MULTITHREADED/COALESCING reader
strategies), GpuOrcScan.scala, GpuBatchScanExec.scala (CSV), writers
(GpuParquetFileFormat.scala, ColumnarOutputWriter.scala, GpuFileFormatDataWriter)."""

from spark_rapids_tpu.io.filescan import (  # noqa: F401
    FileScanNode, FileSourceScanExec, FilePartition,
)
from spark_rapids_tpu.io.writer import (  # noqa: F401
    FileWriteNode, write_columnar, WriteStats,
)
