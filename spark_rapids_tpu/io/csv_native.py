"""Device CSV scan stage one: vectorized host boundary scan + device parse.

Reference: GpuBatchScanExec / CSVPartitionReader hand raw CSV bytes to
cudf's GPU parser (SURVEY.md #25). TPU realization mirrors the parquet
stage-one split (io/parquet_native.py): field BOUNDARIES are metadata —
one vectorized numpy pass finds delimiters/newlines and validates the
row shape — while the BULK work (digit bytes → numbers) runs on device
(ops/csv_decode.py).

Scope (stage one): header optional (schema fields are matched to header
columns BY NAME, like the host reader), single-byte delimiter, '\\n' line
ends, RFC-4180 quoted fields (boundaries masked by quote parity, wrapping
quotes stripped; doubled/stray quotes inside content fall back — numeric
columns never legally contain them), int32/int64/float64 columns on device
(floats conf-gated; exponent/inf/nan notation in the body falls back). The
whole scope decision happens in ONE host pass per file
(`try_scan_for_device`) BEFORE the device iterator is committed —
out-of-scope files return None and take the pyarrow host reader, the same
per-type conservatism as the reference's
spark.rapids.sql.csv.read.*.enabled confs."""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T


class CsvShape:
    """Host-scanned structure of one CSV file, ready for device parsing."""

    def __init__(self, data: np.ndarray, n_rows: int, starts: np.ndarray,
                 lens: np.ndarray, col_of: dict):
        self.data = data          # raw bytes as uint8 (device-bound)
        self.n_rows = n_rows
        self.starts = starts      # (n_rows, n_file_cols) int32
        self.lens = lens          # (n_rows, n_file_cols) int32
        self.col_of = col_of      # schema field name → file column index


def column_in_scope(dtype, allow_floats: bool) -> bool:
    if isinstance(dtype, T.DoubleType):
        return allow_floats
    return isinstance(dtype, (T.IntegerType, T.LongType))


def try_scan_for_device(path: str, schema, delimiter: str = ",",
                        header: bool = True,
                        allow_floats: bool = False) -> CsvShape | None:
    """One host pass deciding scope AND producing the field offsets.
    Returns None for anything out of stage-one scope (caller uses the
    pyarrow host reader) — never raises for well-formed-but-unsupported
    content, so the device iterator is only committed when it can finish."""
    if schema is None or not schema.fields:
        return None
    if not all(column_in_scope(f.data_type, allow_floats)
               for f in schema.fields):
        return None
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return None
    if b"\r" in raw:
        return None
    if raw and not raw.endswith(b"\n"):
        raw += b"\n"
    data = np.frombuffer(raw, dtype=np.uint8)
    delim_byte = delimiter.encode()[0]

    start = 0
    if header:
        first_nl = raw.find(b"\n")
        if first_nl < 0 or b'"' in raw[:first_nl]:
            return None           # quoted headers: host reader
        names = raw[:first_nl].decode("utf-8", "replace").split(delimiter)
        start = first_nl + 1
        col_of = {}
        for f in schema.fields:
            if f.name not in names:
                return None       # host reader owns missing-column handling
            col_of[f.name] = names.index(f.name)
        n_file_cols = len(names)
    else:
        n_file_cols = len(schema.fields)
        col_of = {f.name: i for i, f in enumerate(schema.fields)}

    body = data[start:]
    is_delim = body == delim_byte
    is_nl = body == ord("\n")
    is_quote = body == ord('"')
    n_quotes = int(is_quote.sum())
    if n_quotes:
        # RFC 4180: delimiters/newlines INSIDE quotes are content, not
        # boundaries. A char is in-quotes iff the count of quote chars
        # BEFORE it is odd (doubled quotes toggle twice, preserving parity).
        parity = np.zeros(len(body), np.int64)
        np.cumsum(is_quote, out=parity)
        in_quotes = np.empty(len(body), bool)
        in_quotes[0] = False
        in_quotes[1:] = (parity[:-1] & 1).astype(bool)
        if n_quotes & 1:
            return None           # unterminated quote: host reader
        is_delim = is_delim & ~in_quotes
        is_nl = is_nl & ~in_quotes
    n_rows = int(is_nl.sum())
    if n_rows == 0:
        return CsvShape(data, 0, np.zeros((0, n_file_cols), np.int32),
                        np.zeros((0, n_file_cols), np.int32), col_of)
    # float-notation gate on the BODY only (the header may legally contain
    # e/n/i); exponent, nan and inf spellings need host strtod
    if any(isinstance(f.data_type, T.DoubleType) for f in schema.fields):
        lowered = body | np.uint8(0x20)   # ascii to-lower
        if (np.isin(lowered, np.frombuffer(b"eni", np.uint8))).any():
            return None
    bounds = np.flatnonzero(is_delim | is_nl).astype(np.int64)
    if len(bounds) != n_rows * n_file_cols:
        return None               # ragged rows / embedded delimiters
    b = bounds.reshape(n_rows, n_file_cols)
    if not is_nl[b[:, -1]].all():
        return None               # a row ends in a delimiter, not newline
    prev = np.empty_like(b)
    prev[:, 1:] = b[:, :-1]
    prev[0, 0] = -1
    prev[1:, 0] = b[:-1, -1]
    starts = (prev + 1 + start).astype(np.int32)
    lens = (b - prev - 1).astype(np.int32)
    if n_quotes:
        # unquote wrapped fields: "123" → 123 (content indices shift by one
        # on each side). Quotes that are NOT a simple field wrapping (doubled
        # quotes inside content, stray mid-field quotes) go to the host
        # reader — numeric columns never legally contain them.
        last = np.clip(starts + lens - 1, 0, len(data) - 1)
        first_b = data[np.clip(starts, 0, len(data) - 1)]
        quoted = (lens >= 2) & (first_b == ord('"')) & \
            (data[last] == ord('"'))
        if int(quoted.sum()) * 2 != n_quotes:
            return None
        starts = (starts + quoted).astype(np.int32)
        lens = (lens - 2 * quoted).astype(np.int32)
    return CsvShape(data, n_rows, starts, lens, col_of)


def decode_shape_device(shape: CsvShape, schema, capacity_fn):
    """Parse a scanned file fully on device; returns a ColumnarBatch."""
    import jax.numpy as jnp
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.vector import TpuColumnVector
    from spark_rapids_tpu.ops import csv_decode as CD

    n = shape.n_rows
    cap = capacity_fn(max(n, 1))
    data_d = jnp.asarray(shape.data)
    cols = []
    for f in schema.fields:
        j = shape.col_of[f.name]
        starts = np.full(cap, 0, np.int32)
        lens = np.full(cap, -1, np.int32)
        if n:
            starts[:n] = shape.starts[:, j]
            lens[:n] = shape.lens[:, j]
        s_d, l_d = jnp.asarray(starts), jnp.asarray(lens)
        if isinstance(f.data_type, T.LongType):
            vals, valid = CD.parse_int64(data_d, s_d, l_d, cap)
        elif isinstance(f.data_type, T.IntegerType):
            vals, valid = CD.parse_int32(data_d, s_d, l_d, cap)
        else:
            vals, valid = CD.parse_float64(data_d, s_d, l_d, cap)
        default = jnp.asarray(f.data_type.default_value(), vals.dtype)
        vals = jnp.where(valid, vals, default)
        cols.append(TpuColumnVector(f.data_type, vals, valid))
    return ColumnarBatch(cols, n, schema)
