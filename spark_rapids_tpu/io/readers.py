"""Format readers with the reference's three acceleration strategies.

Reference GpuParquetScan.scala: PERFILE (ParquetPartitionReader:1603, one file at a
time), MULTITHREADED (MultiFileCloudParquetPartitionReader:1377 — background
futures fetch+decode several files so device upload overlaps I/O latency, built for
cloud object stores), COALESCING (MultiFileParquetPartitionReader:958 — stitch many
small files' row groups into ONE device batch to amortize per-batch overhead).

Decode stance (SURVEY.md §7 hard parts): host decode via Arrow C++ first — the
staged plan the survey prescribes; the device gets whole columns in one H2D per
batch. Predicate pushdown prunes row groups from footer statistics before any
column bytes are read (reference filterBlocks, GpuParquetScan.scala:271-295)."""

from __future__ import annotations

import concurrent.futures as futures
import typing

import pyarrow as pa
import pyarrow.dataset
import pyarrow.parquet as pq

from spark_rapids_tpu import types as T


def spark_filter_to_arrow(expr) -> "pa.dataset.Expression | None":
    """Translate a (bound or named) predicate expression into a pyarrow dataset
    expression. Returns None when the expression cannot be translated EXACTLY
    with Spark semantics — the caller must then apply the predicate itself as a
    residual filter (reference ParquetFilters conversion,
    GpuParquetScan.scala:273). In particular float/double comparisons are never
    pushed: Arrow uses IEEE NaN ordering while Spark treats NaN as the largest
    value and NaN == NaN as true."""
    from spark_rapids_tpu.expr import core as E
    from spark_rapids_tpu.expr import predicates as P
    from spark_rapids_tpu.expr import nullexprs as N
    import pyarrow.dataset as ds

    def has_float(e):
        try:
            if isinstance(e.dtype, T.FractionalType):
                return True
        except Exception:
            pass
        return any(has_float(c) for c in getattr(e, "children", []))

    def conv(e):
        if isinstance(e, (E.AttributeReference,)):
            return ds.field(e.name)
        if isinstance(e, E.BoundReference):
            return ds.field(e.name)
        if isinstance(e, E.Literal):
            return e.value  # scalar
        if isinstance(e, P.And):
            return conv(e.children[0]) & conv(e.children[1])
        if isinstance(e, P.Or):
            return conv(e.children[0]) | conv(e.children[1])
        if isinstance(e, P.Not):
            return ~conv(e.children[0])
        if isinstance(e, N.IsNull):
            return conv(e.children[0]).is_null()
        if isinstance(e, N.IsNotNull):
            return ~conv(e.children[0]).is_null()
        ops = {P.EqualTo: "__eq__", P.NotEqual: "__ne__", P.LessThan: "__lt__",
               P.LessThanOrEqual: "__le__", P.GreaterThan: "__gt__",
               P.GreaterThanOrEqual: "__ge__"}
        for cls, m in ops.items():
            if type(e) is cls:
                if has_float(e.children[0]) or has_float(e.children[1]):
                    raise NotImplementedError("float comparison (NaN semantics)")
                l, r = conv(e.children[0]), conv(e.children[1])
                return getattr(l, m)(r)
        raise NotImplementedError(type(e).__name__)

    try:
        out = conv(expr)
    except NotImplementedError:
        return None
    return out if isinstance(out, ds.Expression) else None


class FormatReader:
    """One file → iterator of arrow tables (host decode stage)."""

    format_name = "?"

    def read_file(self, path: str, columns: list | None, filt,
                  batch_rows: int) -> typing.Iterator[pa.Table]:
        raise NotImplementedError

    def schema_of(self, path: str) -> pa.Schema:
        raise NotImplementedError


class ParquetReader(FormatReader):
    """Row-group pruning from footer statistics AND exact residual filtering both
    happen inside the Arrow dataset scanner (C++), so when a filter is pushed the
    scan output is exact — the reference instead keeps Spark's FilterExec above
    the scan and prunes only at row-group granularity."""

    format_name = "parquet"
    _REBASE_MODES = ("EXCEPTION", "CORRECTED", "LEGACY")

    def __init__(self, rebase_mode: str = "EXCEPTION"):
        self.rebase_mode = rebase_mode.upper()
        if self.rebase_mode not in self._REBASE_MODES:
            raise ValueError(
                f"invalid datetimeRebaseModeInRead {rebase_mode!r}; "
                f"expected one of {self._REBASE_MODES}")

    def _rebase(self, tbl: pa.Table) -> pa.Table:
        """Datetime rebase for legacy hybrid-calendar writers (reference
        GpuParquetScan rebase checks; Spark datetimeRebaseModeInRead)."""
        if self.rebase_mode == "CORRECTED":
            return tbl
        from spark_rapids_tpu.shims import (GREGORIAN_SWITCH_DAY,
                                            rebase_julian_to_gregorian_days)
        import numpy as np
        for i, f in enumerate(tbl.schema):
            if not pa.types.is_date32(f.type):
                continue
            col = tbl.column(i).combine_chunks()
            days = col.cast(pa.int32()).to_numpy(zero_copy_only=False)
            valid = ~np.asarray(col.is_null())
            old = valid & (days < GREGORIAN_SWITCH_DAY)
            if not old.any():
                continue
            if self.rebase_mode == "EXCEPTION":
                raise ValueError(
                    f"column '{f.name}' holds dates before 1582-10-15; set "
                    "spark.rapids.tpu.sql.parquet.datetimeRebaseModeInRead "
                    "to LEGACY (hybrid-calendar writer) or CORRECTED "
                    "(proleptic writer)")
            rebased = rebase_julian_to_gregorian_days(
                days.astype("int64")).astype("int32")
            arr = pa.array(rebased, pa.int32()).cast(pa.date32())
            if not valid.all():
                import pyarrow.compute as pc
                arr = pc.if_else(pa.array(valid), arr,
                                 pa.nulls(len(arr), pa.date32()))
            tbl = tbl.set_column(i, f.name, arr)
        return tbl

    def read_file(self, path, columns, filt, batch_rows):
        import pyarrow.dataset as ds
        dset = ds.dataset(path, format="parquet")
        for batch in dset.to_batches(columns=columns, filter=filt,
                                     batch_size=batch_rows, use_threads=False):
            if batch.num_rows:
                yield self._rebase(pa.Table.from_batches([batch]))

    def schema_of(self, path):
        return pq.read_schema(path)


class OrcReader(FormatReader):
    format_name = "orc"

    def read_file(self, path, columns, filt, batch_rows):
        import pyarrow.orc as orc
        f = orc.ORCFile(path)
        # stripe-at-a-time (reference GpuOrcPartitionReader:375 copies stripes)
        for stripe in range(f.nstripes):
            tbl = f.read_stripe(stripe, columns=columns)
            if isinstance(tbl, pa.RecordBatch):
                tbl = pa.Table.from_batches([tbl])
            if filt is not None and tbl.num_rows:
                tbl = pa.Table.from_batches(
                    pa.dataset.dataset(tbl).to_batches(filter=filt),
                    schema=tbl.schema)
            for off in range(0, tbl.num_rows, batch_rows):
                yield tbl.slice(off, batch_rows)

    def schema_of(self, path):
        import pyarrow.orc as orc
        return orc.ORCFile(path).schema


class CsvReader(FormatReader):
    format_name = "csv"

    def __init__(self, header: bool = True, delimiter: str = ",",
                 schema: T.StructType | None = None, null_value: str = ""):
        self.header = header
        self.delimiter = delimiter
        self.schema = schema
        self.null_value = null_value

    def _options(self):
        import pyarrow.csv as pcsv
        read_opts = pcsv.ReadOptions(
            autogenerate_column_names=not self.header,
            column_names=(None if self.header or self.schema is None
                          else [f.name for f in self.schema]))
        parse_opts = pcsv.ParseOptions(delimiter=self.delimiter)
        conv = {}
        if self.schema is not None:
            conv = {f.name: T.to_arrow_type(f.data_type) for f in self.schema}
        convert_opts = pcsv.ConvertOptions(
            column_types=conv, null_values=[self.null_value, "null", "NULL"],
            strings_can_be_null=True)
        return read_opts, parse_opts, convert_opts

    def read_file(self, path, columns, filt, batch_rows):
        import pyarrow.csv as pcsv
        ro, po, co = self._options()
        tbl = pcsv.read_csv(path, read_options=ro, parse_options=po,
                            convert_options=co)
        if columns is not None:
            tbl = tbl.select(columns)
        if filt is not None and tbl.num_rows:
            tbl = pa.Table.from_batches(
                pa.dataset.dataset(tbl).to_batches(filter=filt),
                schema=tbl.schema)
        for off in range(0, tbl.num_rows, batch_rows):
            yield tbl.slice(off, batch_rows)

    def schema_of(self, path):
        import pyarrow.csv as pcsv
        ro, po, co = self._options()
        # streaming reader: schema from the first block only, not a full parse
        with pcsv.open_csv(path, read_options=ro, parse_options=po,
                           convert_options=co) as reader:
            return reader.schema


def reader_for(fmt: str, **kw) -> FormatReader:
    if fmt == "parquet":
        return ParquetReader(rebase_mode=kw.get("rebase_mode", "EXCEPTION"))
    if fmt == "orc":
        return OrcReader()
    if fmt == "csv":
        return CsvReader(**kw)
    raise ValueError(f"unknown format {fmt}")


# -- scan readahead ----------------------------------------------------------

def readahead_tables(gen, depth: int, budget_bytes: int | None = None,
                     stall_metric=None):
    """Bounded background readahead over a table generator: a worker thread
    drains `gen` up to `depth` items ahead of the consumer so host decode of
    batch N+1 overlaps whatever the consumer does with batch N (device
    upload + compute). Order-preserving and exception-transparent: items
    arrive exactly as `gen` would have yielded them, and a producer-side
    error re-raises at the consumer's position. `budget_bytes` additionally
    bounds the BYTES buffered (spill-budget awareness — see
    runtime/memory.host_prefetch_budget); one oversized table may always
    be staged so progress never deadlocks. `stall_metric` (a GpuMetric)
    accumulates the nanoseconds the CONSUMER spent blocked waiting on the
    producer — the "readahead stall time" the profiling tool surfaces: a
    large value means decode, not device compute, is the bottleneck.

    Since the pipelined executor landed this is a thin front over ONE shared
    mechanism — runtime/pipeline.stage_iterator's BoundedBatchQueue — so the
    scan readahead and every other stage boundary share queue semantics and
    one byte-budget policy (the reference analog remains
    MultiFileCloudParquetPartitionReader:1377's prefetch role, generalized
    past the MULTITHREADED reader to batch granularity)."""
    if depth <= 0:
        yield from gen
        return
    from spark_rapids_tpu.runtime import pipeline as P
    yield from P.stage_iterator(
        gen, edge="scan.decode", depth=depth,
        max_bytes=float("inf") if budget_bytes is None else budget_bytes,
        stall_metric=stall_metric)


# -- multi-file strategies ---------------------------------------------------

def perfile_tables(reader, paths, columns, filt, batch_rows):
    """PERFILE: sequential, lowest memory (reference ParquetPartitionReader:1603)."""
    for p in paths:
        yield from reader.read_file(p, columns, filt, batch_rows)


def multithreaded_tables(reader, paths, columns, filt, batch_rows, num_threads,
                         prefetch: int = 4):
    """MULTITHREADED: background futures decode files ahead of the consumer so
    host decode overlaps device compute (reference
    MultiFileCloudParquetPartitionReader:1377 + its thread pool)."""
    if not paths:
        return
    pool = futures.ThreadPoolExecutor(max_workers=max(1, num_threads))
    try:
        def read_whole(p):
            return list(reader.read_file(p, columns, filt, batch_rows))
        pending = [pool.submit(read_whole, p) for p in paths[:prefetch]]
        consumed = min(prefetch, len(paths))
        while pending:
            fut = pending.pop(0)
            if consumed < len(paths):
                pending.append(pool.submit(read_whole, paths[consumed]))
                consumed += 1
            yield from fut.result()
    finally:
        pool.shutdown(wait=False, cancel_futures=True)


def coalescing_tables(reader, paths, columns, filt, batch_rows, target_rows):
    """COALESCING: stitch many files into few big tables so each device batch is
    large (reference MultiFileParquetPartitionReader:958 stitches row groups into
    one host buffer + one decode). `batch_rows` (the configured reader cap) still
    bounds every emitted table; `target_rows` is the coalesce goal."""
    cap = max(batch_rows, 1)
    acc: list[pa.Table] = []
    acc_rows = 0

    def flush():
        t = acc[0] if len(acc) == 1 else pa.concat_tables(
            acc, promote_options="permissive")
        for off in range(0, t.num_rows, cap):
            yield t.slice(off, cap)

    # sequential streaming accumulate-and-flush: peak host memory stays
    # ~target_rows regardless of file sizes. Decode/compute overlap is the
    # MULTITHREADED strategy's job (it pays whole-file buffering for it).
    for tbl in perfile_tables(reader, paths, columns, filt, cap):
        acc.append(tbl)
        acc_rows += tbl.num_rows
        if acc_rows >= target_rows:  # flush() re-slices to cap-row batches
            yield from flush()
            acc, acc_rows = [], 0
    if acc:
        yield from flush()
