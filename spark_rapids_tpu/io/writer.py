"""Columnar file writers with commit protocol and write statistics.

Reference: ColumnarOutputWriter.scala (per-partition writer), GpuParquetFileFormat
(348) / GpuOrcFileFormat (178), GpuFileFormatDataWriter (419: single-directory and
dynamic-partitioning writers), GpuFileFormatWriter (345: job setup/commit),
BasicColumnarWriteStatsTracker (180). The commit protocol mirrors Hadoop's
FileOutputCommitter v2: task writes into `_temporary/<task>/`, task-commit renames
into the final directory, job-commit writes `_SUCCESS`."""

from __future__ import annotations

import dataclasses
import os
import shutil
import threading
import uuid

import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.plan.nodes import PlanNode
from spark_rapids_tpu.runtime.tracing import trace_range


@dataclasses.dataclass
class WriteStats:
    """Reference BasicColumnarWriteStatsTracker: files/partitions/rows/bytes."""
    num_files: int = 0
    num_rows: int = 0
    num_bytes: int = 0
    partitions: list = dataclasses.field(default_factory=list)

    def merge(self, other: "WriteStats"):
        self.num_files += other.num_files
        self.num_rows += other.num_rows
        self.num_bytes += other.num_bytes
        self.partitions.extend(other.partitions)


def _write_table(tbl: pa.Table, path: str, fmt: str, compression: str):
    if fmt == "parquet":
        pq.write_table(tbl, path, compression=compression)
    elif fmt == "orc":
        import pyarrow.orc as orc
        orc.write_table(tbl, path)
    elif fmt == "csv":
        import pyarrow.csv as pcsv
        pcsv.write_csv(tbl, path)
    else:
        raise ValueError(f"unknown format {fmt}")


class _TaskWriter:
    """One task's output: plain or dynamic-partitioned
    (reference GpuFileFormatDataWriter SingleDirectory/DynamicPartition writers)."""

    def __init__(self, temp_dir: str, task_id: int, fmt: str, compression: str,
                 partition_by: list, schema: T.StructType, job_uuid: str,
                 native: bool = False):
        self.temp = os.path.join(temp_dir, f"task_{task_id}")
        os.makedirs(self.temp, exist_ok=True)
        self.fmt = fmt
        self.compression = compression
        self.partition_by = partition_by
        self.schema = schema
        self.stats = WriteStats()
        self._file_counter = 0
        self._task_id = task_id
        self._job_uuid = job_uuid
        self.native = native

    def _next_name(self, subdir: str = "") -> str:
        # job-unique uuid in the filename (Spark's FileOutputCommitter naming)
        # so mode=append never collides with files from an earlier job that
        # used the same task ids.
        ext = {"parquet": "parquet", "orc": "orc", "csv": "csv"}[self.fmt]
        name = (f"part-{self._task_id:05d}-{self._job_uuid}"
                f"-{self._file_counter:04d}.{ext}")
        self._file_counter += 1
        d = os.path.join(self.temp, subdir)
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, name)

    def _native_module(self):
        if self.fmt == "parquet":
            from spark_rapids_tpu.io import parquet_write_native as m
        elif self.fmt == "orc":
            from spark_rapids_tpu.io import orc_write_native as m
        elif self.fmt == "csv":
            from spark_rapids_tpu.io import csv_write_native as m
        else:
            return None
        return m

    def write_batch(self, batch):
        """Device-path write: encode Parquet pages / ORC stripes / CSV text
        straight from the device columns (reference ColumnarOutputWriter
        device-buffer write; GpuOrcFileFormat.scala). Falls back to the
        arrow path for partitioned writes and schemas the native encoders
        can't frame."""
        m = self._native_module() if self.native else None
        if m is not None and not self.partition_by:
            from spark_rapids_tpu.columnar.batch import ColumnarBatch
            from spark_rapids_tpu.columnar.vector import TpuColumnVector
            if (isinstance(batch, ColumnarBatch)
                    and m.supports_schema(self.schema)
                    # exact type: subclasses (ListVector) carry structure the
                    # flat encoders can't frame
                    and all(type(c) is TpuColumnVector
                            for c in batch.columns)):
                path = self._next_name()
                try:
                    if self.fmt == "csv":
                        nbytes = m.write_batch_file(path, batch, self.schema)
                    else:
                        nbytes = m.write_batch_file(
                            path, batch, self.schema, self.compression)
                except (TypeError, ValueError) as e:
                    # schema/codec are pre-validated, so this is an encoder
                    # defect — fall back to arrow but never silently
                    import warnings
                    warnings.warn(
                        f"native {self.fmt} encoder failed ({e!r}); "
                        f"falling back to arrow writer for this task")
                    if os.path.exists(path):
                        os.unlink(path)
                    self._file_counter -= 1
                else:
                    self.stats.num_files += 1
                    self.stats.num_rows += batch.num_rows
                    self.stats.num_bytes += nbytes
                    return
        self.write(batch.to_arrow())

    def write(self, tbl: pa.Table):
        if not self.partition_by:
            path = self._next_name()
            _write_table(tbl, path, self.fmt, self.compression)
            self.stats.num_files += 1
            self.stats.num_rows += tbl.num_rows
            self.stats.num_bytes += os.path.getsize(path)
            return
        # dynamic partitioning: group rows by partition values, one dir per combo
        keys = [tbl.column(c).to_pylist() for c in self.partition_by]
        data_cols = [c for c in tbl.column_names if c not in self.partition_by]
        groups: dict = {}
        for i in range(tbl.num_rows):
            combo = tuple(k[i] for k in keys)
            groups.setdefault(combo, []).append(i)
        for combo, rows in groups.items():
            subdir = os.path.join(*[
                f"{c}={'__HIVE_DEFAULT_PARTITION__' if v is None else v}"
                for c, v in zip(self.partition_by, combo)])
            sub = tbl.select(data_cols).take(pa.array(rows, pa.int64()))
            path = self._next_name(subdir)
            _write_table(sub, path, self.fmt, self.compression)
            self.stats.num_files += 1
            self.stats.num_rows += sub.num_rows
            self.stats.num_bytes += os.path.getsize(path)
            if subdir not in self.stats.partitions:
                self.stats.partitions.append(subdir)

    def commit(self, final_dir: str):
        """Move task output into the final directory (FileOutputCommitter v2)."""
        for dirpath, _, files in os.walk(self.temp):
            rel = os.path.relpath(dirpath, self.temp)
            dest = final_dir if rel == "." else os.path.join(final_dir, rel)
            os.makedirs(dest, exist_ok=True)
            for f in files:
                os.replace(os.path.join(dirpath, f), os.path.join(dest, f))
        shutil.rmtree(self.temp, ignore_errors=True)

    def abort(self):
        shutil.rmtree(self.temp, ignore_errors=True)


def write_columnar(exec_or_node, path: str, fmt: str = "parquet",
                   partition_by: list | None = None, compression: str = "snappy",
                   mode: str = "error", conf=None) -> WriteStats:
    """Write a device exec's (or host node's) output — the
    GpuInsertIntoHadoopFsRelationCommand analog (job setup → per-partition task
    writers → commit + _SUCCESS)."""
    from spark_rapids_tpu.exec.base import TaskContext, TpuExec

    if mode not in ("error", "overwrite", "append", "ignore"):
        raise ValueError(f"unknown save mode {mode!r}")
    if os.path.exists(path) and os.listdir(path):
        if mode == "error":
            raise FileExistsError(path)
        if mode == "ignore":
            return WriteStats()
        if mode == "overwrite":
            shutil.rmtree(path)
    os.makedirs(path, exist_ok=True)
    job_uuid = uuid.uuid4().hex[:12]
    temp_dir = os.path.join(path, f"_temporary-{job_uuid}")
    os.makedirs(temp_dir, exist_ok=True)
    partition_by = partition_by or []
    schema = exec_or_node.output
    total = WriteStats()
    lock = threading.Lock()
    from spark_rapids_tpu import config as CFG
    entry = {"parquet": CFG.PARQUET_WRITER_TYPE, "orc": CFG.ORC_WRITER_TYPE,
             "csv": CFG.CSV_WRITER_TYPE}.get(fmt)
    writer_type = (conf.get(entry) if conf is not None
                   else entry.default) if entry is not None else "ARROW"
    native = str(writer_type).upper() == "NATIVE"

    from spark_rapids_tpu.runtime import metrics as M
    collector = M.current_collector()

    def run_split(split):
        writer = _TaskWriter(temp_dir, split, fmt, compression, partition_by,
                             schema, job_uuid, native=native)
        try:
            if isinstance(exec_or_node, TpuExec):
                with M.collector_context(collector), TaskContext():
                    for batch in exec_or_node.execute_partition(split):
                        writer.write_batch(batch)
            else:
                writer.write(exec_or_node.execute_host(split))
            writer.commit(path)
            with lock:
                total.merge(writer.stats)
        except BaseException:
            writer.abort()
            raise

    from concurrent.futures import ThreadPoolExecutor
    n = exec_or_node.num_partitions
    with ThreadPoolExecutor(max_workers=min(4, n)) as pool:
        list(pool.map(run_split, range(n)))
    shutil.rmtree(temp_dir, ignore_errors=True)
    with open(os.path.join(path, "_SUCCESS"), "w"):
        pass
    return total


class FileWriteNode(PlanNode):
    """Plan node for INSERT INTO path (host side runs the same writer)."""

    def __init__(self, child: PlanNode, path: str, fmt: str = "parquet",
                 partition_by: list | None = None, mode: str = "error"):
        super().__init__(child)
        self.path = path
        self.fmt = fmt
        self.partition_by = partition_by or []
        self.mode = mode

    @property
    def output(self):
        return self.child.output

    def execute_host(self, split):
        raise NotImplementedError("use write_columnar() to run a write job")
