"""Native CSV encode: device buffers -> vectorized host text, no arrow.

Reference: GpuCsvScan's write counterpart rides ColumnarOutputWriter.scala:182
(cudf formats text on-device). CSV is inherently a string format and this
engine's device never materializes per-row strings (strings live as
dictionary codes — io/parquet_write_native.py's stance), so the TPU-native
split is: the device supplies each column's value buffer and validity in ONE
transfer (static slice of the padded capacity), and the host produces bytes
with vectorized numpy ops — no pyarrow Table is ever built.

Formats (documented divergences from the arrow writer live here):
- floats: shortest round-trip repr (numpy astype('U') = Python repr)
- booleans: true/false (Spark CSV casing)
- dates: ISO yyyy-mm-dd; timestamps: ISO with 'T' separator, microseconds
- decimals: fixed-scale from the int64 backing
- strings: RFC-4180 quoting (quote when the value contains delimiter,
  quote, CR or LF; embedded quotes double)
- nulls: empty field
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T


def supports_schema(schema: T.StructType) -> bool:
    ok = (T.BooleanType, T.ByteType, T.ShortType, T.IntegerType, T.LongType,
          T.FloatType, T.DoubleType, T.StringType, T.DateType,
          T.TimestampType, T.DecimalType)
    return all(isinstance(f.data_type, ok) for f in schema.fields)


def _quote_strings(vals: np.ndarray) -> np.ndarray:
    """RFC-4180: quote values containing delimiter/quote/newline."""
    need = (np.char.find(vals, ",") >= 0) | (np.char.find(vals, '"') >= 0) \
        | (np.char.find(vals, "\n") >= 0) | (np.char.find(vals, "\r") >= 0)
    if not need.any():
        return vals
    quoted = np.char.add(
        np.char.add('"', np.char.replace(vals, '"', '""')), '"')
    return np.where(need, quoted, vals)


def _format_column(col, dt: T.DataType, num_rows: int) -> np.ndarray:
    """One device->host transfer (values + validity), then vectorized text.
    Returns a U-dtype array of num_rows formatted fields ('' for null)."""
    vals = np.asarray(col.data[:num_rows])
    valid = np.asarray(col.validity[:num_rows])
    if isinstance(dt, T.StringType):
        if col.dictionary is not None:
            entries = np.array([s.as_py() for s in col.dictionary] + [""],
                               dtype=object)
            codes = np.where(valid, vals, len(entries) - 1)
            txt = entries[codes].astype("U")
        else:
            txt = np.full(num_rows, "", dtype="U1").astype(object)
        txt = _quote_strings(np.asarray(txt, dtype="U"))
    elif isinstance(dt, T.BooleanType):
        txt = np.where(vals, "true", "false")
    elif isinstance(dt, T.DateType):
        txt = vals.astype("datetime64[D]").astype("U")
    elif isinstance(dt, T.TimestampType):
        txt = vals.astype("datetime64[us]").astype("U")
    elif isinstance(dt, T.DecimalType):
        iv = vals.astype(np.int64)
        s = dt.scale
        if s == 0:
            txt = iv.astype("U")
        else:
            sign = np.where(iv < 0, "-", "")
            mag = np.abs(iv)
            whole = (mag // 10**s).astype("U")
            frac = np.char.zfill((mag % 10**s).astype("U"), s)
            txt = np.char.add(np.char.add(np.char.add(sign, whole), "."),
                              frac)
    else:
        # int/float: numpy str conversion (shortest repr for floats)
        txt = vals.astype("U32")
    return np.where(valid, txt, "")


def write_batch_file(path: str, batch, schema: T.StructType,
                     header: bool = True, append: bool = False) -> int:
    """One batch -> CSV bytes appended to `path`. Returns bytes written."""
    n = batch.num_rows
    cols = [_format_column(c, f.data_type, n)
            for f, c in zip(schema.fields, batch.columns)]
    if cols:
        line = cols[0].astype(object)
        for c in cols[1:]:
            line = line + ","
            line = line + c.astype(object)
    else:
        line = np.full(n, "", dtype=object)
    body = "\n".join(line.tolist())
    out = []
    if header:
        out.append(",".join(
            np.asarray(_quote_strings(np.array([f.name for f in
                                                schema.fields], dtype="U")))
            .tolist()))
    if body or n:
        out.append(body)
    blob = ("\n".join(out) + "\n").encode("utf-8")
    with open(path, "ab" if append else "wb") as f:
        f.write(blob)
    return len(blob)
