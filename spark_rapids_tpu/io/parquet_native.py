"""Native parquet page access: thrift metadata + page splitting on host, bulk
index decode on device (stage-one device decode, SURVEY.md §7).

Reference: GpuParquetScan.scala:1235 hands raw column-chunk bytes to
`Table.readParquet` so the GPU does page decode. TPU realization: the THRIFT
page headers and RLE run STRUCTURE are metadata (bytes to kilobytes — parsed
on host, like string dictionaries), while the BULK bytes — bit-packed
dictionary indices and definition levels — go to the device, where one jitted
program unpacks bits and gathers dictionary values (ops/parquet_decode.py).
The parquet dictionary page maps 1:1 onto the engine's own dictionary-encoded
string representation, so a string column never materializes per-row bytes.

Scope: UNCOMPRESSED / SNAPPY / GZIP / ZSTD chunks (compressed page bodies
decompress on host through arrow's C codecs — stage 1.5; the reference uses
nvcomp on GPU), RLE_DICTIONARY-encoded data pages (v1), flat schemas,
physical types INT32/INT64/FLOAT/DOUBLE/BYTE_ARRAY. Anything else falls
back to the arrow decode path per column chunk.
"""

from __future__ import annotations

import struct
import typing

import numpy as np


# -- thrift compact protocol (just enough for PageHeader) --------------------

class _CompactReader:
    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def byte(self) -> int:
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def varint(self) -> int:
        out = shift = 0
        while True:
            b = self.byte()
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def zigzag(self) -> int:
        v = self.varint()
        return (v >> 1) ^ -(v & 1)

    def skip_binary(self):
        # NB: two statements — `self.pos += self.varint()` would load the
        # pre-varint pos before the call mutates it
        n = self.varint()
        self.pos += n

    def read_struct(self) -> dict:
        """Generic struct → {field_id: value}; nested structs recurse, lists
        and binaries are skipped (we never need them in page headers)."""
        out = {}
        fid = 0
        while True:
            head = self.byte()
            if head == 0:
                return out
            delta = head >> 4
            ftype = head & 0x0F
            fid = fid + delta if delta else self.zigzag()
            if ftype in (1, 2):            # BOOLEAN_TRUE / BOOLEAN_FALSE
                out[fid] = ftype == 1
            elif ftype == 3:               # byte
                out[fid] = self.byte()
            elif ftype in (4, 5, 6):       # i16/i32/i64
                out[fid] = self.zigzag()
            elif ftype == 7:               # double
                out[fid] = struct.unpack_from("<d", self.buf, self.pos)[0]
                self.pos += 8
            elif ftype == 8:               # binary/string
                self.skip_binary()
            elif ftype == 12:              # struct
                out[fid] = self.read_struct()
            elif ftype in (9, 10):         # list/set: skip elements
                sz_type = self.byte()
                n = sz_type >> 4
                if n == 15:
                    n = self.varint()
                et = sz_type & 0x0F
                for _ in range(n):
                    if et in (4, 5, 6):
                        self.zigzag()
                    elif et == 8:
                        self.skip_binary()
                    elif et == 12:
                        self.read_struct()
                    elif et == 3:
                        self.byte()
                    elif et == 7:
                        self.pos += 8
                    else:
                        raise NotImplementedError(f"thrift list elem {et}")
            else:
                raise NotImplementedError(f"thrift compact type {ftype}")


class PageHeader(typing.NamedTuple):
    page_type: int            # 0=data, 2=dictionary, 3=data v2
    uncompressed_size: int
    compressed_size: int
    num_values: int
    encoding: int             # 8=RLE_DICTIONARY(PLAIN_DICT=2), 0=PLAIN
    header_len: int
    # v2 only: level-section byte lengths (levels are NEVER compressed) and
    # whether the values section is compressed
    def_len: int = 0
    rep_len: int = 0
    v2_compressed: bool = True


def parse_page_header(buf: bytes, pos: int) -> PageHeader:
    r = _CompactReader(buf, pos)
    d = r.read_struct()
    ptype = d[1]
    dl = rl = 0
    v2c = True
    if ptype == 0:      # DataPageHeader (field 5)
        dph = d.get(5, {})
        nv, enc = dph.get(1, 0), dph.get(2, 0)
    elif ptype == 2:    # DictionaryPageHeader (field 7)
        dph = d.get(7, {})
        nv, enc = dph.get(1, 0), dph.get(2, 0)
    elif ptype == 3:    # DataPageHeaderV2 (field 8)
        dph = d.get(8, {})
        nv, enc = dph.get(1, 0), dph.get(4, 0)
        dl, rl = dph.get(5, 0), dph.get(6, 0)
        v2c = bool(dph.get(7, 1))
    else:
        nv, enc = 0, 0
    return PageHeader(ptype, d[2], d[3], nv, enc, r.pos - pos, dl, rl, v2c)


# -- RLE / bit-packed hybrid structure ---------------------------------------

class RleSegment(typing.NamedTuple):
    kind: str          # "rle" | "packed"
    count: int         # decoded value count
    value: int         # rle: the repeated value
    byte_off: int      # packed: offset of packed bytes in the stream
    byte_len: int


def parse_rle_hybrid(buf: bytes, pos: int, end: int, bit_width: int,
                     total: int) -> list[RleSegment]:
    """Split an RLE/bit-packed hybrid stream into segments. Headers are
    varints (metadata); packed payload bytes are NOT touched here — the
    device unpacks them."""
    r = _CompactReader(buf, pos)
    segs: list[RleSegment] = []
    got = 0
    vbytes = (bit_width + 7) // 8
    while got < total and r.pos < end:
        h = r.varint()
        if h & 1:
            groups = h >> 1
            n = groups * 8
            blen = groups * bit_width  # bytes: 8 values * bw bits / 8
            segs.append(RleSegment("packed", min(n, total - got), 0,
                                   r.pos, blen))
            r.pos += blen
        else:
            run = h >> 1
            v = int.from_bytes(buf[r.pos:r.pos + vbytes], "little") \
                if vbytes else 0
            r.pos += vbytes
            segs.append(RleSegment("rle", min(run, total - got), v, 0, 0))
        got += segs[-1].count
    return segs


def decode_rle_host(buf: bytes, pos: int, end: int, bit_width: int,
                    total: int) -> np.ndarray:
    """Host (numpy-vectorized) hybrid decode — def levels and fallback path."""
    out = np.empty(total, dtype=np.int32)
    at = 0
    for seg in parse_rle_hybrid(buf, pos, end, bit_width, total):
        if seg.kind == "rle":
            out[at:at + seg.count] = seg.value
        else:
            bits = np.unpackbits(
                np.frombuffer(buf, np.uint8, seg.byte_len, seg.byte_off),
                bitorder="little")
            vals = bits.reshape(-1, bit_width)[:seg.count]
            out[at:at + seg.count] = (
                vals.astype(np.int32) * (1 << np.arange(bit_width,
                                                        dtype=np.int32))
            ).sum(axis=1)
        at += seg.count
    return out


# -- column chunk reading -----------------------------------------------------

class ChunkPages(typing.NamedTuple):
    physical_type: str
    dict_values: np.ndarray | list      # decoded PLAIN dictionary (host)
    index_segments: list                # per data page: (num_values,
                                        #   def_levels np | None,
                                        #   bit_width, packed bytes | np idx)
    num_values: int


_FIXED = {"INT32": ("<i4", 4), "INT64": ("<i8", 8),
          "FLOAT": ("<f4", 4), "DOUBLE": ("<f8", 8)}


def _decode_plain_dictionary(physical_type: str, raw: bytes, n: int):
    if physical_type in _FIXED:
        dt, _ = _FIXED[physical_type]
        return np.frombuffer(raw, dtype=dt, count=n).copy()
    if physical_type == "BYTE_ARRAY":
        out, pos = [], 0
        for _ in range(n):
            (ln,) = struct.unpack_from("<I", raw, pos)
            pos += 4
            out.append(raw[pos:pos + ln].decode("utf-8"))
            pos += ln
        return out
    raise NotImplementedError(physical_type)


def read_chunk_pages(path: str, row_group: int, column: int,
                     md=None) -> ChunkPages:
    """Parse one dictionary-encoded column chunk (UNCOMPRESSED, or
    SNAPPY/GZIP/ZSTD with page bodies decompressed on host) into its raw
    device-ready pieces. Raises NotImplementedError when out of scope
    (caller falls back to arrow decode). `md` avoids re-parsing the
    footer per chunk (wide-table footers are MBs)."""
    if md is None:
        import pyarrow.parquet as pq
        md = pq.ParquetFile(path).metadata
    col = md.row_group(row_group).column(column)
    dec = None
    if col.compression != "UNCOMPRESSED":
        # stage 1.5: page bodies decompress on host via arrow's C codecs
        # (the reference decompresses on GPU through nvcomp; the DECODE —
        # the bulk bit work — still runs on device either way)
        import pyarrow as pa
        if col.compression not in ("SNAPPY", "GZIP", "ZSTD"):
            raise NotImplementedError(f"codec {col.compression}")
        try:
            dec = pa.Codec(col.compression.lower())
        except Exception as e:
            raise NotImplementedError(f"codec {col.compression}: {e}")
    if "RLE_DICTIONARY" not in col.encodings and \
            "PLAIN_DICTIONARY" not in col.encodings:
        raise NotImplementedError(f"encodings {col.encodings}")
    if col.physical_type not in _FIXED and \
            col.physical_type != "BYTE_ARRAY":
        raise NotImplementedError(f"type {col.physical_type}")

    max_def = md.schema.column(column).max_definition_level
    if md.schema.column(column).max_repetition_level:
        raise NotImplementedError("nested (repeated) columns")

    with open(path, "rb") as f:
        start = col.dictionary_page_offset or col.data_page_offset
        f.seek(start)
        buf = f.read(col.total_compressed_size)

    # fast path: one native C call scans the whole chunk (thrift headers,
    # def-level RLE decode, hybrid segmentation — native/parquet_host.cpp);
    # the Python loop below is the fallback, the executable spec, and the
    # compressed-chunk path (bodies must decompress before scanning)
    raw_pages = None
    if dec is None:  # compressed bodies must decompress before scanning
        try:
            from spark_rapids_tpu.native import (NativeBuildError,
                                                 scan_chunk_native)
            raw_pages, dict_info = scan_chunk_native(buf, col.num_values,
                                                     max_def)
        except (NativeBuildError, OSError):
            pass  # no native toolchain: parse in Python below
        except NotImplementedError:
            pass  # e.g. v2 data pages: the Python parser below handles them
    if raw_pages is not None:
        d_off, d_len, d_n = dict_info
        dict_vals = _decode_plain_dictionary(
            col.physical_type, buf[d_off:d_off + d_len], d_n)
        pages = []
        for (nv, dl, bw, values_off, body_off, body_len, _np_, rs) in raw_pages:
            page_bytes = buf[body_off:body_off + body_len]
            segs = [RleSegment("packed" if k == 1 else "rle", c, v, bo, bl)
                    for (k, c, v, bo, bl) in rs]
            pages.append((nv, dl, bw, page_bytes, values_off, segs))
        return ChunkPages(col.physical_type, dict_vals, pages, col.num_values)

    pos = 0
    dict_vals = None
    pages = []
    values_seen = 0
    while pos < len(buf) and values_seen < col.num_values:
        ph = parse_page_header(buf, pos)
        body = pos + ph.header_len
        raw_body = buf[body:body + ph.compressed_size]
        if ph.page_type == 2:                       # dictionary page
            page_body = (raw_body if dec is None else
                         bytes(dec.decompress(raw_body,
                                              ph.uncompressed_size)))
            dict_vals = _decode_plain_dictionary(
                col.physical_type, page_body, ph.num_values)
        elif ph.page_type == 0:                     # data page v1
            if ph.encoding not in (8, 2):           # RLE_DICT / PLAIN_DICT
                raise NotImplementedError(f"page encoding {ph.encoding}")
            page_body = (raw_body if dec is None else
                         bytes(dec.decompress(raw_body,
                                              ph.uncompressed_size)))
            # work PAGE-relative so RleSegment offsets index page_bytes
            page_bytes = page_body
            p = 0
            if max_def:
                # optional-field def levels: RLE with 4-byte length prefix
                (dl_len,) = struct.unpack_from("<I", page_bytes, p)
                p += 4
                def_levels = decode_rle_host(page_bytes, p, p + dl_len, 1,
                                             ph.num_values)
                p += dl_len
            else:
                def_levels = np.ones(ph.num_values, dtype=np.int32)
            bw = page_bytes[p]
            p += 1
            n_present = int(def_levels.sum())
            segs = parse_rle_hybrid(page_bytes, p, len(page_bytes), bw,
                                    n_present)
            pages.append((ph.num_values, def_levels, bw, page_bytes,
                          p - 1, segs))
            values_seen += ph.num_values
        elif ph.page_type == 3:                     # data page v2
            if ph.encoding not in (8, 2):
                raise NotImplementedError(f"page encoding {ph.encoding}")
            if ph.rep_len:
                raise NotImplementedError("repeated (nested) v2 page")
            # levels ride UNCOMPRESSED ahead of the (optionally compressed)
            # values section; def levels have NO length prefix in v2
            levels = raw_body[:ph.def_len]
            data = raw_body[ph.def_len:]
            if dec is not None and ph.v2_compressed:
                data = bytes(dec.decompress(
                    data, ph.uncompressed_size - ph.def_len - ph.rep_len))
            if max_def and ph.def_len:
                def_levels = decode_rle_host(levels, 0, ph.def_len, 1,
                                             ph.num_values)
            else:
                def_levels = np.ones(ph.num_values, dtype=np.int32)
            bw = data[0]
            n_present = int(def_levels.sum())
            segs = parse_rle_hybrid(data, 1, len(data), bw, n_present)
            pages.append((ph.num_values, def_levels, bw, data, 0, segs))
            values_seen += ph.num_values
        else:
            raise NotImplementedError(f"page type {ph.page_type}")
        pos = body + ph.compressed_size
    if dict_vals is None:
        raise NotImplementedError("no dictionary page")
    return ChunkPages(col.physical_type, dict_vals, pages, col.num_values)


# -- chunk → engine vector ----------------------------------------------------

def chunk_to_device(pages: ChunkPages, spark_type, capacity: int,
                    encoded: bool = False):
    """Decode a parsed chunk into a TpuColumnVector. The common fast path
    (every hybrid segment bit-packed) unpacks indices ON DEVICE; pages with
    mixed RLE runs fall back to the host hybrid decode, keeping the
    dictionary gather on device either way."""
    import jax.numpy as jnp
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar.vector import TpuColumnVector
    from spark_rapids_tpu.ops import parquet_decode as PD

    is_string = pages.physical_type == "BYTE_ARRAY"
    sorted_dict = None
    if is_string:
        # parquet dictionary == the engine's string dictionary, sorted for
        # order-preserving codes (columnar/arrow.py design)
        from spark_rapids_tpu.ops.strings import sorted_dict_and_rank
        sorted_dict, rank = sorted_dict_and_rank(pages.dict_values)
        dict_dev = jnp.asarray(rank)        # parquet idx -> sorted code
    else:
        dict_dev = jnp.asarray(np.asarray(pages.dict_values))
    from spark_rapids_tpu.columnar.vector import bucket_capacity

    # fast path: ONE data page, all-packed index segments → a single fused
    # program (unpack + dict gather + null spread + canonicalize). The eager
    # per-page pipeline below cost ~25 XLA dispatches per chunk — at TPC-H
    # scan width that dominated hot-query wall time (docs/perf_notes.md r4).
    if len(pages.index_segments) == 1:
        (num_values, def_levels, bw, page_bytes, values_off, segs) = \
            pages.index_segments[0]
        if segs and all(s.kind == "packed" for s in segs):
            packed = b"".join(page_bytes[s.byte_off:s.byte_off + s.byte_len]
                              for s in segs)
            return _decode_single_page_fused(
                packed, bw, def_levels, dict_dev, num_values, capacity,
                pages, spark_type, sorted_dict, encoded=encoded)

    all_vals, all_valid = [], []
    for (num_values, def_levels, bw, page_bytes, values_off, segs) in \
            pages.index_segments:
        pcap = bucket_capacity(max(num_values, 1))
        n_present = int(def_levels.sum())
        if segs and all(s.kind == "packed" for s in segs):
            # segments each hold whole 8-value groups at byte boundaries:
            # concatenating their BYTES preserves bit alignment
            packed = b"".join(page_bytes[s.byte_off:s.byte_off + s.byte_len]
                              for s in segs)
            vals, valid = PD.decode_dictionary_page(
                np.frombuffer(packed, np.uint8), bw, n_present, def_levels,
                dict_dev, pcap)
        else:
            idx = decode_rle_host(page_bytes, values_off + 1,
                                  len(page_bytes), bw, n_present) \
                if segs else np.zeros(0, np.int32)
            nd = int(dict_dev.shape[0])
            idx_d = jnp.zeros((pcap,), jnp.int32).at[:len(idx)].set(
                jnp.asarray(np.clip(idx, 0, max(nd - 1, 0))))
            # an all-null page may carry an EMPTY dictionary — nothing to
            # gather, every slot is the canonical default
            present = dict_dev[idx_d] if nd else jnp.zeros((pcap,),
                                                           dict_dev.dtype)
            dl = jnp.zeros((pcap,), jnp.bool_).at[:len(def_levels)].set(
                jnp.asarray(def_levels.astype(bool)))
            vals, valid = PD.expand_present_to_rows(present, dl, pcap)
        all_vals.append(vals[:num_values])
        all_valid.append(valid[:num_values])

    vals = jnp.concatenate(all_vals) if len(all_vals) > 1 else all_vals[0]
    valid = jnp.concatenate(all_valid) if len(all_valid) > 1 else all_valid[0]
    n = pages.num_values
    out_v = jnp.zeros((capacity,), vals.dtype).at[:n].set(vals[:n])
    out_m = jnp.zeros((capacity,), jnp.bool_).at[:n].set(valid[:n])

    if is_string:
        # canonical-null invariant (columnar/vector.py:10): invalid slots
        # hold code 0, never rank-gather residue — group-by compares raw
        # codes (ops/grouping.py)
        codes = jnp.where(out_m, out_v.astype(jnp.int32), 0)
        cv = TpuColumnVector(T.STRING, codes, out_m)
        return cv.with_dictionary(sorted_dict)
    np_to_spark = {"INT32": T.INT, "INT64": T.LONG,
                   "FLOAT": T.FLOAT, "DOUBLE": T.DOUBLE}
    st = spark_type or np_to_spark[pages.physical_type]
    want = st.jnp_dtype
    if out_v.dtype != jnp.dtype(want):
        out_v = out_v.astype(want)
    default = jnp.asarray(st.default_value(), out_v.dtype)
    out_v = jnp.where(out_m, out_v, default)
    return TpuColumnVector(st, out_v, out_m)


def _page_spec_and_args(packed: bytes, bw: int, def_levels, dict_dev,
                        num_values: int, capacity: int, pages, spark_type):
    """Host prep shared by the standalone fused decode and the encoded-upload
    vector: static EncodedPageSpec + the device argument tuple
    (packed, dict, def-levels, n_present, n). The ONE place page bytes become
    device buffers, so both paths upload identical payloads."""
    import jax.numpy as jnp
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar.vector import bucket_capacity
    from spark_rapids_tpu.ops import parquet_decode as PD
    from spark_rapids_tpu.ops import pallas_kernels as PK

    is_string = pages.physical_type == "BYTE_ARRAY"
    n_present = int(def_levels.sum())
    pcap = max(bucket_capacity(max(n_present, 1)), 8)
    bcap = max(bucket_capacity(max(len(packed), 1)), 8)
    use_pallas = PK.should_use("bitunpack")     # probe OUTSIDE the traced program

    np_to_spark = {"INT32": T.INT, "INT64": T.LONG,
                   "FLOAT": T.FLOAT, "DOUBLE": T.DOUBLE}
    st = T.STRING if is_string else (spark_type
                                     or np_to_spark[pages.physical_type])
    want = jnp.dtype(jnp.int32) if is_string else jnp.dtype(st.jnp_dtype)
    default = 0 if is_string else st.default_value()
    # n_present is only STATIC under pallas (tile shapes); zeroing it
    # otherwise keeps the non-pallas compile cache shared across present
    # counts, exactly like the pre-spec key did
    spec = PD.EncodedPageSpec(bw, pcap, bcap, capacity, str(want), is_string,
                              default, use_pallas,
                              n_present if use_pallas else 0)
    if use_pallas:
        words = PK.bytes_to_words_u32(np.frombuffer(packed, np.uint8))
        packed_in = jnp.asarray(words)
    else:
        ph = np.zeros(bcap, np.uint8)
        ph[:len(packed)] = np.frombuffer(packed, np.uint8)
        packed_in = jnp.asarray(ph)
    dh = np.zeros(capacity, bool)
    nd_lv = min(len(def_levels), capacity)
    dh[:nd_lv] = def_levels[:nd_lv].astype(bool)
    n = min(num_values, pages.num_values, capacity)
    args = (packed_in, dict_dev, jnp.asarray(dh),
            jnp.asarray(n_present, jnp.int32), jnp.asarray(n, jnp.int32))
    return spec, st, args


def _decode_single_page_fused(packed: bytes, bw: int, def_levels, dict_dev,
                              num_values: int, capacity: int, pages,
                              spark_type, sorted_dict, encoded: bool = False):
    """One jitted program per (bit width, shape bucket, output type):
    bit-unpack → dictionary gather → definition-level spread → canonical
    nulls (ops/parquet_decode.decode_page_cols). Cached via the fuse kernel
    cache like every exec stage. Under ``encoded`` the expansion is DEFERRED:
    the encoded buffers are wrapped in an EncodedColumnVector and the first
    consumer runs the same decode body — fused into its own program when it
    can, standalone otherwise."""
    from spark_rapids_tpu.columnar.vector import TpuColumnVector
    from spark_rapids_tpu.columnar.encoded import (EncodedCol,
                                                   EncodedColumnVector)
    from spark_rapids_tpu.ops import parquet_decode as PD
    from spark_rapids_tpu.runtime import fuse

    spec, st, args = _page_spec_and_args(packed, bw, def_levels, dict_dev,
                                         num_values, capacity, pages,
                                         spark_type)
    if encoded:
        enc = EncodedCol(*args, spec, st,
                         sorted_dict if spec.is_string else None)
        return EncodedColumnVector(enc)

    def build():
        def kernel(packed_d, dict_d, dl_d, n_present_t, n_t):
            return PD.decode_page_cols(spec, packed_d, dict_d, dl_d,
                                       n_present_t, n_t)
        return kernel

    key = ("pq_page_decode", spec)
    v, m = fuse.call_fused(key, "ParquetScan.decode", build, args,
                           lambda: build()(*args))
    cv = TpuColumnVector(st, v, m)
    return cv.with_dictionary(sorted_dict) if spec.is_string else cv


def read_row_group_device(path: str, row_group: int, schema,
                          columns: list[str] | None = None, pf=None,
                          encoded: bool = False):
    """Read one row group entirely via the device decode path; out-of-scope
    column chunks (compressed, non-dictionary, nested) fall back to arrow
    PER COLUMN (reference falls back per-file; per-column is strictly
    finer). Pass `pf` to reuse one parsed footer across row groups.

    Every column's H2D payload is metered on the movement ledger with a
    per-path site (scan.encoded / scan.device / scan.fallback), so the
    encoded-upload win shows up as fewer h2d bytes, not just wall clock."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.encoded import EncodedColumnVector
    from spark_rapids_tpu.columnar.vector import bucket_capacity
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar.arrow import array_to_device
    from spark_rapids_tpu.runtime import movement as _MV

    if pf is None:
        import pyarrow.parquet as pq
        pf = pq.ParquetFile(path)
    md = pf.metadata
    # leaf paths: a flat column's path IS its name; nested leaves look like
    # "l.list.element" and must never match a top-level name
    leaf_of = {}
    for i in range(md.num_columns):
        path_in_schema = md.schema.column(i).path
        if "." not in path_in_schema:
            leaf_of[path_in_schema] = i
    want = columns if columns is not None else         [f.name for f in (schema.fields if schema is not None else [])] or         list(leaf_of)
    n_rows = md.row_group(row_group).num_rows
    cap = bucket_capacity(max(n_rows, 1))
    cols, fields = [], []
    for name in want:
        sf = schema[name] if schema is not None else None
        try:
            if name not in leaf_of:
                raise NotImplementedError(f"nested column {name}")
            pages = read_chunk_pages(path, row_group, leaf_of[name], md=md)
            cv = chunk_to_device(
                pages, sf.data_type if sf else None, cap, encoded=encoded)
            if isinstance(cv, EncodedColumnVector):
                _MV.record_h2d(cv.encoded_payload_bytes(),
                               site="scan.encoded")
            else:
                _MV.record_h2d(cv.device_memory_size(), site="scan.device")
        except NotImplementedError:
            arr = pf.read_row_group(row_group, columns=[name]).column(0)
            cv = array_to_device(arr, sf.data_type if sf else None, cap)
            _MV.record_h2d(cv.device_memory_size(), site="scan.fallback")
        cols.append(cv)
        fields.append(sf or T.StructField(name, cols[-1].dtype, True))
    return ColumnarBatch(cols, n_rows, T.StructType(fields))
