"""TypeSig — static type-support algebra for the override layer.

Reference: TypeChecks.scala:129 (TypeSig set algebra with `+`/`-`, nested types,
lit-only marks), :483 (ExprChecks), :878 (CastChecks), :1196 (supported_ops.md doc
generator). The TPU build keeps the same shape: a rule declares which input/output
types it supports; tagging diffs the declared signature against the actual types and
records human-readable reasons when a node must stay on the host."""

from __future__ import annotations

from spark_rapids_tpu import types as T


_ALL_BASIC = (
    T.BooleanType, T.ByteType, T.ShortType, T.IntegerType, T.LongType,
    T.FloatType, T.DoubleType, T.StringType, T.DateType, T.TimestampType,
    T.DecimalType, T.NullType,
)


class TypeSig:
    """An immutable set of supported DataType classes with set algebra."""

    def __init__(self, classes=(), note: str | None = None):
        self.classes = frozenset(classes)
        self.notes = {}
        if note:
            for c in classes:
                self.notes[c] = note

    def __add__(self, other: "TypeSig") -> "TypeSig":
        out = TypeSig(self.classes | other.classes)
        out.notes = {**self.notes, **other.notes}
        return out

    def __sub__(self, other: "TypeSig") -> "TypeSig":
        out = TypeSig(self.classes - other.classes)
        out.notes = {c: n for c, n in self.notes.items() if c in out.classes}
        return out

    def supports(self, dt: T.DataType) -> bool:
        return isinstance(dt, tuple(self.classes)) if self.classes else False

    def reason_not_supported(self, dt: T.DataType, context: str) -> str | None:
        if self.supports(dt):
            return None
        return f"{context} produces/consumes unsupported type {dt}"

    def __repr__(self):
        return "TypeSig(" + ", ".join(sorted(c.__name__ for c in self.classes)) + ")"


BOOLEAN = TypeSig([T.BooleanType])
INTEGRAL = TypeSig([T.ByteType, T.ShortType, T.IntegerType, T.LongType])
FRACTIONAL = TypeSig([T.FloatType, T.DoubleType])
NUMERIC = INTEGRAL + FRACTIONAL
DECIMAL = TypeSig([T.DecimalType])
STRING = TypeSig([T.StringType])
DATE = TypeSig([T.DateType])
TIMESTAMP = TypeSig([T.TimestampType])
DATETIME = DATE + TIMESTAMP
NULL = TypeSig([T.NullType])
ALL = TypeSig(_ALL_BASIC)
COMMON = NUMERIC + BOOLEAN + STRING + DATETIME + NULL
ORDERABLE = COMMON + DECIMAL
NONE = TypeSig()
ARRAY = TypeSig([T.ArrayType])
STRUCT = TypeSig([T.StructDataType])
MAP = TypeSig([T.MapType])
NESTED = ARRAY + STRUCT + MAP


class ExecChecks:
    """Per-exec type signature: all input and output columns must satisfy `sig`
    (reference ExecChecks, TypeChecks.scala:726)."""

    def __init__(self, sig: TypeSig = COMMON + DECIMAL):
        self.sig = sig

    def input_fields(self, node):
        """Input columns to type-check; subclasses may exempt columns an exec
        consumes specially (e.g. GenerateExec's array input)."""
        for child in node.children:
            yield from child.output

    def tag(self, meta) -> None:
        for field in meta.node.output:
            if not self.sig.supports(field.data_type):
                meta.will_not_work(
                    f"unsupported output type {field.data_type} for column "
                    f"'{field.name}'")
        for field in self.input_fields(meta.node):
            if not self.sig.supports(field.data_type):
                meta.will_not_work(
                    f"unsupported input type {field.data_type} for column "
                    f"'{field.name}'")


class ExprChecks:
    """Per-expression signature: child dtypes + result dtype
    (reference ExprChecks, TypeChecks.scala:483)."""

    def __init__(self, output_sig: TypeSig, input_sigs=None):
        self.output_sig = output_sig
        self.input_sigs = input_sigs  # list[TypeSig] | TypeSig | None

    def tag(self, meta) -> None:
        expr = meta.expr
        try:
            dt = expr.dtype
        except Exception:
            meta.will_not_work("cannot resolve result type")
            return
        if not self.output_sig.supports(dt):
            meta.will_not_work(f"unsupported result type {dt}")
        children = getattr(expr, "children", [])
        if self.input_sigs is None:
            return
        sigs = (self.input_sigs if isinstance(self.input_sigs, list)
                else [self.input_sigs] * len(children))
        for c, sig in zip(children, sigs):
            try:
                cdt = c.dtype
            except Exception:
                continue
            if not sig.supports(cdt):
                meta.will_not_work(f"unsupported input type {cdt} for child {c}")


def generate_supported_ops_doc(registry) -> str:
    """Markdown support matrix, the docs/supported_ops.md generator analog
    (reference TypeChecks.scala:1196)."""
    lines = ["# Supported operators and expressions", "",
             "Generated from the override rule registry.", "",
             "## Execs", "", "| Exec | Description | Types |", "|---|---|---|"]
    for cls, rule in sorted(registry.exec_rules.items(), key=lambda kv: kv[0].__name__):
        sig = rule.checks.sig if rule.checks else ALL
        tnames = ", ".join(sorted(c.__name__.replace("Type", "")
                                  for c in sig.classes))
        lines.append(f"| {cls.__name__} | {rule.description} | {tnames} |")
    lines += ["", "## Expressions", "", "| Expression | Description | Result types |",
              "|---|---|---|"]
    for cls, rule in sorted(registry.expr_rules.items(), key=lambda kv: kv[0].__name__):
        sig = rule.checks.output_sig if rule.checks else ALL
        tnames = ", ".join(sorted(c.__name__.replace("Type", "")
                                  for c in sig.classes))
        lines.append(f"| {cls.__name__} | {rule.description} | {tnames} |")
    return "\n".join(lines) + "\n"
