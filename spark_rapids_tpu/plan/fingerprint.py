"""Canonical plan-shape fingerprints.

A fingerprint identifies a plan by its SHAPE — operator tree, output dtypes,
key columns and expression structure — with literal VALUES normalized out, so
`WHERE qty > 300` and `WHERE qty > 314` share one fingerprint while a changed
dtype, key column or operator does not. This is the reuse key of the stats
plane: the PlanHistoryStore (runtime/history.py) records observed peak device
bytes / cardinalities / skew per fingerprint, and scheduler.estimate_footprint
reads them back on the next submission of the same shape. It is deliberately
the same notion of identity a compiled-stage cache or shared plan cache needs:
anything that changes the traced program must change the fingerprint, and
nothing else should.

Contrast with runtime/fuse.py's `expr_key`, which keys literal values too
(a literal is baked into the traced XLA program as a constant); the
fingerprint keys only the literal's TYPE, because observed statistics
generalize across literal values but compiled programs do not.
"""

from __future__ import annotations

import hashlib

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import core as E

# data-carrying fields normalized to shape-only markers: partition payloads
# (ScanNode tables) and scan paths vary per dataset without changing the plan
# shape; cached stats remain keyed to the shape, with the static heuristic
# still blended in as the data-size guard
_DATA_FIELDS = ("partitions", "paths")


def _norm_expr(e, literals: bool = False) -> tuple:
    if isinstance(e, E.Literal):
        return (("lit", repr(e.dtype), repr(e.value)) if literals
                else ("lit", repr(e.dtype)))
    parts = [type(e).__qualname__]
    d = vars(e) if hasattr(e, "__dict__") else {
        s: getattr(e, s, None) for s in getattr(e, "__slots__", ())}
    for k in sorted(d):
        if k == "children":
            continue
        parts.append((k, _norm(d[k], literals)))
    parts.append(tuple(_norm_expr(c, literals)
                       for c in getattr(e, "children", ())))
    return tuple(parts)


def _norm(v, literals: bool = False):
    if isinstance(v, E.Expression):
        return _norm_expr(v, literals)
    if isinstance(v, T.StructType):
        return ("schema", tuple((f.name, repr(f.data_type), bool(f.nullable))
                                for f in v))
    if isinstance(v, T.DataType):
        return repr(v)
    if isinstance(v, (list, tuple)):
        return tuple(_norm(x, literals) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((str(k), _norm(x, literals))
                            for k, x in v.items()))
    if isinstance(v, (str, int, float, bool, bytes, type(None))):
        return (type(v).__name__, v)
    if isinstance(v, type):
        return ("class", v.__qualname__)
    if callable(v):
        return ("fn", getattr(v, "__qualname__", "?"))
    return ("obj", type(v).__qualname__)


def _norm_node(node, literals: bool = False) -> tuple:
    parts = [node.name()]
    d = vars(node) if hasattr(node, "__dict__") else {}
    for k in sorted(d):
        if k == "children":
            continue
        if k.lstrip("_") in _DATA_FIELDS:
            parts.append((k, ("data",)))
            continue
        parts.append((k, _norm(d[k], literals)))
    try:
        out = node.output
        parts.append(("out", tuple((f.name, repr(f.data_type)) for f in out)))
    except Exception:
        pass
    parts.append(tuple(_norm_node(c, literals) for c in node.children))
    return tuple(parts)


def plan_shape(plan) -> tuple:
    """Canonical nested-tuple shape of a PlanNode tree (debug/test surface —
    fingerprint() is the production key)."""
    return _norm_node(plan)


def plan_fingerprint(plan) -> str:
    """Stable hex fingerprint of a plan's shape. Equal across runs and
    processes for equal shapes (sha256 over the canonical repr)."""
    canon = repr(plan_shape(plan)).encode()
    return hashlib.sha256(canon).hexdigest()[:16]


def plan_signature(plan) -> str:
    """Like `plan_fingerprint` but with literal VALUES kept: the identity a
    compiled-program cache needs (`WHERE qty > 300` and `> 314` trace to
    DIFFERENT XLA programs — the literal is a baked-in constant), where the
    stats plane deliberately wants them to collide."""
    canon = repr(_norm_node(plan, literals=True)).encode()
    return hashlib.sha256(canon).hexdigest()[:16]
