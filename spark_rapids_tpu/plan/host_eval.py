"""Host (CPU) expression evaluator — the independent oracle / fallback path.

Reference analogy: in the reference, unsupported nodes simply stay as Spark CPU
execs and Spark's own interpreter runs them (SURVEY.md §1 L3). Our framework is
standalone, so the host path is an independent NumPy implementation of the same
expression semantics. It doubles as the CPU side of the equivalence test harness
(reference SparkQueryCompareTestSuite.scala:183 withCpuSparkSession).

Deliberately NOT jax: a second implementation that can disagree with the device
path is exactly what makes ring-2 tests meaningful.
"""

from __future__ import annotations

import datetime
import math
import re

import numpy as np
import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import core as E
from spark_rapids_tpu.expr import arithmetic as A
from spark_rapids_tpu.expr import predicates as P
from spark_rapids_tpu.expr import nullexprs as N
from spark_rapids_tpu.expr import conditional as C
from spark_rapids_tpu.expr import mathexprs as MM
from spark_rapids_tpu.expr import strings as S
from spark_rapids_tpu.expr import datetime as DT
from spark_rapids_tpu.expr.cast import Cast


class HostCol:
    """Host column: python list of values with None for null (exactness over speed —
    this is the oracle, not the fast path)."""

    __slots__ = ("data", "dtype")

    def __init__(self, data: list, dtype: T.DataType):
        self.data = data
        self.dtype = dtype

    def __len__(self):
        return len(self.data)

    @staticmethod
    def from_arrow(arr, dtype: T.DataType) -> "HostCol":
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        data = arr.to_pylist()
        if isinstance(dtype, T.FloatType):
            data = [None if v is None else float(np.float32(v)) for v in data]
        return HostCol(data, dtype)

    def to_arrow(self):
        return pa.array(self.data, type=T.to_arrow_type(self.dtype))


def table_schema(tbl: pa.Table) -> T.StructType:
    return T.StructType([
        T.StructField(f.name, T.from_arrow_type(f.type), True) for f in tbl.schema])


def eval_host(expr: E.Expression, tbl: pa.Table) -> HostCol:
    """Evaluate an expression tree against a pyarrow table, row-at-a-time."""
    n = tbl.num_rows
    if isinstance(expr, E.Alias):
        return eval_host(expr.child, tbl)
    if isinstance(expr, E.AttributeReference):
        idx = tbl.schema.get_field_index(expr.name)
        return HostCol.from_arrow(tbl.column(idx), expr.dtype)
    if isinstance(expr, E.BoundReference):
        return HostCol.from_arrow(tbl.column(expr.ordinal), expr.dtype)
    if isinstance(expr, E.Literal):
        return HostCol([expr.value] * n, expr.dtype)

    if hasattr(expr, "eval_arrow"):  # PythonUDF: worker-pool arrow exchange
        child_cols = [eval_host(c, tbl) for c in expr.children]
        child_tbl = pa.Table.from_arrays(
            [pa.array(c.data, T.to_arrow_type(c.dtype)) for c in child_cols],
            names=[f"a{i}" for i in range(len(child_cols))])
        out = expr.eval_arrow(child_tbl)
        return HostCol.from_arrow(out, expr.dtype)

    kids = [eval_host(c, tbl) for c in getattr(expr, "children", [])]
    fn = _DISPATCH.get(type(expr))
    if fn is None:
        for klass, f in _DISPATCH.items():
            if isinstance(expr, klass):
                fn = f
                break
    if fn is None:
        raise NotImplementedError(f"host eval for {type(expr).__name__}")
    return fn(expr, kids, n)


# ---- helpers ---------------------------------------------------------------

def _unary(fn):
    def run(expr, kids, n):
        (a,) = kids
        return HostCol([None if v is None else fn(expr, v) for v in a.data],
                       expr.dtype)
    return run


def _binary(fn):
    def run(expr, kids, n):
        a, b = kids
        out = [None if (x is None or y is None) else fn(expr, x, y)
               for x, y in zip(a.data, b.data)]
        return HostCol(out, expr.dtype)
    return run


def _wrap_int(dtype: T.DataType, v: int) -> int:
    bits = {T.ByteType: 8, T.ShortType: 16, T.IntegerType: 32, T.LongType: 64}
    for cls, b in bits.items():
        if isinstance(dtype, cls):
            m = 1 << b
            v = v & (m - 1)
            return v - m if v >= (m >> 1) else v
    return v


def _num(expr, x, y, op):
    dt = expr.dtype
    if isinstance(dt, T.IntegralType):
        return _wrap_int(dt, op(int(x), int(y)))
    r = op(float(x), float(y))
    if isinstance(dt, T.FloatType):
        r = float(np.float32(r))
    return r


# ---- arithmetic ------------------------------------------------------------

def _div(expr, kids, n):
    a, b = kids
    out = []
    for x, y in zip(a.data, b.data):
        if x is None or y is None or y == 0:
            out.append(None)  # Spark: divide by zero → null
        else:
            out.append(float(x) / float(y))
    return HostCol(out, expr.dtype)


def _intdiv(expr, kids, n):
    a, b = kids
    out = []
    for x, y in zip(a.data, b.data):
        if x is None or y is None or y == 0:
            out.append(None)
        else:
            q = abs(int(x)) // abs(int(y))
            out.append(_wrap_int(T.LongType(), -q if (x < 0) != (y < 0) else q))
    return HostCol(out, expr.dtype)


def _rem(expr, kids, n):
    a, b = kids
    out = []
    for x, y in zip(a.data, b.data):
        if x is None or y is None or y == 0:
            out.append(None)
        else:
            r = math.fmod(float(x), float(y)) if isinstance(
                expr.dtype, T.FractionalType) else int(math.fmod(int(x), int(y)))
            if isinstance(expr.dtype, T.FloatType):
                r = float(np.float32(r))
            out.append(r)
    return HostCol(out, expr.dtype)


def _pmod(expr, kids, n):
    a, b = kids
    out = []
    for x, y in zip(a.data, b.data):
        if x is None or y is None or y == 0:
            out.append(None)
        elif isinstance(expr.dtype, T.FractionalType):
            r = math.fmod(float(x), float(y))
            if r != 0 and (r < 0) != (float(y) < 0):
                r += float(y)
            out.append(float(np.float32(r)) if isinstance(expr.dtype, T.FloatType)
                       else r)
        else:
            r = int(math.fmod(int(x), int(y)))
            if r != 0 and (r < 0) != (y < 0):
                r += int(y)
            out.append(_wrap_int(expr.dtype, r))
    return HostCol(out, expr.dtype)


# ---- comparisons (Spark ordering: NaN > everything, NaN == NaN) ------------

def _cmp_key(v):
    if isinstance(v, float) and math.isnan(v):
        return (1, 0.0)
    return (0, v)


def _compare(expr, x, y, op):
    if isinstance(x, float) or isinstance(y, float):
        kx, ky = _cmp_key(float(x)), _cmp_key(float(y))
        return op((kx > ky) - (kx < ky), 0)
    if isinstance(x, bool) or isinstance(y, bool):
        x, y = int(x), int(y)
    return op((x > y) - (x < y), 0)


def _and(expr, kids, n):
    a, b = kids
    out = []
    for x, y in zip(a.data, b.data):
        if x is False or y is False:
            out.append(False)
        elif x is None or y is None:
            out.append(None)
        else:
            out.append(True)
    return HostCol(out, T.BOOLEAN)


def _or(expr, kids, n):
    a, b = kids
    out = []
    for x, y in zip(a.data, b.data):
        if x is True or y is True:
            out.append(True)
        elif x is None or y is None:
            out.append(None)
        else:
            out.append(False)
    return HostCol(out, T.BOOLEAN)


def _in(expr, kids, n):
    col = kids[0]
    vals = list(expr.values)  # In holds a literal python list, not child exprs
    has_null = any(w is None for w in vals)
    non_null = [w for w in vals if w is not None]
    out = []
    for v in col.data:
        if v is None:
            out.append(None)
        elif any(_compare(expr, v, w, lambda c, _: c == 0) for w in non_null):
            out.append(True)
        elif has_null:
            out.append(None)
        else:
            out.append(False)
    return HostCol(out, T.BOOLEAN)


# ---- null / conditional ----------------------------------------------------

def _if(expr, kids, n):
    p, a, b = kids
    return HostCol([x if c is True else y
                    for c, x, y in zip(p.data, a.data, b.data)], expr.dtype)


def _casewhen(expr, kids, n):
    nb = len(expr.branches)
    out = []
    for i in range(n):
        val = kids[2 * nb].data[i] if expr.else_value is not None else None
        for bi in range(nb):
            if kids[2 * bi].data[i] is True:
                val = kids[2 * bi + 1].data[i]
                break
        out.append(val)
    return HostCol(out, expr.dtype)


def _coalesce(expr, kids, n):
    out = []
    for i in range(n):
        val = None
        for k in kids:
            if k.data[i] is not None:
                val = k.data[i]
                break
        out.append(val)
    return HostCol(out, expr.dtype)


# ---- strings ---------------------------------------------------------------

def _substring(expr, kids, n):
    from spark_rapids_tpu.ops.strings import java_substring
    s, pos, ln = kids
    out = []
    for v, p, l in zip(s.data, pos.data, ln.data):
        out.append(None if (v is None or p is None or l is None)
                   else java_substring(v, p, l))
    return HostCol(out, T.STRING)


def _like(expr, kids, n):
    from spark_rapids_tpu.ops.strings import like_to_regex
    s, p = kids
    out = []
    for v, pat in zip(s.data, p.data):
        if v is None or pat is None:
            out.append(None)
        else:
            out.append(re.fullmatch(like_to_regex(pat), v, re.DOTALL) is not None)
    return HostCol(out, T.BOOLEAN)


def _concat(expr, kids, n):
    out = []
    for i in range(n):
        parts = [k.data[i] for k in kids]
        out.append(None if any(p is None for p in parts) else "".join(parts))
    return HostCol(out, T.STRING)


# ---- datetime (days since epoch for DateType; micros for TimestampType) ----

def _as_date(v) -> datetime.date:
    return datetime.date(1970, 1, 1) + datetime.timedelta(days=int(v))


def _date_part(expr, kids, n):
    (a,) = kids
    fn = {
        DT.Year: lambda d: d.year, DT.Month: lambda d: d.month,
        DT.DayOfMonth: lambda d: d.day,
        DT.DayOfWeek: lambda d: (d.isoweekday() % 7) + 1,
        DT.WeekDay: lambda d: d.weekday(),
        DT.DayOfYear: lambda d: d.timetuple().tm_yday,
        DT.Quarter: lambda d: (d.month - 1) // 3 + 1,
    }[type(expr)]
    return HostCol([None if v is None else fn(_as_date(v)) for v in a.data],
                   expr.dtype)


def _time_part(expr, kids, n):
    (a,) = kids
    out = []
    for v in a.data:
        if v is None:
            out.append(None)
            continue
        secs = (int(v) // 1_000_000) % 86400
        if isinstance(expr, DT.Hour):
            out.append(secs // 3600)
        elif isinstance(expr, DT.Minute):
            out.append((secs // 60) % 60)
        else:
            out.append(secs % 60)
    return HostCol(out, expr.dtype)


# ---- cast ------------------------------------------------------------------

def _host_cast(expr, kids, n):
    (a,) = kids
    src, dst = a.dtype, expr.dtype
    out = []
    for v in a.data:
        out.append(None if v is None else _cast_one(v, src, dst, expr))
    return HostCol(out, dst)


def _cast_one(v, src, dst, expr):
    if isinstance(dst, T.StringType):
        if isinstance(src, T.BooleanType):
            return "true" if v else "false"
        if isinstance(src, T.FloatType) or isinstance(src, T.DoubleType):
            return _spark_double_str(float(v), isinstance(src, T.FloatType))
        if isinstance(src, T.DateType):
            return _as_date(v).isoformat()
        if isinstance(src, T.TimestampType):
            dt = (datetime.datetime(1970, 1, 1)
                  + datetime.timedelta(microseconds=int(v)))
            s = dt.strftime("%Y-%m-%d %H:%M:%S")
            if dt.microsecond:
                s += (".%06d" % dt.microsecond).rstrip("0")
            return s
        return str(v)
    if isinstance(dst, T.BooleanType):
        if isinstance(src, T.StringType):
            lv = v.strip().lower()
            if lv in ("t", "true", "y", "yes", "1"):
                return True
            if lv in ("f", "false", "n", "no", "0"):
                return False
            return None
        return bool(v) if not (isinstance(v, float) and math.isnan(v)) else True
    if isinstance(dst, T.IntegralType):
        if isinstance(src, T.StringType):
            try:
                iv = int(float(v.strip())) if "." in v or "e" in v.lower() \
                    else int(v.strip())
            except ValueError:
                return None
            return iv if iv == _wrap_int(dst, iv) else None
        if isinstance(src, T.FractionalType):
            if math.isnan(v) or math.isinf(v):
                return 0 if math.isnan(v) else _clamp_int(dst, v)
            return _clamp_int(dst, v)
        return _wrap_int(dst, int(v))
    if isinstance(dst, (T.FloatType, T.DoubleType)):
        if isinstance(src, T.StringType):
            try:
                f = float(v.strip())
            except ValueError:
                return None
        else:
            f = float(v)
        return float(np.float32(f)) if isinstance(dst, T.FloatType) else f
    if isinstance(dst, T.DateType) and isinstance(src, T.StringType):
        try:
            d = datetime.date.fromisoformat(v.strip()[:10])
            return (d - datetime.date(1970, 1, 1)).days
        except ValueError:
            return None
    if isinstance(dst, T.TimestampType) and isinstance(src, T.DateType):
        return int(v) * 86_400_000_000
    if isinstance(dst, T.DateType) and isinstance(src, T.TimestampType):
        return int(v) // 86_400_000_000 - (1 if int(v) % 86_400_000_000 < 0
                                           and int(v) < 0 else 0)
    return v


def _clamp_int(dst, f):
    lims = {T.ByteType: (-128, 127), T.ShortType: (-32768, 32767),
            T.IntegerType: (-2**31, 2**31 - 1), T.LongType: (-2**63, 2**63 - 1)}
    for cls, (lo, hi) in lims.items():
        if isinstance(dst, cls):
            if math.isinf(f):
                return lo if f < 0 else hi
            return max(lo, min(hi, int(f)))
    return int(f)


def _spark_double_str(d, is_float):
    if math.isnan(d):
        return "NaN"
    if math.isinf(d):
        return "Infinity" if d > 0 else "-Infinity"
    # Java Double.toString-ish: shortest repr, scientific beyond 1e7/1e-3
    if d == int(d) and abs(d) < 1e7:
        return f"{d:.1f}"
    r = repr(float(np.float32(d))) if is_float else repr(d)
    return r


# ---- dispatch table --------------------------------------------------------

_DISPATCH = {
    A.Add: _binary(lambda e, x, y: _num(e, x, y, lambda a, b: a + b)),
    A.Subtract: _binary(lambda e, x, y: _num(e, x, y, lambda a, b: a - b)),
    A.Multiply: _binary(lambda e, x, y: _num(e, x, y, lambda a, b: a * b)),
    A.Divide: _div,
    A.IntegralDivide: _intdiv,
    A.Remainder: _rem,
    A.Pmod: _pmod,
    A.UnaryMinus: _unary(lambda e, v: _wrap_int(e.dtype, -int(v))
                         if isinstance(e.dtype, T.IntegralType) else -v),
    A.Abs: _unary(lambda e, v: _wrap_int(e.dtype, abs(int(v)))
                  if isinstance(e.dtype, T.IntegralType) else abs(v)),
    P.EqualTo: _binary(lambda e, x, y: _compare(e, x, y, lambda c, _: c == 0)),
    P.NotEqual: _binary(lambda e, x, y: _compare(e, x, y, lambda c, _: c != 0)),
    P.LessThan: _binary(lambda e, x, y: _compare(e, x, y, lambda c, _: c < 0)),
    P.LessThanOrEqual: _binary(
        lambda e, x, y: _compare(e, x, y, lambda c, _: c <= 0)),
    P.GreaterThan: _binary(lambda e, x, y: _compare(e, x, y, lambda c, _: c > 0)),
    P.GreaterThanOrEqual: _binary(
        lambda e, x, y: _compare(e, x, y, lambda c, _: c >= 0)),
    P.EqualNullSafe: lambda e, kids, n: HostCol(
        [True if (x is None and y is None)
         else False if (x is None or y is None)
         else _compare(e, x, y, lambda c, _: c == 0)
         for x, y in zip(kids[0].data, kids[1].data)], T.BOOLEAN),
    P.And: _and,
    P.Or: _or,
    P.Not: _unary(lambda e, v: not v),
    P.In: _in,
    N.IsNull: lambda e, kids, n: HostCol(
        [v is None for v in kids[0].data], T.BOOLEAN),
    N.IsNotNull: lambda e, kids, n: HostCol(
        [v is not None for v in kids[0].data], T.BOOLEAN),
    N.IsNaN: lambda e, kids, n: HostCol(
        [False if v is None else (isinstance(v, float) and math.isnan(v))
         for v in kids[0].data], T.BOOLEAN),
    N.Coalesce: _coalesce,
    N.NaNvl: _binary(lambda e, x, y: y if math.isnan(float(x)) else x),
    C.If: _if,
    C.CaseWhen: _casewhen,
    MM.Sqrt: _unary(lambda e, v: math.sqrt(v) if v >= 0 else float("nan")),
    MM.Exp: _unary(lambda e, v: math.exp(v)),
    MM.Sin: _unary(lambda e, v: math.sin(v)),
    MM.Cos: _unary(lambda e, v: math.cos(v)),
    MM.Tan: _unary(lambda e, v: math.tan(v)),
    MM.Floor: _unary(lambda e, v: int(math.floor(v))),
    MM.Ceil: _unary(lambda e, v: int(math.ceil(v))),
    MM.Pow: _binary(lambda e, x, y: float(x) ** float(y)),
    MM.Log: _unary(lambda e, v: math.log(v) if v > 0 else None),
    MM.Log2: _unary(lambda e, v: math.log2(v) if v > 0 else None),
    MM.Log10: _unary(lambda e, v: math.log10(v) if v > 0 else None),
    MM.Log1p: _unary(lambda e, v: math.log1p(v) if v > -1 else None),
    S.Upper: _unary(lambda e, v: v.upper()),
    S.Lower: _unary(lambda e, v: v.lower()),
    S.Length: _unary(lambda e, v: len(v)),
    S.Trim: _unary(lambda e, v: v.strip(" ")),
    S.LTrim: _unary(lambda e, v: v.lstrip(" ")),
    S.RTrim: _unary(lambda e, v: v.rstrip(" ")),
    S.Reverse: _unary(lambda e, v: v[::-1]),
    S.StartsWith: _binary(lambda e, x, y: x.startswith(y)),
    S.EndsWith: _binary(lambda e, x, y: x.endswith(y)),
    S.Contains: _binary(lambda e, x, y: y in x),
    S.Like: _like,
    S.Concat: _concat,
    S.Substring: _substring,
    S.StringReplace: lambda e, kids, n: HostCol(
        [None if (s is None or f is None or r is None)
         else (s.replace(f, r) if f else s)
         for s, f, r in zip(kids[0].data, kids[1].data, kids[2].data)], T.STRING),
    DT.Year: _date_part, DT.Month: _date_part, DT.DayOfMonth: _date_part,
    DT.DayOfWeek: _date_part, DT.WeekDay: _date_part, DT.DayOfYear: _date_part,
    DT.Quarter: _date_part,
    DT.Hour: _time_part, DT.Minute: _time_part, DT.Second: _time_part,
    DT.DateAdd: _binary(lambda e, x, y: int(x) + (int(y) if not isinstance(
        e, DT.DateSub) else -int(y))),
    DT.DateDiff: _binary(lambda e, x, y: int(x) - int(y)),
    Cast: _host_cast,
}
