"""Host (CPU) expression evaluator — the independent oracle / fallback path.

Reference analogy: in the reference, unsupported nodes simply stay as Spark CPU
execs and Spark's own interpreter runs them (SURVEY.md §1 L3). Our framework is
standalone, so the host path is an independent NumPy implementation of the same
expression semantics. It doubles as the CPU side of the equivalence test harness
(reference SparkQueryCompareTestSuite.scala:183 withCpuSparkSession).

Deliberately NOT jax: a second implementation that can disagree with the device
path is exactly what makes ring-2 tests meaningful.
"""

from __future__ import annotations

import datetime
import math
import re

import numpy as np
import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import core as E
from spark_rapids_tpu.expr import arithmetic as A
from spark_rapids_tpu.expr import predicates as P
from spark_rapids_tpu.expr import nullexprs as N
from spark_rapids_tpu.expr import conditional as C
from spark_rapids_tpu.expr import mathexprs as MM
from spark_rapids_tpu.expr import strings as S
from spark_rapids_tpu.expr import datetime as DT
from spark_rapids_tpu.expr.cast import Cast


class HostCol:
    """Host column: python list of values with None for null (exactness over speed —
    this is the oracle, not the fast path)."""

    __slots__ = ("data", "dtype")

    def __init__(self, data: list, dtype: T.DataType):
        self.data = data
        self.dtype = dtype

    def __len__(self):
        return len(self.data)

    @staticmethod
    def from_arrow(arr, dtype: T.DataType) -> "HostCol":
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        data = arr.to_pylist()
        if isinstance(dtype, T.FloatType):
            data = [None if v is None else float(np.float32(v)) for v in data]
        elif isinstance(dtype, T.DateType):
            # internal convention: days since epoch (module docstring above)
            data = [None if v is None else
                    (v - datetime.date(1970, 1, 1)).days
                    if isinstance(v, datetime.date) else int(v)
                    for v in data]
        elif isinstance(dtype, T.TimestampType):
            def _us(v):
                td = v.replace(tzinfo=None) - datetime.datetime(1970, 1, 1)
                return (td.days * 86_400 + td.seconds) * 1_000_000 \
                    + td.microseconds
            data = [None if v is None else
                    _us(v) if isinstance(v, datetime.datetime) else int(v)
                    for v in data]
        elif isinstance(dtype, T.DecimalType):
            import decimal as _dec
            # internal convention: unscaled int64 (types.py DECIMAL64)
            data = [None if v is None else
                    int(v.scaleb(dtype.scale)) if isinstance(v, _dec.Decimal)
                    else int(v)
                    for v in data]
        return HostCol(data, dtype)

    def to_arrow(self):
        if isinstance(self.dtype, T.DecimalType):
            import decimal as _dec
            vals = [None if v is None else
                    _dec.Decimal(int(v)).scaleb(-self.dtype.scale)
                    for v in self.data]
            return pa.array(vals, type=T.to_arrow_type(self.dtype))
        return pa.array(self.data, type=T.to_arrow_type(self.dtype))


def table_schema(tbl: pa.Table) -> T.StructType:
    return T.StructType([
        T.StructField(f.name, T.from_arrow_type(f.type), True) for f in tbl.schema])


def eval_host(expr: E.Expression, tbl: pa.Table) -> HostCol:
    """Evaluate an expression tree against a pyarrow table, row-at-a-time."""
    n = tbl.num_rows
    if isinstance(expr, E.Alias):
        return eval_host(expr.child, tbl)
    if isinstance(expr, E.AttributeReference):
        idx = tbl.schema.get_field_index(expr.name)
        return HostCol.from_arrow(tbl.column(idx), expr.dtype)
    if isinstance(expr, E.BoundReference):
        return HostCol.from_arrow(tbl.column(expr.ordinal), expr.dtype)
    if isinstance(expr, E.Literal):
        return HostCol([expr.value] * n, expr.dtype)

    if hasattr(expr, "eval_arrow"):  # PythonUDF: worker-pool arrow exchange
        child_cols = [eval_host(c, tbl) for c in expr.children]
        child_tbl = pa.Table.from_arrays(
            [pa.array(c.data, T.to_arrow_type(c.dtype)) for c in child_cols],
            names=[f"a{i}" for i in range(len(child_cols))])
        out = expr.eval_arrow(child_tbl)
        return HostCol.from_arrow(out, expr.dtype)

    kids = [eval_host(c, tbl) for c in getattr(expr, "children", [])]
    fn = _DISPATCH.get(type(expr))
    if fn is None:
        for klass, f in _DISPATCH.items():
            if isinstance(expr, klass):
                fn = f
                break
    if fn is None:
        raise NotImplementedError(f"host eval for {type(expr).__name__}")
    return fn(expr, kids, n)


# ---- helpers ---------------------------------------------------------------

def _unary(fn):
    def run(expr, kids, n):
        (a,) = kids
        return HostCol([None if v is None else fn(expr, v) for v in a.data],
                       expr.dtype)
    return run


def _binary(fn):
    def run(expr, kids, n):
        a, b = kids
        out = [None if (x is None or y is None) else fn(expr, x, y)
               for x, y in zip(a.data, b.data)]
        return HostCol(out, expr.dtype)
    return run


def _wrap_int(dtype: T.DataType, v: int) -> int:
    bits = {T.ByteType: 8, T.ShortType: 16, T.IntegerType: 32, T.LongType: 64}
    for cls, b in bits.items():
        if isinstance(dtype, cls):
            m = 1 << b
            v = v & (m - 1)
            return v - m if v >= (m >> 1) else v
    return v


def _num(expr, x, y, op):
    dt = expr.dtype
    if isinstance(dt, T.IntegralType):
        return _wrap_int(dt, op(int(x), int(y)))
    if isinstance(dt, T.DecimalType):
        # add/subtract: rescale both unscaled ints to the (max) result
        # scale, then the integer op is exact — same as the device's
        # cast-to-promoted-then-add. Multiply has its own column fn (_mul).
        from spark_rapids_tpu.expr.arithmetic import _as_dec
        d1 = _as_dec(expr.left.dtype)
        d2 = _as_dec(expr.right.dtype)
        return op(int(x) * 10 ** (dt.scale - d1.scale),
                  int(y) * 10 ** (dt.scale - d2.scale))
    r = op(_fval(x, expr.left.dtype), _fval(y, expr.right.dtype))
    if isinstance(dt, T.FloatType):
        r = float(np.float32(r))
    return r


def _fval(v, dt) -> float:
    """Numeric value as float — decimal host cols carry UNSCALED ints."""
    if isinstance(dt, T.DecimalType):
        return float(int(v)) / (10.0 ** dt.scale)
    return float(v)


def _rhu(q: float):
    return int(math.floor(q + 0.5) if q >= 0 else math.ceil(q - 0.5))


def _mul(expr, kids, n):
    """Multiply; the decimal path mirrors the device (arithmetic.Multiply):
    same exact-int64 / float64 split, HALF_UP rescale, overflow → null.
    Host decimal columns carry UNSCALED ints (same as the device)."""
    a, b = kids
    dt = expr.dtype
    if not isinstance(dt, T.DecimalType):
        return _binary(lambda e, x, y: _num(e, x, y,
                                            lambda p, q: p * q))(expr, kids,
                                                                 n)
    from spark_rapids_tpu.expr.arithmetic import _as_dec
    d1 = _as_dec(expr.left.dtype)
    d2 = _as_dec(expr.right.dtype)
    drop = d1.scale + d2.scale - dt.scale
    exact = d1.precision + d2.precision + 1 <= 18
    div = 10 ** drop
    bound = 10 ** dt.precision
    out = []
    for x, y in zip(a.data, b.data):
        if x is None or y is None:
            out.append(None)
            continue
        if exact:
            prod = int(x) * int(y)
            if drop:
                q = (abs(prod) + div // 2) // div
                prod = -q if prod < 0 else q
        else:
            prod = _rhu(float(int(x)) * float(int(y)) / (10.0 ** drop))
        out.append(None if abs(prod) >= bound else prod)
    return HostCol(out, dt)


# ---- arithmetic ------------------------------------------------------------

def _div(expr, kids, n):
    a, b = kids
    dt = expr.dtype
    out = []
    if isinstance(dt, T.DecimalType):
        # mirror of the device decimal divide (same float64 rounding);
        # host decimal columns carry unscaled ints
        from spark_rapids_tpu.expr.arithmetic import _as_dec
        d1 = _as_dec(expr.left.dtype)
        d2 = _as_dec(expr.right.dtype)
        k = dt.scale + d2.scale - d1.scale
        for x, y in zip(a.data, b.data):
            if x is None or y is None or y == 0:
                out.append(None)
                continue
            vals = _rhu(float(int(x)) / float(int(y)) * (10.0 ** k))
            out.append(None if abs(vals) >= 10 ** dt.precision else vals)
        return HostCol(out, dt)
    for x, y in zip(a.data, b.data):
        if x is None or y is None or y == 0:
            out.append(None)  # Spark: divide by zero → null
        else:
            out.append(_fval(x, expr.left.dtype)
                       / _fval(y, expr.right.dtype))
    return HostCol(out, expr.dtype)


def _intdiv(expr, kids, n):
    a, b = kids
    out = []
    for x, y in zip(a.data, b.data):
        if x is None or y is None or y == 0:
            out.append(None)
        else:
            q = abs(int(x)) // abs(int(y))
            out.append(_wrap_int(T.LongType(), -q if (x < 0) != (y < 0) else q))
    return HostCol(out, expr.dtype)


def _rem(expr, kids, n):
    a, b = kids
    out = []
    for x, y in zip(a.data, b.data):
        if x is None or y is None or y == 0:
            out.append(None)
        else:
            r = math.fmod(float(x), float(y)) if isinstance(
                expr.dtype, T.FractionalType) else int(math.fmod(int(x), int(y)))
            if isinstance(expr.dtype, T.FloatType):
                r = float(np.float32(r))
            out.append(r)
    return HostCol(out, expr.dtype)


def _pmod(expr, kids, n):
    a, b = kids
    out = []
    for x, y in zip(a.data, b.data):
        if x is None or y is None or y == 0:
            out.append(None)
        elif isinstance(expr.dtype, T.FractionalType):
            r = math.fmod(float(x), float(y))
            if r != 0 and (r < 0) != (float(y) < 0):
                r += float(y)
            out.append(float(np.float32(r)) if isinstance(expr.dtype, T.FloatType)
                       else r)
        else:
            r = int(math.fmod(int(x), int(y)))
            if r != 0 and (r < 0) != (y < 0):
                r += int(y)
            out.append(_wrap_int(expr.dtype, r))
    return HostCol(out, expr.dtype)


# ---- comparisons (Spark ordering: NaN > everything, NaN == NaN) ------------

def _cmp_key(v):
    if isinstance(v, float) and math.isnan(v):
        return (1, 0.0)
    return (0, v)


def _compare(expr, x, y, op):
    if isinstance(x, float) or isinstance(y, float):
        kx, ky = _cmp_key(float(x)), _cmp_key(float(y))
        return op((kx > ky) - (kx < ky), 0)
    if isinstance(x, bool) or isinstance(y, bool):
        x, y = int(x), int(y)
    return op((x > y) - (x < y), 0)


def _and(expr, kids, n):
    a, b = kids
    out = []
    for x, y in zip(a.data, b.data):
        if x is False or y is False:
            out.append(False)
        elif x is None or y is None:
            out.append(None)
        else:
            out.append(True)
    return HostCol(out, T.BOOLEAN)


def _or(expr, kids, n):
    a, b = kids
    out = []
    for x, y in zip(a.data, b.data):
        if x is True or y is True:
            out.append(True)
        elif x is None or y is None:
            out.append(None)
        else:
            out.append(False)
    return HostCol(out, T.BOOLEAN)


def _in(expr, kids, n):
    col = kids[0]
    vals = list(expr.values)  # In holds a literal python list, not child exprs
    has_null = any(w is None for w in vals)
    non_null = [w for w in vals if w is not None]
    out = []
    for v in col.data:
        if v is None:
            out.append(None)
        elif any(_compare(expr, v, w, lambda c, _: c == 0) for w in non_null):
            out.append(True)
        elif has_null:
            out.append(None)
        else:
            out.append(False)
    return HostCol(out, T.BOOLEAN)


# ---- null / conditional ----------------------------------------------------

def _if(expr, kids, n):
    p, a, b = kids
    return HostCol([x if c is True else y
                    for c, x, y in zip(p.data, a.data, b.data)], expr.dtype)


def _casewhen(expr, kids, n):
    nb = len(expr.branches)
    out = []
    for i in range(n):
        val = kids[2 * nb].data[i] if expr.else_value is not None else None
        for bi in range(nb):
            if kids[2 * bi].data[i] is True:
                val = kids[2 * bi + 1].data[i]
                break
        out.append(val)
    return HostCol(out, expr.dtype)


def _coalesce(expr, kids, n):
    out = []
    for i in range(n):
        val = None
        for k in kids:
            if k.data[i] is not None:
                val = k.data[i]
                break
        out.append(val)
    return HostCol(out, expr.dtype)


# ---- strings ---------------------------------------------------------------

def _substring(expr, kids, n):
    from spark_rapids_tpu.ops.strings import java_substring
    if len(kids) == 2:      # substring(s, pos): to end of string
        s, pos = kids
        ln = HostCol([2**31 - 1] * n, T.INT)
    else:
        s, pos, ln = kids
    out = []
    for v, p, l in zip(s.data, pos.data, ln.data):
        out.append(None if (v is None or p is None or l is None)
                   else java_substring(v, p, l))
    return HostCol(out, T.STRING)


def _like(expr, kids, n):
    from spark_rapids_tpu.ops.strings import like_to_regex
    s, p = kids
    out = []
    for v, pat in zip(s.data, p.data):
        if v is None or pat is None:
            out.append(None)
        else:
            out.append(re.fullmatch(like_to_regex(pat), v, re.DOTALL) is not None)
    return HostCol(out, T.BOOLEAN)


def _concat(expr, kids, n):
    out = []
    for i in range(n):
        parts = [k.data[i] for k in kids]
        out.append(None if any(p is None for p in parts) else "".join(parts))
    return HostCol(out, T.STRING)


# ---- datetime (days since epoch for DateType; micros for TimestampType) ----

def _as_date(v) -> datetime.date:
    return datetime.date(1970, 1, 1) + datetime.timedelta(days=int(v))


def _date_part(expr, kids, n):
    (a,) = kids
    fn = {
        DT.Year: lambda d: d.year, DT.Month: lambda d: d.month,
        DT.DayOfMonth: lambda d: d.day,
        DT.DayOfWeek: lambda d: (d.isoweekday() % 7) + 1,
        DT.WeekDay: lambda d: d.weekday(),
        DT.DayOfYear: lambda d: d.timetuple().tm_yday,
        DT.Quarter: lambda d: (d.month - 1) // 3 + 1,
    }[type(expr)]
    return HostCol([None if v is None else fn(_as_date(v)) for v in a.data],
                   expr.dtype)


def _time_part(expr, kids, n):
    (a,) = kids
    out = []
    for v in a.data:
        if v is None:
            out.append(None)
            continue
        secs = (int(v) // 1_000_000) % 86400
        if isinstance(expr, DT.Hour):
            out.append(secs // 3600)
        elif isinstance(expr, DT.Minute):
            out.append((secs // 60) % 60)
        else:
            out.append(secs % 60)
    return HostCol(out, expr.dtype)


# ---- cast ------------------------------------------------------------------

def _host_cast(expr, kids, n):
    (a,) = kids
    src, dst = a.dtype, expr.dtype
    out = []
    for v in a.data:
        out.append(None if v is None else _cast_one(v, src, dst, expr))
    return HostCol(out, dst)


def _int_bounds(dst):
    bits = {T.ByteType: 8, T.ShortType: 16, T.IntegerType: 32, T.LongType: 64}
    b = next(n for cls, n in bits.items() if isinstance(dst, cls))
    return -(1 << (b - 1)), (1 << (b - 1)) - 1


def _cast_decimal_one(v, src, dst):
    """Mirror of the device _cast_decimal (expr/cast.py:97): overflow → null,
    truncate-toward-zero to ints, HALF_UP on scale reduction."""
    if isinstance(src, T.DecimalType) and isinstance(dst, T.DecimalType):
        ds = dst.scale - src.scale
        if ds >= 0:
            out = int(v) * (10 ** ds)
        else:
            div = 10 ** (-ds)
            mag = abs(int(v))
            qm, rm = divmod(mag, div)
            qm += (2 * rm >= div)
            out = -qm if v < 0 else qm
        return out if abs(out) < 10 ** dst.precision else None
    if isinstance(src, T.IntegralType) and isinstance(dst, T.DecimalType):
        out = int(v) * (10 ** dst.scale)
        return out if abs(out) < 10 ** dst.precision else None
    if isinstance(src, T.DecimalType) and isinstance(dst, T.IntegralType):
        q = abs(int(v)) // (10 ** src.scale)  # truncate toward zero
        q = -q if v < 0 else q
        lo, hi = _int_bounds(dst)
        return q if lo <= q <= hi else None
    if isinstance(src, T.DecimalType) and isinstance(dst, (T.FloatType,
                                                           T.DoubleType)):
        f = int(v) / (10 ** src.scale)
        return float(np.float32(f)) if isinstance(dst, T.FloatType) else f
    if isinstance(src, (T.FloatType, T.DoubleType)) and \
            isinstance(dst, T.DecimalType):
        scaled = float(v) * (10 ** dst.scale)
        if math.isnan(scaled) or math.isinf(scaled):
            return None
        mag = math.floor(abs(scaled) + 0.5)
        out = -mag if scaled < 0 else mag
        return out if abs(out) < 10 ** dst.precision else None
    if isinstance(src, T.DecimalType) and isinstance(dst, T.StringType):
        import decimal as _dec
        return str(_dec.Decimal(int(v)).scaleb(-src.scale))
    if isinstance(src, T.DecimalType) and isinstance(dst, T.BooleanType):
        return int(v) != 0
    raise NotImplementedError(f"host decimal cast {src} -> {dst}")


def _cast_one(v, src, dst, expr):
    if isinstance(src, T.DecimalType) or isinstance(dst, T.DecimalType):
        return _cast_decimal_one(v, src, dst)
    if isinstance(dst, T.StringType):
        if isinstance(src, T.BooleanType):
            return "true" if v else "false"
        if isinstance(src, T.FloatType) or isinstance(src, T.DoubleType):
            return _spark_double_str(float(v), isinstance(src, T.FloatType))
        if isinstance(src, T.DateType):
            return _as_date(v).isoformat()
        if isinstance(src, T.TimestampType):
            dt = (datetime.datetime(1970, 1, 1)
                  + datetime.timedelta(microseconds=int(v)))
            s = dt.strftime("%Y-%m-%d %H:%M:%S")
            if dt.microsecond:
                s += (".%06d" % dt.microsecond).rstrip("0")
            return s
        return str(v)
    if isinstance(dst, T.BooleanType):
        if isinstance(src, T.StringType):
            lv = v.strip().lower()
            if lv in ("t", "true", "y", "yes", "1"):
                return True
            if lv in ("f", "false", "n", "no", "0"):
                return False
            return None
        return bool(v) if not (isinstance(v, float) and math.isnan(v)) else True
    if isinstance(dst, T.IntegralType):
        if isinstance(src, T.StringType):
            try:
                iv = int(float(v.strip())) if "." in v or "e" in v.lower() \
                    else int(v.strip())
            except ValueError:
                return None
            return iv if iv == _wrap_int(dst, iv) else None
        if isinstance(src, T.FractionalType):
            if math.isnan(v) or math.isinf(v):
                return 0 if math.isnan(v) else _clamp_int(dst, v)
            return _clamp_int(dst, v)
        return _wrap_int(dst, int(v))
    if isinstance(dst, (T.FloatType, T.DoubleType)):
        if isinstance(src, T.StringType):
            try:
                f = float(v.strip())
            except ValueError:
                return None
        else:
            f = float(v)
        return float(np.float32(f)) if isinstance(dst, T.FloatType) else f
    if isinstance(dst, T.DateType) and isinstance(src, T.StringType):
        try:
            d = datetime.date.fromisoformat(v.strip()[:10])
            return (d - datetime.date(1970, 1, 1)).days
        except ValueError:
            return None
    if isinstance(dst, T.TimestampType) and isinstance(src, T.StringType):
        from spark_rapids_tpu.expr.cast import _parse_timestamp
        return _parse_timestamp(v)
    if isinstance(dst, T.TimestampType) and isinstance(src, T.DateType):
        return int(v) * 86_400_000_000
    if isinstance(dst, T.DateType) and isinstance(src, T.TimestampType):
        return int(v) // 86_400_000_000  # Python // floors, as Spark needs
    return v


def _clamp_int(dst, f):
    lims = {T.ByteType: (-128, 127), T.ShortType: (-32768, 32767),
            T.IntegerType: (-2**31, 2**31 - 1), T.LongType: (-2**63, 2**63 - 1)}
    for cls, (lo, hi) in lims.items():
        if isinstance(dst, cls):
            if math.isinf(f):
                return lo if f < 0 else hi
            return max(lo, min(hi, int(f)))
    return int(f)


def _spark_double_str(d, is_float):
    if math.isnan(d):
        return "NaN"
    if math.isinf(d):
        return "Infinity" if d > 0 else "-Infinity"
    # Java Double.toString-ish: shortest repr, scientific beyond 1e7/1e-3
    if d == int(d) and abs(d) < 1e7:
        return f"{d:.1f}"
    r = repr(float(np.float32(d))) if is_float else repr(d)
    return r


# ---- dispatch table --------------------------------------------------------

_DISPATCH = {
    A.Add: _binary(lambda e, x, y: _num(e, x, y, lambda a, b: a + b)),
    A.Subtract: _binary(lambda e, x, y: _num(e, x, y, lambda a, b: a - b)),
    A.Multiply: _mul,
    A.Divide: _div,
    A.IntegralDivide: _intdiv,
    A.Remainder: _rem,
    A.Pmod: _pmod,
    A.UnaryMinus: _unary(lambda e, v: _wrap_int(e.dtype, -int(v))
                         if isinstance(e.dtype, T.IntegralType) else -v),
    A.Abs: _unary(lambda e, v: _wrap_int(e.dtype, abs(int(v)))
                  if isinstance(e.dtype, T.IntegralType) else abs(v)),
    P.EqualTo: _binary(lambda e, x, y: _compare(e, x, y, lambda c, _: c == 0)),
    P.NotEqual: _binary(lambda e, x, y: _compare(e, x, y, lambda c, _: c != 0)),
    P.LessThan: _binary(lambda e, x, y: _compare(e, x, y, lambda c, _: c < 0)),
    P.LessThanOrEqual: _binary(
        lambda e, x, y: _compare(e, x, y, lambda c, _: c <= 0)),
    P.GreaterThan: _binary(lambda e, x, y: _compare(e, x, y, lambda c, _: c > 0)),
    P.GreaterThanOrEqual: _binary(
        lambda e, x, y: _compare(e, x, y, lambda c, _: c >= 0)),
    P.EqualNullSafe: lambda e, kids, n: HostCol(
        [True if (x is None and y is None)
         else False if (x is None or y is None)
         else _compare(e, x, y, lambda c, _: c == 0)
         for x, y in zip(kids[0].data, kids[1].data)], T.BOOLEAN),
    P.And: _and,
    P.Or: _or,
    P.Not: _unary(lambda e, v: not v),
    P.In: _in,
    N.IsNull: lambda e, kids, n: HostCol(
        [v is None for v in kids[0].data], T.BOOLEAN),
    N.IsNotNull: lambda e, kids, n: HostCol(
        [v is not None for v in kids[0].data], T.BOOLEAN),
    N.IsNaN: lambda e, kids, n: HostCol(
        [False if v is None else (isinstance(v, float) and math.isnan(v))
         for v in kids[0].data], T.BOOLEAN),
    N.Coalesce: _coalesce,
    N.NaNvl: _binary(lambda e, x, y: y if math.isnan(float(x)) else x),
    C.If: _if,
    C.CaseWhen: _casewhen,
    MM.Sqrt: _unary(lambda e, v: math.sqrt(v) if v >= 0 else float("nan")),
    MM.Exp: _unary(lambda e, v: math.exp(v)),
    MM.Sin: _unary(lambda e, v: math.sin(v)),
    MM.Cos: _unary(lambda e, v: math.cos(v)),
    MM.Tan: _unary(lambda e, v: math.tan(v)),
    MM.Floor: _unary(lambda e, v: int(math.floor(v))),
    MM.Ceil: _unary(lambda e, v: int(math.ceil(v))),
    MM.Round: _unary(lambda e, v: _round_half_up(e, v)),
    MM.Pow: _binary(lambda e, x, y: float(x) ** float(y)),
    MM.Log: _unary(lambda e, v: math.log(v) if v > 0 else None),
    MM.Log2: _unary(lambda e, v: math.log2(v) if v > 0 else None),
    MM.Log10: _unary(lambda e, v: math.log10(v) if v > 0 else None),
    MM.Log1p: _unary(lambda e, v: math.log1p(v) if v > -1 else None),
    S.Upper: _unary(lambda e, v: v.upper()),
    S.Lower: _unary(lambda e, v: v.lower()),
    S.Length: _unary(lambda e, v: len(v)),
    S.Trim: _unary(lambda e, v: v.strip(" ")),
    S.LTrim: _unary(lambda e, v: v.lstrip(" ")),
    S.RTrim: _unary(lambda e, v: v.rstrip(" ")),
    S.Reverse: _unary(lambda e, v: v[::-1]),
    S.StartsWith: _binary(lambda e, x, y: x.startswith(y)),
    S.EndsWith: _binary(lambda e, x, y: x.endswith(y)),
    S.Contains: _binary(lambda e, x, y: y in x),
    S.Like: _like,
    S.Concat: _concat,
    S.Substring: _substring,
    S.StringReplace: lambda e, kids, n: HostCol(
        [None if (s is None or f is None or r is None)
         else (s.replace(f, r) if f else s)
         for s, f, r in zip(kids[0].data, kids[1].data, kids[2].data)], T.STRING),
    DT.Year: _date_part, DT.Month: _date_part, DT.DayOfMonth: _date_part,
    DT.DayOfWeek: _date_part, DT.WeekDay: _date_part, DT.DayOfYear: _date_part,
    DT.Quarter: _date_part,
    DT.Hour: _time_part, DT.Minute: _time_part, DT.Second: _time_part,
    DT.DateAdd: _binary(lambda e, x, y: int(x) + (int(y) if not isinstance(
        e, DT.DateSub) else -int(y))),
    DT.DateDiff: _binary(lambda e, x, y: int(x) - int(y)),
    Cast: _host_cast,
}


# ---- round-2 expression surface (bitwise, strings, datetime, misc) ---------

def _shift_host(expr, kids, n):
    import spark_rapids_tpu.expr.arithmetic as _A2
    base_t = expr.children[0].dtype
    is_long = isinstance(base_t, T.LongType)
    width = 63 if is_long else 31
    bits = 64 if is_long else 32
    out = []
    for b, a in zip(kids[0].data, kids[1].data):
        if b is None or a is None:
            out.append(None)
            continue
        b = int(b)
        amt = int(a) & width
        if isinstance(expr, _A2.ShiftLeft):
            v = b << amt
        elif isinstance(expr, _A2.ShiftRightUnsigned):
            v = (b & ((1 << bits) - 1)) >> amt
        else:
            v = b >> amt
        out.append(_wrap_int(expr.dtype, v))
    return HostCol(out, expr.dtype)


def _least_greatest(expr, kids, n):
    import spark_rapids_tpu.expr.conditional as _C2
    greatest = isinstance(expr, _C2.Greatest)

    def key(v):
        if isinstance(v, float) and math.isnan(v):
            return (1, 0.0)
        return (0, v)
    out = []
    for i in range(n):
        vals = [k.data[i] for k in kids if k.data[i] is not None]
        if not vals:
            out.append(None)
        else:
            out.append((max if greatest else min)(vals, key=key))
    return HostCol(out, expr.dtype)


def _concat_ws(expr, kids, n):
    sep = expr.children[0].value
    out = []
    for i in range(n):
        parts = [k.data[i] for k in kids[1:] if k.data[i] is not None]
        out.append(sep.join(parts))
    return HostCol(out, T.STRING)


def _string_fn_host(expr, kids, n):
    args = [c.value for c in expr.children[1:]]
    return HostCol([None if s is None else expr.fn(s, *args)
                    for s in kids[0].data], expr.dtype)


def _locate_host(expr, kids, n):
    p = expr.children[0].value
    st = expr.children[2].value
    out = []
    for s in kids[1].data:
        if s is None or p is None or st is None:
            out.append(None)
        elif st <= 0:
            out.append(0)
        else:
            out.append(s.find(p, st - 1) + 1)
    return HostCol(out, T.INT)


def _regexp_replace_host(expr, kids, n):
    import re as _re
    from spark_rapids_tpu.expr.strings import _java_replacement_to_python
    rx = _re.compile(expr.children[1].value)
    rep = _java_replacement_to_python(expr.children[2].value)
    return HostCol([None if s is None else rx.sub(rep, s)
                    for s in kids[0].data], T.STRING)


def _regexp_extract_host(expr, kids, n):
    import re as _re
    rx = _re.compile(expr.children[1].value)
    idx = expr.children[2].value

    def ext(s):
        m = rx.search(s)
        if m is None:
            return ""
        g = m.group(int(idx))
        return g if g is not None else ""
    return HostCol([None if s is None else ext(s) for s in kids[0].data],
                   T.STRING)


def _unix_ts_host(expr, kids, n):
    src = expr.children[0].dtype
    fmt = expr.children[1].value
    out = []
    for v in kids[0].data:
        if v is None:
            out.append(None)
        elif isinstance(src, T.TimestampType):
            out.append(int(v) // 1_000_000)
        elif isinstance(src, T.DateType):
            out.append(int(v) * 86_400)
        else:
            from spark_rapids_tpu.expr.datetime import java_fmt_to_strftime
            try:
                dt = datetime.datetime.strptime(v, java_fmt_to_strftime(fmt))
                out.append(int((dt - datetime.datetime(1970, 1, 1))
                               .total_seconds()))
            except (ValueError, TypeError):
                out.append(None)
    return HostCol(out, T.LONG)


def _from_unixtime_host(expr, kids, n):
    from spark_rapids_tpu.expr.datetime import java_fmt_to_strftime
    pyfmt = java_fmt_to_strftime(expr.children[1].value)
    out = []
    for v in kids[0].data:
        out.append(None if v is None else
                   (datetime.datetime(1970, 1, 1)
                    + datetime.timedelta(seconds=int(v))).strftime(pyfmt))
    return HostCol(out, T.STRING)


def _date_format_host(expr, kids, n):
    from spark_rapids_tpu.expr.datetime import java_fmt_to_strftime
    pyfmt = java_fmt_to_strftime(expr.children[1].value)
    is_date = isinstance(expr.children[0].dtype, T.DateType)
    out = []
    for v in kids[0].data:
        if v is None:
            out.append(None)
        elif is_date:
            out.append((datetime.date(1970, 1, 1)
                        + datetime.timedelta(days=int(v))).strftime(pyfmt))
        else:
            out.append((datetime.datetime(1970, 1, 1)
                        + datetime.timedelta(microseconds=int(v)))
                       .strftime(pyfmt))
    return HostCol(out, T.STRING)


def _add_months_host(expr, kids, n):
    import calendar
    out = []
    for d, m in zip(kids[0].data, kids[1].data):
        if d is None or m is None:
            out.append(None)
            continue
        date = datetime.date(1970, 1, 1) + datetime.timedelta(days=int(d))
        total = date.year * 12 + (date.month - 1) + int(m)
        y, mo = divmod(total, 12)
        dom = min(date.day, calendar.monthrange(y, mo + 1)[1])
        out.append((datetime.date(y, mo + 1, dom)
                    - datetime.date(1970, 1, 1)).days)
    return HostCol(out, T.DATE)


def _months_between_host(expr, kids, n):
    import calendar
    out = []
    for e_, s_ in zip(kids[0].data, kids[1].data):
        if e_ is None or s_ is None:
            out.append(None)
            continue
        d1 = datetime.date(1970, 1, 1) + datetime.timedelta(days=int(e_))
        d2 = datetime.date(1970, 1, 1) + datetime.timedelta(days=int(s_))
        last1 = d1.day == calendar.monthrange(d1.year, d1.month)[1]
        last2 = d2.day == calendar.monthrange(d2.year, d2.month)[1]
        months = (d1.year - d2.year) * 12 + (d1.month - d2.month)
        frac = 0.0 if (d1.day == d2.day or (last1 and last2)) else \
            (d1.day - d2.day) / 31.0
        v = months + frac
        if expr.round_off:
            v = round(v * 1e8) / 1e8
        out.append(float(v))
    return HostCol(out, T.DOUBLE)


def _trunc_date_host(expr, kids, n):
    lvl = (expr.children[1].value or "").lower()
    out = []
    for d in kids[0].data:
        if d is None:
            out.append(None)
            continue
        date = datetime.date(1970, 1, 1) + datetime.timedelta(days=int(d))
        if lvl in ("year", "yyyy", "yy"):
            t = date.replace(month=1, day=1)
        elif lvl in ("month", "mon", "mm"):
            t = date.replace(day=1)
        elif lvl == "quarter":
            t = date.replace(month=((date.month - 1) // 3) * 3 + 1, day=1)
        elif lvl == "week":
            t = date - datetime.timedelta(days=date.weekday())
        else:
            out.append(None)
            continue
        out.append((t - datetime.date(1970, 1, 1)).days)
    return HostCol(out, T.DATE)


def _murmur3_host(expr, kids, n):
    import struct as _struct
    from spark_rapids_tpu.ops.hashing import (murmur3_int_host,
                                              murmur3_long_host,
                                              murmur3_bytes_host)

    def murmur3_double_host(v, h):
        if v == 0.0:
            v = 0.0  # -0.0 hashes as +0.0 (Spark normalizes)
        bits = _struct.unpack("<q", _struct.pack("<d", v))[0]
        return murmur3_long_host(bits, h)
    out = []
    for i in range(n):
        h = expr.seed
        for k, ch in zip(kids, expr.children):
            v = k.data[i]
            if v is None:
                continue
            dt = ch.dtype
            if isinstance(dt, (T.LongType, T.TimestampType)):
                h = murmur3_long_host(int(v), h)
            elif isinstance(dt, T.DecimalType):
                h = murmur3_long_host(int(v), h)
            elif isinstance(dt, T.DoubleType):
                h = murmur3_double_host(float(v), h)
            elif isinstance(dt, T.FloatType):
                import struct as _struct
                bits = _struct.unpack(
                    "<i", _struct.pack("<f", float(v)))[0]
                h = murmur3_int_host(bits, h)
            elif isinstance(dt, T.StringType):
                h = murmur3_bytes_host(v.encode("utf-8"), h)
            elif isinstance(dt, T.BooleanType):
                h = murmur3_int_host(1 if v else 0, h)
            else:
                h = murmur3_int_host(int(v), h)
        out.append(h)
    return HostCol(out, T.INT)


def _struct_field_host(expr, kids, n):
    # kids[0] holds per-row dicts (from _create_struct_host or arrow structs)
    return HostCol([None if v is None else v.get(expr.field)
                    for v in kids[0].data], expr.dtype)


def _size_host(expr, kids, n):
    return HostCol([-1 if v is None else len(v) for v in kids[0].data], T.INT)


def _get_array_item_host(expr, kids, n):
    out = []
    for arr, i in zip(kids[0].data, kids[1].data):
        if arr is None or i is None or i < 0 or i >= len(arr):
            out.append(None)
        else:
            out.append(arr[int(i)])
    return HostCol(out, expr.dtype)


def _create_map_host(expr, kids, n):
    out = []
    for i in range(n):
        m = {}
        for kc, vc in zip(kids[0::2], kids[1::2]):
            m[kc.data[i]] = vc.data[i]   # later pairs win, Spark map semantics
        out.append(m)
    return HostCol(out, expr.dtype)


def _get_map_value_host(expr, kids, n):
    return HostCol([None if (m is None or k is None) else m.get(k)
                    for m, k in zip(kids[0].data, kids[1].data)], expr.dtype)


def _create_array_host(expr, kids, n):
    return HostCol([[k.data[i] for k in kids] for i in range(n)], expr.dtype)


def _create_struct_host(expr, kids, n):
    names = expr.field_names
    val_kids = kids[1::2]
    return HostCol([{nm: k.data[i] for nm, k in zip(names, val_kids)}
                    for i in range(n)], expr.dtype)


def _ieee_div(a: float, b: float) -> float:
    """IEEE-754 division like the device (x/0 → ±inf, 0/0 → nan), where
    Python float division would raise ZeroDivisionError."""
    return float(np.float64(a) / np.float64(b))


def _at_least_n_host(expr, kids, n):
    out = []
    for i in range(n):
        cnt = 0
        for k in kids:
            v = k.data[i]
            if v is not None and not (isinstance(v, float) and math.isnan(v)):
                cnt += 1
        out.append(cnt >= expr.n)
    return HostCol(out, T.BOOLEAN)


def _element_at_host(expr, kids, n):
    out = []
    strict = getattr(expr, "strict_zero", False)
    for arr, i in zip(kids[0].data, kids[1].data):
        if strict and i == 0:
            # pre-3.4 shim generations (shims/__init__.py)
            raise RuntimeError("SQL array indices start at 1")
        if arr is None or i is None or i == 0:
            out.append(None)
        else:
            j = int(i) - 1 if i > 0 else len(arr) + int(i)
            out.append(arr[j] if 0 <= j < len(arr) else None)
    return HostCol(out, expr.dtype)


def _array_contains_host(expr, kids, n):
    out = []
    for arr, v in zip(kids[0].data, kids[1].data):
        if arr is None or v is None:
            out.append(None)
        elif any(x == v for x in arr if x is not None):
            out.append(True)
        elif any(x is None for x in arr):
            out.append(None)
        else:
            out.append(False)
    return HostCol(out, T.BOOLEAN)


def _jax_udf_host(expr, kids, n):
    """Run the user's jax fn on the host platform over unpadded arrays (the
    oracle mirrors the device contract, minus padding)."""
    import jax.numpy as jnp
    arrs = []
    for k in kids:
        np_dt = T.to_numpy_dtype(k.dtype)
        vals = np.array([v if v is not None else k.dtype.default_value()
                         for v in k.data], dtype=np_dt)
        valid = np.array([v is not None for v in k.data], dtype=bool)
        arrs.append((jnp.asarray(vals), jnp.asarray(valid)))
    if expr.null_aware:
        vals, valid = expr.fn(*arrs)
    else:
        vals = expr.fn(*(v for v, _ in arrs))
        valid = np.ones(n, dtype=bool)
        for _, m in arrs:
            valid = valid & np.asarray(m)
    vals = np.asarray(vals)
    valid = np.asarray(valid)
    rt = expr.return_type
    py = lambda v: (float(v) if isinstance(rt, (T.FloatType, T.DoubleType))
                    else bool(v) if isinstance(rt, T.BooleanType) else int(v))
    return HostCol([py(v) if m else None for v, m in zip(vals, valid)], rt)


def _register_round2():
    import spark_rapids_tpu.expr.arithmetic as A2
    import spark_rapids_tpu.expr.conditional as C2
    import spark_rapids_tpu.expr.strings as S2
    import spark_rapids_tpu.expr.datetime as DT2
    import spark_rapids_tpu.expr.misc as MX
    import spark_rapids_tpu.expr.decimalexprs as DX
    import spark_rapids_tpu.expr.complexexprs as CX
    from spark_rapids_tpu.udf.device_udf import JaxUDF

    _DISPATCH.update({
        JaxUDF: _jax_udf_host,
        A2.BitwiseAnd: _binary(
            lambda e, x, y: _wrap_int(e.dtype, int(x) & int(y))),
        A2.BitwiseOr: _binary(
            lambda e, x, y: _wrap_int(e.dtype, int(x) | int(y))),
        A2.BitwiseXor: _binary(
            lambda e, x, y: _wrap_int(e.dtype, int(x) ^ int(y))),
        A2.BitwiseNot: _unary(lambda e, v: _wrap_int(e.dtype, ~int(v))),
        A2.ShiftLeft: _shift_host,
        A2.ShiftRight: _shift_host,
        A2.ShiftRightUnsigned: _shift_host,
        C2.Least: _least_greatest,
        C2.Greatest: _least_greatest,
        MM.Sinh: _unary(lambda e, v: math.sinh(v)),
        MM.Cosh: _unary(lambda e, v: math.cosh(v)),
        MM.Tanh: _unary(lambda e, v: math.tanh(v)),
        MM.Asinh: _unary(lambda e, v: math.asinh(v)),
        MM.Acosh: _unary(
            lambda e, v: math.acosh(v) if v >= 1 else float("nan")),
        MM.Atanh: _unary(
            lambda e, v: math.atanh(v) if -1 < v < 1 else float("nan")),
        MM.Expm1: _unary(lambda e, v: math.expm1(v)),
        MM.Rint: _unary(lambda e, v: float(round(v / 2) * 2) if abs(
            v - round(v)) == 0.5 and round(v) % 2 else float(round(v))),
        MM.Cot: _unary(lambda e, v: _ieee_div(math.cos(v), math.sin(v))),
        MM.Logarithm: _binary(
            lambda e, b, x: _ieee_div(math.log(x), math.log(b))
            if x > 0 and b > 0 else None),
        A2.UnaryPositive: lambda e, kids, n: kids[0],
        N.AtLeastNNonNulls: _at_least_n_host,
        S2.Md5: _unary(lambda e, v: __import__("hashlib").md5(
            v.encode("utf-8")).hexdigest()),
        CX.ElementAt: _element_at_host,
        CX.ArrayContains: _array_contains_host,
        S2.ConcatWs: _concat_ws,
        S2.StringLPad: _string_fn_host,
        S2.StringRPad: _string_fn_host,
        S2.StringRepeat: _string_fn_host,
        S2.SubstringIndex: _string_fn_host,
        S2.StringTranslate: _string_fn_host,
        S2.FindInSet: _string_fn_host,
        S2.StringLocate: _locate_host,
        S2.RegExpReplace: _regexp_replace_host,
        S2.RegExpExtract: _regexp_extract_host,
        S2.InitCap: _unary(
            lambda e, v: "".join(
                c.upper() if (i == 0 or v[i - 1] == " ") else c.lower()
                for i, c in enumerate(v))),
        S2.StringLocate: _locate_host,
        DT2.UnixTimestamp: _unix_ts_host,
        DT2.ToUnixTimestamp: _unix_ts_host,
        DT2.FromUnixTime: _from_unixtime_host,
        DT2.DateFormatClass: _date_format_host,
        DT2.AddMonths: _add_months_host,
        DT2.MonthsBetween: _months_between_host,
        DT2.TruncDate: _trunc_date_host,
        DT2.LastDay: _unary(lambda e, v: _last_day_host(v)),
        MX.Murmur3Hash: _murmur3_host,
        DX.PromotePrecision: lambda e, kids, n: HostCol(
            kids[0].data, e.dtype),
        DX.CheckOverflow: lambda e, kids, n: HostCol(
            [None if (v is None or abs(int(v)) >= 10 ** e.to.precision)
             else int(v) for v in kids[0].data], e.dtype),
        DX.UnscaledValue: lambda e, kids, n: HostCol(
            [None if v is None else int(v) for v in kids[0].data], T.LONG),
        DX.MakeDecimal: lambda e, kids, n: HostCol(
            [None if (v is None or abs(int(v)) >= 10 ** e.to.precision)
             else int(v) for v in kids[0].data], e.dtype),
        CX.CreateNamedStruct: _create_struct_host,
        CX.CreateArray: _create_array_host,
        CX.GetStructField: _struct_field_host,
        CX.GetArrayItem: _get_array_item_host,
        CX.Size: _size_host,
        CX.CreateMap: _create_map_host,
        CX.GetMapValue: _get_map_value_host,
    })
    from spark_rapids_tpu.expr.strings import StringSplit, java_split
    from spark_rapids_tpu.expr.mathexprs import BRound
    from spark_rapids_tpu.expr.predicates import InSet
    from spark_rapids_tpu.expr.datetime import DateAddInterval, TimeAdd

    def _split_host(expr, kids, n):
        pat, lim = expr.pattern_limit()
        return HostCol([None if v is None else java_split(v, pat, lim)
                        for v in kids[0].data], expr.dtype)

    from spark_rapids_tpu.expr.strings import GetJsonObject, json_path_get

    def _json_host(expr, kids, n):
        path = expr.children[1].value
        return HostCol([json_path_get(v, path) for v in kids[0].data],
                       T.STRING)

    def _scan_meta_host(expr, kids, n):
        # host fallback has no batch provenance — Spark's own away-from-scan
        # contract: "" / -1 (docs/compatibility.md)
        if isinstance(expr, MX.InputFileName):
            return HostCol([""] * n, T.STRING)
        return HostCol([-1] * n, T.LONG)

    _DISPATCH.update({
        MX.ScalarSubquery: lambda e, kids, n: HostCol([e.value] * n, e.dtype),
        MX.InputFileName: _scan_meta_host,
        MX.InputFileBlockStart: _scan_meta_host,
        MX.InputFileBlockLength: _scan_meta_host,
        GetJsonObject: _json_host,
        StringSplit: _split_host,
        BRound: _unary(lambda e, v: _bround_half_even(e, v)),
        InSet: _in,
        TimeAdd: _binary(lambda e, x, y: int(x) + int(y)),
        DateAddInterval: _binary(lambda e, x, y: int(x) + int(y)),
    })


def _bround_half_even(expr, v):
    """Spark bround: HALF_EVEN (banker's), the host oracle for BRound."""
    import decimal as _dec
    d = expr.digits
    src = expr.children[0].dtype
    if isinstance(src, T.IntegralType):
        if d >= 0:
            return v
        q = _dec.Decimal(int(v)).scaleb(d).quantize(
            _dec.Decimal(1), rounding=_dec.ROUND_HALF_EVEN)
        return _wrap_int(src, int(q) * (10 ** (-d)))
    if isinstance(src, T.DecimalType):
        ds = src.scale - d
        if ds <= 0:
            return v
        q = _dec.Decimal(int(v)).scaleb(-ds).quantize(
            _dec.Decimal(1), rounding=_dec.ROUND_HALF_EVEN)
        return int(q) * (10 ** ds)
    q = _dec.Decimal(repr(float(v))).quantize(
        _dec.Decimal(1).scaleb(-d), rounding=_dec.ROUND_HALF_EVEN)
    return float(q)


def _round_half_up(expr, v):
    """Spark/Hive round: HALF_UP away from zero (not banker's). Integral
    results wrap like the device's astype; scaled infinities stay inf."""
    d = expr.digits
    src = expr.children[0].dtype
    if isinstance(src, T.IntegralType):
        if d >= 0:
            return v
        div = 10 ** (-d)
        q = (abs(int(v)) + div // 2) // div * div
        return _wrap_int(src, -q if v < 0 else q)
    if isinstance(src, T.DecimalType):
        ds = src.scale - d
        if ds <= 0:
            return int(v)
        div = 10 ** ds
        q = (abs(int(v)) + div // 2) // div * div
        return -q if v < 0 else q
    if math.isnan(v) or math.isinf(v):
        return v
    scaled = abs(v) * (10.0 ** d)
    if math.isinf(scaled):  # overflowed the scale multiply: device keeps inf/x
        out = (-scaled if v < 0 else scaled) / (10.0 ** d)
    else:
        out = (-math.floor(scaled + 0.5) if v < 0
               else math.floor(scaled + 0.5)) / (10.0 ** d)
    return float(np.float32(out)) if isinstance(src, T.FloatType) else out


def _last_day_host(days):
    import calendar
    d = datetime.date(1970, 1, 1) + datetime.timedelta(days=int(days))
    last = calendar.monthrange(d.year, d.month)[1]
    return (d.replace(day=last) - datetime.date(1970, 1, 1)).days


_register_round2()
