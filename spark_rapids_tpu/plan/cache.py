"""DataFrame caching — materialize-once plan nodes.

Reference (SURVEY.md #42): ParquetCachedBatchSerializer caches dataframes as
GPU-written parquet blobs with a CPU fallback path. Two tiers here, selected by
conf `spark.rapids.tpu.sql.cache.serializer`:
  - "device": partitions materialize as SpillableColumnarBatches in the spill
    hierarchy (evictable HBM→host→disk) — the fast path;
  - "parquet": partitions are written once as parquet blobs in a temp dir and
    re-read on use — survives device memory pressure entirely, byte-compatible
    with external readers (the reference's actual design)."""

from __future__ import annotations

import os
import shutil
import tempfile
import threading

import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu.plan.nodes import PlanNode


class CacheNode(PlanNode):
    def __init__(self, child: PlanNode, serializer: str = "device",
                 session=None):
        super().__init__(child)
        assert serializer in ("device", "parquet")
        self.serializer = serializer
        self.session = session
        self._n_parts = child.num_partitions  # pinned: survives child mutation
        self._lock = threading.Lock()
        self._host_tables: list | None = None
        self._device_batches: list | None = None
        self._parquet_dir: str | None = None

    @property
    def output(self):
        return self.child.output

    @property
    def num_partitions(self):
        return self._n_parts

    # -- materialization ----------------------------------------------------
    def _materialize_host(self):
        with self._lock:
            if self._host_tables is None:
                self._host_tables = [self.child.execute_host(i)
                                     for i in range(self._n_parts)]
        return self._host_tables

    def materialize_device(self, conf):
        """Run the DEVICE plan for the child once; cache per-partition results.
        Returns the number of cached device partitions — the DEVICE plan's
        partitioning (e.g. an aggregate's post-exchange layout), which may
        differ from the host interpreter's (called by CachedScanExec)."""
        from spark_rapids_tpu.exec.base import TaskContext
        from spark_rapids_tpu.ops.concat import concat_batches
        from spark_rapids_tpu.plan.transitions import to_device_plan
        from spark_rapids_tpu.runtime import memory as mem
        with self._lock:
            if self.serializer == "parquet":
                if self._parquet_dir is None:
                    self._write_parquet(conf)
                return len(os.listdir(self._parquet_dir))
            if self._device_batches is not None:
                return len(self._device_batches)
            hybrid = to_device_plan(self.child, conf)
            out = []
            for split in range(hybrid.num_partitions):
                with TaskContext():
                    batches = list(hybrid.execute_partition(split))
                if batches:
                    # retained: cache partitions OUTLIVE the materializing
                    # query on purpose (until unpersist), so the end-of-query
                    # leak detector must not flag them; the query tag stays
                    # for fair-share demotion accounting
                    with mem.alloc_site("cache.device", retained=True):
                        out.append(mem.SpillableColumnarBatch(
                            concat_batches(batches)))
                else:
                    out.append(None)
            self._device_batches = out
            return len(out)

    def _write_parquet(self, conf):
        from spark_rapids_tpu.exec.base import TaskContext
        from spark_rapids_tpu.plan.transitions import to_device_plan
        d = tempfile.mkdtemp(prefix="tpu-cache-")
        hybrid = to_device_plan(self.child, conf)
        for split in range(hybrid.num_partitions):
            with TaskContext():
                tables = [b.to_arrow()
                          for b in hybrid.execute_partition(split)]
            tbl = (pa.concat_tables(tables) if tables else self._empty())
            pq.write_table(tbl, os.path.join(d, f"part-{split:05d}.parquet"))
        self._parquet_dir = d

    def read_partition(self, split: int):
        """Device-side read of a cached partition."""
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        if self.serializer == "parquet":
            tbl = pq.read_table(
                os.path.join(self._parquet_dir, f"part-{split:05d}.parquet"))
            return ColumnarBatch.from_arrow(tbl, self.output)
        sb = self._device_batches[split]
        return None if sb is None else sb.get_batch()

    def execute_host(self, split):
        return self._materialize_host()[split]

    def unpersist(self):
        with self._lock:
            if self._device_batches:
                for sb in self._device_batches:
                    if sb is not None:
                        sb.close()
            self._device_batches = None
            self._host_tables = None
            if self._parquet_dir:
                shutil.rmtree(self._parquet_dir, ignore_errors=True)
                self._parquet_dir = None

    def name(self):
        return f"Cache[{self.serializer}]"


class CachedScanExec:
    """Leaf device exec over a CacheNode (imports deferred to avoid plan↔exec
    import cycles at module load)."""

    def __new__(cls, node: CacheNode, conf=None):
        from spark_rapids_tpu.exec.base import TpuExec, acquire_semaphore

        class _Exec(TpuExec):
            def __init__(self, node, conf):
                super().__init__(conf=conf)
                self.node = node

            @property
            def output(self):
                return self.node.output

            @property
            def num_partitions(self):
                # the DEVICE cache layout, not the host interpreter's; forces
                # materialization at planning time (once)
                return self.node.materialize_device(self.conf)

            def execute_partition(self, split):
                def it():
                    self.node.materialize_device(self.conf)
                    batch = self.node.read_partition(split)
                    if batch is not None:
                        acquire_semaphore(self.metrics)
                        yield batch
                return self.wrap_output(it())

            def args_string(self):
                return self.node.name()

        return _Exec(node, conf)
