"""Host window interpreter — the CPU oracle for WindowNode (plain python loops,
deliberately independent of the device's segmented-scan kernels)."""

from __future__ import annotations

import functools
import math

import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Alias, bind_references
from spark_rapids_tpu.expr.aggregates import (AggregateFunction, Average, Count,
                                              Max, Min, Sum)
from spark_rapids_tpu.expr.windows import (DenseRank, Lag, Lead, Rank, RowNumber,
                                           WindowExpression)
from spark_rapids_tpu.plan.host_eval import eval_host


def _unalias(e):
    return e.child if isinstance(e, Alias) else e


def _cmp_key(v):
    if v is None:
        return None
    if isinstance(v, float) and math.isnan(v):
        return (1, 0.0)
    if isinstance(v, bool):
        return (0, int(v))
    return (0, v)


def host_window(node, tbl: pa.Table) -> pa.Table:
    schema = node.child.output
    exprs = [bind_references(e, schema) for e in node.window_exprs]
    spec0 = _unalias(exprs[0]).spec
    n = tbl.num_rows

    part_cols = [eval_host(e, tbl).data for e in spec0.partition_by]
    order_cols = [(eval_host(e, tbl).data, asc, nf)
                  for (e, asc, nf) in spec0.order_by]

    def sort_cmp(i, j):
        for (data, asc, nf) in order_cols:
            a, b = data[i], data[j]
            if a is None and b is None:
                continue
            if a is None:
                return -1 if nf else 1
            if b is None:
                return 1 if nf else -1
            ka, kb = _cmp_key(a), _cmp_key(b)
            if ka == kb:
                continue
            r = -1 if ka < kb else 1
            return r if asc else -r
        return i - j

    # group rows by partition key, keep insertion order then sort within
    groups: dict = {}
    for i in range(n):
        k = tuple(_cmp_key(c[i]) for c in part_cols)
        groups.setdefault(k, []).append(i)
    for k in groups:
        groups[k].sort(key=functools.cmp_to_key(sort_cmp))

    out_order: list[int] = []
    results = [[None] * n for _ in exprs]
    for k, rows in sorted(groups.items(),
                          key=lambda kv: tuple(
                              (x is None, x) for x in kv[0])):
        out_order.extend(rows)
        for ei, e in enumerate(exprs):
            we = _unalias(e)
            vals = _eval_one(we, rows, tbl, order_cols)
            for r, v in zip(rows, vals):
                results[ei][r] = v

    arrays = [tbl.column(i).take(pa.array(out_order, pa.int64()))
              for i in range(tbl.num_columns)]
    names = list(tbl.column_names)
    for ei, e in enumerate(exprs):
        f = node.output.fields[tbl.num_columns + ei]
        arrays.append(pa.array([results[ei][r] for r in out_order],
                               T.to_arrow_type(f.data_type)))
        names.append(f.name)
    return pa.Table.from_arrays(arrays, names=names)


def _tie_groups(rows, order_cols):
    """Indices of rows grouped by equal order keys, in order."""
    tg = []
    for i, r in enumerate(rows):
        if i == 0:
            tg.append([i])
            continue
        prev = rows[i - 1]
        same = all(_cmp_key(d[r]) == _cmp_key(d[prev]) for (d, _, _) in order_cols)
        if same:
            tg[-1].append(i)
        else:
            tg.append([i])
    return tg


def _frame_bounds(we, i, rows, order_cols):
    """[lo, hi] inclusive positions within `rows` for row position i."""
    fr = we.spec.frame
    n = len(rows)
    if fr.is_unbounded_both:
        return 0, n - 1
    if fr.frame_type == "range":
        if fr.preceding is None and fr.following == 0:
            # unbounded preceding → current row including ties
            for tg in _tie_groups(rows, order_cols):
                if i in tg:
                    return 0, tg[-1]
            return 0, i
        # bounded range frame over ONE order key (Spark RangeBoundOrdering:
        # null±offset compares equal to nulls only, NaN is its own peer class)
        if len(order_cols) != 1:
            raise ValueError(  # Spark rejects this at analysis too
                "bounded range frame requires exactly one order key")
        (data, asc, _nf) = order_cols[0]
        v = data[rows[i]]
        v_nan = isinstance(v, float) and math.isnan(v)

        def in_lo(u):
            if v is None or v_nan:   # peer group on bounded sides
                return (u is None) if v is None else \
                    (isinstance(u, float) and math.isnan(u))
            if u is None or (isinstance(u, float) and math.isnan(u)):
                return False
            return (u >= v - fr.preceding) if asc else \
                (u <= v + fr.preceding)

        def in_hi(u):
            if v is None or v_nan:
                return (u is None) if v is None else \
                    (isinstance(u, float) and math.isnan(u))
            if u is None or (isinstance(u, float) and math.isnan(u)):
                return False
            return (u <= v + fr.following) if asc else \
                (u >= v - fr.following)

        lo = 0
        if fr.preceding is not None:
            lo = n
            for j in range(n):
                if in_lo(data[rows[j]]):
                    lo = j
                    break
        hi = n - 1
        if fr.following is not None:
            hi = -1
            for j in range(n - 1, -1, -1):
                if in_hi(data[rows[j]]):
                    hi = j
                    break
        return lo, hi
    lo = 0 if fr.preceding is None else max(0, i - fr.preceding)
    hi = n - 1 if fr.following is None else min(n - 1, i + fr.following)
    return lo, hi


def _eval_one(we, rows, tbl, order_cols):
    f = we.func
    n = len(rows)
    if isinstance(f, RowNumber):
        return list(range(1, n + 1))
    if isinstance(f, (Rank, DenseRank)):
        out = []
        rank_v, dense_v, seen = 0, 0, 0
        for tg in _tie_groups(rows, order_cols):
            dense_v += 1
            rank_v = seen + 1
            for _ in tg:
                out.append(dense_v if isinstance(f, DenseRank) else rank_v)
                seen += 1
        return out
    if isinstance(f, (Lead, Lag)):
        data = eval_host(f.children[0], tbl).data
        off = f.offset if isinstance(f, Lead) else -f.offset
        out = []
        for i in range(n):
            j = i + off
            out.append(data[rows[j]] if 0 <= j < n else f.default)
        return out
    assert isinstance(f, AggregateFunction)
    data = (eval_host(f.children[0], tbl).data if f.children else None)
    out = []
    for i in range(n):
        lo, hi = _frame_bounds(we, i, rows, order_cols)
        frame_rows = rows[lo:hi + 1]
        if isinstance(f, Count):
            out.append(len(frame_rows) if data is None else
                       sum(1 for r in frame_rows if data[r] is not None))
            continue
        vals = [data[r] for r in frame_rows if data[r] is not None]
        if not vals:
            out.append(None)
        elif isinstance(f, Sum):
            out.append(sum(vals))
        elif isinstance(f, Average):
            out.append(float(sum(vals)) / len(vals))
        elif isinstance(f, Min):
            out.append(min(vals, key=_cmp_key))
        elif isinstance(f, Max):
            out.append(max(vals, key=_cmp_key))
        else:
            raise NotImplementedError(type(f).__name__)
    return out
