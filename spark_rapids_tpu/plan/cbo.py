"""Cost-based optimizer — dual host/device cost model.

Reference (SURVEY.md #13): CostBasedOptimizer.scala:52 builds a CpuCostModel
and a GpuCostModel, walks the tagged meta tree, costs each contiguous
device-capable section on both sides (including row↔columnar transition
costs at the section boundary), and reverts sections where acceleration
would not pay (`costPreventsRunningOnGpu`).

TPU translation of the cost terms:
  host cost    = Σ rows(op) · weight(op) · host.rowCost
  device cost  = Σ [dispatchCost + rows(op) · weight(op) · tpu.rowCost]
                 + boundary_rows · transferRowCost      (H2D at leaves,
                                                          D2H at the root)
The fixed per-operator dispatch term models what dominates on TPU for small
inputs: jit dispatch + tunnel latency, the analog of the reference's
per-exec coefficient tables. `optimizer.minRows` remains as a hard floor
(cheaper than costing when the answer is obvious).
"""

from __future__ import annotations

from spark_rapids_tpu import config as CFG
from spark_rapids_tpu.plan import nodes as NN


def estimate_rows(node, _memo: dict | None = None) -> int:
    """Static cardinality estimate (the cost models' shared row-count term).
    Memoized per optimize() pass — parquet estimates open footers."""
    if _memo is None:
        _memo = {}
    key = id(node)
    if key in _memo:
        return _memo[key]
    rows = _estimate_rows(node, _memo)
    _memo[key] = rows
    return rows


def _estimate_rows(node, memo) -> int:
    from spark_rapids_tpu.io.filescan import FileScanNode
    from spark_rapids_tpu.plan.cache import CacheNode

    def est(n):
        return estimate_rows(n, memo)

    if isinstance(node, NN.ScanNode):
        return sum(t.num_rows for t in node.partitions)
    if isinstance(node, FileScanNode):
        # cached on the node: scans persist across planning passes (the
        # build-side chooser and optimize() both ask), and re-opening every
        # parquet footer per pass scales with file count. Keyed on file
        # mtimes so a retained plan over files that grew/shrank (or a
        # pruning-pass shallow clone of a stale node) re-estimates.
        import os

        def _mt(p):
            try:
                return os.path.getmtime(p)
            except OSError:
                return 0.0
        fp = tuple((p, _mt(p))
                   for part in node.partitions for p in part.paths)
        if (getattr(node, "_est_rows", None) is not None
                and getattr(node, "_est_rows_fp", None) == fp):
            return node._est_rows
        total = 0
        for part in node.partitions:
            for p in part.paths:
                try:
                    if node.fmt == "parquet":
                        import pyarrow.parquet as pq
                        total += pq.ParquetFile(p).metadata.num_rows
                    else:
                        total += max(1, os.path.getsize(p) // 64)
                except Exception:
                    total += 1 << 20  # unknown: assume big (stay on device)
        node._est_rows, node._est_rows_fp = total, fp
        return total
    if isinstance(node, NN.RangeNode):
        return max(0, -(-(node.end - node.start) // node.step))
    if isinstance(node, NN.FilterNode):
        return max(1, est(node.child) // 2)   # selectivity 0.5
    if isinstance(node, NN.AggregateNode):
        return max(1, est(node.child) // 10)  # grouping factor
    if isinstance(node, NN.JoinNode):
        return max(est(node.left), est(node.right))
    if isinstance(node, NN.LimitNode):
        return min(node.n, est(node.child))
    if isinstance(node, NN.UnionNode):
        return sum(est(c) for c in node.children)
    if isinstance(node, NN.GenerateNode):
        return est(node.child) * 4             # explode fan-out guess
    if isinstance(node, CacheNode):
        return est(node.child)
    if node.children:
        return max(est(c) for c in node.children)
    return 1 << 20


# relative per-row operator weights (the reference keys its coefficient
# table by exec class the same way)
_OP_WEIGHTS = (
    (NN.SortNode, 6.0),
    (NN.JoinNode, 5.0),
    (NN.WindowNode, 5.0),
    (NN.AggregateNode, 3.0),
    (NN.ExchangeNode, 2.0),
    (NN.GenerateNode, 2.0),
    (NN.ExpandNode, 2.0),
)


def _op_weight(node) -> float:
    for cls, w in _OP_WEIGHTS:
        if isinstance(node, cls):
            return w
    return 1.0


class _CostModel:
    """One side of the dual model: per-op cost from shared cardinality."""

    def __init__(self, row_cost: float, dispatch_cost: float = 0.0):
        self.row_cost = row_cost
        self.dispatch_cost = dispatch_cost

    def op_cost(self, node, rows: int) -> float:
        return self.dispatch_cost + rows * _op_weight(node) * self.row_cost


def optimize(meta) -> None:
    """Walk the tagged meta tree; revert device sections the dual cost model
    says are unprofitable (reference CostBasedOptimizer.optimize, called
    between tagging and conversion)."""
    conf = meta.conf
    if not conf.get(CFG.OPTIMIZER_ENABLED):
        return
    host = _CostModel(conf.get(CFG.OPTIMIZER_HOST_ROW_COST))
    tpu = _CostModel(conf.get(CFG.OPTIMIZER_TPU_ROW_COST),
                     conf.get(CFG.OPTIMIZER_TPU_DISPATCH_COST))
    xfer = conf.get(CFG.OPTIMIZER_TRANSFER_ROW_COST)
    memo = {}
    # pass 1 — hard floor, PER NODE: a tiny operator (a global limit, a
    # low-cardinality root) never pays for dispatch, but pinning it must not
    # drag a large upstream scan off the device with it
    _apply_min_rows(meta, conf.get(CFG.OPTIMIZER_MIN_ROWS), memo)
    # pass 2 — dual cost comparison over the remaining device sections
    _optimize_sections(meta, host, tpu, xfer, memo, parent_on_tpu=False)


def _apply_min_rows(meta, min_rows: int, memo: dict) -> None:
    from spark_rapids_tpu.plan.cache import CacheNode
    node = getattr(meta, "node", None)
    if (node is not None and meta.can_run_on_tpu
            and not isinstance(node, CacheNode)):
        rows = estimate_rows(node, memo)
        if rows < min_rows:
            meta.will_not_work(
                f"cost model: ~{rows} rows < optimizer.minRows={min_rows};"
                " transfer+dispatch overhead exceeds device speedup")
    for m in _plan_metas(meta):
        _apply_min_rows(m, min_rows, memo)


def _plan_metas(meta):
    """Child metas that wrap plan nodes (expression metas are costed with
    their operator, not separately)."""
    return [m for m in meta.child_metas if hasattr(m, "node")]


def _section(meta, memo):
    """Collect the maximal contiguous device-capable subtree rooted at
    `meta`: (section metas, host-boundary metas below it)."""
    nodes, fringe = [meta], []
    for m in _plan_metas(meta):
        if m.can_run_on_tpu:
            sub_nodes, sub_fringe = _section(m, memo)
            nodes.extend(sub_nodes)
            fringe.extend(sub_fringe)
        else:
            fringe.append(m)
    return nodes, fringe


def _optimize_sections(meta, host, tpu, xfer, memo, parent_on_tpu):
    from spark_rapids_tpu.plan.cache import CacheNode
    node = getattr(meta, "node", None)
    on_tpu = node is not None and meta.can_run_on_tpu
    if on_tpu and not parent_on_tpu and not isinstance(node, CacheNode):
        section, fringe = _section(meta, memo)
        # a cache inside the section may hold device-materialized batches;
        # reverting would re-execute its child — never profitable
        if not any(isinstance(m.node, CacheNode) for m in section):
            host_cost = tpu_cost = 0.0
            for m in section:
                rows = estimate_rows(m.node, memo)
                host_cost += host.op_cost(m.node, rows)
                tpu_cost += tpu.op_cost(m.node, rows)
            # transitions: H2D for every host child feeding the section,
            # D2H for the section's result
            boundary = estimate_rows(meta.node, memo)
            for m in fringe:
                boundary += estimate_rows(m.node, memo)
            tpu_cost += boundary * xfer
            if tpu_cost >= host_cost:
                why = (f"cost model: device {tpu_cost * 1e3:.2f}ms >= "
                       f"host {host_cost * 1e3:.2f}ms over "
                       f"{len(section)}-op section")
                for m in section:
                    m.will_not_work(why)
                on_tpu = False
    for m in _plan_metas(meta):
        _optimize_sections(m, host, tpu, xfer, memo, on_tpu)
