"""Cost-based optimizer — reject unprofitable device sections.

Reference (SURVEY.md #13): CostBasedOptimizer.scala:52 with CpuCostModel /
GpuCostModel: after tagging, estimate each section's cost on both sides and keep
it on the CPU when acceleration wouldn't pay. On TPU the dominant term for small
inputs is H2D transfer + dispatch latency (tens of ms over the tunnel), so the
model pins a meta subtree to the host when its estimated row count is below
`spark.rapids.tpu.sql.optimizer.minRows` and no device-resident source feeds it."""

from __future__ import annotations

from spark_rapids_tpu import config as CFG
from spark_rapids_tpu.plan import nodes as NN


def estimate_rows(node, _memo: dict | None = None) -> int:
    """Static cardinality estimate (the CpuCostModel's row-count term).
    Memoized per optimize() pass — parquet estimates open footers."""
    if _memo is None:
        _memo = {}
    key = id(node)
    if key in _memo:
        return _memo[key]
    rows = _estimate_rows(node, _memo)
    _memo[key] = rows
    return rows


def _estimate_rows(node, memo) -> int:
    from spark_rapids_tpu.io.filescan import FileScanNode
    from spark_rapids_tpu.plan.cache import CacheNode

    def est(n):
        return estimate_rows(n, memo)

    if isinstance(node, NN.ScanNode):
        return sum(t.num_rows for t in node.partitions)
    if isinstance(node, FileScanNode):
        total = 0
        for part in node.partitions:
            for p in part.paths:
                try:
                    if node.fmt == "parquet":
                        import pyarrow.parquet as pq
                        total += pq.ParquetFile(p).metadata.num_rows
                    else:
                        import os
                        total += max(1, os.path.getsize(p) // 64)
                except Exception:
                    total += 1 << 20  # unknown: assume big (stay on device)
        return total
    if isinstance(node, NN.RangeNode):
        return max(0, -(-(node.end - node.start) // node.step))
    if isinstance(node, NN.FilterNode):
        return max(1, est(node.child) // 2)   # selectivity 0.5
    if isinstance(node, NN.AggregateNode):
        return max(1, est(node.child) // 10)  # grouping factor
    if isinstance(node, NN.JoinNode):
        return max(est(node.left), est(node.right))
    if isinstance(node, NN.LimitNode):
        return min(node.n, est(node.child))
    if isinstance(node, NN.UnionNode):
        return sum(est(c) for c in node.children)
    if isinstance(node, CacheNode):
        return est(node.child)
    if node.children:
        return max(est(c) for c in node.children)
    return 1 << 20


def optimize(meta) -> None:
    """Walk the tagged meta tree; pin small subtrees to the host (reference
    CostBasedOptimizer.optimize, called between tagging and conversion)."""
    conf = meta.conf
    if not conf.get(CFG.OPTIMIZER_ENABLED):
        return
    min_rows = conf.get(CFG.OPTIMIZER_MIN_ROWS)
    _optimize_meta(meta, min_rows, {})


def _optimize_meta(meta, min_rows: int, memo: dict) -> None:
    from spark_rapids_tpu.plan.cache import CacheNode
    node = getattr(meta, "node", None)
    if node is not None and meta.can_run_on_tpu:
        # a cache may already hold device-materialized data; pinning it to the
        # host would re-execute its child from scratch — never profitable
        if not isinstance(node, CacheNode):
            rows = estimate_rows(node, memo)
            if rows < min_rows:
                meta.will_not_work(
                    f"cost model: ~{rows} rows < optimizer.minRows={min_rows};"
                    " transfer+dispatch overhead exceeds device speedup")
    for m in meta.child_metas:
        _optimize_meta(m, min_rows, memo)
