"""CPU physical plan nodes — the framework's "Spark plan" that the override layer
rewrites onto the TPU.

Reference analogy: Spark's SparkPlan nodes (ProjectExec, FilterExec,
HashAggregateExec, SortMergeJoinExec, ShuffleExchangeExec…) that GpuOverrides wraps
and replaces (GpuOverrides.scala:2723 wrapPlan). Since this framework is standalone,
these nodes come with a host NumPy/pyarrow interpreter: a node left on the host
actually executes there (partial-plan fallback, like ops the reference tags
willNotWorkOnGpu and leaves to Spark).
"""

from __future__ import annotations

import math
import typing

import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import core as E
from spark_rapids_tpu.expr.aggregates import (
    AggregateFunction, Average, CollectList, CollectSet, Count, First, Last,
    Max, Min, PivotFirst, StddevPop, StddevSamp, Sum, VariancePop,
    VarianceSamp,
)
from spark_rapids_tpu.plan.host_eval import HostCol, eval_host


class PlanNode:
    """Base CPU plan node. `execute_host(split)` returns one pa.Table per partition."""

    def __init__(self, *children: "PlanNode"):
        self.children = list(children)

    @property
    def child(self) -> "PlanNode":
        return self.children[0]

    @property
    def output(self) -> T.StructType:
        raise NotImplementedError

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions if self.children else 1

    def execute_host(self, split: int) -> pa.Table:
        raise NotImplementedError

    def collect_host(self) -> pa.Table:
        tables = [self.execute_host(i) for i in range(self.num_partitions)]
        return pa.concat_tables(tables) if tables else self._empty()

    def _empty(self) -> pa.Table:
        return pa.Table.from_arrays(
            [pa.array([], T.to_arrow_type(f.data_type)) for f in self.output],
            names=[f.name for f in self.output])

    def name(self) -> str:
        return type(self).__name__.replace("Node", "")

    def args_string(self) -> str:
        return ""

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + f"{self.name()} {self.args_string()}".rstrip()]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)


def _project_table(tbl: pa.Table, exprs, out_schema: T.StructType) -> pa.Table:
    cols = []
    for e, f in zip(exprs, out_schema):
        hc = eval_host(e, tbl)
        if isinstance(f.data_type, T.DecimalType):
            # HostCol decimals carry UNSCALED ints — to_arrow applies the
            # boundary conversion (a raw pa.array would misread the scale)
            cols.append(HostCol(hc.data, f.data_type).to_arrow())
        else:
            # out-schema type coercion (host literals default to wide ints)
            cols.append(pa.array(hc.data, T.to_arrow_type(f.data_type)))
    # from_arrays, not a dict: duplicate output names must survive
    return pa.Table.from_arrays(list(cols), names=[f.name for f in out_schema])


def _expr_name(e: E.Expression, i: int) -> str:
    if isinstance(e, E.Alias):
        return e.name
    if isinstance(e, (E.AttributeReference, E.BoundReference)):
        return e.name
    return f"col{i}"


class ScanNode(PlanNode):
    """In-memory scan over pre-partitioned arrow tables (LocalTableScan analog)."""

    def __init__(self, partitions: list, schema: T.StructType | None = None):
        super().__init__()
        self.partitions = list(partitions)
        assert self.partitions, "ScanNode needs at least one partition"
        if schema is None:
            from spark_rapids_tpu.plan.host_eval import table_schema
            schema = table_schema(self.partitions[0])
        self._schema = schema

    @property
    def output(self):
        return self._schema

    @property
    def num_partitions(self):
        return len(self.partitions)

    def execute_host(self, split):
        return self.partitions[split]


class RangeNode(PlanNode):
    def __init__(self, start: int, end: int, step: int = 1, num_slices: int = 1):
        super().__init__()
        self.start, self.end, self.step = start, end, step
        self.num_slices = num_slices

    @property
    def output(self):
        return T.StructType([T.StructField("id", T.LONG, False)])

    @property
    def num_partitions(self):
        return self.num_slices

    def execute_host(self, split):
        total = max(0, -(-(self.end - self.start) // self.step))
        per = -(-total // self.num_slices)
        lo, hi = split * per, min(total, (split + 1) * per)
        vals = [self.start + i * self.step for i in range(lo, hi)]
        return pa.table({"id": pa.array(vals, pa.int64())})

    def args_string(self):
        return f"({self.start}, {self.end}, {self.step})"


class ProjectNode(PlanNode):
    def __init__(self, project_list: list, child: PlanNode):
        super().__init__(child)
        self.project_list = [E.bind_references(e, child.output)
                             for e in project_list]

    @property
    def output(self):
        return T.StructType([
            T.StructField(_expr_name(e, i), e.dtype, e.nullable)
            for i, e in enumerate(self.project_list)])

    def execute_host(self, split):
        return _project_table(self.child.execute_host(split), self.project_list,
                              self.output)

    def args_string(self):
        return str(self.project_list)


class FilterNode(PlanNode):
    def __init__(self, condition: E.Expression, child: PlanNode):
        super().__init__(child)
        self.condition = E.bind_references(condition, child.output)

    @property
    def output(self):
        return self.child.output

    def execute_host(self, split):
        tbl = self.child.execute_host(split)
        pred = eval_host(self.condition, tbl)
        mask = pa.array([v is True for v in pred.data])
        return tbl.filter(mask)

    def args_string(self):
        return repr(self.condition)


class LimitNode(PlanNode):
    def __init__(self, n: int, child: PlanNode, global_limit: bool = False):
        super().__init__(child)
        self.n = n
        self.global_limit = global_limit

    @property
    def output(self):
        return self.child.output

    @property
    def num_partitions(self):
        return 1 if self.global_limit else self.child.num_partitions

    def execute_host(self, split):
        if not self.global_limit:
            return self.child.execute_host(split).slice(0, self.n)
        remaining = self.n
        parts = []
        for i in range(self.child.num_partitions):
            if remaining <= 0:
                break
            t = self.child.execute_host(i).slice(0, remaining)
            remaining -= t.num_rows
            parts.append(t)
        return pa.concat_tables(parts) if parts else self._empty()

    def args_string(self):
        return f"n={self.n}"


class UnionNode(PlanNode):
    def __init__(self, *children: PlanNode):
        super().__init__(*children)

    @property
    def output(self):
        return self.children[0].output

    @property
    def num_partitions(self):
        return sum(c.num_partitions for c in self.children)

    def execute_host(self, split):
        for c in self.children:
            if split < c.num_partitions:
                t = c.execute_host(split)
                names = [f.name for f in self.output]
                return t.rename_columns(names)
            split -= c.num_partitions
        raise IndexError(split)


class AggregateNode(PlanNode):
    """Group-by aggregate; exact Spark null/NaN grouping semantics on the host."""

    def __init__(self, group_exprs: list, agg_exprs: list, child: PlanNode):
        super().__init__(child)
        self.group_exprs = [E.bind_references(e, child.output)
                            for e in group_exprs]
        self.agg_exprs = [E.bind_references(e, child.output) for e in agg_exprs]

    @property
    def output(self):
        fields = [T.StructField(_expr_name(e, i), e.dtype, True)
                  for i, e in enumerate(self.group_exprs)]
        for i, e in enumerate(self.agg_exprs):
            fields.append(T.StructField(
                _expr_name(e, len(fields)), e.dtype, e.nullable))
        return T.StructType(fields)

    @property
    def num_partitions(self):
        return 1  # host interpreter aggregates globally

    @staticmethod
    def _group_key(vals):
        out = []
        for v in vals:
            if isinstance(v, float) and math.isnan(v):
                out.append(("nan",))
            elif isinstance(v, float) and v == 0.0:
                out.append(0.0)  # -0.0 == 0.0 for grouping
            else:
                out.append(v)
        return tuple(out)

    def execute_host(self, split):
        tables = [self.child.execute_host(i)
                  for i in range(self.child.num_partitions)]
        tbl = pa.concat_tables(tables)
        keys = [eval_host(e, tbl) for e in self.group_exprs]
        groups: dict = {}
        order: list = []
        for i in range(tbl.num_rows):
            k = self._group_key([kc.data[i] for kc in keys])
            if k not in groups:
                groups[k] = []
                order.append(k)
            groups[k].append(i)
        if not self.group_exprs and not order:
            order.append(())
            groups[()] = []

        agg_inputs = []
        for e in self.agg_exprs:
            f = e.child if isinstance(e, E.Alias) else e
            assert isinstance(f, AggregateFunction), f
            if isinstance(f, Count) and not f.children:
                agg_inputs.append((f, None))
            elif isinstance(f, PivotFirst):
                agg_inputs.append((f, (eval_host(f.children[0], tbl),
                                       eval_host(f.children[1], tbl))))
            else:
                agg_inputs.append((f, eval_host(f.children[0], tbl)))

        out_cols = [[] for _ in self.output]
        for k in order:
            rows = groups[k]
            ki = 0
            for ki, kc in enumerate(keys):
                out_cols[ki].append(kc.data[rows[0]] if rows else None)
            base = len(keys)
            for ai, (f, data) in enumerate(agg_inputs):
                out_cols[base + ai].append(self._agg_one(f, data, rows))
        return pa.table({
            fld.name: pa.array(col, T.to_arrow_type(fld.data_type))
            for fld, col in zip(self.output, out_cols)})

    @staticmethod
    def _agg_one(f: AggregateFunction, data, rows):
        if isinstance(f, Count):
            if data is None:
                return len(rows)
            return sum(1 for i in rows if data.data[i] is not None)
        if isinstance(f, PivotFirst):
            vals_c, piv_c = data
            out = [None] * len(f.pivot_values)
            index = {v: j for j, v in enumerate(f.pivot_values)}
            for i in rows:
                j = index.get(piv_c.data[i])
                if j is not None and out[j] is None:
                    out[j] = vals_c.data[i]
            return out
        vals = [data.data[i] for i in rows if data.data[i] is not None]
        if isinstance(f, Sum):
            if not vals:
                return None
            s = sum(vals)
            return _wrap_sum(s, f.dtype)
        if isinstance(f, Average):
            if not vals:
                return None
            return float(sum(vals)) / len(vals)
        if isinstance(f, Min):
            return _minmax(vals, is_min=True)
        if isinstance(f, Max):
            return _minmax(vals, is_min=False)
        if isinstance(f, First):
            if f.ignore_nulls:
                return vals[0] if vals else None
            return data.data[rows[0]] if rows else None
        if isinstance(f, Last):
            if f.ignore_nulls:
                return vals[-1] if vals else None
            return data.data[rows[-1]] if rows else None
        if isinstance(f, CollectSet):           # before CollectList (subclass)
            # arrays/structs are unhashable; dedupe on a structural key
            seen, out = set(), []
            for v in vals:
                key = repr(v) if isinstance(v, (list, dict)) else v
                if key not in seen:
                    seen.add(key)
                    out.append(v)
            return out
        if isinstance(f, CollectList):
            return list(vals)
        if isinstance(f, (VariancePop, VarianceSamp)):
            n = len(vals)
            # class hierarchy: StddevPop(VariancePop), StddevSamp(VarianceSamp)
            ddof = 0 if isinstance(f, VariancePop) else 1
            if n == 0 or n - ddof <= 0:
                return None
            mean = sum(float(v) for v in vals) / n
            var = sum((float(v) - mean) ** 2 for v in vals) / (n - ddof)
            if isinstance(f, (StddevPop, StddevSamp)):
                return var ** 0.5
            return var
        raise NotImplementedError(type(f).__name__)

    def args_string(self):
        return f"keys={self.group_exprs} aggs={self.agg_exprs}"


def _wrap_sum(s, dtype):
    if isinstance(dtype, T.IntegralType):
        m = 1 << 64
        s = int(s) & (m - 1)
        return s - m if s >= (m >> 1) else s
    return float(s)


def _minmax(vals, is_min):
    if not vals:
        return None
    def key(v):
        if isinstance(v, float) and math.isnan(v):
            return (1, 0.0)
        if isinstance(v, bool):
            return (0, int(v))
        return (0, v)
    return (min if is_min else max)(vals, key=key)


class JoinNode(PlanNode):
    """Equi-join (or cross join when no keys) with Spark null semantics:
    null keys never match."""

    TYPES = ("inner", "left", "right", "full", "leftsemi", "leftanti", "cross")

    def __init__(self, left: PlanNode, right: PlanNode, left_keys: list,
                 right_keys: list, join_type: str = "inner",
                 condition: E.Expression | None = None):
        super().__init__(left, right)
        assert join_type in self.TYPES, join_type
        self.left_keys = [E.bind_references(e, left.output) for e in left_keys]
        self.right_keys = [E.bind_references(e, right.output)
                           for e in right_keys]
        self.join_type = join_type
        self.condition = condition

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    @property
    def output(self):
        if self.join_type in ("leftsemi", "leftanti"):
            return self.left.output
        fields = []
        lnull = self.join_type in ("right", "full")
        rnull = self.join_type in ("left", "full")
        for f in self.left.output:
            fields.append(T.StructField(f.name, f.data_type,
                                        f.nullable or lnull))
        for f in self.right.output:
            fields.append(T.StructField(f.name, f.data_type,
                                        f.nullable or rnull))
        return T.StructType(fields)

    @property
    def num_partitions(self):
        return 1

    @staticmethod
    def _keys_of(tbl, key_exprs):
        cols = [eval_host(e, tbl) for e in key_exprs]
        out = []
        for i in range(tbl.num_rows):
            vals = [c.data[i] for c in cols]
            if any(v is None for v in vals):
                out.append(None)  # null key never matches
            else:
                out.append(AggregateNode._group_key(vals))
        return out

    def _pair_schema(self) -> T.StructType:
        """Condition evaluation always sees left+right regardless of join type
        (semi/anti output is left-only but the condition references both sides)."""
        return T.StructType(list(self.left.output.fields)
                            + list(self.right.output.fields))

    def _cond_ok(self, ltbl, rtbl, li, ri):
        if self.condition is None:
            return True
        arrays = ([ltbl.column(i).slice(li, 1).combine_chunks()
                   for i in range(ltbl.num_columns)]
                  + [rtbl.column(i).slice(ri, 1).combine_chunks()
                     for i in range(rtbl.num_columns)])
        names = ltbl.column_names + rtbl.column_names
        joined = pa.Table.from_arrays(arrays, names=names)
        cond = E.bind_references(self.condition, self._pair_schema())
        return eval_host(cond, joined).data[0] is True

    def execute_host(self, split):
        ltbl = pa.concat_tables([self.left.execute_host(i)
                                 for i in range(self.left.num_partitions)])
        rtbl = pa.concat_tables([self.right.execute_host(i)
                                 for i in range(self.right.num_partitions)])
        if self.join_type == "cross" or not self.left_keys:
            pairs = [(i, j) for i in range(ltbl.num_rows)
                     for j in range(rtbl.num_rows)
                     if self._cond_ok(ltbl, rtbl, i, j)]
            return self._emit(ltbl, rtbl, pairs,
                              {i for i, _ in pairs}, {j for _, j in pairs})

        lkeys = self._keys_of(ltbl, self.left_keys)
        rkeys = self._keys_of(rtbl, self.right_keys)
        rindex: dict = {}
        for j, k in enumerate(rkeys):
            if k is not None:
                rindex.setdefault(k, []).append(j)

        pairs = []
        matched_l: set = set()
        matched_r: set = set()
        for i, k in enumerate(lkeys):
            for j in (rindex.get(k, []) if k is not None else []):
                if self._cond_ok(ltbl, rtbl, i, j):
                    pairs.append((i, j))
                    matched_l.add(i)
                    matched_r.add(j)
        return self._emit(ltbl, rtbl, pairs, matched_l, matched_r)

    def _emit(self, ltbl, rtbl, pairs, matched_l, matched_r):
        jt = self.join_type
        if jt == "leftsemi":
            idx = sorted(matched_l)
            return ltbl.take(pa.array(idx, pa.int64()))
        if jt == "leftanti":
            idx = [i for i in range(ltbl.num_rows) if i not in matched_l]
            return ltbl.take(pa.array(idx, pa.int64()))
        li = [p[0] for p in pairs]
        ri = [p[1] for p in pairs]
        if jt in ("left", "full"):
            for i in range(ltbl.num_rows):
                if i not in matched_l:
                    li.append(i)
                    ri.append(None)
        if jt in ("right", "full"):
            for j in range(rtbl.num_rows):
                if j not in matched_r:
                    li.append(None)
                    ri.append(j)
        li_arr, ri_arr = pa.array(li, pa.int64()), pa.array(ri, pa.int64())
        # from_arrays (not a dict) so duplicate names across sides survive
        arrays = ([ltbl.column(i).take(li_arr).combine_chunks()
                   for i in range(ltbl.num_columns)]
                  + [rtbl.column(i).take(ri_arr).combine_chunks()
                     for i in range(rtbl.num_columns)])
        return pa.Table.from_arrays(arrays, names=[f.name for f in self.output])

    def args_string(self):
        return (f"{self.join_type} lkeys={self.left_keys} "
                f"rkeys={self.right_keys}")


class SortNode(PlanNode):
    def __init__(self, sort_exprs: list, child: PlanNode, global_sort: bool = True):
        """sort_exprs: list of (expr, ascending, nulls_first)."""
        super().__init__(child)
        self.sort_exprs = [(E.bind_references(e, child.output), asc, nf)
                           for (e, asc, nf) in sort_exprs]
        self.global_sort = global_sort

    @property
    def output(self):
        return self.child.output

    @property
    def num_partitions(self):
        return 1 if self.global_sort else self.child.num_partitions

    def execute_host(self, split):
        if self.global_sort:
            tbl = pa.concat_tables([self.child.execute_host(i)
                                    for i in range(self.child.num_partitions)])
        else:
            tbl = self.child.execute_host(split)
        import functools
        cols = [eval_host(e, tbl) for (e, _, _) in self.sort_exprs]

        def cmp(i, j):
            for c, (e, asc, nulls_first) in zip(cols, self.sort_exprs):
                a, b = c.data[i], c.data[j]
                if a is None and b is None:
                    continue
                if a is None:
                    return -1 if nulls_first else 1
                if b is None:
                    return 1 if nulls_first else -1
                ka, kb = _minmax_key(a), _minmax_key(b)
                if ka == kb:
                    continue
                r = -1 if ka < kb else 1
                return r if asc else -r
            return i - j  # stable
        idx = sorted(range(tbl.num_rows), key=functools.cmp_to_key(cmp))
        return tbl.take(pa.array(idx, pa.int64()))

    def args_string(self):
        return str([(repr(e), asc, nf) for e, asc, nf in self.sort_exprs])


def _minmax_key(v):
    if isinstance(v, float) and math.isnan(v):
        return (1, 0.0)
    if isinstance(v, bool):
        return (0, int(v))
    return (0, v)


class ExchangeNode(PlanNode):
    """Repartition rows across `num_out` partitions (ShuffleExchangeExec analog)."""

    def __init__(self, child: PlanNode, partitioning: str, num_out: int,
                 keys: list | None = None):
        super().__init__(child)
        assert partitioning in ("hash", "single", "roundrobin", "range")
        self.partitioning = partitioning
        self.num_out = num_out
        self.keys = [E.bind_references(e, child.output) for e in (keys or [])]

    @property
    def output(self):
        return self.child.output

    @property
    def num_partitions(self):
        return self.num_out

    def execute_host(self, split):
        from spark_rapids_tpu.ops import hashing as H
        out_rows = []
        for i in range(self.child.num_partitions):
            tbl = self.child.execute_host(i)
            if self.partitioning == "single":
                pids = [0] * tbl.num_rows
            elif self.partitioning == "roundrobin":
                pids = [(r + i) % self.num_out for r in range(tbl.num_rows)]
            elif self.partitioning == "hash":
                cols = [eval_host(e, tbl) for e in self.keys]
                pids = []
                for r in range(tbl.num_rows):
                    h = 42
                    for c in cols:
                        v = c.data[r]
                        if v is None:
                            continue
                        h = _host_hash_one(v, c.dtype, h)
                    pids.append(h % self.num_out)  # python % == Spark Pmod
            else:
                raise NotImplementedError("host range partitioning")
            keep = [r for r in range(tbl.num_rows) if pids[r] == split]
            out_rows.append(tbl.take(pa.array(keep, pa.int64())))
        return pa.concat_tables(out_rows) if out_rows else self._empty()

    def args_string(self):
        return f"{self.partitioning}({self.num_out}) keys={self.keys}"


def _host_hash_one(v, dtype, seed):
    from spark_rapids_tpu.ops import hashing as H
    if isinstance(dtype, T.StringType):
        return H.murmur3_bytes_host(v.encode("utf-8"), seed)
    if isinstance(dtype, (T.LongType, T.TimestampType)):
        return H.murmur3_long_host(int(v), seed)
    if isinstance(dtype, T.DoubleType):
        import struct
        bits = struct.unpack("<q", struct.pack("<d", float(v)))[0]
        if math.isnan(float(v)):
            bits = 0x7ff8000000000000
        return H.murmur3_long_host(bits, seed)
    if isinstance(dtype, T.FloatType):
        import struct
        f32 = float(v)
        bits = struct.unpack("<i", struct.pack("<f", f32))[0]
        if math.isnan(f32):
            bits = 0x7fc00000
        return H.murmur3_int_host(bits, seed)
    if isinstance(dtype, T.BooleanType):
        return H.murmur3_int_host(1 if v else 0, seed)
    return H.murmur3_int_host(int(v), seed)


class WindowNode(PlanNode):
    """Window aggregation over partition/order specs (GpuWindowExec analog).
    Host interpreter lives in plan/host_window.py; the device exec in exec/window.py."""

    def __init__(self, window_exprs: list, child: PlanNode):
        """window_exprs: list of Alias(WindowExpression)."""
        super().__init__(child)
        self.window_exprs = [E.bind_references(e, child.output)
                             for e in window_exprs]

    @property
    def output(self):
        fields = list(self.child.output.fields)
        for i, e in enumerate(self.window_exprs):
            fields.append(T.StructField(_expr_name(e, len(fields)), e.dtype, True))
        return T.StructType(fields)

    @property
    def num_partitions(self):
        return 1

    def execute_host(self, split):
        from spark_rapids_tpu.plan.host_window import host_window
        tbl = pa.concat_tables([self.child.execute_host(i)
                                for i in range(self.child.num_partitions)])
        return host_window(self, tbl)


def build_rollup_expand(child: "PlanNode", keys: list):
    """ROLLUP lowering shared by the SQL front-end and DataFrame.rollup():
    the hierarchy-level grouping sets [all, all-1, ..., []] through the
    general grouping-sets Expand below."""
    n = len(keys)
    return build_grouping_sets_expand(
        child, keys, [list(range(level)) for level in range(n, -1, -1)])


def build_grouping_sets_expand(child: "PlanNode", keys: list, sets: list):
    """GROUPING SETS/CUBE/ROLLUP lowering: one Expand projection per
    grouping set, with group columns outside the set nulled out + a
    grouping-id literal whose bit i (MSB = first key, Spark convention) is
    1 when key i is nulled in that set (Spark's Expand form; reference
    GpuExpandExec role). `keys` must be BOUND column references; `sets` is
    a list of kept-key index lists. Returns (expand_node, group_refs,
    gid_ref)."""
    fields = list(child.output.fields)
    n = len(keys)
    projections = []
    for kept in sets:
        kept = set(kept)
        gid = sum(1 << (n - 1 - i) for i in range(n) if i not in kept)
        proj = [E.BoundReference(i, f.data_type, f.nullable, f.name)
                for i, f in enumerate(fields)]
        for gi, g in enumerate(keys):
            proj.append(g if gi in kept else E.Literal(None, g.dtype))
        proj.append(E.Literal(gid, T.INT))
        projections.append(proj)
    out_fields = fields + [
        T.StructField(f"_g{i}", g.dtype, True) for i, g in enumerate(keys)
    ] + [T.StructField("_gid", T.INT, False)]
    expand = ExpandNode(projections, out_fields, child)
    base = len(fields)
    group_refs = [
        E.BoundReference(base + i, g.dtype, True,
                         getattr(g, "name", None) or f"_g{i}")
        for i, g in enumerate(keys)]
    gid_ref = E.BoundReference(base + n, T.INT, False, "_gid")
    return expand, group_refs, gid_ref


class ExpandNode(PlanNode):
    """Each input row expands to len(projections) rows (GpuExpandExec analog,
    reference GpuExpandExec.scala)."""

    def __init__(self, projections: list, out_fields: list, child: PlanNode):
        super().__init__(child)
        self.projections = [[E.bind_references(e, child.output) for e in proj]
                            for proj in projections]
        self._out = T.StructType(out_fields)

    @property
    def output(self):
        return self._out

    def execute_host(self, split):
        tbl = self.child.execute_host(split)
        parts = [_project_table(tbl, proj, self.output)
                 for proj in self.projections]
        combined = pa.concat_tables(parts)
        # Spark emits projections interleaved per input row
        n, k = tbl.num_rows, len(self.projections)
        idx = [p * n + r for r in range(n) for p in range(k)]
        return combined.take(pa.array(idx, pa.int64()))


class GenerateNode(PlanNode):
    """explode/posexplode(array) generator (GpuGenerateExec analog). Device
    side the list column rides the arrow bridge as a ListVector and the
    expansion is one gather program (exec/generate.py)."""

    def __init__(self, generator_col: str, child: PlanNode, outer: bool = False,
                 element_type: T.DataType = None, pos: bool = False):
        super().__init__(child)
        self.generator_col = generator_col
        self.outer = outer
        self.pos = pos
        self.element_type = element_type or T.LONG
        taken = {f.name for f in child.output if f.name != generator_col}
        for out_name in (("pos", "col") if pos else ("col",)):
            if out_name in taken:  # Spark allows duplicate names; we don't
                raise ValueError(
                    f"explode output column '{out_name}' collides with an "
                    f"input column — rename the input first")

    @property
    def output(self):
        fields = [f for f in self.child.output if f.name != self.generator_col]
        if self.pos:
            fields.append(T.StructField("pos", T.INT, self.outer))
        fields.append(T.StructField("col", self.element_type, True))
        return T.StructType(fields)

    def execute_host(self, split):
        tbl = self.child.execute_host(split)
        gen = tbl.column(self.generator_col).to_pylist()
        keep_names = [f.name for f in self.output
                      if f.name not in ("col", "pos")]
        rows = {n: [] for n in keep_names}
        out_vals = []
        out_pos = []
        for i, arr in enumerate(gen):
            # null or empty array: explode drops the row, explode_outer keeps it
            items = arr if arr else ([None] if self.outer else [])
            for p, v in enumerate(items):
                for nme in keep_names:
                    rows[nme].append(tbl.column(nme)[i].as_py())
                out_vals.append(v)
                out_pos.append(p if arr else None)
        data = {n: pa.array(rows[n], T.to_arrow_type(
            next(f.data_type for f in self.output if f.name == n)))
            for n in keep_names}
        if self.pos:
            data["pos"] = pa.array(out_pos, pa.int32())
        data["col"] = pa.array(out_vals, T.to_arrow_type(self.element_type))
        return pa.table(data)


class MapInPandasNode(PlanNode):
    """df.mapInPandas(fn, schema) (reference GpuMapInPandasExec role). The
    host path runs the user fn in-process over the partition's batches."""

    def __init__(self, fn, schema: T.StructType, child: PlanNode):
        super().__init__(child)
        self.fn = fn
        self.schema = schema

    @property
    def output(self):
        return self.schema

    def execute_host(self, split):
        tbl = self.child.execute_host(split)
        dfs = iter([tbl.to_pandas()] if tbl.num_rows else [])
        outs = [pa.Table.from_pandas(df, schema=self.schema.to_arrow(),
                                     preserve_index=False)
                for df in self.fn(dfs)]
        return pa.concat_tables(outs) if outs else self._empty()

    def args_string(self):
        return f"fn={getattr(self.fn, '__name__', 'fn')}"


class GroupedMapInPandasNode(PlanNode):
    """groupBy(keys).applyInPandas(fn, schema) (reference
    GpuFlatMapGroupsInPandasExec role)."""

    def __init__(self, key_names: list, fn, schema: T.StructType,
                 child: PlanNode):
        super().__init__(child)
        self.key_names = list(key_names)
        self.fn = fn
        self.schema = schema
        for k in self.key_names:
            child.output.index_of(k)  # raises on unknown key

    @property
    def output(self):
        return self.schema

    @property
    def num_partitions(self):
        return 1  # host path groups globally

    def execute_host(self, split):
        tables = [self.child.execute_host(i)
                  for i in range(self.child.num_partitions)]
        df = pa.concat_tables(tables).to_pandas()
        outs = []
        if len(df):
            for _, g in df.groupby(self.key_names, dropna=False, sort=False):
                outs.append(pa.Table.from_pandas(
                    self.fn(g.reset_index(drop=True)),
                    schema=self.schema.to_arrow(), preserve_index=False))
        return pa.concat_tables(outs) if outs else self._empty()

    def args_string(self):
        return f"keys={self.key_names} fn={getattr(self.fn, '__name__', 'fn')}"


class CoGroupedMapInPandasNode(PlanNode):
    """cogroup(l, r).applyInPandas(fn, schema) (reference
    GpuFlatMapCoGroupsInPandasExec role)."""

    def __init__(self, left_keys: list, right_keys: list, fn,
                 schema: T.StructType, left: PlanNode, right: PlanNode):
        super().__init__(left, right)
        self.left_key_names = list(left_keys)
        self.right_key_names = list(right_keys)
        self.fn = fn
        self.schema = schema
        if len(self.left_key_names) != len(self.right_key_names):
            raise ValueError("cogroup key lists must have equal arity")
        for k in self.left_key_names:
            left.output.index_of(k)
        for k in self.right_key_names:
            right.output.index_of(k)

    @property
    def output(self):
        return self.schema

    @property
    def num_partitions(self):
        return 1

    def execute_host(self, split):
        from spark_rapids_tpu.udf.pandas_exec import _norm_key
        l = pa.concat_tables([self.children[0].execute_host(i)
                              for i in range(self.children[0].num_partitions)])
        r = pa.concat_tables([self.children[1].execute_host(i)
                              for i in range(self.children[1].num_partitions)])
        ldf, rdf = l.to_pandas(), r.to_pandas()

        def groups(df, keys):
            order, out = [], {}
            if len(df):
                for key, g in df.groupby(keys, dropna=False, sort=False):
                    k = _norm_key(key if isinstance(key, tuple) else (key,))
                    out[k] = g.reset_index(drop=True)
                    order.append(k)
            return out, order

        lg, lorder = groups(ldf, self.left_key_names)
        rg, rorder = groups(rdf, self.right_key_names)
        outs = []
        for k in lorder + [k for k in rorder if k not in lg]:
            le = lg.get(k, ldf.iloc[0:0])
            re = rg.get(k, rdf.iloc[0:0])
            outs.append(pa.Table.from_pandas(
                self.fn(le, re), schema=self.schema.to_arrow(),
                preserve_index=False))
        return pa.concat_tables(outs) if outs else self._empty()

    def args_string(self):
        return (f"lkeys={self.left_key_names} rkeys={self.right_key_names} "
                f"fn={getattr(self.fn, '__name__', 'fn')}")


class AggregateInPandasNode(PlanNode):
    """groupBy(keys).agg(pandas_agg_udf) (reference GpuAggregateInPandasExec
    role). udfs: list of (fn, [input col names], output name, dtype)."""

    def __init__(self, key_names: list, udfs: list, child: PlanNode):
        super().__init__(child)
        self.key_names = list(key_names)
        self.udfs = list(udfs)
        for k in self.key_names:
            child.output.index_of(k)

    @property
    def output(self):
        fields = []
        for k in self.key_names:
            f = self.child.output[self.child.output.index_of(k)]
            fields.append(T.StructField(k, f.data_type, True))
        for fn, cols, name, dtype in self.udfs:
            fields.append(T.StructField(name, dtype, True))
        return T.StructType(fields)

    @property
    def num_partitions(self):
        return 1

    def execute_host(self, split):
        df = pa.concat_tables([self.child.execute_host(i)
                               for i in range(self.child.num_partitions)]
                              ).to_pandas()
        schema = self.output.to_arrow()
        rows = {f.name: [] for f in schema}
        nkeys = len(self.key_names)
        if len(df):
            for key, g in df.groupby(self.key_names, dropna=False, sort=False):
                key = key if isinstance(key, tuple) else (key,)
                for i, k in enumerate(self.key_names):
                    v = key[i]
                    if isinstance(v, float) and v != v:
                        v = None  # pandas surfaces a null int64 key as NaN
                    rows[k].append(v)
                for fn, cols, name, _ in self.udfs:
                    rows[name].append(
                        fn(*[g[c].reset_index(drop=True) for c in cols]))
        cols = [pa.array(rows[f.name], type=f.type) for f in schema]
        return pa.Table.from_arrays(cols, schema=schema)

    def args_string(self):
        return f"keys={self.key_names} udfs={len(self.udfs)}"


class RemoteSourceNode(PlanNode):
    """Stage input: shuffle blocks served by cluster executors (the stage
    boundary the MiniCluster driver leaves behind after scheduling a map
    stage — reference role: ShuffledRowRDD reading RapidsShuffleManager
    blocks, RapidsShuffleInternalManagerBase.scala:200).

    `locations` are (host, port) block servers; partition r is the union of
    every executor's blocks for reduce id r. When the driver ships a task it
    PINS the node to that task's reduce id (pinned_reduce), making the node
    single-partition so stage-local planning never inserts exchanges."""

    def __init__(self, shuffle_id: int, schema: T.StructType, n_parts: int,
                 locations: list, pinned_reduce: int | None = None,
                 epoch: int = 0):
        super().__init__()
        self.shuffle_id = shuffle_id
        self.schema = schema
        self.n_parts = n_parts
        self.locations = list(locations)
        self.pinned_reduce = pinned_reduce
        # map-output epoch this node's metadata (locations) was stamped at;
        # the driver's MapOutputTracker bumps the shuffle's epoch whenever
        # map outputs are invalidated/recomputed, and discards any task
        # reply computed under a stale epoch (the reducer may have seen a
        # half-rebuilt partition)
        self.epoch = epoch

    @property
    def output(self):
        return self.schema

    @property
    def num_partitions(self):
        return 1 if self.pinned_reduce is not None else self.n_parts

    def pinned(self, reduce_id: int) -> "RemoteSourceNode":
        return RemoteSourceNode(self.shuffle_id, self.schema, self.n_parts,
                                self.locations, pinned_reduce=reduce_id,
                                epoch=self.epoch)

    def execute_host(self, split):
        from spark_rapids_tpu import config as CFG
        from spark_rapids_tpu.config import RapidsConf
        from spark_rapids_tpu.shuffle.transport import (InflightThrottle,
                                                        TcpShuffleClient)
        conf = RapidsConf()
        bounce = conf.get(CFG.SHUFFLE_BOUNCE_BUFFER_SIZE)
        throttle = InflightThrottle(conf.get(CFG.SHUFFLE_MAX_INFLIGHT_BYTES))
        rid = self.pinned_reduce if self.pinned_reduce is not None else split
        tables = []
        for addr in self.locations:
            client = TcpShuffleClient(tuple(addr), bounce, throttle)
            for batch in client.fetch_blocks(self.shuffle_id, rid):
                tables.append(batch.to_arrow())
        return pa.concat_tables(tables) if tables else self._empty()

    def args_string(self):
        return (f"shuffle={self.shuffle_id} parts={self.n_parts} "
                f"pinned={self.pinned_reduce} hosts={len(self.locations)} "
                f"epoch={self.epoch}")
