"""L3 planner/override layer: plan rewrite engine, metas, type checks, transitions.

Reference: GpuOverrides.scala:431/3013, RapidsMeta.scala:70, TypeChecks.scala:129,
GpuTransitionOverrides.scala:40, CostBasedOptimizer.scala:52 (SURVEY.md §1 L3)."""

from spark_rapids_tpu.plan.nodes import (  # noqa: F401
    PlanNode, ScanNode, ProjectNode, FilterNode, AggregateNode, JoinNode,
    SortNode, LimitNode, UnionNode, RangeNode, ExchangeNode, WindowNode,
    ExpandNode, GenerateNode,
)
from spark_rapids_tpu.plan.overrides import TpuOverrides, explain_plan  # noqa: F401
