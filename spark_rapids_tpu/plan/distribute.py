"""Distributed planning: EnsureRequirements at the plan level.

Reference analogy: Spark's EnsureRequirements inserts ShuffleExchangeExec
wherever a child's output partitioning does not satisfy an operator's
required distribution; the MiniCluster driver (cluster/minicluster.py) then
splits the plan at the explicit ExchangeNodes into stages, exactly like
Spark's DAGScheduler splits at ShuffleDependency boundaries.

The single-process engine instead inserts exchanges at the EXEC level inside
TpuOverrides conversions — that is invisible to a cluster scheduler, so the
distributed path makes every data movement explicit in the PLAN first. After
this pass, any operator that needs co-located rows (keyed aggregate, equi
join, window partitions, grouped pandas UDFs, global sort/limit) sits above
an ExchangeNode that guarantees it; shipping each stage task with its
sources pinned to one reduce partition then makes every stage-local
conversion take the single-partition (no internal exchange) path.
"""

from __future__ import annotations

from spark_rapids_tpu.expr import core as E
from spark_rapids_tpu.plan import nodes as NN


def _hash_dist(child, keys, n_parts):
    """Hash-exchange unless the child is already exchanged on the same keys."""
    if not keys:
        return _single_dist(child)
    if (isinstance(child, NN.ExchangeNode) and child.partitioning == "hash"
            and [repr(k) for k in child.keys] == [repr(k) for k in keys]):
        return child
    return NN.ExchangeNode(child, "hash", n_parts, keys=keys)


def dist_parts(node) -> int:
    """Partition count under DISTRIBUTED execution. PlanNode.num_partitions
    describes the single-process host interpreter (e.g. AggregateNode says 1
    because the interpreter aggregates globally); distributed operators are
    partition-preserving above the exchange this pass gave them."""
    if isinstance(node, NN.ExchangeNode):
        return node.num_out
    if isinstance(node, NN.RemoteSourceNode):
        return node.num_partitions
    if isinstance(node, NN.UnionNode):
        return sum(dist_parts(c) for c in node.children)
    if not node.children:
        return node.num_partitions
    return dist_parts(node.children[0])


def _single_dist(child):
    if dist_parts(child) == 1:
        return child
    return NN.ExchangeNode(child, "single", 1)


def ensure_distribution(node: NN.PlanNode, n_parts: int) -> NN.PlanNode:
    """Bottom-up rewrite inserting the exchanges each operator requires."""
    node.children = [ensure_distribution(c, n_parts) for c in node.children]

    if isinstance(node, NN.AggregateNode):
        keys = [k for k in node.group_exprs]
        node.children = [_hash_dist(node.child, keys, n_parts)]
    elif isinstance(node, NN.JoinNode):
        left, right = node.children
        if node.left_keys:
            # co-partition both sides with the same arity
            node.children = [
                _hash_dist(left, node.left_keys, n_parts),
                _hash_dist(right, node.right_keys, n_parts)]
        else:
            # keyless (cross / conditional) join: all rows in one task
            node.children = [_single_dist(left), _single_dist(right)]
    elif isinstance(node, NN.SortNode) and getattr(node, "global_sort", False):
        node.children = [_single_dist(node.child)]
    elif isinstance(node, NN.LimitNode) and node.global_limit:
        node.children = [_single_dist(node.child)]
    elif isinstance(node, NN.WindowNode):
        from spark_rapids_tpu.expr import windows as WX

        def _unalias(e):
            return e.child if isinstance(e, E.Alias) else e
        spec = _unalias(node.window_exprs[0]).spec
        part_by = list(spec.partition_by)
        node.children = ([_hash_dist(node.child, part_by, n_parts)]
                         if part_by else [_single_dist(node.child)])
    elif isinstance(node, NN.GroupedMapInPandasNode):
        keys = [E.col(k) for k in node.key_names]
        node.children = [_hash_dist(node.child, keys, n_parts)]
    elif isinstance(node, NN.AggregateInPandasNode):
        keys = [E.col(k) for k in node.key_names]
        node.children = ([_hash_dist(node.child, keys, n_parts)]
                         if keys else [_single_dist(node.child)])
    elif isinstance(node, NN.CoGroupedMapInPandasNode):
        left, right = node.children
        node.children = [
            _hash_dist(left, [E.col(k) for k in node.left_key_names], n_parts),
            _hash_dist(right, [E.col(k) for k in node.right_key_names],
                       n_parts)]
    return node


def stage_order(root: NN.PlanNode) -> list:
    """Exchanges in bottom-up (dependency) order. Each entry is
    (exchange_node, parent_node, child_index); the root 'result stage' is the
    plan itself after all exchanges are replaced."""
    out = []

    def walk(node, parent, idx):
        for i, c in enumerate(node.children):
            walk(c, node, i)
        if isinstance(node, NN.ExchangeNode) and parent is not None:
            out.append((node, parent, idx))

    walk(root, None, 0)
    return out
