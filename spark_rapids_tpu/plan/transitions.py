"""Transition pass — host↔device bridges and coalesce insertion.

Reference: GpuTransitionOverrides.scala:40/:484 inserts GpuRowToColumnarExec /
GpuColumnarToRowExec / HostColumnarToGpu fences and coalesce nodes
(:305 insertCoalesce). Here the fences are DeviceBridgeExec (host rows → device
columns, the RowToColumnar analog) and HostBridgeNode (device columns → host arrow,
the ColumnarToRow analog); coalesce is inserted after exchanges per the child's
coalesce goal (GpuTransitionOverrides.scala:57-63)."""

from __future__ import annotations

import copy

import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import TpuExec, acquire_semaphore
from spark_rapids_tpu.plan.nodes import PlanNode


class DeviceBridgeExec(TpuExec):
    """Runs a host plan subtree and moves its output onto the device
    (reference GpuRowToColumnarExec / HostColumnarToGpu,
    GpuRowToColumnarExec.scala:788, HostColumnarToGpu.scala:249)."""

    def __init__(self, host_node: PlanNode, conf=None):
        from spark_rapids_tpu.config import RapidsConf
        super().__init__(conf=conf or RapidsConf())
        self.host_node = host_node

    @property
    def output(self):
        return self.host_node.output

    @property
    def num_partitions(self):
        return self.host_node.num_partitions

    def execute_partition(self, split):
        def it():
            tbl = self.host_node.execute_host(split)
            acquire_semaphore(self.metrics)
            yield ColumnarBatch.from_arrow(tbl, self.output)
        return self.wrap_output(it())


class HostBridgeNode(PlanNode):
    """Runs a device subtree and materializes arrow tables for a host parent
    (reference GpuColumnarToRowExec, GpuColumnarToRowExec.scala:341)."""

    def __init__(self, tpu_exec: TpuExec):
        super().__init__()
        self.tpu_exec = tpu_exec

    @property
    def output(self):
        return self.tpu_exec.output

    @property
    def num_partitions(self):
        return self.tpu_exec.num_partitions

    def execute_host(self, split):
        from spark_rapids_tpu.exec.base import TaskContext
        tables = []
        with TaskContext():
            for batch in self.tpu_exec.execute_partition(split):
                tables.append(batch.to_arrow())
        if not tables:
            return self._empty()
        return pa.concat_tables(tables)

    def name(self):
        return "HostBridge"

    def tree_string(self, indent: int = 0):
        lines = ["  " * indent + "HostBridge [device subtree below]"]
        lines.append(self.tpu_exec.tree_string(indent + 1)
                     if hasattr(self.tpu_exec, "tree_string")
                     else "  " * (indent + 1) + type(self.tpu_exec).__name__)
        return "\n".join(lines)


def build_hybrid(meta):
    """Postorder conversion: fully-supported subtrees become TpuExec trees; a host
    node above a converted subtree reads through a HostBridgeNode; a converted node
    above a host subtree reads through a DeviceBridgeExec. Returns either a TpuExec
    (whole plan on device) or a PlanNode (root stayed on host)."""
    node = meta.node
    kids = [build_hybrid(m) for m in meta.child_metas]

    if meta.can_run_on_tpu and meta.rule is not None:
        # lift host children onto the device through bridges
        dev_kids = [k if isinstance(k, TpuExec) else DeviceBridgeExec(k, meta.conf)
                    for k in kids]
        return meta.rule.convert(meta, dev_kids)

    # node stays on host: device children drop back through bridges. Rewire a
    # shallow COPY so the user's logical plan is never mutated — a DataFrame
    # re-planned for a second action must not see stale HostBridgeNode wrappers
    # holding already-consumed exec instances.
    host_kids = [k if isinstance(k, PlanNode) else HostBridgeNode(k)
                 for k in kids]
    clone = copy.copy(node)
    clone.children = host_kids
    return clone


def to_device_plan(plan, conf) -> TpuExec:
    """Apply the overrides and guarantee a device root (bridging a host root up
    through DeviceBridgeExec) — shared by ML export and the cache."""
    from spark_rapids_tpu.plan.overrides import TpuOverrides
    hybrid = TpuOverrides(conf).apply(plan)
    if not isinstance(hybrid, TpuExec):
        hybrid = DeviceBridgeExec(hybrid, conf)
    return hybrid


def execute_hybrid(plan) -> pa.Table:
    """Collect a hybrid plan to a host arrow table regardless of where the root
    landed (test harness entry)."""
    if isinstance(plan, TpuExec):
        return plan.execute_collect()
    return plan.collect_host()
