"""RapidsMeta analog — per-node wrappers carrying tagging state and conversion.

Reference: RapidsMeta.scala:70 (base wrapper), :162 (willNotWorkOnGpu), :253
(tagForGpu), :633 (convertIfNeeded); SparkPlanMeta:512, BaseExprMeta:737. Each plan
node / expression gets a meta that records why it cannot run on the TPU; conversion
replaces supported subtrees and leaves the rest on the host."""

from __future__ import annotations

import typing

from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.expr import core as E


class RapidsMeta:
    def __init__(self, conf: RapidsConf, parent: "RapidsMeta | None" = None):
        self.conf = conf
        self.parent = parent
        self.reasons: list[str] = []
        self.child_metas: list[RapidsMeta] = []

    def will_not_work(self, reason: str) -> None:
        """Record a reason this node must stay on the host
        (reference willNotWorkOnGpu, RapidsMeta.scala:162)."""
        if reason not in self.reasons:
            self.reasons.append(reason)

    @property
    def can_run_on_tpu(self) -> bool:
        return not self.reasons

    @property
    def can_this_and_children_run(self) -> bool:
        return self.can_run_on_tpu and all(
            m.can_this_and_children_run for m in self.child_metas)

    def tag_for_tpu(self) -> None:
        raise NotImplementedError

    def explain(self, indent: int = 0, all_nodes: bool = True) -> str:
        raise NotImplementedError


class ExprMeta(RapidsMeta):
    """Wrapper for one expression node (reference BaseExprMeta:737)."""

    def __init__(self, expr: E.Expression, rule, conf, parent=None):
        super().__init__(conf, parent)
        self.expr = expr
        self.rule = rule
        from spark_rapids_tpu.plan.overrides import wrap_expr
        self.child_metas = [wrap_expr(c, conf, self)
                            for c in getattr(expr, "children", [])]

    def tag_for_tpu(self):
        if self.rule is None:
            self.will_not_work(
                f"expression {type(self.expr).__name__} has no TPU implementation")
        else:
            if self.rule.checks is not None:
                self.rule.checks.tag(self)
            if self.rule.disabled_by_conf(self.conf):
                self.will_not_work(
                    f"expression {type(self.expr).__name__} disabled by conf "
                    f"{self.rule.conf_key}")
            if self.rule.tag_fn is not None:
                self.rule.tag_fn(self)
        for m in self.child_metas:
            m.tag_for_tpu()

    def explain(self, indent=0, all_nodes=True):
        status = "will run on TPU" if self.can_run_on_tpu else (
            "cannot run on TPU because " + "; ".join(self.reasons))
        mine = "  " * indent + f"@{type(self.expr).__name__} {status}"
        lines = [mine] if (all_nodes or not self.can_run_on_tpu) else []
        for m in self.child_metas:
            sub = m.explain(indent + 1, all_nodes)
            if sub:
                lines.append(sub)
        return "\n".join(lines)


class PlanMeta(RapidsMeta):
    """Wrapper for one plan node (reference SparkPlanMeta:512)."""

    def __init__(self, node, rule, conf, parent=None):
        super().__init__(conf, parent)
        self.node = node
        self.rule = rule
        from spark_rapids_tpu.plan.overrides import wrap_expr, wrap_plan_meta
        self.child_metas = [wrap_plan_meta(c, conf, self)
                            for c in node.children]
        self.expr_metas = [wrap_expr(e, conf, self)
                           for e in self._node_expressions()]

    def _node_expressions(self) -> list:
        from spark_rapids_tpu.plan import nodes as NN
        n = self.node
        if isinstance(n, NN.ProjectNode):
            return list(n.project_list)
        if isinstance(n, NN.FilterNode):
            return [n.condition]
        if isinstance(n, NN.AggregateNode):
            return list(n.group_exprs) + list(n.agg_exprs)
        if isinstance(n, NN.JoinNode):
            ex = list(n.left_keys) + list(n.right_keys)
            if n.condition is not None:
                ex.append(n.condition)
            return ex
        if isinstance(n, NN.SortNode):
            return [e for (e, _, _) in n.sort_exprs]
        if isinstance(n, NN.ExchangeNode):
            return list(n.keys)
        if isinstance(n, NN.ExpandNode):
            return [e for proj in n.projections for e in proj]
        if isinstance(n, NN.WindowNode):
            return list(n.window_exprs)
        return []

    def tag_for_tpu(self):
        if self.rule is None:
            self.will_not_work(
                f"exec {type(self.node).__name__} has no TPU implementation")
        else:
            if self.rule.checks is not None:
                self.rule.checks.tag(self)
            if self.rule.disabled_by_conf(self.conf):
                self.will_not_work(
                    f"exec {type(self.node).__name__} disabled by conf "
                    f"{self.rule.conf_key}")
            if self.rule.tag_fn is not None:
                self.rule.tag_fn(self)
        for m in self.expr_metas:
            m.tag_for_tpu()
        # an unsupported expression anywhere in the node pins the node to host
        for m in self.expr_metas:
            if not m.can_this_and_children_run:
                self.will_not_work(
                    "not all expressions can run on TPU: " + _first_reason(m))
        for m in self.child_metas:
            m.tag_for_tpu()

    def convert_if_needed(self):
        """Produce the hybrid plan: TpuExec subtrees where possible, host nodes
        elsewhere, with transitions inserted by plan/transitions.py
        (reference convertIfNeeded, RapidsMeta.scala:633)."""
        from spark_rapids_tpu.plan.transitions import build_hybrid
        return build_hybrid(self)

    def explain(self, indent=0, all_nodes=True):
        status = ("will run on TPU" if self.can_run_on_tpu else
                  "cannot run on TPU because " + "; ".join(self.reasons))
        lines = ["  " * indent + f"*{type(self.node).__name__} {status}"]
        for m in self.expr_metas:
            sub = m.explain(indent + 1, all_nodes)
            if sub:
                lines.append(sub)
        for m in self.child_metas:
            lines.append(m.explain(indent + 1, all_nodes))
        return "\n".join(lines)


def _first_reason(meta: RapidsMeta) -> str:
    if meta.reasons:
        return meta.reasons[0]
    for m in meta.child_metas:
        r = _first_reason(m)
        if r:
            return r
    return ""
