"""TpuOverrides — the plan rewrite engine (GpuOverrides analog).

Reference: GpuOverrides.scala:431 (rule registry), :2723 (wrapPlan), :3013/3037
(apply: wrap → tag → explain → convert), RapidsConf `spark.rapids.sql.explain`.
Rules are keyed by node/expression class; tagging records host-pinning reasons;
conversion produces a hybrid host/TPU plan with transitions inserted."""

from __future__ import annotations

import typing

from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.expr import core as E
from spark_rapids_tpu.plan import typesig as TS
from spark_rapids_tpu.plan.meta import ExprMeta, PlanMeta


class ExprRule:
    """Reference ExprRule, GpuOverrides.scala:204."""

    def __init__(self, description: str, checks: TS.ExprChecks | None = None,
                 conf_key: str | None = None, tag_fn=None):
        self.description = description
        self.checks = checks
        self.conf_key = conf_key
        self.tag_fn = tag_fn

    def disabled_by_conf(self, conf: RapidsConf) -> bool:
        if not self.conf_key:
            return False
        from spark_rapids_tpu import config as CFG
        entry = (self.conf_key if not isinstance(self.conf_key, str)
                 else CFG._REGISTERED[self.conf_key])
        return not conf.get(entry)


class ExecRule:
    """Reference ExecRule, GpuOverrides.scala:260. `convert(meta, tpu_children)`
    builds the TpuExec for an approved node."""

    def __init__(self, description: str, convert, checks: TS.ExecChecks | None = None,
                 conf_key: str | None = None, tag_fn=None):
        self.description = description
        self.convert = convert
        self.checks = checks
        self.conf_key = conf_key
        self.tag_fn = tag_fn

    def disabled_by_conf(self, conf: RapidsConf) -> bool:
        if not self.conf_key:
            return False
        from spark_rapids_tpu import config as CFG
        entry = (self.conf_key if not isinstance(self.conf_key, str)
                 else CFG._REGISTERED[self.conf_key])
        return not conf.get(entry)


class Registry:
    def __init__(self):
        self.exec_rules: dict = {}
        self.expr_rules: dict = {}

    def exec_rule(self, node_cls, rule: ExecRule):
        self.exec_rules[node_cls] = rule

    def expr_rule(self, expr_cls, rule: ExprRule):
        self.expr_rules[expr_cls] = rule

    def lookup_expr(self, expr) -> ExprRule | None:
        r = self.expr_rules.get(type(expr))
        if r is not None:
            return r
        for cls, rule in self.expr_rules.items():
            if isinstance(expr, cls):
                return rule
        return None

    def lookup_exec(self, node) -> ExecRule | None:
        return self.exec_rules.get(type(node))


REGISTRY = Registry()


def wrap_expr(expr: E.Expression, conf: RapidsConf, parent=None) -> ExprMeta:
    return ExprMeta(expr, REGISTRY.lookup_expr(expr), conf, parent)


def wrap_plan_meta(node, conf: RapidsConf, parent=None) -> PlanMeta:
    return PlanMeta(node, REGISTRY.lookup_exec(node), conf, parent)


def extract_python_udfs(plan):
    """Spark ExtractPythonUDFs analog: pull PythonUDF calls out of filter
    conditions, sort keys, and aggregate inputs into a projection below the
    operator, so the UDF rides ArrowEvalPythonExec (the
    GpuArrowEvalPythonExec path) while the residual operator stays on
    device. Filter(cond[udf]) becomes
    Project[orig] ∘ Filter(cond[ref]) ∘ Project[orig..., udf AS __pyudf_j];
    Sort and Aggregate are rewritten the same way.
    Non-mutating: rebuilt nodes are fresh; untouched subtrees are shared.
    """
    import copy as _copy
    from spark_rapids_tpu.plan.nodes import (AggregateNode, FilterNode,
                                             ProjectNode, SortNode,
                                             _expr_name)
    from spark_rapids_tpu.udf.python_runtime import PythonUDF

    def replace_canonical(expr, ref_fn):
        r = ref_fn(expr)
        if r is not None:
            return r
        if not expr.children:
            return expr
        return expr.with_children(
            [replace_canonical(c, ref_fn) for c in expr.children])

    def outermost_udfs(exprs):
        """(canonical outermost udfs, occurrence-id → canonical map).
        bind_references copies expression nodes, so identity dedupe misses
        reuse — canonicalize on STRUCTURE (function + repr'd argument
        tree): one projected column (and one worker round trip) feeds
        every use site."""
        udfs = []
        for e in exprs:
            udfs.extend(e.collect(lambda x: isinstance(x, PythonUDF)))

        def skey(u):
            return (id(u.fn), u.vectorized, repr(u.children))
        by_key, canon = {}, {}
        for u in udfs:
            k = skey(u)
            by_key.setdefault(k, u)
            canon[id(u)] = by_key[k]
        uniq = list(by_key.values())
        # drop UDFs nested inside another extracted UDF — the outer one's
        # worker evaluation computes them, a separate column would be dead
        nested = {skey(d) for u in uniq for c in u.children
                  for d in c.collect(lambda x: isinstance(x, PythonUDF))}
        return [u for u in uniq if skey(u) not in nested], canon

    def extract(exprs, child):
        """(rewritten exprs, udf projection node, base refs) or None."""
        udfs, canon = outermost_udfs(exprs)
        if not udfs:
            return None
        base = [E.BoundReference(i, f.data_type, f.nullable, f.name)
                for i, f in enumerate(child.output.fields)]
        k = len(base)
        proj, ref_of = list(base), {}
        for j, u in enumerate(udfs):
            ref_of[id(u)] = E.BoundReference(k + j, u.dtype, True,
                                             f"__pyudf_{j}")
            proj.append(E.Alias(u, f"__pyudf_{j}"))
        # every occurrence (bind_references may have copied the same UDF
        # into distinct objects) maps to its canonical column
        from spark_rapids_tpu.udf.python_runtime import PythonUDF as _PU

        def canonical_ref(x):
            if isinstance(x, _PU):
                c = canon.get(id(x))
                if c is not None and id(c) in ref_of:
                    return ref_of[id(c)]
            return None
        new_exprs = [replace_canonical(e, canonical_ref) for e in exprs]
        return new_exprs, ProjectNode(proj, child), base

    def rewrite(node):
        kids = [rewrite(c) for c in node.children]
        if any(k is not o for k, o in zip(kids, node.children)):
            node = _copy.copy(node)
            node.children = kids
        # NB: join conditions are NOT extracted — the pair schema carries
        # duplicate key names that the arrow bridge cannot materialize; a
        # UDF join condition pins the join to host (documented limitation)
        if isinstance(node, FilterNode):
            got = extract([node.condition], node.children[0])
            if got is None:
                return node
            (cond,), proj, base = got
            return ProjectNode(base, FilterNode(cond, proj))
        if isinstance(node, SortNode):
            keys = [e for (e, _a, _nf) in node.sort_exprs]
            got = extract(keys, node.children[0])
            if got is None:
                return node
            new_keys, proj, base = got
            new_sort = [(ne, a, nf) for ne, (_e, a, nf)
                        in zip(new_keys, node.sort_exprs)]
            return ProjectNode(base,
                               SortNode(new_sort, proj, node.global_sort))
        if isinstance(node, AggregateNode):
            exprs = list(node.group_exprs) + list(node.agg_exprs)
            got = extract(exprs, node.children[0])
            if got is None:
                return node
            new_exprs, proj, _base = got
            ng = len(node.group_exprs)
            # preserve output column names: a group key replaced wholesale
            # by a __pyudf_ reference would otherwise rename the column
            new_groups = [
                ne if _expr_name(ne, i) == _expr_name(oe, i)
                else E.Alias(ne, _expr_name(oe, i))
                for i, (ne, oe) in enumerate(zip(new_exprs[:ng],
                                                 node.group_exprs))]
            return AggregateNode(new_groups, new_exprs[ng:], proj)
        return node

    return rewrite(plan)


class TpuOverrides:
    """Entry point: CPU plan → hybrid plan (reference GpuOverrides.apply:3017)."""

    def __init__(self, conf: RapidsConf | None = None):
        self.conf = conf or RapidsConf()

    def apply(self, plan):
        if not self.conf.is_sql_enabled:
            return plan
        from spark_rapids_tpu.plan.pruning import prune_columns
        plan = prune_columns(plan)   # Catalyst ColumnPruning analog
        plan = extract_python_udfs(plan)
        meta = wrap_plan_meta(plan, self.conf)
        meta.tag_for_tpu()
        from spark_rapids_tpu.plan.cbo import optimize
        optimize(meta)  # no-op unless spark.rapids.tpu.sql.optimizer.enabled
        explain = self.conf.explain
        if explain != "NONE":
            print(meta.explain(all_nodes=(explain == "ALL")))
        return meta.convert_if_needed()


def explain_plan(plan, conf: RapidsConf | None = None, all_nodes=True) -> str:
    conf = conf or RapidsConf()
    plan = extract_python_udfs(plan)
    meta = wrap_plan_meta(plan, conf)
    meta.tag_for_tpu()
    from spark_rapids_tpu.plan.cbo import optimize
    optimize(meta)
    return meta.explain(all_nodes=all_nodes)


# ---------------------------------------------------------------------------
# Rule registration (reference GpuOverrides.scala:773-2987)
# ---------------------------------------------------------------------------

def _register_all():
    from spark_rapids_tpu.expr import arithmetic as A
    from spark_rapids_tpu.expr import predicates as P
    from spark_rapids_tpu.expr import nullexprs as N
    from spark_rapids_tpu.expr import conditional as C
    from spark_rapids_tpu.expr import mathexprs as MM
    from spark_rapids_tpu.expr import strings as S
    from spark_rapids_tpu.expr import datetime as DT
    from spark_rapids_tpu.expr import aggregates as AG
    from spark_rapids_tpu.expr.cast import Cast
    from spark_rapids_tpu.plan import nodes as NN

    R = REGISTRY

    # -- expressions ---------------------------------------------------------
    num = TS.NUMERIC
    ordr = TS.ORDERABLE
    comm = TS.COMMON

    def ex(cls, desc, out_sig, in_sig=None, conf_key=None, tag_fn=None):
        R.expr_rule(cls, ExprRule(desc, TS.ExprChecks(out_sig, in_sig),
                                  conf_key, tag_fn))

    ex(E.AttributeReference, "column reference", TS.ALL)
    ex(E.BoundReference, "bound column reference", TS.ALL)
    ex(E.Literal, "literal value", TS.ALL)
    ex(E.Alias, "named expression", TS.ALL)

    for cls in (A.Add, A.Subtract, A.Multiply):
        ex(cls, f"{cls.__name__.lower()} of two numbers", num + TS.DECIMAL, num + TS.DECIMAL)
    ex(A.Divide, "division (double or decimal)", TS.FRACTIONAL + TS.DECIMAL)
    ex(A.IntegralDivide, "integral division", TS.INTEGRAL)
    ex(A.Remainder, "remainder", num)
    ex(A.Pmod, "positive modulo", num)
    ex(A.UnaryMinus, "negation", num + TS.DECIMAL)
    ex(A.Abs, "absolute value", num + TS.DECIMAL)

    for cls in (P.EqualTo, P.NotEqual, P.LessThan, P.LessThanOrEqual,
                P.GreaterThan, P.GreaterThanOrEqual, P.EqualNullSafe):
        ex(cls, "comparison", TS.BOOLEAN, ordr)
    for cls in (P.And, P.Or, P.Not):
        ex(cls, "boolean logic", TS.BOOLEAN, TS.BOOLEAN)
    ex(P.In, "IN membership", TS.BOOLEAN)

    for cls in (N.IsNull, N.IsNotNull):
        ex(cls, "null test", TS.BOOLEAN, TS.ALL)
    ex(N.IsNaN, "NaN test", TS.BOOLEAN, TS.FRACTIONAL)
    ex(N.Coalesce, "first non-null", comm + TS.DECIMAL)
    ex(N.NaNvl, "NaN replacement", TS.FRACTIONAL)
    ex(C.If, "conditional", comm + TS.DECIMAL)
    ex(C.CaseWhen, "case/when", comm + TS.DECIMAL)

    for cls in (MM.Sqrt, MM.Exp, MM.Sin, MM.Cos, MM.Tan, MM.Asin, MM.Acos,
                MM.Atan, MM.Cbrt, MM.Signum, MM.ToDegrees, MM.ToRadians,
                MM.Log, MM.Log2, MM.Log10, MM.Log1p, MM.Pow, MM.Atan2):
        ex(cls, "math function", TS.FRACTIONAL, TS.FRACTIONAL)
    ex(MM.Floor, "floor", TS.INTEGRAL + TS.FRACTIONAL)
    ex(MM.Ceil, "ceiling", TS.INTEGRAL + TS.FRACTIONAL)
    ex(MM.Round, "half-up rounding", num)

    for cls in (S.Upper, S.Lower, S.Trim, S.LTrim, S.RTrim, S.Reverse,
                S.InitCap, S.Concat, S.StringReplace, S.Substring, S.Md5):
        ex(cls, "string function", TS.STRING, TS.STRING + TS.INTEGRAL)
    ex(S.Length, "string length", TS.TypeSig([T.IntegerType]), TS.STRING)
    for cls in (S.StartsWith, S.EndsWith, S.Contains, S.Like, S.RLike):
        ex(cls, "string predicate", TS.BOOLEAN, TS.STRING)

    for cls in (DT.Year, DT.Month, DT.DayOfMonth, DT.DayOfWeek, DT.WeekDay,
                DT.DayOfYear, DT.Quarter, DT.LastDay):
        ex(cls, "date part", TS.TypeSig([T.IntegerType, T.DateType]), TS.DATE)
    for cls in (DT.Hour, DT.Minute, DT.Second):
        ex(cls, "time part", TS.TypeSig([T.IntegerType]), TS.TIMESTAMP)
    ex(DT.DateAdd, "date arithmetic", TS.DATE)
    ex(DT.DateDiff, "date difference", TS.TypeSig([T.IntegerType]), TS.DATE)
    ex(DT.UnixTimestampSeconds, "timestamp→seconds", TS.TypeSig([T.LongType]))

    def tag_cast(meta):
        c = meta.expr
        from spark_rapids_tpu import config as CFG
        if (isinstance(c.children[0].dtype, T.StringType)
                and isinstance(c.dtype, T.FractionalType)
                and not meta.conf.get(CFG.ENABLE_CAST_STRING_TO_FLOAT)):
            meta.will_not_work(
                "cast string→float disabled: rounding may differ from Spark "
                "(enable with spark.rapids.tpu.sql.castStringToFloat.enabled)")
        if (isinstance(c.children[0].dtype, T.StringType)
                and isinstance(c.dtype, (T.DateType, T.TimestampType))):
            from spark_rapids_tpu.shims import shim_for
            shim = shim_for(meta.conf)
            lenient = (shim.lenient_string_to_date
                       if isinstance(c.dtype, T.DateType)
                       else shim.lenient_string_to_timestamp)
            if lenient:
                meta.will_not_work(
                    f"Spark 3.0-generation lenient {c.dtype} strings are "
                    "not implemented by the device parser (shim "
                    f"{shim!r} pins this cast to host)")
    ex(Cast, "type cast", TS.ALL, None, None, tag_cast)

    for cls in (AG.Sum, AG.Count, AG.Min, AG.Max, AG.Average, AG.First,
                AG.Last):
        ex(cls, "aggregate function", comm + TS.DECIMAL)
    for cls in (AG.VariancePop, AG.VarianceSamp, AG.StddevPop, AG.StddevSamp):
        ex(cls, "central-moment aggregate", TS.FRACTIONAL, num)

    # -- bitwise (reference org/apache/spark/sql/rapids/bitwise.scala) -------
    for cls in (A.BitwiseAnd, A.BitwiseOr, A.BitwiseXor):
        ex(cls, "bitwise binary op", TS.INTEGRAL, TS.INTEGRAL)
    ex(A.BitwiseNot, "bitwise not", TS.INTEGRAL, TS.INTEGRAL)
    for cls in (A.ShiftLeft, A.ShiftRight, A.ShiftRightUnsigned):
        ex(cls, "java shift", TS.INTEGRAL, TS.INTEGRAL)

    # -- more math (mathExpressions.scala) ------------------------------------
    for cls in (MM.Sinh, MM.Cosh, MM.Tanh, MM.Asinh, MM.Acosh, MM.Atanh,
                MM.Expm1, MM.Rint, MM.Cot):
        ex(cls, "math function", TS.FRACTIONAL, TS.FRACTIONAL)
    ex(MM.Logarithm, "log with arbitrary base", TS.FRACTIONAL, TS.FRACTIONAL)
    ex(A.UnaryPositive, "unary plus", TS.NUMERIC + TS.DECIMAL,
       TS.NUMERIC + TS.DECIMAL)
    ex(N.AtLeastNNonNulls, "dropna predicate", TS.BOOLEAN, TS.ALL)
    ex(C.Least, "least of arguments", ordr)
    ex(C.Greatest, "greatest of arguments", ordr)

    # -- more strings (stringFunctions.scala) ---------------------------------
    def _lit_args_tag(first_child_count=1):
        def tag(meta):
            e = meta.expr
            for a in e.children[first_child_count:]:
                if not isinstance(a, E.Literal):
                    meta.will_not_work(
                        f"{type(e).__name__} requires literal arguments on "
                        "the device (reference has the same limit)")
                    return
        return tag

    def tag_concat_ws(meta):
        sep = meta.expr.children[0]
        if not isinstance(sep, E.Literal) or sep.value is None:
            meta.will_not_work("concat_ws separator must be a non-null literal")

    ex(S.ConcatWs, "concat with separator, nulls skipped", TS.STRING,
       TS.STRING, None, tag_concat_ws)
    for cls in (S.StringLPad, S.StringRPad, S.StringRepeat, S.SubstringIndex,
                S.StringTranslate, S.FindInSet):
        ex(cls, "string function", TS.STRING + TS.TypeSig([T.IntegerType]),
           TS.STRING + TS.INTEGRAL, None, _lit_args_tag())

    def tag_locate(meta):
        e = meta.expr
        if not (isinstance(e.children[0], E.Literal)
                and isinstance(e.children[2], E.Literal)):
            meta.will_not_work("locate substr/start must be literals")
    ex(S.StringLocate, "locate/instr", TS.TypeSig([T.IntegerType]),
       TS.STRING + TS.INTEGRAL, None, tag_locate)

    def tag_regexp(meta):
        import re as _re
        e = meta.expr
        for a in e.children[1:]:
            if not isinstance(a, E.Literal):
                meta.will_not_work("regexp pattern/args must be literals")
                return
        try:
            _re.compile(e.children[1].value)
        except _re.error as err:
            meta.will_not_work(f"pattern not supported on device: {err}")
    for cls in (S.RegExpReplace, S.RegExpExtract):
        ex(cls, "regular expression function",
           TS.STRING + TS.TypeSig([T.IntegerType]), TS.STRING + TS.INTEGRAL,
           None, tag_regexp)

    # -- datetime parse/format (datetimeExpressions.scala) --------------------
    def tag_dt_format(meta):
        e = meta.expr
        fe = e.children[-1]
        if not isinstance(fe, E.Literal):
            meta.will_not_work("datetime format must be a literal")
            return
        try:
            DT.java_fmt_to_strftime(fe.value)
        except (ValueError, TypeError) as err:
            meta.will_not_work(str(err))

    for cls in (DT.UnixTimestamp, DT.ToUnixTimestamp):
        ex(cls, "string/ts → unix seconds", TS.TypeSig([T.LongType]),
           TS.STRING + TS.DATE + TS.TIMESTAMP, None, tag_dt_format)
    ex(DT.FromUnixTime, "unix seconds → string", TS.STRING,
       TS.INTEGRAL + TS.STRING, None, tag_dt_format)
    ex(DT.DateFormatClass, "date_format", TS.STRING,
       TS.DATE + TS.TIMESTAMP + TS.STRING, None, tag_dt_format)
    ex(DT.DateSub, "date arithmetic", TS.DATE)
    ex(DT.AddMonths, "calendar month add", TS.DATE)
    ex(DT.MonthsBetween, "months between dates", TS.FRACTIONAL,
       TS.DATE + TS.TIMESTAMP)
    def tag_trunc(meta):
        if not isinstance(meta.expr.children[1], E.Literal):
            meta.will_not_work("trunc format must be a literal")
    ex(DT.TruncDate, "date truncation", TS.DATE, TS.DATE + TS.STRING,
       None, tag_trunc)

    # -- hash / non-deterministic (HashFunctions.scala, randomExpressions) ---
    from spark_rapids_tpu.expr import misc as MX
    ex(MX.Murmur3Hash, "spark murmur3 hash", TS.TypeSig([T.IntegerType]),
       comm + TS.DECIMAL)
    ex(MX.Rand, "uniform random (per-partition stream, like the reference "
       "NOT bit-identical with CPU Spark)", TS.FRACTIONAL)
    ex(MX.SparkPartitionID, "partition id", TS.TypeSig([T.IntegerType]))
    ex(MX.InputFileName, "scan provenance: file path", TS.STRING)
    ex(MX.ScalarSubquery, "pre-executed scalar subquery value", TS.ALL)
    ex(MX.InputFileBlockStart, "scan provenance: block start",
       TS.TypeSig([T.LongType]))
    ex(MX.InputFileBlockLength, "scan provenance: block length",
       TS.TypeSig([T.LongType]))
    ex(MX.MonotonicallyIncreasingID, "monotonically increasing id",
       TS.TypeSig([T.LongType]))

    # -- decimal plan exprs (decimalExpressions.scala) ------------------------
    from spark_rapids_tpu.expr import decimalexprs as DX
    for cls in (DX.PromotePrecision, DX.CheckOverflow, DX.UnscaledValue,
                DX.MakeDecimal):
        ex(cls, "decimal precision plumbing", TS.DECIMAL + TS.INTEGRAL,
           TS.DECIMAL + TS.INTEGRAL)

    # -- complex-type create/extract (complexTypeCreator/Extractors.scala) ---
    from spark_rapids_tpu.expr import complexexprs as CX

    def tag_create(meta):
        p = meta.parent
        pe = getattr(p, "expr", None) if p is not None else None
        if not isinstance(pe, (CX.GetStructField, CX.GetArrayItem, CX.Size,
                               CX.ElementAt, CX.ArrayContains,
                               CX.GetMapValue)):
            meta.will_not_work(
                "nested values have no flat device form; only fused "
                "create+extract pairs run on device (struct(..).f, arr[i])")

    def tag_split(meta):
        import re as _re
        e = meta.expr
        if not isinstance(e.children[1], E.Literal):
            meta.will_not_work("split pattern must be a literal")
            return
        try:
            _re.compile(e.children[1].value)
        except _re.error as err:
            # neither side supports non-python regex syntax (the host oracle
            # uses the same `re` engine — documented engine limitation,
            # docs/compatibility.md, same as the regexp_* functions)
            meta.will_not_work(f"pattern not supported (python regex): {err}")
            return
        tag_create(meta)  # fused-only, same parent rule as CreateArray

    def tag_extract(meta):
        from spark_rapids_tpu.expr.strings import StringSplit as _Split
        e = meta.expr
        ok = (CX.CreateNamedStruct, CX.CreateArray)
        if isinstance(e, CX.GetMapValue):
            ok = (CX.CreateMap,)
        if isinstance(e, (CX.GetArrayItem, CX.Size)):
            ok = ok + (_Split,)          # fused split(...)[i] / size(split)
        if not isinstance(e.children[0], ok):
            meta.will_not_work(
                "extraction from a materialized nested column runs on host")
        if isinstance(e.children[0], _Split) and isinstance(
                e, CX.GetArrayItem) and not isinstance(
                e.children[1], E.Literal):
            meta.will_not_work("split(...)[i] needs a literal index")

    nested_ok = TS.ALL + TS.NESTED
    ex(CX.CreateNamedStruct, "struct construction (fused)", nested_ok,
       TS.ALL, None, tag_create)
    ex(CX.CreateArray, "array construction (fused)", nested_ok, TS.ALL,
       None, tag_create)
    ex(CX.GetStructField, "struct field extraction", TS.ALL, nested_ok,
       None, tag_extract)
    ex(CX.GetArrayItem, "array element extraction", TS.ALL, nested_ok,
       None, tag_extract)
    ex(CX.Size, "collection size", TS.TypeSig([T.IntegerType]), nested_ok,
       None, tag_extract)
    def tag_element_at(meta):
        tag_extract(meta)
        from spark_rapids_tpu.shims import shim_for
        if shim_for(meta.conf).element_at_zero_errors:
            # pre-3.4 generations raise on index 0; flag the expression so
            # host eval and literal-index device eval enforce it, and pin
            # data-dependent indexes to the host where the row-level error
            # can actually be raised
            meta.expr.strict_zero = True
            if not isinstance(meta.expr.children[1], E.Literal):
                meta.will_not_work(
                    "element_at with a non-literal index under a pre-3.4 "
                    "shim: the index-0 error is data-dependent (host only)")
    ex(CX.ElementAt, "1-based array element extraction", TS.ALL, nested_ok,
       None, tag_element_at)
    ex(CX.ArrayContains, "array membership (fused)", TS.BOOLEAN, nested_ok,
       None, tag_extract)
    ex(CX.CreateMap, "map construction (fused)", nested_ok, TS.ALL,
       None, tag_create)
    ex(CX.GetMapValue, "map value extraction (fused)", TS.ALL, nested_ok,
       None, tag_extract)
    ex(S.StringSplit, "split to array (fused extract only)", nested_ok,
       TS.STRING + TS.INTEGRAL, None, tag_split)
    def tag_bround(meta):
        e = meta.expr
        if (isinstance(e.children[0].dtype, T.FractionalType)
                and e.digits != 0):
            meta.will_not_work(
                "bround on floats with digits != 0 uses decimal-string tie "
                "semantics the device cannot reproduce (runs on host)")
    ex(MM.BRound, "half-even rounding", num + TS.DECIMAL, num + TS.DECIMAL,
       None, tag_bround)
    ex(P.InSet, "optimized literal-set membership", TS.BOOLEAN, ordr)
    ex(DT.TimeAdd, "timestamp + literal interval",
       TS.TypeSig([T.TimestampType]),
       TS.TypeSig([T.TimestampType, T.LongType, T.IntegerType]))
    def tag_json(meta):
        if not isinstance(meta.expr.children[1], E.Literal):
            meta.will_not_work("json path must be a literal (reference "
                               "GpuGetJsonObject has the same limit)")
    ex(S.GetJsonObject, "JSON path extraction", TS.STRING, TS.STRING,
       None, tag_json)

    def tag_collect(meta):
        meta.will_not_work(
            "collect_list/collect_set produce array results with no "
            "fixed-width device form; the aggregate runs on host")
    ex(AG.CollectList, "collect to array (host)", TS.ALL + TS.NESTED,
       TS.ALL, None, tag_collect)
    ex(AG.PivotFirst, "pivot first-value aggregate (host)",
       TS.ALL + TS.NESTED, TS.ALL, None, tag_collect)

    ex(DT.DateAddInterval, "date + literal day interval",
       TS.TypeSig([T.DateType]),
       TS.TypeSig([T.DateType, T.IntegerType, T.LongType]))

    from spark_rapids_tpu.udf.python_runtime import PythonUDF

    def tag_pyudf(meta):
        # only projections route through ArrowEvalPythonExec; a UDF anywhere
        # else (filter condition, sort key, join condition, agg input) has no
        # device path and must pin its exec to the host
        p = meta.parent
        while p is not None and not hasattr(p, "node"):
            p = p.parent
        if p is None or not isinstance(p.node, NN.ProjectNode):
            meta.will_not_work(
                "python UDF outside a projection runs on the host "
                "(device path exists only via ArrowEvalPythonExec)")

    R.expr_rule(PythonUDF, ExprRule(
        "python UDF via arrow worker exchange (GpuArrowEvalPythonExec analog)",
        None, None, tag_pyudf))

    from spark_rapids_tpu.udf.device_udf import JaxUDF
    # accelerated user UDF (reference RapidsUDF.evaluateColumnar): fuses into
    # the surrounding device program; strings excluded (a user fn would see
    # dictionary codes, not characters)
    ex(JaxUDF, "user jax UDF fused into the device program",
       TS.NUMERIC + TS.BOOLEAN + TS.DATETIME + TS.DECIMAL,
       TS.NUMERIC + TS.BOOLEAN + TS.DATETIME + TS.DECIMAL)

    from spark_rapids_tpu.expr import windows as WX
    ex(WX.WindowExpression, "window expression", TS.ALL)
    for cls in (WX.RowNumber, WX.Rank, WX.DenseRank):
        ex(cls, "ranking window function", TS.TypeSig([T.IntegerType]))
    ex(WX.Lead, "lead/lag offset function", TS.ALL)
    ex(WX.Lag, "lead/lag offset function", TS.ALL)

    # -- execs ---------------------------------------------------------------
    from spark_rapids_tpu.exec import basic as XB
    from spark_rapids_tpu.exec import aggregate as XA
    from spark_rapids_tpu.exec import joins as XJ
    from spark_rapids_tpu.exec import sort as XS
    from spark_rapids_tpu.exec.exchange import ShuffleExchangeExec
    from spark_rapids_tpu.shuffle import partitioning as SP

    def _mesh_n(conf) -> int:
        """Mesh width when collective exchanges are enabled, else 0
        (spark.rapids.tpu.mesh.enabled routes exchanges over ICI all_to_all,
        the reference's RapidsShuffleManager/UCX analog)."""
        from spark_rapids_tpu import config as CFG
        if not conf.get(CFG.MESH_ENABLED):
            return 0
        from spark_rapids_tpu.distributed.exchange import mesh_devices
        return len(mesh_devices(conf))

    def _hash_exchange(keys, child, conf, adaptive=False):
        """Hash exchange: mesh collective when configured, threaded block-store
        otherwise (reference GpuShuffleExchangeExec with/without the UCX
        RapidsShuffleManager). `adaptive` wraps the exchange in the AQE
        coalescing reader — only valid for single-consumer exchanges
        (aggregate/window), never the co-partitioned sides of a join."""
        from spark_rapids_tpu import config as CFG
        n_mesh = _mesh_n(conf)
        if n_mesh > 1:
            from spark_rapids_tpu.distributed.exchange import MeshExchangeExec
            return MeshExchangeExec(SP.HashPartitioner(keys, n_mesh), child,
                                    conf=conf)
        ex = ShuffleExchangeExec(
            SP.HashPartitioner(keys, child.num_partitions), child, conf=conf)
        # explicit conf wins; otherwise the emulated Spark generation
        # decides — AQE is default-on only since 3.2 (shims, SPARK-33679)
        if CFG.ADAPTIVE_COALESCE_ENABLED.key in conf.settings:
            adaptive_on = conf.get(CFG.ADAPTIVE_COALESCE_ENABLED)
        else:
            from spark_rapids_tpu.shims import shim_for
            adaptive_on = shim_for(conf).adaptive_coalesce_default
        if adaptive and adaptive_on:
            from spark_rapids_tpu.exec.exchange import AdaptiveShuffleReaderExec
            return AdaptiveShuffleReaderExec(ex, conf=conf)
        return ex

    def conv_scan(meta, kids):
        return XB.ArrowScanExec(meta.node.partitions, meta.node.output,
                                conf=meta.conf)

    def conv_range(meta, kids):
        n = meta.node
        return XB.RangeExec(n.start, n.end, n.step, n.num_slices, conf=meta.conf)

    def conv_project(meta, kids):
        from spark_rapids_tpu.udf.python_runtime import (ArrowEvalPythonExec,
                                                         PythonUDF)
        has_udf = any(e.collect(lambda x: isinstance(x, PythonUDF))
                      for e in meta.node.project_list)
        if has_udf:
            # reference GpuArrowEvalPythonExec: udf projections run through the
            # python worker exchange instead of a device kernel
            return ArrowEvalPythonExec(meta.node.project_list, kids[0],
                                       conf=meta.conf)
        return XB.ProjectExec(meta.node.project_list, kids[0], conf=meta.conf)

    def conv_filter(meta, kids):
        # HAVING fusion: a Filter directly above a finalizing aggregate folds
        # into the finalize kernel (exec/aggregate.fuse_having) — the
        # separate FilterExec dispatch and its full-width capacity disappear,
        # and the surviving groups re-land right-sized. Semantics-preserving:
        # the predicate sees exactly the aggregate's output columns.
        from spark_rapids_tpu.expr.misc import is_context_free
        child = kids[0]
        if (meta.conf.stage_fusion_enabled
                and isinstance(child, XA.HashAggregateExec)
                and child.mode != XA.PARTIAL
                and is_context_free(meta.node.condition)):
            child.fuse_having(meta.node.condition)
            return child
        return XB.FilterExec(meta.node.condition, kids[0], conf=meta.conf)

    def conv_limit(meta, kids):
        n, child = meta.node.n, kids[0]
        if not meta.node.global_limit:
            return XB.LocalLimitExec(n, child, conf=meta.conf)
        if child.num_partitions > 1:
            # Spark plans LocalLimit → single-partition exchange → GlobalLimit
            child = XS._GatherAllExec(
                XB.LocalLimitExec(n, child, conf=meta.conf), conf=meta.conf)
        return XB.GlobalLimitExec(n, child, conf=meta.conf)

    def conv_union(meta, kids):
        return XB.UnionExec(*kids, conf=meta.conf)

    def conv_aggregate(meta, kids):
        n = meta.node
        child = kids[0]
        # whole-stage hoist of child Filter/Project execs into the
        # aggregation kernel: predicates mask rows in-kernel and projections
        # re-derive inputs there, skipping their dispatches and full-width
        # intermediate batches (whole-stage-codegen role; the reference's
        # GpuHashAggregateExec receives codegen-fused stages the same way)
        from spark_rapids_tpu.expr.misc import is_context_free

        def clean_filter(f):
            return is_context_free(f.condition)

        def clean_project(p):
            # is_context_free covers the positional exprs too (Rand,
            # MonotonicallyIncreasingID are CONTEXT_SENSITIVE members)
            return is_context_free(*p.project_list)

        prefilter = preproject = None
        pre_on_proj = False
        if meta.conf.stage_fusion_enabled:
            # arbitrary-depth Filter/Project stacks compose into raw-terms
            # (prefilter, preproject) via BoundReference substitution
            from spark_rapids_tpu.plan.stages import compose_prestage
            prefilter, preproject, child = compose_prestage(child)
        else:
            # legacy depth-2 patterns (fusion knob off)
            if isinstance(child, XB.FilterExec) and clean_filter(child):
                prefilter = child.condition           # Agg(Filter(...))
                child = child.children[0]
                if isinstance(child, XB.ProjectExec) and clean_project(child):
                    preproject = child.project_list   # Agg(Filter(Project(x)))
                    child = child.children[0]
                    pre_on_proj = True                # condition binds to proj
            elif isinstance(child, XB.ProjectExec) and clean_project(child):
                preproject = child.project_list       # Agg(Project(...))
                child = child.children[0]
                if isinstance(child, XB.FilterExec) and clean_filter(child):
                    prefilter = child.condition       # Agg(Project(Filter(x)))
                    child = child.children[0]
        fused = dict(prefilter=prefilter, preproject=preproject,
                     prefilter_on_projected=pre_on_proj)
        if child.num_partitions == 1 or not n.group_exprs:
            if child.num_partitions > 1:
                # global aggregation without keys: gather all partitions first
                child = XS._GatherAllExec(child, conf=meta.conf)
            return XA.HashAggregateExec(n.group_exprs, n.agg_exprs, child,
                                        mode=XA.COMPLETE, conf=meta.conf,
                                        **fused)
        partial = XA.HashAggregateExec(n.group_exprs, n.agg_exprs, child,
                                       mode=XA.PARTIAL, conf=meta.conf,
                                       **fused)
        nkeys = len(n.group_exprs)
        key_names = [f.name for f in partial.output][:nkeys]
        keys = [E.col(k) for k in key_names]
        ex_node = _hash_exchange(keys, partial, meta.conf, adaptive=True)
        return XA.HashAggregateExec(keys, n.agg_exprs, ex_node, mode=XA.FINAL,
                                    conf=meta.conf)

    def tag_join(meta):
        n = meta.node
        if n.condition is not None and n.left_keys and n.join_type != "inner":
            meta.will_not_work(
                "conditional outer hash join not supported (reference "
                "GpuHashJoin.tagJoin)")
        if not n.left_keys and n.join_type == "right":
            # nested-loop handles left-preserving types only (build side = right,
            # reference GpuBroadcastNestedLoopJoinExec build-side rules)
            meta.will_not_work(
                "keyless right outer join needs a left build side "
                "(not yet supported); runs on host")

    def conv_join(meta, kids):
        n = meta.node
        left, right = kids
        jt = {"left": "leftouter", "right": "rightouter",
              "full": "fullouter"}.get(n.join_type, n.join_type)
        if not n.left_keys or n.join_type == "cross":
            return XJ.NestedLoopJoinExec(
                "inner" if jt == "cross" else jt, left, right,
                condition=n.condition, conf=meta.conf)
        n_mesh = _mesh_n(meta.conf)
        # inner joins may build either side; pick the smaller estimated child
        # (reference GpuJoinUtils.getGpuBuildSide from Spark's size-based
        # buildSide choice). Other types stream the preserved side.
        build_side = "right"
        if jt == "inner":
            from spark_rapids_tpu.plan.cbo import estimate_rows
            if estimate_rows(n.left) < estimate_rows(n.right):
                build_side = "left"
        if n_mesh > 1:
            # shuffled hash join over co-partitioned mesh exchanges (reference
            # GpuShuffledHashJoinBase.scala:97 riding GpuShuffleExchangeExec):
            # both sides hash-partition by their keys with the same Spark-exact
            # murmur3, so equal keys land on the same device
            from spark_rapids_tpu.distributed.exchange import MeshExchangeExec
            lex = MeshExchangeExec(
                SP.HashPartitioner(n.left_keys, n_mesh), left, conf=meta.conf)
            rex = MeshExchangeExec(
                SP.HashPartitioner(n.right_keys, n_mesh), right, conf=meta.conf)
            return XJ.HashJoinExec(
                jt, n.left_keys, n.right_keys, lex, rex,
                condition=n.condition, build_side=build_side, conf=meta.conf)
        # whole-stage hoist of the stream side's Filter (and an intervening
        # Project), or a bare Project, into the probe/emit kernels — inner
        # single-int-key joins only: filtered rows emit zero pairs, so no
        # semantics change; outer/semi/anti emit per-unfiltered-row and keep
        # their FilterExec. A bare Project's exprs re-derive on post-join
        # gathered rows in the emit kernel, so the full-width projected
        # intermediate never materializes. Broadcast path only — the mesh
        # path partitions the stream BEFORE probing and must filter
        # pre-exchange.
        stream_prefilter = stream_preproject = stream_schema = None
        left_keys, right_keys = n.left_keys, n.right_keys
        if jt == "inner" and len(n.left_keys) == 1:
            from spark_rapids_tpu.expr.misc import is_context_free as clean
            import spark_rapids_tpu.exec.joins as _XJm

            si = 0 if build_side == "right" else 1
            skid = (left, right)[si]
            proj = fkid = None
            if (isinstance(skid, XB.ProjectExec)
                    and isinstance(skid.children[0], XB.FilterExec)
                    and clean(*skid.project_list)):
                proj, fkid = skid, skid.children[0]
            elif isinstance(skid, XB.FilterExec):
                fkid = skid
            elif (meta.conf.stage_fusion_enabled
                    and isinstance(skid, XB.ProjectExec)
                    and clean(*skid.project_list)):
                proj = skid   # bare Project: emit-kernel hoist, no prefilter
            if ((proj is not None or fkid is not None)
                    and _XJm._int_backed(n.left_keys[0].dtype)
                    and _XJm._int_backed(n.right_keys[0].dtype)
                    and clean(*n.left_keys, *n.right_keys)
                    and (fkid is None or clean(fkid.condition))):
                stream_prefilter = (fkid.condition if fkid is not None
                                    else None)
                new_kid = (fkid if fkid is not None else proj).children[0]
                skeys = list((left_keys, right_keys)[si])
                if proj is not None:
                    # keys were bound against the project's output: substitute
                    # each reference with the project expression it names, so
                    # they evaluate against the RAW child (Alias unwrapped —
                    # it is a naming shell, not a value node)
                    plist = [e.child if isinstance(e, E.Alias) else e
                             for e in proj.project_list]
                    skeys = [k.transform(
                        lambda x: plist[x.ordinal]
                        if isinstance(x, E.BoundReference) else x)
                        for k in skeys]
                    stream_preproject = proj.project_list
                    stream_schema = proj.output
                else:
                    stream_schema = None
                if si == 0:
                    left, left_keys = new_kid, skeys
                else:
                    right, right_keys = new_kid, skeys
        bhj = XJ.BroadcastHashJoinExec(
            jt, left_keys, right_keys, left, right, condition=n.condition,
            build_side=build_side, conf=meta.conf,
            stream_prefilter=stream_prefilter,
            stream_preproject=stream_preproject,
            stream_schema=stream_schema)
        if meta.conf.stage_fusion_enabled:
            # probe-chain fusion: a BHJ whose stream child is another BHJ (or
            # an already-formed chain) collapses into one per-batch kernel
            return XJ.maybe_chain(bhj, conf=meta.conf)
        return bhj

    def conv_sort(meta, kids):
        from spark_rapids_tpu.ops.sorting import SortOrder
        n = meta.node
        exprs = [e for (e, _, _) in n.sort_exprs]
        orders = [SortOrder(ascending=asc, nulls_first=nf)
                  for (_, asc, nf) in n.sort_exprs]
        n_mesh = _mesh_n(meta.conf)
        if n_mesh > 1 and n.global_sort:
            # total order via range exchange + per-device sort (the reference's
            # GpuRangePartitioner + per-partition GpuSortExec shape): partition
            # d holds keys ≤ partition d+1, so reading partitions in order is
            # globally sorted without a gather
            from spark_rapids_tpu.distributed.exchange import MeshExchangeExec
            part = SP.RangePartitioner(exprs, orders, n_mesh)
            child = MeshExchangeExec(part, kids[0], conf=meta.conf)
            return XS.SortExec(exprs, orders, child, global_sort=False,
                               conf=meta.conf)
        return XS.SortExec(exprs, orders, kids[0], global_sort=n.global_sort,
                           conf=meta.conf)

    def conv_exchange(meta, kids):
        n = meta.node
        if n.partitioning == "hash":
            p = SP.HashPartitioner(n.keys, n.num_out)
        elif n.partitioning == "single":
            p = SP.SinglePartitioner()
        elif n.partitioning == "roundrobin":
            p = SP.RoundRobinPartitioner(n.num_out)
        else:
            from spark_rapids_tpu.ops.sorting import SortOrder
            sort_orders = [SortOrder() for _ in n.keys]
            p = SP.RangePartitioner(n.keys, sort_orders, n.num_out)
        n_mesh = _mesh_n(meta.conf)
        if n_mesh > 1 and n.num_out == n_mesh and n.partitioning != "single":
            from spark_rapids_tpu.distributed.exchange import MeshExchangeExec
            return MeshExchangeExec(p, kids[0], conf=meta.conf)
        return ShuffleExchangeExec(p, kids[0], conf=meta.conf)

    def exr(cls, desc, convert, sig=TS.ORDERABLE, conf_key=None, tag_fn=None):
        R.exec_rule(cls, ExecRule(desc, convert, TS.ExecChecks(sig), conf_key,
                                  tag_fn))

    exr(NN.ScanNode, "in-memory scan onto device", conv_scan)
    exr(NN.RangeNode, "range generator", conv_range)
    exr(NN.ProjectNode, "columnar projection", conv_project)
    exr(NN.FilterNode, "columnar filter", conv_filter)
    exr(NN.LimitNode, "row limit", conv_limit)
    exr(NN.UnionNode, "union all", conv_union)
    exr(NN.AggregateNode, "hash aggregate (two-phase over exchange)",
        conv_aggregate)
    exr(NN.JoinNode, "broadcast/nested-loop join", conv_join,
        tag_fn=tag_join)
    from spark_rapids_tpu.exec.window import WindowExec, supported_window_expr
    from spark_rapids_tpu.expr.core import Alias

    def _unalias(e):
        return e.child if isinstance(e, Alias) else e

    def tag_window(meta):
        n = meta.node
        specs = set()
        for e in n.window_exprs:
            we = _unalias(e)
            if not isinstance(we, WX.WindowExpression):
                meta.will_not_work(f"not a window expression: {we!r}")
                continue
            reason = supported_window_expr(we)
            if reason:
                meta.will_not_work(reason)
            specs.add(repr((we.spec.partition_by, we.spec.order_by)))
        if len(specs) > 1:
            meta.will_not_work(
                "multiple window partition/order specs in one node "
                "(the planner splits these into chained WindowExecs — TODO)")

    def conv_window(meta, kids):
        n = meta.node
        child = kids[0]
        we0 = _unalias(n.window_exprs[0])
        if child.num_partitions > 1:
            if we0.spec.partition_by:
                child = _hash_exchange(list(we0.spec.partition_by), child,
                                       meta.conf, adaptive=True)
            else:
                child = XS._GatherAllExec(child, conf=meta.conf)
        return WindowExec(n.window_exprs, child, conf=meta.conf)

    exr(NN.SortNode, "device sort", conv_sort)
    exr(NN.ExchangeNode, "shuffle exchange", conv_exchange)
    from spark_rapids_tpu.exec.expand import ExpandExec

    def conv_expand(meta, kids):
        n = meta.node
        return ExpandExec(n.projections, n.output, kids[0], conf=meta.conf)

    exr(NN.WindowNode, "window via segmented scans", conv_window,
        tag_fn=tag_window)
    exr(NN.ExpandNode, "interleaved multi-projection expand", conv_expand)

    from spark_rapids_tpu.plan.cache import CachedScanExec, CacheNode

    def conv_cache(meta, kids):
        # kids are ignored: the cache materializes its child itself, once
        return CachedScanExec(meta.node, conf=meta.conf)

    exr(CacheNode, "materialized dataframe cache", conv_cache)

    from spark_rapids_tpu.exec.generate import GenerateExec

    def tag_generate(meta):
        n = meta.node
        try:
            f = n.child.output[n.generator_col]
        except KeyError:
            meta.will_not_work(f"no such column {n.generator_col}")
            return
        if not isinstance(f.data_type, T.ArrayType):
            meta.will_not_work(
                f"generator input {n.generator_col} is {f.data_type}, "
                "not an array")
        elif f.data_type.element_type != n.element_type:
            meta.will_not_work(
                f"declared element type {n.element_type} != actual "
                f"{f.data_type.element_type}")
        elif isinstance(n.element_type, (T.ArrayType, T.StructDataType)):
            meta.will_not_work(
                f"nested element type {n.element_type} not supported on "
                "device (flat element vectors only)")

    def conv_generate(meta, kids):
        n = meta.node
        return GenerateExec(n.generator_col, kids[0], outer=n.outer,
                            element_type=n.element_type, pos=n.pos,
                            conf=meta.conf)

    class GenerateChecks(TS.ExecChecks):
        """The generator input column is ALLOWED to be an array (that is the
        point); everything else follows the normal signature (reference
        TypeChecks per-exec param overrides for GpuGenerateExec)."""

        def input_fields(self, node):
            return (f for f in super().input_fields(node)
                    if f.name != node.generator_col)

    R.exec_rule(NN.GenerateNode, ExecRule(
        "explode via one device gather program", conv_generate,
        GenerateChecks(TS.ORDERABLE), None, tag_generate))

    # -- pandas-UDF exec family (reference execution/python/ GpuMapInPandas,
    # GpuFlatMapGroupsInPandas, GpuFlatMapCoGroupsInPandas,
    # GpuAggregateInPandas) -----------------------------------------------
    from spark_rapids_tpu.udf.pandas_exec import (
        AggregateInPandasExec, CoGroupedMapInPandasExec,
        GroupedMapInPandasExec, MapInPandasExec)

    def conv_map_in_pandas(meta, kids):
        n = meta.node
        return MapInPandasExec(n.fn, n.schema, kids[0], conf=meta.conf)

    def conv_grouped_map(meta, kids):
        n = meta.node
        child = kids[0]
        if child.num_partitions > 1:
            # groups must be whole within a partition (Spark required
            # distribution for FlatMapGroupsInPandas)
            child = _hash_exchange([E.col(k) for k in n.key_names], child,
                                   meta.conf, adaptive=True)
        return GroupedMapInPandasExec(n.key_names, n.fn, n.schema, child,
                                      conf=meta.conf)

    def conv_cogrouped_map(meta, kids):
        n = meta.node
        left, right = kids
        nparts = max(left.num_partitions, right.num_partitions)
        if nparts > 1:
            # co-partition both sides with the SAME partitioner arity so
            # matching groups land in the same split (never adaptive: the
            # coalescing reader would break co-partitioning)
            left = ShuffleExchangeExec(
                SP.HashPartitioner([E.col(k) for k in n.left_key_names],
                                   nparts), left, conf=meta.conf)
            right = ShuffleExchangeExec(
                SP.HashPartitioner([E.col(k) for k in n.right_key_names],
                                   nparts), right, conf=meta.conf)
        return CoGroupedMapInPandasExec(
            n.left_key_names, n.right_key_names, n.fn, n.schema, left, right,
            conf=meta.conf)

    def conv_agg_in_pandas(meta, kids):
        n = meta.node
        child = kids[0]
        if child.num_partitions > 1:
            if n.key_names:
                child = _hash_exchange([E.col(k) for k in n.key_names], child,
                                       meta.conf, adaptive=True)
            else:
                child = XS._GatherAllExec(child, conf=meta.conf)
        udfs = [(fn, cols) for fn, cols, _, _ in n.udfs]
        return AggregateInPandasExec(n.key_names, udfs, n.output, child,
                                     conf=meta.conf)

    def conv_remote_source(meta, kids):
        from spark_rapids_tpu.cluster.remote import RemoteFetchExec
        n = meta.node
        return RemoteFetchExec(n.shuffle_id, n.schema, n.n_parts, n.locations,
                               n.pinned_reduce, epoch=getattr(n, "epoch", 0),
                               conf=meta.conf)

    exr(NN.RemoteSourceNode, "remote shuffle fetch over TCP peers",
        conv_remote_source)

    exr(NN.MapInPandasNode, "mapInPandas via arrow worker exchange",
        conv_map_in_pandas)
    exr(NN.GroupedMapInPandasNode,
        "grouped applyInPandas over a hash exchange", conv_grouped_map)
    exr(NN.CoGroupedMapInPandasNode,
        "cogrouped applyInPandas over co-partitioned exchanges",
        conv_cogrouped_map)
    exr(NN.AggregateInPandasNode,
        "grouped pandas aggregate UDFs over a hash exchange",
        conv_agg_in_pandas)


_register_all()
