"""Column pruning: narrow file scans to the columns the plan actually uses.

Reference: Spark's Catalyst ColumnPruning + SchemaPruning rules feed
GpuParquetScan/GpuOrcScan a pruned readSchema, so the GPU decodes only live
columns (GpuParquetScan.scala readDataSchema). This engine builds plans with
eagerly BOUND ordinals (plan/nodes.py binds at construction), so the pass
both narrows the FileScanNode schema and REBINDS every ordinal above it.

`_prune(node, required)` returns `(new_node, mapping)` where `required` is
the set of output ordinals the parent consumes (None = all) and `mapping`
maps old output ordinals to new ones for every column that survived. Nodes
whose output is expression-defined (Project, Aggregate) absorb the
remapping; pass-through nodes (Filter, Sort, Limit, Exchange) propagate it.
Unhandled node types conservatively require all of their children's columns
— correctness never depends on a node being listed here.

The rewrite is IDENTITY-PRESERVING: a subtree where nothing narrows returns
the ORIGINAL node objects. TpuOverrides.apply runs this pass per execution,
and stateful nodes (CacheNode's materialized batches) must survive repeat
applies — a gratuitous copy would orphan their state. CacheNode is
additionally a pruning barrier: its cache holds the child's full-width
output, so the pass never narrows beneath one.

Run by TpuOverrides.apply before tagging, and safe for host-interpreted
plans too (pruned nodes execute_host the same way).
"""

from __future__ import annotations

import copy

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import core as E
from spark_rapids_tpu.plan import nodes as N
from spark_rapids_tpu.io.filescan import FileScanNode


def _refs(expr) -> set:
    return {e.ordinal for e in
            expr.collect(lambda x: isinstance(x, E.BoundReference))}


def _is_ident(mapping: dict) -> bool:
    return all(o == n for o, n in mapping.items())


def _remap(expr, mapping: dict):
    if _is_ident(mapping):
        return expr

    def fn(e):
        if isinstance(e, E.BoundReference):
            return E.BoundReference(mapping[e.ordinal], e.dtype, e.nullable,
                                    e.name)
        return e
    return expr.transform(fn)


def _identity(node):
    return node, {i: i for i in range(len(node.output.fields))}


def prune_columns(root: N.PlanNode) -> N.PlanNode:
    """Return an equivalent plan whose file scans read only live columns.
    Subtrees with nothing to narrow come back as the original objects."""
    new_root, _ = _prune(root, None)
    return new_root


def _all(node) -> set:
    return set(range(len(node.output.fields)))


def _prune(node: N.PlanNode, required: set | None):
    from spark_rapids_tpu.plan.cache import CacheNode
    if isinstance(node, CacheNode):
        # barrier: the cache stores full-width child batches, and the node
        # itself carries materialized state a rebuild would orphan
        return _identity(node)
    if isinstance(node, FileScanNode):
        return _prune_scan(node, required)
    if isinstance(node, N.ProjectNode):
        keep = (sorted(required) if required is not None
                else list(range(len(node.project_list))))
        if not keep:                       # count(*)-style: keep one column
            keep = [0]
        kept_exprs = [node.project_list[i] for i in keep]
        child_req = set()
        for e in kept_exprs:
            child_req |= _refs(e)
        child, cmap = _prune(node.child, child_req)
        mapping = {o: i for i, o in enumerate(keep)}
        if child is node.child and _is_ident(cmap) and _is_ident(mapping) \
                and len(keep) == len(node.project_list):
            return node, mapping
        new = N.ProjectNode([_remap(e, cmap) for e in kept_exprs], child)
        return new, mapping
    if isinstance(node, N.FilterNode):
        req = (required if required is not None else _all(node))
        child, cmap = _prune(node.child, req | _refs(node.condition))
        if child is node.child and _is_ident(cmap):
            return node, cmap
        return N.FilterNode(_remap(node.condition, cmap), child), cmap
    if isinstance(node, N.SortNode):
        req = (required if required is not None else _all(node))
        need = set(req)
        for e, _, _ in node.sort_exprs:
            need |= _refs(e)
        child, cmap = _prune(node.child, need)
        if child is node.child and _is_ident(cmap):
            return node, cmap
        new = N.SortNode([(_remap(e, cmap), asc, nf)
                          for (e, asc, nf) in node.sort_exprs], child,
                         node.global_sort)
        return new, cmap
    if isinstance(node, N.LimitNode):
        child, cmap = _prune(node.child, required)
        if child is node.child:
            return node, cmap
        return N.LimitNode(node.n, child, node.global_limit), cmap
    if isinstance(node, N.ExchangeNode):
        req = (required if required is not None else _all(node))
        need = set(req)
        for e in node.keys:
            need |= _refs(e)
        child, cmap = _prune(node.child, need)
        if child is node.child and _is_ident(cmap):
            return node, cmap
        new = N.ExchangeNode(child, node.partitioning, node.num_out,
                             [_remap(e, cmap) for e in node.keys])
        return new, cmap
    if isinstance(node, N.AggregateNode):
        child_req = set()
        for e in node.group_exprs + node.agg_exprs:
            child_req |= _refs(e)
        child, cmap = _prune(node.child, child_req)
        if child is node.child and _is_ident(cmap):
            return _identity(node)
        new = N.AggregateNode([_remap(e, cmap) for e in node.group_exprs],
                              [_remap(e, cmap) for e in node.agg_exprs],
                              child)
        return _identity(new)
    if isinstance(node, N.JoinNode):
        nleft = len(node.left.output.fields)
        semi = node.join_type in ("leftsemi", "leftanti")
        req = (required if required is not None else _all(node))
        lreq = {i for i in req if i < nleft}
        rreq = (set() if semi else {i - nleft for i in req if i >= nleft})
        for e in node.left_keys:
            lreq |= _refs(e)
        for e in node.right_keys:
            rreq |= _refs(e)
        if node.condition is not None:
            # the extra condition is stored unbound (name-resolved later):
            # keep every column it names, on whichever side defines it
            names = {a.name for a in node.condition.collect(
                lambda x: isinstance(x, (E.AttributeReference,
                                         E.BoundReference)))}
            for i, f in enumerate(node.left.output.fields):
                if f.name in names:
                    lreq.add(i)
            for i, f in enumerate(node.right.output.fields):
                if f.name in names:
                    rreq.add(i)
        left, lmap = _prune(node.left, lreq)
        right, rmap = _prune(node.right, rreq)
        if left is node.left and right is node.right and _is_ident(lmap) \
                and _is_ident(rmap):
            return _identity(node)
        new = N.JoinNode(left, right,
                         [_remap(e, lmap) for e in node.left_keys],
                         [_remap(e, rmap) for e in node.right_keys],
                         node.join_type, node.condition)
        nleft_new = len(left.output.fields)
        mapping = dict(lmap)
        if not semi:
            for o, n2 in rmap.items():
                mapping[o + nleft] = n2 + nleft_new
        return new, mapping
    # unhandled node type: conservatively require ALL columns of every child
    # (children may still prune deeper inside their own subtrees)
    new_children = [_prune(c, None)[0] for c in node.children]
    if any(nc is not oc for nc, oc in zip(new_children, node.children)):
        node = copy.copy(node)
        node.children = list(new_children)
    return _identity(node)


def _prune_scan(node: FileScanNode, required: set | None):
    fields = node.output.fields
    if required is None or len(required) >= len(fields):
        return _identity(node)
    if node.fmt not in ("parquet", "orc"):
        # row-oriented formats (CSV) parse every field anyway, and their
        # reader options may carry a full parse schema — don't narrow
        return _identity(node)
    n_data = len(fields) - node._n_partition_cols
    keep = sorted(required)
    if not keep:
        keep = [0]
    # partition-value columns are per-file constants appended after the data
    # columns; keep them all so _append_partition_values stays aligned
    keep_data = [i for i in keep if i < n_data]
    if not keep_data:
        keep_data = [0]
    kept = keep_data + list(range(n_data, len(fields)))
    # pushed filters resolve by NAME against the scan schema — their columns
    # must survive the narrowing
    if node.pushed_filter is not None:
        names = {a.name for a in node.pushed_filter.collect(
            lambda x: isinstance(x, (E.AttributeReference,
                                     E.BoundReference)))}
        extra = [i for i, f in enumerate(fields[:n_data])
                 if f.name in names and i not in kept]
        kept = sorted(set(kept) | set(extra))
    else:
        kept = sorted(set(kept))
    if len(kept) == len(fields):
        return _identity(node)
    new = copy.copy(node)
    new._schema = T.StructType([fields[i] for i in kept])
    new._n_partition_cols = node._n_partition_cols
    return new, {o: i for i, o in enumerate(kept)}
