"""Whole-stage structure over the physical exec tree.

Reference contrast: Spark marks codegen-fused regions in explain() output with
`*(k)` stage prefixes (WholeStageCodegenExec). Here the analogous unit is a
maximal contiguous region of DEVICE operators between pipeline breakers
(exchanges, host materializations, scans): every operator inside one region
replays fused per-batch XLA programs (runtime/fuse.py) and several collapse
entirely into a neighbor's kernel (aggregate pre/post hoists, join stream
hoists). This module is the planner/read-out side of that story:

- `compose_prestage` folds an arbitrary-depth stack of context-free
  Filter/Project execs into (prefilter, preproject) terms an aggregate's
  kernel evaluates inline (plan/overrides.conv_aggregate);
- `assign_stages` / `describe_stages` compute the stage regions and which
  logical operators each physical node absorbed;
- `explain_fused` renders the `*(k)`-annotated tree plus a per-stage summary
  (members, fused-in operators, per-node dispatch counts when a finished
  query's collector is supplied);
- `emit_stage_events` mirrors the stage structure to the structured event
  log (`stage.fused`, one record per stage) so offline tooling can join
  stages with the per-node dispatch ledger.
"""

from __future__ import annotations

# Pipeline breakers: operators that materialize, reshuffle or leave the
# device — a fused per-batch program cannot span them. Matched by class NAME
# so this module needs no exec imports (several would cycle).
BOUNDARY_EXECS = frozenset({
    "ShuffleExchangeExec", "MeshExchangeExec", "AdaptiveShuffleReaderExec",
    "_GatherAllExec", "ArrowScanExec", "RangeExec", "ArrowEvalPythonExec",
    "CacheExec", "CoalesceExec", "HostFallbackExec",
})


def compose_prestage(child, max_depth: int = 8):
    """Fold the stack of context-free Filter/Project execs under an
    aggregate into `(prefilter, preproject, base_child)`.

    Predicates AND-compose; every expression is rebased onto the BASE
    child's output by substituting each BoundReference with the projection
    term it names (Alias unwrapped — a naming shell, not a value node), so
    the consumer evaluates the whole stack inside one kernel with
    `prefilter_on_projected=False` semantics: the filter masks RAW rows,
    the projection re-derives its columns on whatever survives. Returns
    `(None, None, child)` when nothing composable; `max_depth` bounds the
    rebase blowup on pathological towers (beyond it the remaining execs
    simply keep their own fused programs)."""
    from spark_rapids_tpu.exec import basic as XB
    from spark_rapids_tpu.expr import core as E
    from spark_rapids_tpu.expr import predicates as P
    from spark_rapids_tpu.expr.misc import is_context_free

    stack = []
    cur = child
    while len(stack) < max_depth:
        if isinstance(cur, XB.FilterExec) and is_context_free(cur.condition):
            stack.append(cur)
        elif (isinstance(cur, XB.ProjectExec)
                and is_context_free(*cur.project_list)):
            stack.append(cur)
        else:
            break
        cur = cur.children[0]
    if not stack:
        return None, None, child

    def rebase(e, terms):
        if terms is None:
            return e
        plist = [t.child if isinstance(t, E.Alias) else t for t in terms]
        return e.transform(lambda x: plist[x.ordinal]
                           if isinstance(x, E.BoundReference) else x)

    terms = None   # projection exprs in base terms (None = identity)
    cond = None
    for node in reversed(stack):   # bottom-up: closest to the base first
        if isinstance(node, XB.FilterExec):
            c = rebase(node.condition, terms)
            cond = c if cond is None else P.And(cond, c)
        else:
            terms = [rebase(t, terms) for t in node.project_list]
    return cond, terms, cur


def fused_members(node) -> list:
    """Human-readable list of the logical operators this physical node
    absorbed (aggregate pre/post hoists, join stream hoists) — duck-typed on
    the hoist attributes so new hosts join the read-out for free."""
    out = []
    pf = getattr(node, "postfilter", None)
    if pf is not None:
        out.append(f"Filter[HAVING] {pf!r}")
    pre = getattr(node, "prefilter", None)
    if pre is not None:
        out.append(f"Filter {pre!r}")
    prj = getattr(node, "preproject", None)
    if prj is not None:
        out.append(f"Project {prj!r}")
    spf = getattr(node, "stream_prefilter", None)
    if spf is not None:
        out.append(f"Filter[stream] {spf!r}")
    spp = getattr(node, "stream_preproject", None)
    if spp is not None:
        out.append(f"Project[stream] {spp!r}")
    for h in getattr(node, "hops", None) or []:
        out.append(f"BroadcastHashJoin[{h.join_type}] "
                   f"lk={h.left_keys!r} rk={h.right_keys!r}")
        hpf = getattr(h, "stream_prefilter", None)
        if hpf is not None:
            out.append(f"Filter[stream] {hpf!r}")
        hpp = getattr(h, "stream_preproject", None)
        if hpp is not None:
            out.append(f"Project[stream] {hpp!r}")
    return out


def _stream_child_index(node) -> int | None:
    """For joins the fused per-batch pipeline continues into the STREAM side
    only — the build side materializes (concat_all) and starts a new stage."""
    sci = getattr(node, "stream_child_index", None)
    if sci is not None:
        return sci
    sil = getattr(node, "stream_is_left", None)
    if sil is None or len(node.children) != 2:
        return None
    return 0 if sil else 1


def assign_stages(root) -> dict:
    """{id(node): stage_number} for every exec in a fused stage; boundary
    execs carry no stage. Numbering is preorder, 1-based (Spark's `*(k)`)."""
    stages: dict = {}
    counter = [0]

    def visit(node, parent_stage):
        name = type(node).__name__
        if name in BOUNDARY_EXECS:
            my = None
        elif parent_stage is not None:
            my = parent_stage
        else:
            counter[0] += 1
            my = counter[0]
        if my is not None:
            stages[id(node)] = my
        si = _stream_child_index(node)
        for i, c in enumerate(node.children):
            # join build side / boundary children start fresh stages
            child_stage = my if (my is not None
                                 and (si is None or i == si)) else None
            visit(c, child_stage)

    visit(root, None)
    return stages


def describe_stages(root) -> list:
    """Per-stage summary in stage order: members (preorder class names with
    node ids) and the logical operators fused into each member."""
    stages = assign_stages(root)
    by_stage: dict = {}

    def visit(node):
        k = stages.get(id(node))
        if k is not None:
            ent = by_stage.setdefault(
                k, {"stage": k, "members": [], "fused": []})
            ent["members"].append({
                "name": type(node).__name__,
                "node": getattr(node, "_node_id", None),
            })
            ent["fused"].extend(fused_members(node))
        for c in node.children:
            visit(c)

    visit(root)
    return [by_stage[k] for k in sorted(by_stage)]


def render_tree(root) -> str:
    """The exec tree with Spark's WholeStageCodegen notation: stage members
    render as `*(k) Name`, boundary execs plain."""
    stages = assign_stages(root)
    lines = []

    def visit(node, indent):
        k = stages.get(id(node))
        mark = f"*({k}) " if k is not None else ""
        args = node.args_string()
        lines.append("  " * indent + mark + type(node).__name__
                     + (" " + args if args else ""))
        for c in node.children:
            visit(c, indent + 1)

    visit(root, 0)
    return "\n".join(lines) + "\n"


def explain_fused(root, collector=None) -> str:
    """`explain(fused=True)` body: the stage-annotated tree plus one summary
    block per stage naming its members, the logical operators fused into
    them, and (when a finished query's collector is supplied) each member's
    dispatch and batch counts — dispatches/batch is the fusion win metric
    (docs/perf_notes.md round 7)."""
    out = [render_tree(root)]
    per_node: dict = {}
    if collector is not None:
        from spark_rapids_tpu.runtime import stats as STATS
        for e in STATS.node_table(collector):
            if e["id"] is not None:
                per_node[e["id"]] = e
    out.append("== Fused stages ==")
    for ent in describe_stages(root):
        names = []
        for m in ent["members"]:
            label = m["name"]
            e = per_node.get(m["node"])
            if e is not None and e.get("dispatches") is not None:
                label += (f" [dispatches={e['dispatches']}"
                          + (f" batches={e['batches']}"
                             if e.get("batches") else "") + "]")
            names.append(label)
        out.append(f"Stage {ent['stage']}: " + ", ".join(names))
        for f in ent["fused"]:
            out.append(f"    fused: {f}")
    return "\n".join(out) + "\n"


def emit_stage_events(root, query_id) -> None:
    """One `stage.fused` event-log record per stage (query-scoped): the
    offline join key between the stage structure and the per-node dispatch
    ledger in `plan.stats`."""
    from spark_rapids_tpu.runtime import eventlog as EL
    if not EL.enabled():
        return
    for ent in describe_stages(root):
        EL.emit("stage.fused", query=query_id, stage=ent["stage"],
                members=[m["name"] for m in ent["members"]],
                nodes=[m["node"] for m in ent["members"]],
                fused=ent["fused"])
