"""RemoteFetchExec — stage input reading peer executors' shuffle blocks.

Reference: RapidsShuffleIterator (RapidsShuffleInternalManagerBase.scala /
RapidsShuffleClient.doFetch) — a reduce task's input iterator that fetches
its partition's blocks from every mapper's block server. Here each fetch is
the TcpTransport windowed/throttled protocol; blocks deserialize straight to
device batches.

Movement-aware short-circuit (unified mesh-cluster plane): when one of the
peer addresses IS this executor's own block server — which movement-aware
placement (cluster/minicluster.PlacementPolicy preferred picks) arranges on
purpose — the fetch reads the local ShuffleBlockStore directly instead of
taking a TCP loop through its own server (the reference's
RapidsCachingReader local-block path). The local read still runs inside the
ShuffleFetchIterator ladder, so chaos checkpoints and cancellation behave
identically, and block (map_split, seq) keys keep the canonical merge
order."""

from __future__ import annotations

from spark_rapids_tpu import config as CFG
from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.base import TpuExec, acquire_semaphore
from spark_rapids_tpu.runtime import metrics as M

_local_address: "tuple | None" = None


def set_local_address(addr) -> None:
    """Executor bring-up registers its own block-server address so fetches
    addressed to self short-circuit into the local store."""
    global _local_address
    _local_address = tuple(addr) if addr is not None else None


def local_address():
    return _local_address


class LocalStoreClient:
    """Duck-typed ShuffleClient serving this process's own blocks straight
    from the ShuffleBlockStore — no socket, no serialization round-trip.
    Yields the same (map_split, seq)-keyed stream as the TCP client so the
    union merge stays canonical."""

    def fetch_blocks_with_keys(self, shuffle_id: int, reduce_id: int):
        from spark_rapids_tpu.runtime import movement as MV
        from spark_rapids_tpu.runtime import tracing
        from spark_rapids_tpu.shuffle.manager import ShuffleBlockStore
        tracing.span_event("fetch.local", shuffle=shuffle_id,
                           reduce=reduce_id)
        for seq, b in ShuffleBlockStore.get().read_partition_with_keys(
                shuffle_id, reduce_id):
            # zero network bytes — the read never leaves the process; only
            # the store-unit payload column moves, under the `local` link,
            # so the short-circuit can never inflate the TCP ledger
            MV.record("shuffle.recv", 0, link="local", site="fetch.local",
                      payload_bytes=b.device_memory_size())
            yield seq, b


class RemoteFetchExec(TpuExec):
    def __init__(self, shuffle_id: int, schema: T.StructType, n_parts: int,
                 locations: list, pinned_reduce: int | None = None,
                 epoch: int = 0, conf=None):
        super().__init__(conf=conf)
        self.shuffle_id = shuffle_id
        self.schema = schema
        self.n_parts = n_parts
        self.locations = list(locations)
        self.pinned_reduce = pinned_reduce
        # map-output epoch the driver stamped at task-ship time; rides the
        # fetch-retry events so stale-metadata fetches are attributable
        self.epoch = epoch
        self._fetch_time = self.metrics.metric(M.READ_FS_TIME, M.MODERATE)

    @property
    def output(self):
        return self.schema

    @property
    def num_partitions(self):
        return 1 if self.pinned_reduce is not None else self.n_parts

    def execute_partition(self, split):
        from spark_rapids_tpu.shuffle.fetch import iter_union_blocks
        from spark_rapids_tpu.shuffle.transport import (InflightThrottle,
                                                        TcpShuffleClient)
        rid = self.pinned_reduce if self.pinned_reduce is not None else split
        bounce = self.conf.get(CFG.SHUFFLE_BOUNCE_BUFFER_SIZE)
        throttle = InflightThrottle(
            self.conf.get(CFG.SHUFFLE_MAX_INFLIGHT_BYTES))
        retries = self.conf.get(CFG.SHUFFLE_FETCH_MAX_RETRIES)
        # fresh client per attempt (a dead connection must not be reused);
        # per-peer retry+backoff via the shuffle fetch ladder — peers hold
        # DISJOINT block sets here, so there is no failover, and a peer
        # that stays dead surfaces as TransportError for the driver's
        # lineage-scoped recompute to classify. The executor's OWN address
        # short-circuits to the local block store (movement-aware
        # placement schedules reducers onto their byte-dominant host
        # precisely so this read is local)
        short_circuit = (local_address()
                         if self.conf.get(
                             CFG.CLUSTER_PLACEMENT_MOVEMENT_AWARE)
                         else None)
        factories = [
            (lambda: LocalStoreClient()) if tuple(addr) == short_circuit
            else (lambda a=tuple(addr): TcpShuffleClient(a, bounce, throttle))
            for addr in self.locations]

        def it():
            for batch in iter_union_blocks(factories, self.shuffle_id, rid,
                                           max_retries=retries,
                                           epoch=self.epoch):
                acquire_semaphore(self.metrics)
                yield batch
        return self.wrap_output(it())

    def args_string(self):
        return (f"shuffle={self.shuffle_id} pinned={self.pinned_reduce} "
                f"peers={len(self.locations)} epoch={self.epoch}")
