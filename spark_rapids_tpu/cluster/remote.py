"""RemoteFetchExec — stage input reading peer executors' shuffle blocks.

Reference: RapidsShuffleIterator (RapidsShuffleInternalManagerBase.scala /
RapidsShuffleClient.doFetch) — a reduce task's input iterator that fetches
its partition's blocks from every mapper's block server. Here each fetch is
the TcpTransport windowed/throttled protocol; blocks deserialize straight to
device batches."""

from __future__ import annotations

from spark_rapids_tpu import config as CFG
from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.base import TpuExec, acquire_semaphore
from spark_rapids_tpu.runtime import metrics as M


class RemoteFetchExec(TpuExec):
    def __init__(self, shuffle_id: int, schema: T.StructType, n_parts: int,
                 locations: list, pinned_reduce: int | None = None,
                 epoch: int = 0, conf=None):
        super().__init__(conf=conf)
        self.shuffle_id = shuffle_id
        self.schema = schema
        self.n_parts = n_parts
        self.locations = list(locations)
        self.pinned_reduce = pinned_reduce
        # map-output epoch the driver stamped at task-ship time; rides the
        # fetch-retry events so stale-metadata fetches are attributable
        self.epoch = epoch
        self._fetch_time = self.metrics.metric(M.READ_FS_TIME, M.MODERATE)

    @property
    def output(self):
        return self.schema

    @property
    def num_partitions(self):
        return 1 if self.pinned_reduce is not None else self.n_parts

    def execute_partition(self, split):
        from spark_rapids_tpu.shuffle.fetch import iter_union_blocks
        from spark_rapids_tpu.shuffle.transport import (InflightThrottle,
                                                        TcpShuffleClient)
        rid = self.pinned_reduce if self.pinned_reduce is not None else split
        bounce = self.conf.get(CFG.SHUFFLE_BOUNCE_BUFFER_SIZE)
        throttle = InflightThrottle(
            self.conf.get(CFG.SHUFFLE_MAX_INFLIGHT_BYTES))
        retries = self.conf.get(CFG.SHUFFLE_FETCH_MAX_RETRIES)
        # fresh client per attempt (a dead connection must not be reused);
        # per-peer retry+backoff via the shuffle fetch ladder — peers hold
        # DISJOINT block sets here, so there is no failover, and a peer
        # that stays dead surfaces as TransportError for the driver's
        # lineage-scoped recompute to classify
        factories = [
            (lambda a=tuple(addr): TcpShuffleClient(a, bounce, throttle))
            for addr in self.locations]

        def it():
            for batch in iter_union_blocks(factories, self.shuffle_id, rid,
                                           max_retries=retries,
                                           epoch=self.epoch):
                acquire_semaphore(self.metrics)
                yield batch
        return self.wrap_output(it())

    def args_string(self):
        return (f"shuffle={self.shuffle_id} pinned={self.pinned_reduce} "
                f"peers={len(self.locations)} epoch={self.epoch}")
