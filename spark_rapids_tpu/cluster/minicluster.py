"""MiniCluster: driver + N executor OS processes running one query end-to-end.

Reference (SURVEY.md §1 L6, components #29-#33): on a real Spark cluster the
reference's plugin rides Spark's own scheduling — the driver's DAGScheduler
splits the plan at ShuffleDependency boundaries, executor JVMs run tasks, and
RapidsShuffleInternalManagerBase.scala:200 + the UCX transport move shuffle
blocks between executor processes (Plugin.scala:137-211 wires the executor
side up). Standalone, this module IS that cluster: a spawn-based executor
pool, a stage scheduler splitting the plan at explicit ExchangeNodes
(plan/distribute.py is the EnsureRequirements analog), and the existing
TcpTransport + ShuffleBlockStore as the inter-process data plane.

Execution model:
- the driver rewrites the logical plan with ensure_distribution(), then
  schedules each ExchangeNode bottom-up as a MAP STAGE: every map task
  executes one split of the exchange's child subtree on some executor,
  partitions rows with the exchange's partitioner, and parks the buckets in
  that executor's block store under a driver-assigned shuffle id;
- the consumed exchange is replaced by a RemoteSourceNode carrying every
  executor's block-server address; downstream tasks fetch their reduce
  partition from all peers over TCP (union of blocks = the partition);
- tasks ship with their RemoteSourceNodes PINNED to the task's reduce id, so
  the subtree is single-partition on the executor and stage-local planning
  (TpuOverrides) never inserts its own exchanges;
- the final (result) stage returns Arrow IPC bytes to the driver.

Scope note: stages whose inputs are not co-partitioned (e.g. a UNION mixing
a scan leaf with a shuffle source) run as one task with unpinned sources —
correct (the task redistributes locally) but not parallel across executors.
"""

from __future__ import annotations

import itertools
import multiprocessing as mp
import os
import traceback

import pyarrow as pa

# NOTE: engine imports stay INSIDE functions — the spawn bootstrap imports
# this module in the executor child BEFORE _executor_main can select the jax
# platform, and importing the engine under the axon env would initialize the
# TPU backend in every executor.


# ---------------------------------------------------------------------------
# executor process
# ---------------------------------------------------------------------------

def _executor_main(conn, platform: str, conf_settings: dict):
    """Executor entry (spawned): block server + task loop (the standalone
    Plugin.scala:137-211 executor-side bring-up analog)."""
    if platform:
        os.environ["JAX_PLATFORMS"] = platform
    import jax
    if platform:
        jax.config.update("jax_platforms", platform)
    import cloudpickle
    import spark_rapids_tpu  # noqa: F401  (x64 etc.)
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.exec.base import TaskContext
    from spark_rapids_tpu.plan.transitions import to_device_plan
    from spark_rapids_tpu.shuffle.manager import ShuffleBlockStore
    from spark_rapids_tpu.shuffle.transport import TcpTransport

    conf = RapidsConf(conf_settings)
    store = ShuffleBlockStore.get()
    transport = TcpTransport(conf)
    conn.send({"op": "ready", "port": transport.port, "pid": os.getpid()})

    def run_map(task):
        plan = task["plan"]
        part = task["partitioner"].bind(plan.output)
        sid = task["shuffle_id"]
        store.ensure_shuffle(sid)
        exec_root = to_device_plan(plan, conf)
        with TaskContext():
            for split in task["splits"]:
                for batch in exec_root.execute_partition(split):
                    for pid, piece in part.partition(batch, split):
                        if piece.num_rows:
                            store.write_block(sid, pid, piece)
        return {"sizes": store.partition_sizes(sid, part.num_partitions)}

    def run_result(task):
        plan = task["plan"]
        exec_root = to_device_plan(plan, conf)
        tables = []
        with TaskContext():
            for split in task["splits"]:
                for batch in exec_root.execute_partition(split):
                    tables.append(batch.to_arrow())
        if not tables:
            out = plan.output.to_arrow().empty_table()
        else:
            out = pa.concat_tables(tables)
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, out.schema) as w:
            w.write_table(out)
        return {"ipc": sink.getvalue().to_pybytes()}

    while True:
        msg = conn.recv()
        op = msg["op"]
        if op == "stop":
            transport.shutdown()
            conn.send({"op": "bye"})
            break
        try:
            if op == "map":
                reply = run_map(cloudpickle.loads(msg["task"]))
            elif op == "result":
                reply = run_result(cloudpickle.loads(msg["task"]))
            elif op == "ensure_shuffle":
                store.ensure_shuffle(msg["shuffle_id"])
                reply = {}
            elif op == "drop_shuffle":
                store.unregister_shuffle(msg["shuffle_id"])
                reply = {}
            else:
                raise ValueError(f"unknown op {op}")
            reply.update({"op": "done", "ok": True})
        except BaseException:  # noqa: BLE001 — shipped back to the driver
            reply = {"op": "done", "ok": False,
                     "error": traceback.format_exc()}
        conn.send(reply)


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _clone_plan(plan):
    import cloudpickle
    return cloudpickle.loads(cloudpickle.dumps(plan))


def _pin_sources(plan, reduce_id: int):
    """Deep-replace every RemoteSourceNode with a pinned copy."""
    from spark_rapids_tpu.plan import nodes as NN
    if isinstance(plan, NN.RemoteSourceNode):
        return plan.pinned(reduce_id)
    plan.children = [_pin_sources(c, reduce_id) for c in plan.children]
    return plan


def _collect_sources(plan, out):
    from spark_rapids_tpu.plan import nodes as NN
    if isinstance(plan, NN.RemoteSourceNode):
        out.append(plan)
    for c in plan.children:
        _collect_sources(c, out)
    return out


def _has_non_source_leaves(plan):
    from spark_rapids_tpu.plan import nodes as NN
    if not plan.children:
        return not isinstance(plan, NN.RemoteSourceNode)
    return any(_has_non_source_leaves(c) for c in plan.children)


class MiniCluster:
    """Driver for N executor processes; `collect(df)` runs the DataFrame's
    plan across them (DAGScheduler + cluster-manager stand-in)."""

    def __init__(self, n_executors: int = 2, conf=None, platform: str = "cpu"):
        from spark_rapids_tpu.config import RapidsConf
        self.conf = conf or RapidsConf()
        self.n_executors = n_executors
        self._shuffle_ids = itertools.count(1000)
        ctx = mp.get_context("spawn")
        self._conns, self._procs, self.addresses = [], [], []
        for _ in range(n_executors):
            parent, child = ctx.Pipe()
            p = ctx.Process(target=_executor_main,
                            args=(child, platform, dict(self.conf.settings)),
                            daemon=True)
            p.start()
            hello = parent.recv()
            assert hello["op"] == "ready"
            self._conns.append(parent)
            self._procs.append(p)
            self.addresses.append(("127.0.0.1", hello["port"]))
        self._rr = itertools.cycle(range(n_executors))

    # -- task plumbing ------------------------------------------------------
    def _dispatch(self, jobs):
        """jobs: list of (executor_idx, op, task_dict). Runs each executor's
        queue sequentially, executors in parallel; returns replies in job
        order."""
        import cloudpickle
        by_exec: dict[int, list] = {}
        for j, (ei, op, task) in enumerate(jobs):
            by_exec.setdefault(ei, []).append((j, op, task))
        replies = [None] * len(jobs)
        # send one task per executor at a time (the Pipe is a simple duplex
        # channel); round-robin until all queues drain
        pending = {ei: list(q) for ei, q in by_exec.items()}
        inflight = {}
        while pending or inflight:
            for ei, q in list(pending.items()):
                if ei not in inflight and q:
                    j, op, task = q.pop(0)
                    self._conns[ei].send(
                        {"op": op, "task": cloudpickle.dumps(task)})
                    inflight[ei] = j
                if not q:
                    del pending[ei]
            for ei, j in list(inflight.items()):
                reply = self._conns[ei].recv()
                if not reply.get("ok"):
                    raise RuntimeError(
                        f"executor {ei} task failed:\n{reply.get('error')}")
                replies[j] = reply
                del inflight[ei]
        return replies

    # -- scheduling ---------------------------------------------------------
    def collect(self, df) -> pa.Table:
        from spark_rapids_tpu.plan.distribute import (ensure_distribution,
                                                      stage_order)
        plan = _clone_plan(df._plan)
        plan = ensure_distribution(plan, self.n_executors)
        for exchange, parent, idx in stage_order(plan):
            source = self._run_map_stage(exchange)
            parent.children[idx] = source
        return self._run_result_stage(plan)

    def _run_map_stage(self, exchange):
        from spark_rapids_tpu.plan import nodes as NN
        from spark_rapids_tpu.shuffle import partitioning as SP
        child = exchange.child
        if exchange.partitioning == "hash":
            part = SP.HashPartitioner(exchange.keys, exchange.num_out)
        elif exchange.partitioning == "single":
            part = SP.SinglePartitioner()
        elif exchange.partitioning == "roundrobin":
            part = SP.RoundRobinPartitioner(exchange.num_out)
        else:
            raise NotImplementedError(
                "range partitioning needs driver-side sampling (use "
                "sort with a single exchange in MiniCluster)")
        sid = next(self._shuffle_ids)
        # every executor must know the shuffle id — a peer with no map task
        # for it still serves (empty) metadata requests from reducers
        for c in self._conns:
            c.send({"op": "ensure_shuffle", "shuffle_id": sid})
        for c in self._conns:
            reply = c.recv()
            assert reply.get("ok"), reply
        jobs = []
        for split, task in self._stage_tasks(child):
            task.update({"shuffle_id": sid, "partitioner": part})
            jobs.append((next(self._rr), "map", task))
        self._dispatch(jobs)
        return NN.RemoteSourceNode(sid, child.output, part.num_partitions,
                                   list(self.addresses))

    def _stage_tasks(self, subtree):
        """Yield (split, task) covering every partition of `subtree`.
        Co-partitioned shuffle inputs → one pinned task per reduce id;
        leaf-only stages → one task per leaf split; mixed → one task."""
        sources = _collect_sources(subtree, [])
        if sources and not _has_non_source_leaves(subtree) and \
                len({s.n_parts for s in sources}) == 1:
            n = sources[0].n_parts
            for r in range(n):
                yield r, {"plan": _pin_sources(_clone_plan(subtree), r),
                          "splits": [0]}
        elif not sources:
            for s in range(subtree.num_partitions):
                yield s, {"plan": subtree, "splits": [s]}
        else:
            yield 0, {"plan": subtree,
                      "splits": list(range(subtree.num_partitions))}

    def _run_result_stage(self, plan) -> pa.Table:
        jobs = [(next(self._rr), "result", task)
                for _, task in self._stage_tasks(plan)]
        replies = self._dispatch(jobs)
        tables = []
        for r in replies:
            t = pa.ipc.open_stream(r["ipc"]).read_all()
            if t.num_rows or not tables:
                tables.append(t)
        return pa.concat_tables(tables)

    def shutdown(self):
        for c in self._conns:
            try:
                c.send({"op": "stop"})
                c.recv()
            except (EOFError, OSError):
                pass
        for p in self._procs:
            p.join(timeout=10)
            if p.is_alive():
                p.terminate()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False
