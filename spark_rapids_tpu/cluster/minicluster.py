"""MiniCluster: driver + N executor OS processes running one query end-to-end.

Reference (SURVEY.md §1 L6, components #29-#33): on a real Spark cluster the
reference's plugin rides Spark's own scheduling — the driver's DAGScheduler
splits the plan at ShuffleDependency boundaries, executor JVMs run tasks, and
RapidsShuffleInternalManagerBase.scala:200 + the UCX transport move shuffle
blocks between executor processes (Plugin.scala:137-211 wires the executor
side up). Standalone, this module IS that cluster: a spawn-based executor
pool, a stage scheduler splitting the plan at explicit ExchangeNodes
(plan/distribute.py is the EnsureRequirements analog), and the existing
TcpTransport + ShuffleBlockStore as the inter-process data plane.

Execution model:
- the driver rewrites the logical plan with ensure_distribution(), then
  schedules each ExchangeNode bottom-up as a MAP STAGE: every map task
  executes one split of the exchange's child subtree on some executor,
  partitions rows with the exchange's partitioner, and parks the buckets in
  that executor's block store under a driver-assigned shuffle id;
- the consumed exchange is replaced by a RemoteSourceNode carrying every
  executor's block-server address; downstream tasks fetch their reduce
  partition from all peers over TCP (union of blocks = the partition);
- tasks ship with their RemoteSourceNodes PINNED to the task's reduce id, so
  the subtree is single-partition on the executor and stage-local planning
  (TpuOverrides) never inserts its own exchanges;
- the final (result) stage returns Arrow IPC bytes to the driver.

Fault tolerance: a dead executor (broken pipe / EOF on its channel, or a task
failing with a transport error against a dead peer) raises ExecutorLostError;
the driver HEALS the pool (respawns the slot with a fresh block server) and
re-runs the query's stages from the start with fresh shuffle ids — the
standalone, coarser-grained form of Spark's FetchFailed → lineage recompute
(reference RapidsShuffleIterator.scala:82,153), bounded by max_attempts.
"""

from __future__ import annotations

import itertools
import multiprocessing as mp
import os
import traceback

import pyarrow as pa

# NOTE: engine imports stay INSIDE functions — the spawn bootstrap imports
# this module in the executor child BEFORE _executor_main can select the jax
# platform, and importing the engine under the axon env would initialize the
# TPU backend in every executor.


# ---------------------------------------------------------------------------
# executor process
# ---------------------------------------------------------------------------

def _executor_main(conn, platform: str, conf_settings: dict):
    """Executor entry (spawned): block server + task loop (the standalone
    Plugin.scala:137-211 executor-side bring-up analog)."""
    if platform:
        os.environ["JAX_PLATFORMS"] = platform
    import jax
    if platform:
        jax.config.update("jax_platforms", platform)
    import cloudpickle
    import spark_rapids_tpu  # noqa: F401  (x64 etc.)
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.exec.base import TaskContext
    from spark_rapids_tpu.plan.transitions import to_device_plan
    from spark_rapids_tpu.shuffle.manager import ShuffleBlockStore
    from spark_rapids_tpu.shuffle.transport import TcpTransport

    conf = RapidsConf(conf_settings)
    store = ShuffleBlockStore.get()
    transport = TcpTransport(conf)
    conn.send({"op": "ready", "port": transport.port, "pid": os.getpid()})

    def run_map(task):
        plan = task["plan"]
        part = task["partitioner"].bind(plan.output)
        sid = task["shuffle_id"]
        store.ensure_shuffle(sid)
        exec_root = to_device_plan(plan, conf)
        with TaskContext():
            for split in task["splits"]:
                seq = 0
                for batch in exec_root.execute_partition(split):
                    seq += 1
                    for pid, piece in part.partition(batch, split):
                        if piece.num_rows:
                            # stable per-reduce-partition block order (same
                            # contract as the local exchange map writer)
                            store.write_block(sid, pid, piece,
                                              seq=(split, seq))
        return {"sizes": store.partition_sizes(sid, part.num_partitions)}

    def run_result(task):
        plan = task["plan"]
        exec_root = to_device_plan(plan, conf)
        tables = []
        with TaskContext():
            for split in task["splits"]:
                for batch in exec_root.execute_partition(split):
                    tables.append(batch.to_arrow())
        if not tables:
            out = plan.output.to_arrow().empty_table()
        else:
            out = pa.concat_tables(tables)
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, out.schema) as w:
            w.write_table(out)
        return {"ipc": sink.getvalue().to_pybytes()}

    while True:
        msg = conn.recv()
        op = msg["op"]
        if op == "stop":
            transport.shutdown()
            conn.send({"op": "bye"})
            break
        try:
            if op == "map":
                reply = run_map(cloudpickle.loads(msg["task"]))
            elif op == "result":
                reply = run_result(cloudpickle.loads(msg["task"]))
            elif op == "ensure_shuffle":
                store.ensure_shuffle(msg["shuffle_id"])
                reply = {}
            elif op == "drop_shuffle":
                store.unregister_shuffle(msg["shuffle_id"])
                reply = {}
            else:
                raise ValueError(f"unknown op {op}")
            reply.update({"op": "done", "ok": True})
        except BaseException:  # noqa: BLE001 — shipped back to the driver
            reply = {"op": "done", "ok": False,
                     "error": traceback.format_exc()}
        conn.send(reply)


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _clone_plan(plan):
    import cloudpickle
    return cloudpickle.loads(cloudpickle.dumps(plan))


def _pin_sources(plan, reduce_id: int):
    """Deep-replace every RemoteSourceNode with a pinned copy."""
    from spark_rapids_tpu.plan import nodes as NN
    if isinstance(plan, NN.RemoteSourceNode):
        return plan.pinned(reduce_id)
    plan.children = [_pin_sources(c, reduce_id) for c in plan.children]
    return plan


def _collect_sources(plan, out):
    from spark_rapids_tpu.plan import nodes as NN
    if isinstance(plan, NN.RemoteSourceNode):
        out.append(plan)
    for c in plan.children:
        _collect_sources(c, out)
    return out


def _has_non_source_leaves(plan):
    from spark_rapids_tpu.plan import nodes as NN
    if not plan.children:
        return not isinstance(plan, NN.RemoteSourceNode)
    return any(_has_non_source_leaves(c) for c in plan.children)


class ExecutorLostError(RuntimeError):
    """An executor process died (channel broke) or a task failed against a
    dead shuffle peer; the driver heals the pool and retries the query."""


class MiniCluster:
    """Driver for N executor processes; `collect(df)` runs the DataFrame's
    plan across them (DAGScheduler + cluster-manager stand-in)."""

    def __init__(self, n_executors: int = 2, conf=None, platform: str = "cpu",
                 max_attempts: int = 3):
        from spark_rapids_tpu.config import RapidsConf
        self.conf = conf or RapidsConf()
        self.n_executors = n_executors
        self.max_attempts = max_attempts
        self._platform = platform
        self._shuffle_ids = itertools.count(1000)
        self._conns = [None] * n_executors
        self._procs = [None] * n_executors
        self.addresses = [None] * n_executors
        for ei in range(n_executors):
            self._spawn_executor(ei)
        self._rr = itertools.cycle(range(n_executors))
        self.task_log: list = []        # (stage_op, executor_idx) per task
        self._after_stage_hook = None   # test fault-injection point

    def _spawn_executor(self, ei: int):
        ctx = mp.get_context("spawn")
        parent, child = ctx.Pipe()
        p = ctx.Process(target=_executor_main,
                        args=(child, self._platform,
                              dict(self.conf.settings)),
                        daemon=True)
        p.start()
        hello = parent.recv()
        assert hello["op"] == "ready"
        self._conns[ei] = parent
        self._procs[ei] = p
        self.addresses[ei] = ("127.0.0.1", hello["port"])

    def _heal(self):
        """Restart the WHOLE pool. Survivors may hold in-flight tasks whose
        replies would desynchronize the request/reply pipe protocol on
        retry (a stale ok=True task reply would be consumed as the next
        ensure_shuffle ack); since the retry re-runs every stage anyway,
        clean processes are both simpler and correct (Spark's
        executor-replacement role)."""
        for ei, p in enumerate(self._procs):
            try:
                self._conns[ei].close()
            except OSError:
                pass
            if p is not None:
                if p.is_alive():
                    p.terminate()
                p.join(timeout=5)
                if p.is_alive():
                    p.kill()
                    p.join(timeout=5)
            self._spawn_executor(ei)

    # -- task plumbing ------------------------------------------------------
    def _dispatch(self, jobs):
        """jobs: list of (executor_idx, op, task_dict). Runs each executor's
        queue sequentially, executors in parallel; returns replies in job
        order. A broken channel or a transport-failure reply raises
        ExecutorLostError (caught by collect()'s retry ladder)."""
        import cloudpickle
        by_exec: dict[int, list] = {}
        for j, (ei, op, task) in enumerate(jobs):
            by_exec.setdefault(ei, []).append((j, op, task))
            self.task_log.append((op, ei))
        if len(self.task_log) > 4096:    # observability ring, not a ledger
            del self.task_log[:-2048]
        replies = [None] * len(jobs)
        # send one task per executor at a time (the Pipe is a simple duplex
        # channel); round-robin until all queues drain
        pending = {ei: list(q) for ei, q in by_exec.items()}
        inflight = {}
        while pending or inflight:
            for ei, q in list(pending.items()):
                if ei not in inflight and q:
                    j, op, task = q.pop(0)
                    try:
                        self._conns[ei].send(
                            {"op": op, "task": cloudpickle.dumps(task)})
                    except (BrokenPipeError, OSError) as e:
                        raise ExecutorLostError(
                            f"executor {ei} channel broke on send: {e}") \
                            from e
                    inflight[ei] = j
                if not q:
                    del pending[ei]
            for ei, j in list(inflight.items()):
                try:
                    reply = self._conns[ei].recv()
                except (EOFError, OSError) as e:
                    raise ExecutorLostError(
                        f"executor {ei} died mid-task: {e}") from e
                if not reply.get("ok"):
                    err = reply.get("error") or ""
                    if "TransportError" in err:
                        # fetch against a dead peer: a stage-level loss, not
                        # a task bug — retry through the heal ladder
                        raise ExecutorLostError(
                            f"executor {ei} fetch failed:\n{err}")
                    raise RuntimeError(
                        f"executor {ei} task failed:\n{err}")
                replies[j] = reply
                del inflight[ei]
        return replies

    # -- scheduling ---------------------------------------------------------
    def collect(self, df) -> pa.Table:
        last = None
        for attempt in range(self.max_attempts):
            try:
                return self._collect_once(df)
            except ExecutorLostError as e:
                # lineage recompute, coarse-grained: heal the pool and re-run
                # all stages with fresh shuffle ids (Spark FetchFailed →
                # stage retry; reference RapidsShuffleIterator.scala:82,153)
                last = e
                self._heal()
        raise last

    def _collect_once(self, df) -> pa.Table:
        from spark_rapids_tpu.plan.distribute import (ensure_distribution,
                                                      stage_order)
        plan = _clone_plan(df._plan)
        plan = ensure_distribution(plan, self.n_executors)
        for exchange, parent, idx in stage_order(plan):
            source = self._run_map_stage(exchange)
            parent.children[idx] = source
            if self._after_stage_hook is not None:
                self._after_stage_hook(self)
        return self._run_result_stage(plan)

    def _run_map_stage(self, exchange):
        from spark_rapids_tpu.plan import nodes as NN
        from spark_rapids_tpu.shuffle import partitioning as SP
        child = exchange.child
        if exchange.partitioning == "hash":
            part = SP.HashPartitioner(exchange.keys, exchange.num_out)
        elif exchange.partitioning == "single":
            part = SP.SinglePartitioner()
        elif exchange.partitioning == "roundrobin":
            part = SP.RoundRobinPartitioner(exchange.num_out)
        else:
            raise NotImplementedError(
                "range partitioning needs driver-side sampling (use "
                "sort with a single exchange in MiniCluster)")
        sid = next(self._shuffle_ids)
        # every executor must know the shuffle id — a peer with no map task
        # for it still serves (empty) metadata requests from reducers
        try:
            for c in self._conns:
                c.send({"op": "ensure_shuffle", "shuffle_id": sid})
            for c in self._conns:
                reply = c.recv()
                assert reply.get("ok"), reply
        except (BrokenPipeError, EOFError, OSError) as e:
            raise ExecutorLostError(f"ensure_shuffle: {e}") from e
        jobs = []
        for split, task in self._stage_tasks(child):
            task.update({"shuffle_id": sid, "partitioner": part})
            jobs.append((next(self._rr), "map", task))
        self._dispatch(jobs)
        return NN.RemoteSourceNode(sid, child.output, part.num_partitions,
                                   list(self.addresses))

    def _stage_tasks(self, subtree):
        """Yield (split, task) covering every partition of `subtree`.
        Co-partitioned shuffle inputs → one pinned task per reduce id;
        everything else → one task per partition of the subtree (a UNION of
        a scan leaf with a shuffle source spreads its leaf splits and reduce
        partitions across executors instead of serializing in one task)."""
        sources = _collect_sources(subtree, [])
        if sources and not _has_non_source_leaves(subtree) and \
                len({s.n_parts for s in sources}) == 1:
            n = sources[0].n_parts
            for r in range(n):
                yield r, {"plan": _pin_sources(_clone_plan(subtree), r),
                          "splits": [0]}
        else:
            for s in range(subtree.num_partitions):
                yield s, {"plan": subtree, "splits": [s]}

    def _run_result_stage(self, plan) -> pa.Table:
        jobs = [(next(self._rr), "result", task)
                for _, task in self._stage_tasks(plan)]
        replies = self._dispatch(jobs)
        tables = []
        for r in replies:
            t = pa.ipc.open_stream(r["ipc"]).read_all()
            if t.num_rows or not tables:
                tables.append(t)
        return pa.concat_tables(tables)

    def shutdown(self):
        for c in self._conns:
            try:
                c.send({"op": "stop"})
                c.recv()
            except (EOFError, OSError):
                pass
        for p in self._procs:
            p.join(timeout=10)
            if p.is_alive():
                p.terminate()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False
