"""MiniCluster: driver + N executor OS processes running one query end-to-end.

Reference (SURVEY.md §1 L6, components #29-#33): on a real Spark cluster the
reference's plugin rides Spark's own scheduling — the driver's DAGScheduler
splits the plan at ShuffleDependency boundaries, executor JVMs run tasks, and
RapidsShuffleInternalManagerBase.scala:200 + the UCX transport move shuffle
blocks between executor processes (Plugin.scala:137-211 wires the executor
side up). Standalone, this module IS that cluster: a spawn-based executor
pool, a stage scheduler splitting the plan at explicit ExchangeNodes
(plan/distribute.py is the EnsureRequirements analog), and the existing
TcpTransport + ShuffleBlockStore as the inter-process data plane.

Execution model:
- the driver rewrites the logical plan with ensure_distribution(), then
  schedules each ExchangeNode bottom-up as a MAP STAGE: every map task
  executes one split of the exchange's child subtree on some executor,
  partitions rows with the exchange's partitioner, and parks the buckets in
  that executor's block store under a driver-assigned shuffle id;
- the consumed exchange is replaced by a RemoteSourceNode carrying every
  executor's block-server address; downstream tasks fetch their reduce
  partition from all peers over TCP (union of blocks = the partition);
- tasks ship with their RemoteSourceNodes PINNED to the task's reduce id, so
  the subtree is single-partition on the executor and stage-local planning
  (TpuOverrides) never inserts its own exchanges;
- the final (result) stage returns Arrow IPC bytes to the driver.

Fault tolerance — recovery proportional to what was lost (the Spark
task-retry / FetchFailed → lineage-recompute ladder, reference
RapidsShuffleIterator.scala:82,153):

- a **MapOutputTracker** on the driver records, per shuffle id, which
  executor hosts each map split's blocks, epoch-stamped: the epoch bumps
  whenever a shuffle's outputs are invalidated, and any task reply computed
  under a stale epoch is discarded and re-run (the reducer may have read a
  half-rebuilt partition);
- **task attempts**: a failed task (exception, injected fault, or a
  `cluster.task.timeoutSeconds` deadline) retries up to
  `cluster.task.maxFailures` times, preferring a different executor;
  per-executor failure strikes **blacklist** an executor from placement
  after `cluster.blacklist.maxTaskFailures`;
- **lineage-scoped recompute**: on executor death (broken channel, or the
  driver's poll of the heartbeat manager's expire_dead), the driver respawns
  the slot, consults the tracker for exactly the map splits that lived on
  the dead peer, re-runs only those under a bumped epoch, re-publishes
  addresses into every live RemoteSourceNode, and reuses every surviving
  stage output verbatim; the whole-query `_heal()` retry remains only as a
  final fallback once `cluster.stage.maxRecomputes` is exhausted;
- optional **speculative execution** (`cluster.speculation.enabled`):
  stragglers past `speculation.multiplier` × the median completed task time
  are duplicated on idle executors; the first completion wins (dedup keyed
  by `(shuffle_id, map_split)`) and the loser's blocks are dropped so
  results stay bit-identical.
"""

from __future__ import annotations

import collections
import itertools
import multiprocessing as mp
import os
import statistics
import time
import traceback

import pyarrow as pa

# NOTE: engine imports stay INSIDE functions — the spawn bootstrap imports
# this module in the executor child BEFORE _executor_main can select the jax
# platform, and importing the engine under the axon env would initialize the
# TPU backend in every executor.


# ---------------------------------------------------------------------------
# executor process
# ---------------------------------------------------------------------------

def _mesh_conf_raw(conf_settings: dict):
    """Parse the cluster.mesh knobs from the RAW settings dict — needed
    BEFORE any spark_rapids_tpu import (the config module pulls in jax,
    and the XLA device-count flag must be set first)."""
    pre = "spark.rapids.tpu.cluster.mesh."
    enabled = str(conf_settings.get(pre + "enabled", "")
                  ).strip().lower() in ("true", "1", "yes")
    try:
        n = int(conf_settings.get(pre + "devicesPerExecutor", 0) or 0)
    except (TypeError, ValueError):
        n = 0
    return enabled, n


def _executor_main(conn, executor_index: int, platform: str,
                   conf_settings: dict):
    """Executor entry (spawned): block server + task loop (the standalone
    Plugin.scala:137-211 executor-side bring-up analog)."""
    if platform:
        os.environ["JAX_PLATFORMS"] = platform
    mesh_on, mesh_n = _mesh_conf_raw(conf_settings)
    if mesh_on and platform == "cpu":
        # the local mesh needs >=2 devices; on the CPU platform they only
        # exist if the XLA host-device flag is set before jax initializes
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                f"{mesh_n if mesh_n > 0 else 8}").strip()
    import jax
    if platform:
        jax.config.update("jax_platforms", platform)
    import cloudpickle
    import spark_rapids_tpu  # noqa: F401  (x64 etc.)
    from spark_rapids_tpu import config as CFG
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.exec.base import TaskContext
    from spark_rapids_tpu.plan.transitions import to_device_plan
    from spark_rapids_tpu.runtime import eventlog as EL
    from spark_rapids_tpu.runtime import faults as F
    from spark_rapids_tpu.runtime import tracing
    from spark_rapids_tpu.shuffle.manager import ShuffleBlockStore
    from spark_rapids_tpu.shuffle.transport import TcpTransport

    conf = RapidsConf(conf_settings)
    # arm the chaos injector in the executor too (exec_kill / oom / transport
    # sites fire where the work actually runs); the driver strips the spec
    # from RESPAWNED replacements so COUNT triggers cannot re-fire forever
    F.configure(conf.get(CFG.TEST_FAULTS), conf.get(CFG.TEST_FAULTS_SEED))
    # executor-side telemetry sinks: spans and event-log records land in
    # per-process files under the SAME directories the driver uses, merged
    # later by timestamp + the clock offset the driver measures below
    tdir = conf.get(CFG.TRACE_DIR)
    if tdir:
        tracing.configure_spans(tdir, process=f"executor-{executor_index}")
    edir = conf.get(CFG.EVENT_LOG_DIR)
    if edir:
        EL.configure(edir, max_bytes=conf.get(CFG.EVENT_LOG_MAX_BYTES),
                     keep=conf.get(CFG.EVENT_LOG_KEEP_FILES))
    # the movement ledger meters this process's own boundary crossings —
    # same knobs as the driver so merged per-process samples line up
    from spark_rapids_tpu.runtime import movement as MV
    MV.configure(
        sample_interval_bytes=conf.get(CFG.MOVEMENT_SAMPLE_INTERVAL),
        enabled=conf.get(CFG.MOVEMENT_ENABLED))
    # device + memory bring-up with the CLUSTER conf (the plugin.py:82
    # executor-side analog): without this the lazily-built DeviceManager
    # uses a default conf and out-of-core budgets (hbm.limitBytes,
    # spillStorageSize) silently do not apply on executors
    from spark_rapids_tpu.runtime.memory import DeviceManager
    DeviceManager.initialize(conf)
    store = ShuffleBlockStore.get()
    transport = TcpTransport(conf)
    # the reduce side short-circuits fetches addressed to THIS executor's
    # block server straight into the local store (cluster/remote.py) — the
    # read movement-aware placement schedules for
    from spark_rapids_tpu.cluster import remote as R
    R.set_local_address(("127.0.0.1", transport.port))
    # local mesh bring-up (unified mesh-cluster plane): report the ACTUAL
    # attached width on the handshake so the driver sizes mesh task groups
    # to what this process really has (mesh.attach / degraded re-plans)
    mesh_width = 0
    if mesh_on:
        try:
            from spark_rapids_tpu.distributed.mesh import LocalMesh
            mesh_width = LocalMesh.get(mesh_n).n
        except Exception:
            mesh_width = 0
    conn.send({"op": "ready", "port": transport.port, "pid": os.getpid(),
               "mesh": mesh_width})

    def run_mesh_map(task):
        """A MESH map task: up to mesh-width lanes (one map split each) run
        in one task; per partition wave, every lane's current batch gets
        its Spark-exact partition ids from ONE jitted shard_map dispatch on
        the local mesh, with the wave's per-partition row counts psum-ed
        over ICI (distributed/mesh.LocalMesh).

        TWO-LEVEL EXCHANGE (docs/cluster.md): when the driver shipped a
        `reduce_owned` set (the reduce partitions whose consumers will be
        placed on THIS executor) and the wave schema is fixed-width, the
        owned partitions' content moves lane→lane as `lax.all_to_all` over
        ICI (LocalMesh.exchange_wave) and the receiving lane writes the
        shards straight into the process-local block store under the SAME
        (map_split, seq) keys the per-batch path would use — so
        iter_union_blocks' canonical-key merge keeps bit-identity with the
        TCP plane by construction, and only cross-host partitions are
        sliced with the exact per-batch path and parked for the TCP fetch.
        String-keyed waves (counts is None) and variable-width schemas
        fall back to slice-and-park for every partition WITHOUT breaking
        the mesh group. Any failure of the mesh itself (bring-up, shrink,
        collective, exchange) surfaces as MeshDegradedError → the driver's
        degraded fallback; failures INSIDE a lane's subtree execution stay
        ordinary task failures and ride the attempt ladder."""
        import numpy as np
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        from spark_rapids_tpu.columnar.vector import TpuColumnVector
        from spark_rapids_tpu.distributed.mesh import (LocalMesh,
                                                       MeshDegradedError)
        from spark_rapids_tpu.shuffle.partitioning import (
            slice_into_partitions)
        plan = task["plan"]
        lanes = task["mesh_lanes"]
        sid = task["shuffle_id"]
        part = task["partitioner"].bind(plan.output)
        owned = sorted(task.get("reduce_owned") or ())
        two_level = bool(owned) and LocalMesh.exchangeable_schema(plan.output)
        store.ensure_shuffle(sid)
        tracing.set_process_trace(task.get("trace"))
        try:
            # mesh_kill / mesh_hang / degrade chaos sites: INSIDE the
            # degrade guard, so exec_kill dies mid-collective with partial
            # blocks parked, hang wedges until the task deadline, and
            # error proves the transparent mesh→TCP fallback
            F.maybe_inject_any("cluster.mesh.begin")
            F.maybe_inject_any(f"cluster.mesh.begin.{executor_index}")
            lm = LocalMesh.get(mesh_n)
            if lm.n < len(lanes):
                raise MeshDegradedError(
                    f"mesh shrank: width {lm.n} < {len(lanes)} lanes")
        except MeshDegradedError:
            raise
        except Exception as e:
            raise MeshDegradedError(f"mesh bring-up failed: {e!r}") from e
        waves = rows_exchanged = ici_rows = 0
        with tracing.span("task.mesh_map", shuffle=sid,
                          lanes=len(lanes)), TaskContext():
            iters, seqs = [], []
            for lane in lanes:
                if lane["pin"] is not None:
                    lplan = _pin_sources(_clone_plan(plan), lane["pin"])
                    lsplit = 0
                else:
                    lplan = _clone_plan(plan)
                    lsplit = lane["split"]
                iters.append(to_device_plan(lplan, conf)
                             .execute_partition(lsplit))
                seqs.append(0)
            live = list(range(len(lanes)))
            while live:
                wave = []
                for li in list(live):
                    try:
                        wave.append((li, next(iters[li])))
                    except StopIteration:
                        live.remove(li)
                if not wave:
                    break
                try:
                    F.maybe_inject_any("cluster.mesh")
                    F.maybe_inject_any(f"cluster.mesh.{executor_index}")
                    pids_list, counts = lm.partition_wave(
                        [b for _, b in wave], part)
                except MeshDegradedError:
                    raise
                except Exception as e:
                    raise MeshDegradedError(
                        f"mesh collective failed: {e!r}") from e
                waves += 1
                if counts is not None:
                    rows_exchanged += int(counts.sum())
                # level 1: owned partitions' content rides ICI — routed
                # round-robin over the wave's live lanes; the dest lane
                # choice only balances ICI traffic (the block store is
                # process-local, so any lane's write serves the consumer
                # placed on this executor)
                dm = None
                if two_level and counts is not None:
                    dm = np.full((part.num_partitions,), -1, np.int32)
                    for i, rid in enumerate(owned):
                        dm[rid] = i % len(wave)
                    try:
                        F.maybe_inject_any("cluster.mesh.exchange")
                        F.maybe_inject_any(
                            f"cluster.mesh.exchange.{executor_index}")
                        rvals, rmasks, rpids, rcounts = lm.exchange_wave(
                            [b for _, b in wave], pids_list, dm,
                            part.num_partitions)
                    except MeshDegradedError:
                        raise
                    except Exception as e:
                        raise MeshDegradedError(
                            f"mesh exchange failed: {e!r}") from e
                # level 2: cross-host (and fallback) partitions slice with
                # the exact per-batch path and park for the TCP fetch
                for (li, b), pids in zip(wave, pids_list):
                    seqs[li] += 1
                    for pid, piece in slice_into_partitions(
                            b, pids, part.num_partitions):
                        if dm is not None and dm[pid] >= 0:
                            continue  # rode ICI in this wave
                        if piece.num_rows:
                            store.write_block(
                                sid, pid, piece,
                                seq=(lanes[li]["split"], seqs[li]))
                if dm is not None:
                    # receiving lanes park the ICI shards under the SOURCE
                    # lane's (map_split, seq) key — identical to what the
                    # per-batch path would have written for that wave
                    for d in range(len(wave)):
                        for s in range(len(wave)):
                            if int(rcounts[d][s]) == 0:
                                continue
                            src_schema = wave[s][1].schema or plan.output
                            cols = [TpuColumnVector(
                                        f.data_type, rvals[c][d][s],
                                        rmasks[c][d][s])
                                    for c, f in enumerate(src_schema)]
                            mini = ColumnarBatch(cols, int(rcounts[d][s]),
                                                 src_schema)
                            src_li = wave[s][0]
                            for pid, piece in slice_into_partitions(
                                    mini, rpids[d][s],
                                    part.num_partitions):
                                if piece.num_rows:
                                    store.write_block(
                                        sid, pid, piece,
                                        seq=(lanes[src_li]["split"],
                                             seqs[src_li]))
                                    ici_rows += piece.num_rows
        return {"sizes": store.partition_sizes(sid, part.num_partitions),
                "split_sizes": {
                    lane["split"]: store.split_partition_sizes(
                        sid, part.num_partitions, lane["split"])
                    for lane in lanes},
                "mesh": {"waves": waves, "lanes": len(lanes),
                         "rows_exchanged": rows_exchanged,
                         "ici_rows": ici_rows}}

    def run_map(task):
        if task.get("mesh_lanes") is not None:
            return run_mesh_map(task)
        plan = task["plan"]
        part = task["partitioner"].bind(plan.output)
        sid = task["shuffle_id"]
        # the map task's identity within the shuffle: pins block order per
        # reduce partition AND lets the driver drop exactly this task's
        # output (speculation losers, stale/failed attempts)
        map_split = task["map_split"]
        store.ensure_shuffle(sid)
        # the task's trace id pins the PROCESS (one task at a time here), so
        # pipeline worker threads and the shuffle fetch path inherit it
        tracing.set_process_trace(task.get("trace"))
        # task-START checkpoint (distinct site from the per-batch one so
        # batch-counted @SKIP triggers stay stable): lets exec_kill/hang
        # fire even for a task whose input produces zero batches
        F.maybe_inject_any("cluster.map.begin")
        F.maybe_inject_any(f"cluster.map.begin.{executor_index}")
        exec_root = to_device_plan(plan, conf)
        with tracing.span("task.map", shuffle=sid, split=map_split), \
                TaskContext():
            for split in task["splits"]:
                seq = 0
                for batch in exec_root.execute_partition(split):
                    # chaos checkpoint (any armed kind fires, like the
                    # pipeline queue sites): exec_kill dies mid-task with
                    # blocks partially written, error drives task-attempt
                    # retries, hang drives the task deadline
                    F.maybe_inject_any("cluster.map")
                    F.maybe_inject_any(f"cluster.map.{executor_index}")
                    seq += 1
                    for pid, piece in part.partition(batch, split):
                        if piece.num_rows:
                            # stable per-reduce-partition block order (same
                            # contract as the local exchange map writer)
                            store.write_block(sid, pid, piece,
                                              seq=(map_split, seq))
        # per-split map-output statistics ride every reply so the driver's
        # MapOutputTracker can place reducers where their bytes live
        return {"sizes": store.partition_sizes(sid, part.num_partitions),
                "split_sizes": {map_split: store.split_partition_sizes(
                    sid, part.num_partitions, map_split)}}

    def run_result(task):
        plan = task["plan"]
        tracing.set_process_trace(task.get("trace"))
        F.maybe_inject_any("cluster.result.begin")
        F.maybe_inject_any(f"cluster.result.begin.{executor_index}")
        exec_root = to_device_plan(plan, conf)
        tables = []
        with tracing.span("task.result", splits=len(task["splits"])), \
                TaskContext():
            for split in task["splits"]:
                for batch in exec_root.execute_partition(split):
                    F.maybe_inject_any("cluster.result")
                    F.maybe_inject_any(f"cluster.result.{executor_index}")
                    tables.append(batch.to_arrow())
        if not tables:
            out = plan.output.to_arrow().empty_table()
        else:
            out = pa.concat_tables(tables)
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, out.schema) as w:
            w.write_table(out)
        return {"ipc": sink.getvalue().to_pybytes()}

    while True:
        msg = conn.recv()
        op = msg["op"]
        if op == "stop":
            transport.shutdown()
            conn.send({"op": "bye"})
            break
        try:
            if op == "map":
                reply = run_map(cloudpickle.loads(msg["task"]))
                # task-completion flush: the driver's profiler merge reads
                # the LAST movement.sample per process, so every finished
                # task leaves a current ledger snapshot behind
                MV.maybe_emit(force=True)
            elif op == "result":
                reply = run_result(cloudpickle.loads(msg["task"]))
                MV.maybe_emit(force=True)
            elif op == "clock":
                # driver-side two-timestamp exchange: our wall clock, read
                # as close to the reply as the pipe protocol allows
                reply = {"t": time.time()}
            elif op == "clock_set":
                # the measured offset toward the driver's clock: stamped
                # into event-log records and span files so merged timelines
                # order correctly across processes
                EL.set_clock_offset(msg["offset"])
                reply = {}
            elif op == "ensure_shuffle":
                store.ensure_shuffle(msg["shuffle_id"])
                reply = {}
            elif op == "drop_shuffle":
                store.unregister_shuffle(msg["shuffle_id"])
                reply = {}
            elif op == "drop_map_output":
                reply = {"dropped": store.drop_map_output(
                    msg["shuffle_id"], msg["map_split"])}
            else:
                raise ValueError(f"unknown op {op}")
            reply.update({"op": "done", "ok": True})
        except BaseException as exc:  # noqa: BLE001 — shipped to the driver
            reply = {"op": "done", "ok": False,
                     "error": traceback.format_exc()}
            # typed marker: the driver treats a degraded mesh as a
            # transparent re-plan, NOT a task failure (no attempt strike)
            if type(exc).__name__ == "MeshDegradedError":
                reply["mesh_degraded"] = True
        finally:
            # the task's trace id must not bleed into the next task (or
            # into fetch serving between tasks)
            tracing.set_process_trace(None)
        conn.send(reply)


# ---------------------------------------------------------------------------
# driver-side plan plumbing
# ---------------------------------------------------------------------------

def _clone_plan(plan):
    import cloudpickle
    return cloudpickle.loads(cloudpickle.dumps(plan))


def _pin_sources(plan, reduce_id: int):
    """Deep-replace every RemoteSourceNode with a pinned copy."""
    from spark_rapids_tpu.plan import nodes as NN
    if isinstance(plan, NN.RemoteSourceNode):
        return plan.pinned(reduce_id)
    plan.children = [_pin_sources(c, reduce_id) for c in plan.children]
    return plan


def _collect_sources(plan, out):
    from spark_rapids_tpu.plan import nodes as NN
    if isinstance(plan, NN.RemoteSourceNode):
        out.append(plan)
    for c in plan.children:
        _collect_sources(c, out)
    return out


def _has_non_source_leaves(plan):
    from spark_rapids_tpu.plan import nodes as NN
    if not plan.children:
        return not isinstance(plan, NN.RemoteSourceNode)
    return any(_has_non_source_leaves(c) for c in plan.children)


class ExecutorLostError(RuntimeError):
    """Partial (lineage-scoped) recovery was exhausted or impossible: the
    driver heals the whole pool and retries the query — the final rung of
    the recovery ladder, not the first responder it used to be."""


class PlacementPolicy:
    """Deterministic, seedable round-robin task placement (replaces the old
    bare itertools.cycle): the seed rotates which executor receives the
    first task, so attempt/blacklist tests can pin which executor hosts
    which map split. `prefer_not` lets a retry avoid the executors that
    already failed the task when an alternative exists. `preferred` is the
    movement-aware override: when the caller already knows which executor
    holds the task's biggest input (MapOutputTracker byte accounting), that
    host wins WITHOUT advancing the round-robin cursor, so the rotation
    schedule of ordinary picks stays deterministic around it."""

    def __init__(self, n_executors: int, seed: int = 0):
        self.n = max(n_executors, 1)
        self._next = seed % self.n

    def pick(self, eligible, prefer_not=(), preferred=None):
        if (preferred is not None and preferred in eligible
                and preferred not in prefer_not):
            return preferred
        order = [(self._next + i) % self.n for i in range(self.n)]
        choices = [e for e in order
                   if e in eligible and e not in prefer_not] \
            or [e for e in order if e in eligible]
        if not choices:
            return None
        c = choices[0]
        self._next = (c + 1) % self.n
        return c


class _ShuffleState:
    __slots__ = ("shuffle_id", "subtree", "partitioner", "mode", "splits",
                 "hosts", "epoch", "recomputes", "split_sizes", "owners")

    def __init__(self, shuffle_id, subtree, partitioner, mode, splits):
        self.shuffle_id = shuffle_id
        self.subtree = subtree          # map-stage child plan (lineage)
        self.partitioner = partitioner
        self.mode = mode                # "pinned" | "plain" task shape
        self.splits = list(splits)
        self.hosts = {}                 # map_split -> executor index
        self.epoch = 0                  # bumped on every invalidation
        self.recomputes = 0             # partial recomputes consumed
        self.split_sizes = {}           # map_split -> [bytes per reduce id]
        # two-level exchange: reduce id -> owning executor (None = shuffle
        # runs single-level). Owned partitions' content rides ICI inside
        # the owner's mesh tasks and the partition's consumer is placed at
        # the owner, so those bytes are read via the local short-circuit
        self.owners = None


class MapOutputTracker:
    """Driver-side map-output registry (Spark MapOutputTrackerMaster
    analog): which executor hosts each map split's blocks, per shuffle,
    epoch-stamped so stale reads are detectable, plus enough lineage
    (subtree + partitioner + task shape) to re-run exactly the lost
    splits."""

    def __init__(self):
        self._shuffles: dict[int, _ShuffleState] = {}

    def register_shuffle(self, shuffle_id, subtree, partitioner, mode,
                         splits) -> _ShuffleState:
        st = _ShuffleState(shuffle_id, subtree, partitioner, mode, splits)
        self._shuffles[shuffle_id] = st
        return st

    def state(self, shuffle_id) -> _ShuffleState | None:
        return self._shuffles.get(shuffle_id)

    def sids(self) -> list:
        return sorted(self._shuffles)

    def epoch(self, shuffle_id) -> int:
        st = self._shuffles.get(shuffle_id)
        return st.epoch if st is not None else 0

    def epochs(self, shuffle_ids) -> dict:
        return {sid: self.epoch(sid) for sid in shuffle_ids}

    def register_map_output(self, shuffle_id, map_split, executor_idx,
                            sizes=None):
        """Record the split's host and (when the reply carried them) its
        per-reduce-partition byte sizes — the statistic movement-aware
        reduce placement reads. Re-registration after a partial recompute
        overwrites both, so the bytes always follow the live copy."""
        st = self._shuffles[shuffle_id]
        st.hosts[map_split] = executor_idx
        if sizes is not None:
            st.split_sizes[map_split] = list(sizes)

    def invalidate_splits(self, shuffle_id, splits) -> None:
        """Drop specific splits' outputs (degraded mesh task, partial
        attempt) and bump the shuffle's epoch so any in-flight reply that
        read the pre-drop layout is discarded and re-run."""
        st = self._shuffles.get(shuffle_id)
        if st is None:
            return
        st.epoch += 1
        for s in splits:
            st.hosts.pop(s, None)
            st.split_sizes.pop(s, None)

    def bytes_by_executor(self, shuffle_ids, reduce_id) -> dict:
        """executor -> map-output bytes it holds for `reduce_id` across
        `shuffle_ids` (Theseus-style movement statistic: the reduce task's
        cheapest host is the one already holding the most of its input)."""
        out: dict = {}
        for sid in shuffle_ids:
            st = self._shuffles.get(sid)
            if st is None:
                continue
            for split, ei in st.hosts.items():
                sizes = st.split_sizes.get(split)
                if sizes and 0 <= reduce_id < len(sizes):
                    out[ei] = out.get(ei, 0) + sizes[reduce_id]
        return out

    def executor_load(self, executor_idx) -> int:
        """Total shuffle bytes parked on one executor across every live
        shuffle — the spill-pressure proxy placement demotion checks."""
        total = 0
        for st in self._shuffles.values():
            for split, ei in st.hosts.items():
                if ei == executor_idx:
                    total += sum(st.split_sizes.get(split, ()))
        return total

    def on_executor_lost(self, executor_idx) -> list:
        """Invalidate every map split hosted on the dead executor; returns
        [(state, [lost splits])] in ascending shuffle-id (= dependency)
        order, with each affected shuffle's epoch bumped."""
        out = []
        for sid in sorted(self._shuffles):
            st = self._shuffles[sid]
            lost = sorted(s for s, h in st.hosts.items() if h == executor_idx)
            if lost:
                st.epoch += 1
                for s in lost:
                    del st.hosts[s]
                    st.split_sizes.pop(s, None)
                out.append((st, lost))
        return out

    def subtrees(self) -> list:
        return [st.subtree for st in self._shuffles.values()]


class _TaskSpec:
    __slots__ = ("idx", "op", "subtree", "pin", "split", "shuffle_id",
                 "partitioner", "read_sids", "attempts", "tried",
                 "speculated", "lanes")

    def __init__(self, idx, op, subtree, pin, split, shuffle_id=None,
                 partitioner=None, lanes=None):
        self.idx = idx
        self.op = op                    # "map" | "result"
        self.subtree = subtree
        self.pin = pin                  # reduce id to pin sources to, or None
        self.split = split              # map split id / subtree partition
        self.shuffle_id = shuffle_id
        self.partitioner = partitioner
        # mesh map task: [(split, pin_or_None)] — one lane per local mesh
        # device; None means the ordinary single-split task shape
        self.lanes = lanes
        self.read_sids = sorted({s.shuffle_id for s in
                                 _collect_sources(subtree, [])})
        self.attempts = 0
        self.tried: set = set()
        self.speculated = False

    def splits_covered(self) -> list:
        return ([s for s, _ in self.lanes] if self.lanes is not None
                else [self.split])


class _Running:
    __slots__ = ("spec", "t0", "epochs", "speculative", "gen")

    def __init__(self, spec, t0, epochs, speculative, gen):
        self.spec = spec
        self.t0 = t0
        self.epochs = epochs            # {sid: epoch} at dispatch time
        self.speculative = speculative
        self.gen = gen                  # executor incarnation at dispatch


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

class MiniCluster:
    """Driver for N executor processes; `collect(df)` runs the DataFrame's
    plan across them (DAGScheduler + cluster-manager stand-in)."""

    def __init__(self, n_executors: int = 2, conf=None, platform: str = "cpu",
                 max_attempts: int = 3):
        from spark_rapids_tpu import config as CFG
        from spark_rapids_tpu.config import RapidsConf
        from spark_rapids_tpu.shuffle.heartbeat import (
            RapidsShuffleHeartbeatManager)
        self.conf = conf or RapidsConf()
        self.n_executors = n_executors
        self.max_attempts = max_attempts
        self._platform = platform
        self._shuffle_ids = itertools.count(1000)
        self._conns = [None] * n_executors
        self._procs = [None] * n_executors
        self._gen = [0] * n_executors       # incarnation per slot
        self._exec_ids = [None] * n_executors
        self.addresses = [None] * n_executors
        self._hb = RapidsShuffleHeartbeatManager(
            timeout_s=self.conf.get(CFG.CLUSTER_HEARTBEAT_TIMEOUT))
        self._tracker = MapOutputTracker()
        self._current_root = None           # plan of the in-flight query
        self._exec_failures = [0] * n_executors
        self._blacklist: set = set()
        self._placement = PlacementPolicy(
            n_executors, self.conf.get(CFG.CLUSTER_PLACEMENT_SEED))
        self._task_max_failures = self.conf.get(CFG.CLUSTER_TASK_MAX_FAILURES)
        self._task_timeout_s = self.conf.get(CFG.CLUSTER_TASK_TIMEOUT)
        self._blacklist_max = self.conf.get(
            CFG.CLUSTER_BLACKLIST_MAX_TASK_FAILURES)
        self._stage_max_recomputes = self.conf.get(
            CFG.CLUSTER_STAGE_MAX_RECOMPUTES)
        self._speculation = self.conf.get(CFG.CLUSTER_SPECULATION_ENABLED)
        self._speculation_mult = self.conf.get(
            CFG.CLUSTER_SPECULATION_MULTIPLIER)
        # unified mesh-cluster plane state (docs/cluster.md): per-slot
        # attached mesh width from the spawn handshake, and whether the
        # slot's mesh is still trusted for mesh task groups
        self._mesh_enabled = self.conf.get(CFG.CLUSTER_MESH_ENABLED)
        self._two_level = self.conf.get(CFG.CLUSTER_MESH_TWO_LEVEL)
        self._mesh = [0] * n_executors
        self._mesh_ok = [False] * n_executors
        self._movement_aware = self.conf.get(
            CFG.CLUSTER_PLACEMENT_MOVEMENT_AWARE)
        self._max_loaded_bytes = self.conf.get(
            CFG.CLUSTER_PLACEMENT_MAX_LOADED_BYTES)
        self._spawn_retries = self.conf.get(CFG.CLUSTER_SPAWN_MAX_RETRIES)
        self.mesh_stats = {"mesh_tasks": 0, "waves": 0, "degraded": 0,
                           "ici_rows": 0}
        self.placement_stats = {"preferred": 0, "demoted": 0}
        for ei in range(n_executors):
            self._spawn_executor(ei)
        self.task_log: list = []        # (stage_op, executor_idx) per task
        self._after_stage_hook = None   # test fault-injection point

    # -- pool management ----------------------------------------------------
    def _spawn_executor(self, ei: int, arm_faults: bool = True):
        """Bring up slot `ei` with ONE bounded retry on a transient
        socket/pipe bring-up failure (cluster.spawn.maxRetries): a flaky
        handshake must not cost the slot — or, on the loss-recovery path,
        the whole query — before a second attempt was even made. Retries
        are visible as executor.spawn.retry events; they never charge the
        executor a blacklist strike (nothing ran yet)."""
        from spark_rapids_tpu.runtime import tracing
        last = None
        for attempt in range(self._spawn_retries + 1):
            try:
                return self._spawn_executor_once(ei, arm_faults)
            except RuntimeError as e:
                last = e
                if attempt < self._spawn_retries:
                    tracing.span_event("executor.spawn.retry", executor=ei,
                                       attempt=attempt + 1,
                                       error=str(e)[:200])
        raise last

    def _spawn_executor_once(self, ei: int, arm_faults: bool = True):
        from spark_rapids_tpu import config as CFG
        ctx = mp.get_context("spawn")
        parent, child = ctx.Pipe()
        settings = dict(self.conf.settings)
        if not arm_faults:
            # replacement executors come up clean: re-parsing a COUNT
            # trigger in the respawn would fire the same fault forever
            settings.pop(CFG.TEST_FAULTS.key, None)
        p = ctx.Process(target=_executor_main,
                        args=(child, ei, self._platform, settings),
                        daemon=True)
        p.start()
        # bounded handshake: a child that dies during bring-up must surface
        # as an error, not hang the driver in recv() forever
        if not parent.poll(120):
            p.kill()
            p.join(timeout=5)
            raise RuntimeError(f"executor {ei} never came up")
        try:
            hello = parent.recv()
        except (EOFError, OSError) as e:
            p.join(timeout=5)
            raise RuntimeError(f"executor {ei} died during bring-up") from e
        assert hello["op"] == "ready"
        # two-timestamp clock exchange riding the registration handshake
        # (the heartbeat register below is the same handshake's driver
        # half): executor_clock + offset ≈ driver_clock, error bounded by
        # half the pipe round-trip — the correction that lets executor
        # event-log records and span files merge onto the driver timeline
        from spark_rapids_tpu.runtime import tracing
        try:
            t0 = time.time()
            parent.send({"op": "clock"})
            clock = parent.recv()
            t1 = time.time()
            offset = tracing.estimate_clock_offset(t0, clock["t"], t1)
            parent.send({"op": "clock_set", "offset": offset})
            assert parent.recv().get("ok")
        except (EOFError, OSError) as e:
            p.kill()
            p.join(timeout=5)
            raise RuntimeError(
                f"executor {ei} died during clock handshake") from e
        self._conns[ei] = parent
        self._procs[ei] = p
        self.addresses[ei] = ("127.0.0.1", hello["port"])
        self._gen[ei] += 1
        old_eid = self._exec_ids[ei]
        if old_eid is not None:
            # a replaced incarnation must not fire a spurious expiry later
            self._hb.deregister(old_eid)
        eid = f"exec-{ei}-g{self._gen[ei]}"
        self._hb.register(eid, "127.0.0.1", hello["port"])
        self._exec_ids[ei] = eid
        self._exec_failures[ei] = 0
        self._blacklist.discard(ei)
        # mesh plane: the handshake reports the ACTUAL local mesh width
        # (0 = none); a respawned slot attaches a fresh, trusted mesh —
        # the dead incarnation's mesh generation died with it
        self._mesh[ei] = hello.get("mesh", 0) or 0
        self._mesh_ok[ei] = self._mesh[ei] >= 2
        if self._mesh[ei]:
            tracing.span_event("mesh.attach", executor=ei,
                               devices=self._mesh[ei],
                               generation=self._gen[ei])

    def _heal(self):
        """Restart the WHOLE pool — the LAST rung of the recovery ladder,
        reached only when lineage-scoped recovery is exhausted
        (cluster.stage.maxRecomputes) or no executor is placeable.
        Survivors may hold in-flight tasks whose replies would
        desynchronize the request/reply pipe protocol on retry; since the
        retry re-runs every stage anyway, clean processes are both simpler
        and correct (Spark's executor-replacement role)."""
        for ei, p in enumerate(self._procs):
            try:
                self._conns[ei].close()
            except OSError:
                pass
            if p is not None:
                if p.is_alive():
                    p.terminate()
                p.join(timeout=5)
                if p.is_alive():
                    p.kill()
                    p.join(timeout=5)
            self._spawn_executor(ei, arm_faults=False)
        self._tracker = MapOutputTracker()

    # -- liveness -----------------------------------------------------------
    def _poll_liveness(self) -> list:
        """Beat the heartbeat manager for every live executor process, then
        poll expire_dead (the driver-side failure detector the reference
        runs in RapidsShuffleHeartbeatManager); returns the slot indices
        the manager expired."""
        for ei, p in enumerate(self._procs):
            if p is not None and p.is_alive():
                try:
                    self._hb.heartbeat(self._exec_ids[ei])
                except KeyError:
                    pass
        expired = self._hb.expire_dead()
        slots = []
        by_eid = {eid: ei for ei, eid in enumerate(self._exec_ids)}
        for peer in expired:
            ei = by_eid.get(peer.executor_id)
            if ei is not None:
                slots.append(ei)
        return slots

    def check_liveness(self) -> list:
        """Public poll: expire dead executors via the heartbeat manager and
        run the same lineage-scoped recovery as a mid-task loss. Returns
        the recovered slot indices."""
        recovered = []
        for ei in self._poll_liveness():
            if self._procs[ei] is not None and not self._procs[ei].is_alive():
                self._handle_executor_loss(
                    ei, {}, collections.deque(), frozenset(),
                    reason="heartbeat.expired")
                recovered.append(ei)
        return recovered

    # -- loss recovery ------------------------------------------------------
    def _handle_executor_loss(self, ei, running, pending, busy,
                              reason="channel", depth=0, done=None,
                              total=None):
        """The lineage-scoped recovery path: respawn the slot, invalidate
        exactly the map splits the dead peer hosted, re-run only those
        under a bumped epoch, and re-publish addresses. In-flight work on
        other executors keeps running; its replies are discarded if the
        epoch moved underneath them. An in-flight MESH task on the dead
        executor — a participant lost inside the collective (mesh_kill) or
        wedged in it past the task deadline (mesh_hang) — is NOT retried as
        a mesh task: its mesh generation is invalidated (mesh.detach) and
        its lanes re-plan onto the per-split TCP path under a bumped epoch
        (the degraded-mode fallback, counted in meshDegradedFallbacks)."""
        from spark_rapids_tpu.runtime import metrics as M
        from spark_rapids_tpu.runtime import tracing
        M.resilience_add(M.EXECUTORS_LOST)
        tracing.span_event("executor.lost", executor=ei,
                           generation=self._gen[ei], reason=reason)
        if self._mesh[ei]:
            tracing.span_event("mesh.detach", executor=ei,
                               generation=self._gen[ei], reason=reason)
        run = running.pop(ei, None)
        if run is not None and (done is None or run.spec.idx not in done):
            if run.spec.lanes is not None:
                self._degrade_mesh_spec(run.spec, ei, pending, total,
                                        reason=f"executor.lost:{reason}",
                                        executor_dead=True)
            else:
                pending.appendleft(run.spec)
        try:
            self._conns[ei].close()
        except OSError:
            pass
        p = self._procs[ei]
        if p is not None:
            if p.is_alive():
                p.kill()
            p.join(timeout=10)
        self._spawn_executor(ei, arm_faults=False)
        # the fresh block store must know every live shuffle id — a peer
        # with no blocks still serves (empty) metadata to reducers
        for sid in self._tracker.sids():
            self._conns[ei].send({"op": "ensure_shuffle", "shuffle_id": sid})
            reply = self._conns[ei].recv()
            assert reply.get("ok"), reply
        self._republish_addresses()
        lost = self._tracker.on_executor_lost(ei)
        for st, splits in lost:
            st.recomputes += 1
            if st.recomputes > self._stage_max_recomputes:
                raise ExecutorLostError(
                    f"shuffle {st.shuffle_id} exceeded "
                    f"cluster.stage.maxRecomputes="
                    f"{self._stage_max_recomputes}; healing the pool")
        for st, splits in lost:
            M.resilience_add(M.STAGE_PARTIAL_RECOMPUTES)
            M.resilience_add(M.MAP_TASKS_RECOMPUTED, len(splits))
            tracing.span_event("stage.recompute.partial",
                               shuffle=st.shuffle_id, epoch=st.epoch,
                               splits=len(splits),
                               total_splits=len(st.splits))
            specs = [self._make_map_spec(st, s, i)
                     for i, s in enumerate(splits)]
            # recompute runs on executors not busy with outer work (the
            # respawned slot is always idle, so progress is guaranteed)
            self._run_tasks(specs, busy=frozenset(busy) | set(running),
                            depth=depth + 1)

    def _republish_addresses(self):
        """Push the (possibly respawned) pool's addresses into every live
        RemoteSourceNode — the driver's plan and every tracked lineage
        subtree share node objects, so one walk re-points future task
        ships and recomputes at the new block servers."""
        roots = list(self._tracker.subtrees())
        if self._current_root is not None:
            roots.append(self._current_root)
        seen = set()
        for root in roots:
            for src in _collect_sources(root, []):
                if id(src) not in seen:
                    seen.add(id(src))
                    src.locations = [tuple(a) for a in self.addresses]

    def _stamp_epochs(self, plan):
        for src in _collect_sources(plan, []):
            src.epoch = self._tracker.epoch(src.shuffle_id)

    # -- task plumbing ------------------------------------------------------
    def _make_map_spec(self, st: _ShuffleState, split: int,
                       idx: int | None = None) -> _TaskSpec:
        return _TaskSpec(idx if idx is not None else split, "map",
                         st.subtree,
                         split if st.mode == "pinned" else None, split,
                         shuffle_id=st.shuffle_id,
                         partitioner=st.partitioner)

    def _build_task(self, spec: _TaskSpec, ei: int | None = None) -> dict:
        from spark_rapids_tpu.runtime import tracing
        if spec.lanes is not None:
            # mesh map task: ship the UNPINNED subtree once; the executor
            # pins a clone per lane (one lane per local mesh device)
            plan = _clone_plan(spec.subtree)
            self._stamp_epochs(plan)
            task = {"plan": plan, "splits": [],
                    "mesh_lanes": [{"split": s, "pin": p}
                                   for s, p in spec.lanes],
                    "shuffle_id": spec.shuffle_id,
                    "partitioner": spec.partitioner,
                    "trace": tracing.current_trace_id()}
            st = self._tracker.state(spec.shuffle_id)
            if ei is not None and st is not None and st.owners is not None:
                # two-level exchange: the reduce partitions THIS executor
                # owns ride ICI inside the task's waves; the rest slice
                # and park for the TCP fetch
                task["reduce_owned"] = [r for r, o in enumerate(st.owners)
                                        if o == ei]
            return task
        if spec.pin is not None:
            plan = _pin_sources(_clone_plan(spec.subtree), spec.pin)
            splits = [0]
        else:
            plan = spec.subtree
            splits = [spec.split]
        self._stamp_epochs(plan)
        task = {"plan": plan, "splits": splits,
                "trace": tracing.current_trace_id()}
        if spec.op == "map":
            task.update({"shuffle_id": spec.shuffle_id,
                         "partitioner": spec.partitioner,
                         "map_split": spec.split})
        return task

    def _drop_map_output(self, ei: int, spec: _TaskSpec, running, pending,
                         busy, depth=0, done=None):
        """Evict one map attempt's blocks from a LIVE executor (speculation
        loser, stale-epoch or failed attempt that may have written partial
        output); a dead executor's blocks died with its store. A mesh
        task's attempt drops every lane's split."""
        try:
            for s in spec.splits_covered():
                self._conns[ei].send({"op": "drop_map_output",
                                      "shuffle_id": spec.shuffle_id,
                                      "map_split": s})
                reply = self._conns[ei].recv()
                assert reply.get("ok"), reply
        except (BrokenPipeError, EOFError, OSError):
            self._handle_executor_loss(ei, running, pending, busy,
                                       depth=depth, done=done)

    def _degrade_mesh_spec(self, spec: _TaskSpec, ei, pending, total,
                           reason: str, executor_dead: bool,
                           running=None, busy=frozenset(), depth=0,
                           done=None):
        """Degraded-mode fallback (the robustness core of the unified
        plane): a mesh task that cannot run — or finish — on an executor's
        local mesh is transparently re-planned as SINGLE-split TCP tasks
        under a bumped map-output epoch, bit-identical to the healthy run.
        No task-attempt strike is charged: degradation is capacity loss,
        not task failure. When the executor survived (mesh shrank, chips
        unavailable, collective error) its partial blocks are evicted
        first and its mesh is distrusted for future groups; a dead
        executor's blocks died with its store and its RESPAWN attaches a
        fresh, trusted mesh."""
        from spark_rapids_tpu.runtime import metrics as M
        from spark_rapids_tpu.runtime import tracing
        splits = spec.splits_covered()
        M.resilience_add(M.MESH_DEGRADED_FALLBACKS)
        self.mesh_stats["degraded"] += 1
        tracing.span_event("mesh.degraded", executor=ei,
                           shuffle=spec.shuffle_id, splits=len(splits),
                           reason=reason)
        if not executor_dead and ei is not None and ei >= 0:
            if self._mesh_ok[ei]:
                self._mesh_ok[ei] = False
                tracing.span_event("mesh.detach", executor=ei,
                                   generation=self._gen[ei],
                                   reason="degraded")
            self._drop_map_output(ei, spec, running if running is not None
                                  else {}, pending, busy, depth=depth,
                                  done=done)
        # bump the epoch so an in-flight reply that read the pre-drop
        # layout is discarded, then re-plan each lane as its own TCP task
        self._tracker.invalidate_splits(spec.shuffle_id, splits)
        st = self._tracker.state(spec.shuffle_id)
        if total is not None:
            total.discard(spec.idx)
        for s in splits:
            nspec = self._make_map_spec(
                st, s, idx=("degraded", spec.shuffle_id, s, st.epoch))
            if total is not None:
                total.add(nspec.idx)
            pending.append(nspec)

    def _charge_failure(self, ei: int, spec: _TaskSpec, reason: str,
                        err: str = ""):
        from spark_rapids_tpu.runtime import metrics as M
        from spark_rapids_tpu.runtime import tracing
        spec.attempts += 1
        spec.tried.add(ei)
        M.resilience_add(M.TASK_ATTEMPTS)
        tracing.span_event("task.attempt", executor=ei, op=spec.op,
                           split=spec.split, shuffle=spec.shuffle_id,
                           attempt=spec.attempts, reason=reason,
                           error=err[-200:] if err else "")
        self._exec_failures[ei] += 1
        if (ei not in self._blacklist
                and self._exec_failures[ei] >= self._blacklist_max):
            self._blacklist.add(ei)
            M.resilience_add(M.EXECUTORS_BLACKLISTED)
            tracing.span_event("executor.blacklisted", executor=ei,
                               failures=self._exec_failures[ei])

    def _preferred_executor(self, spec: _TaskSpec, eligible):
        """Movement-aware placement: the executor already holding the most
        map-output bytes for this reduce partition (Theseus's
        movement-optimized scheduling — the read becomes a local
        block-store short-circuit instead of a TCP fetch). Spill-aware
        demotion: an executor parking more than placement.maxLoadedBytes
        of shuffle data is over its HBM/host budget proxy, and piling its
        reduce work on top would only force disk spills — demote to
        round-robin (placement.demoted)."""
        from spark_rapids_tpu.runtime import tracing
        by = self._tracker.bytes_by_executor(spec.read_sids, spec.pin)
        if not by:
            return None
        best = max(sorted(by), key=lambda e: by[e])
        if by[best] <= 0 or best not in eligible or best in spec.tried:
            return None
        load = self._tracker.executor_load(best)
        if load > self._max_loaded_bytes:
            self.placement_stats["demoted"] += 1
            tracing.span_event("placement.demoted", executor=best,
                               loaded_bytes=load,
                               budget=self._max_loaded_bytes,
                               reduce=spec.pin)
            return None
        self.placement_stats["preferred"] += 1
        return best

    def _owner_executor(self, spec: _TaskSpec, eligible):
        """Two-level placement: the executor OWNING the task's reduce
        partition(s) under the upstream shuffles' ownership assignment —
        the host whose mesh tasks already routed those partitions' content
        over ICI into its local store. Mesh consumer groups vote with
        every lane's pin; ties and unowned shuffles return None (fall back
        to byte-based preference / round-robin)."""
        pins = ([p for _, p in spec.lanes if p is not None]
                if spec.lanes is not None
                else [spec.pin] if spec.pin is not None else [])
        if not pins:
            return None
        votes: dict = {}
        for sid in spec.read_sids:
            st = self._tracker.state(sid)
            if st is None or st.owners is None:
                continue
            for p in pins:
                if 0 <= p < len(st.owners):
                    votes[st.owners[p]] = votes.get(st.owners[p], 0) + 1
        if not votes:
            return None
        best = max(sorted(votes), key=lambda e: votes[e])
        if best not in eligible or best in spec.tried:
            return None
        self.placement_stats["owner"] = \
            self.placement_stats.get("owner", 0) + 1
        return best

    # -- the scheduler loop -------------------------------------------------
    def _run_tasks(self, specs: list, busy=frozenset(), depth: int = 0
                   ) -> dict:
        """Run every spec to completion across the pool; returns
        {spec.idx: reply}. One in-flight task per executor (the Pipe is a
        simple duplex channel); handles attempts, blacklisting, deadlines,
        executor loss (with nested lineage recompute) and speculation."""
        import multiprocessing.connection as mpc

        from spark_rapids_tpu.runtime import metrics as M
        from spark_rapids_tpu.runtime import tracing
        if depth > 8:
            raise ExecutorLostError("recovery recursion exhausted")
        pending = collections.deque(specs)
        running: dict[int, _Running] = {}
        done: dict = {}
        durations: list = []
        # MUTABLE: a degraded mesh task swaps its group idx for per-split
        # idxs, so completion tracks whatever the plan degraded into
        total = {s.idx for s in specs}

        def dispatch(spec, speculative=False):
            import cloudpickle
            eligible = {ei for ei in range(self.n_executors)
                        if ei not in running and ei not in busy
                        and ei not in self._blacklist
                        and self._procs[ei] is not None
                        and self._procs[ei].is_alive()}
            preferred = None
            if spec.lanes is not None:
                # a mesh group may only land on a trusted mesh at least as
                # wide as the group; when NO placeable executor still has
                # one (all degraded/blacklisted), the group itself degrades
                capable = {ei for ei in range(self.n_executors)
                           if self._mesh_ok[ei]
                           and self._mesh[ei] >= len(spec.lanes)
                           and ei not in self._blacklist
                           and self._procs[ei] is not None
                           and self._procs[ei].is_alive()}
                if not capable:
                    return "degrade"
                eligible &= capable
                # two-level: a consumer mesh group prefers the executor
                # owning its lanes' reduce partitions — the owned bytes
                # are already in that executor's local store
                if self._movement_aware and spec.read_sids:
                    preferred = self._owner_executor(spec, eligible)
            elif (self._movement_aware and spec.pin is not None
                    and spec.read_sids):
                preferred = (self._owner_executor(spec, eligible)
                             or self._preferred_executor(spec, eligible))
            ei = self._placement.pick(eligible, prefer_not=spec.tried,
                                      preferred=preferred)
            if ei is None:
                return None
            task = self._build_task(spec, ei)
            epochs = self._tracker.epochs(spec.read_sids)
            try:
                self._conns[ei].send(
                    {"op": spec.op, "task": cloudpickle.dumps(task)})
            except (BrokenPipeError, OSError):
                self._handle_executor_loss(ei, running, pending, busy,
                                           depth=depth, done=done,
                                           total=total)
                return False
            running[ei] = _Running(spec, time.monotonic(), epochs,
                                   speculative, self._gen[ei])
            self.task_log.append(
                (spec.op if spec.lanes is None else "map.mesh", ei))
            if len(self.task_log) > 4096:   # observability ring, not a ledger
                del self.task_log[:-2048]
            return ei

        def handle_reply(ei, run, reply):
            spec = run.spec
            if not reply.get("ok"):
                err = reply.get("error") or ""
                if reply.get("mesh_degraded") and spec.lanes is not None:
                    # the executor is alive but its mesh is not (shrank,
                    # chips unavailable, collective failed): transparent
                    # re-plan onto the TCP path, no attempt strike
                    reason = (err.strip().splitlines() or ["mesh"])[-1]
                    self._degrade_mesh_spec(
                        spec, ei, pending, total, reason=reason[-160:],
                        executor_dead=False, running=running, busy=busy,
                        depth=depth, done=done)
                    return
                if "TransportError" in err:
                    dead = [k for k, p in enumerate(self._procs)
                            if p is not None and not p.is_alive()]
                    if dead:
                        # a fetch against a dead peer is not the task's
                        # fault (Spark: FetchFailed doesn't count against
                        # task attempts) — recover the peers, retry free
                        for k in dead:
                            self._handle_executor_loss(k, running, pending,
                                                       busy, depth=depth,
                                                       done=done)
                        if spec.op == "map":
                            self._drop_map_output(ei, spec, running, pending,
                                                  busy, depth=depth,
                                                  done=done)
                        if spec.idx not in done:
                            pending.appendleft(spec)
                        return
                # a real task failure: partial map output on a LIVE
                # executor must be evicted before the retry re-writes it
                if spec.op == "map":
                    self._drop_map_output(ei, spec, running, pending, busy,
                                          depth=depth, done=done)
                self._charge_failure(ei, spec, "failure", err)
                if spec.attempts >= self._task_max_failures:
                    raise RuntimeError(
                        f"task {spec.op}/{spec.split} failed "
                        f"{spec.attempts} times "
                        f"(cluster.task.maxFailures="
                        f"{self._task_max_failures}); last error:\n{err}")
                if spec.idx not in done:
                    pending.append(spec)
                return
            if spec.idx in done:
                # a duplicate (speculation) or re-run lost the race: the
                # winner's blocks are the only copy allowed to survive
                M.resilience_add(M.SPECULATION_LOST)
                tracing.span_event("speculation.lost", executor=ei,
                                   op=spec.op, split=spec.split,
                                   shuffle=spec.shuffle_id)
                if spec.op == "map":
                    self._drop_map_output(ei, spec, running, pending, busy,
                                          depth=depth, done=done)
                return
            if run.epochs != self._tracker.epochs(spec.read_sids):
                # computed against metadata that moved underneath it (a
                # peer died and its splits were rebuilt mid-flight): the
                # reply may have read a half-rebuilt partition — discard
                M.resilience_add(M.TASK_ATTEMPTS)
                tracing.span_event("task.attempt", executor=ei, op=spec.op,
                                   split=spec.split, shuffle=spec.shuffle_id,
                                   attempt=spec.attempts + 1,
                                   reason="stale_epoch")
                if spec.op == "map":
                    self._drop_map_output(ei, spec, running, pending, busy,
                                          depth=depth, done=done)
                pending.appendleft(spec)
                return
            done[spec.idx] = reply
            durations.append(time.monotonic() - run.t0)
            if spec.op == "map":
                sizes = reply.get("split_sizes") or {}
                for s in spec.splits_covered():
                    self._tracker.register_map_output(spec.shuffle_id, s,
                                                      ei, sizes.get(s))
                if spec.lanes is not None:
                    mesh = reply.get("mesh") or {}
                    self.mesh_stats["mesh_tasks"] += 1
                    self.mesh_stats["waves"] += mesh.get("waves", 0)
                    self.mesh_stats["ici_rows"] += mesh.get("ici_rows", 0)
            if run.speculative:
                M.resilience_add(M.SPECULATION_WON)
                tracing.span_event("speculation.won", executor=ei,
                                   op=spec.op, split=spec.split,
                                   shuffle=spec.shuffle_id)

        while not total.issubset(done.keys()) or running:
            # heartbeat-manager failure detection (expire_dead), polled by
            # the driver every scheduling round
            for ei in self._poll_liveness():
                if (self._procs[ei] is not None
                        and not self._procs[ei].is_alive()):
                    self._handle_executor_loss(ei, running, pending, busy,
                                               reason="heartbeat.expired",
                                               depth=depth, done=done,
                                               total=total)
            # a nested recovery may have respawned a slot under an outer
            # in-flight task: its reply can never arrive on the new pipe
            for ei, run in list(running.items()):
                if run.gen != self._gen[ei]:
                    del running[ei]
                    if run.spec.idx not in done:
                        pending.appendleft(run.spec)
            # fill idle executors (a False dispatch respawned the slot it
            # targeted, so retrying the same spec makes progress; a
            # "degrade" dispatch found NO placeable mesh executor left for
            # the group — it re-plans per-split and the loop continues)
            while pending:
                r = dispatch(pending[0])
                if r is None:
                    break               # no idle eligible executor
                if r is False:
                    continue
                if r == "degrade":
                    spec = pending.popleft()
                    self._degrade_mesh_spec(spec, -1, pending, total,
                                            reason="no_mesh_executor",
                                            executor_dead=True)
                    continue
                pending.popleft()
            if not running:
                if not pending and total.issubset(done.keys()):
                    break
                if pending:
                    raise ExecutorLostError(
                        f"no placeable executor for {len(pending)} pending "
                        f"task(s) (blacklisted={sorted(self._blacklist)})")
            conns = {self._conns[ei]: ei for ei in running}
            ready = mpc.wait(list(conns), timeout=0.05)
            now = time.monotonic()
            if not ready:
                # deadline scan: a task past cluster.task.timeoutSeconds is
                # on a wedged executor — the pipe protocol cannot cancel a
                # task, so the executor is killed and replaced
                if self._task_timeout_s > 0:
                    for ei, run in list(running.items()):
                        if now - run.t0 > self._task_timeout_s:
                            self._charge_failure(ei, run.spec, "timeout")
                            if run.spec.attempts >= self._task_max_failures:
                                raise RuntimeError(
                                    f"task {run.spec.op}/{run.spec.split} "
                                    f"timed out {run.spec.attempts} times")
                            self._handle_executor_loss(ei, running, pending,
                                                       busy,
                                                       reason="task.timeout",
                                                       depth=depth,
                                                       done=done,
                                                       total=total)
                # speculation: duplicate stragglers on idle executors
                if (self._speculation and depth == 0 and not pending
                        and running and durations):
                    med = statistics.median(durations)
                    for ei, run in list(running.items()):
                        if (run.speculative or run.spec.speculated
                                or run.spec.idx in done
                                or run.spec.lanes is not None):
                            # mesh groups are never speculated: a duplicate
                            # group racing a straggler would double-write N
                            # lanes' blocks for one slow chip
                            continue
                        if now - run.t0 <= self._speculation_mult * med:
                            continue
                        run.spec.speculated = True
                        dispatch(run.spec, speculative=True)
                continue
            for conn in ready:
                ei = conns[conn]
                if ei not in running:
                    continue            # pool changed while iterating
                try:
                    reply = conn.recv()
                except (EOFError, OSError):
                    self._handle_executor_loss(ei, running, pending, busy,
                                               depth=depth, done=done,
                                               total=total)
                    continue
                run = running.pop(ei)
                handle_reply(ei, run, reply)
        return done

    # -- scheduling ---------------------------------------------------------
    def collect(self, df) -> pa.Table:
        last = None
        for attempt in range(self.max_attempts):
            try:
                return self._collect_once(df)
            except ExecutorLostError as e:
                # the FINAL fallback: lineage-scoped recovery was exhausted,
                # heal the pool and re-run all stages with fresh shuffle ids
                last = e
                self._heal()
        raise last

    def _collect_once(self, df) -> pa.Table:
        import uuid

        from spark_rapids_tpu.plan.distribute import (ensure_distribution,
                                                      stage_order)
        from spark_rapids_tpu.runtime import tracing
        plan = _clone_plan(df._plan)
        plan = ensure_distribution(plan, self.n_executors)
        self._tracker = MapOutputTracker()
        self._current_root = plan
        # one trace id for the whole distributed query: inherited from an
        # ambient session query when there is one, else minted here; every
        # task ships it (_build_task) so executor spans and their shuffle
        # fetches land on the same merged timeline
        trace_id = tracing.current_trace_id() or \
            f"cluster-{os.getpid():x}-{uuid.uuid4().hex[:8]}"
        try:
            with tracing.trace_context(trace_id), \
                    tracing.span("cluster.query",
                                 executors=self.n_executors):
                for exchange, parent, idx in stage_order(plan):
                    source = self._run_map_stage(exchange)
                    parent.children[idx] = source
                    if self._after_stage_hook is not None:
                        self._after_stage_hook(self)
                out = self._run_result_stage(plan)
        finally:
            self._current_root = None
        self._cleanup_shuffles(self._tracker.sids())
        # the finished query's lineage is dead weight: a loss between
        # queries should respawn the slot, not recompute dropped shuffles
        self._tracker = MapOutputTracker()
        return out

    def _broadcast_ensure_shuffle(self, sid: int):
        """Every executor must know the shuffle id — a peer with no map
        task for it still serves (empty) metadata requests from reducers.
        An executor lost mid-broadcast is recovered in place (the respawn
        path re-ensures every tracked shuffle, including this one)."""
        for ei in range(self.n_executors):
            for _ in range(2):
                try:
                    self._conns[ei].send({"op": "ensure_shuffle",
                                          "shuffle_id": sid})
                    reply = self._conns[ei].recv()
                    assert reply.get("ok"), reply
                    break
                except (BrokenPipeError, EOFError, OSError):
                    self._handle_executor_loss(
                        ei, {}, collections.deque(), frozenset())
            else:
                raise ExecutorLostError(
                    f"executor {ei} unreachable for ensure_shuffle")

    def _run_map_stage(self, exchange):
        from spark_rapids_tpu.plan import nodes as NN
        from spark_rapids_tpu.runtime import eventlog as EL
        from spark_rapids_tpu.runtime import metrics as M
        from spark_rapids_tpu.shuffle import partitioning as SP
        child = exchange.child
        if exchange.partitioning == "hash":
            part = SP.HashPartitioner(exchange.keys, exchange.num_out)
        elif exchange.partitioning == "single":
            part = SP.SinglePartitioner()
        elif exchange.partitioning == "roundrobin":
            part = SP.RoundRobinPartitioner(exchange.num_out)
        else:
            raise NotImplementedError(
                "range partitioning needs driver-side sampling (use "
                "sort with a single exchange in MiniCluster)")
        sid = next(self._shuffle_ids)
        mode, splits = self._stage_shape(child)
        st = self._tracker.register_shuffle(sid, child, part, mode, splits)
        # two-level exchange: assign every reduce partition an OWNING
        # executor up front (round-robin over placeable executors, so the
        # assignment is deterministic and balanced). Map tasks route owned
        # partitions' content over ICI; consumer placement below routes the
        # partition's reader to the owner, turning those bytes into local
        # short-circuit reads instead of loopback/TCP fetches
        if (self._two_level and self._mesh_group_width() >= 2
                and len(splits) >= 2
                and isinstance(part, SP.HashPartitioner)):
            placeable = [ei for ei in range(self.n_executors)
                         if ei not in self._blacklist
                         and self._procs[ei] is not None
                         and self._procs[ei].is_alive()]
            if placeable:
                st.owners = [placeable[r % len(placeable)]
                             for r in range(part.num_partitions)]
        self._broadcast_ensure_shuffle(sid)
        self._run_tasks(self._make_stage_specs(st))
        # stats plane: per-reduce-partition byte totals from the tracker's
        # split sizes, recorded into the ambient query's collector so the
        # shuffle-skew read-outs (plan.stats, profiler) cover mesh-plane map
        # stages too — not only the local exchange path
        if st.split_sizes:
            totals = [0] * part.num_partitions
            for split_sizes in st.split_sizes.values():
                for rid, b in enumerate(split_sizes[:part.num_partitions]):
                    totals[rid] += int(b)
            collector = M.current_collector()
            if collector is not None:
                collector.record_shuffle_sizes(None, sid, totals)
            if EL.enabled():
                # driver-side skew record: executors ran the map tasks, so
                # without this the DRIVER's log has no partition sizes and
                # the profiler's skew table goes blind on cluster runs
                EL.emit("stage.map.end", shuffle=sid,
                        partition_sizes=totals)
        return NN.RemoteSourceNode(sid, child.output, part.num_partitions,
                                   [tuple(a) for a in self.addresses],
                                   epoch=self._tracker.epoch(sid))

    def _mesh_group_width(self) -> int:
        """Lane width for mesh map tasks: the NARROWEST trusted mesh among
        placeable executors (groups must fit wherever they land); 0 when
        the mesh plane is off or no trusted mesh remains."""
        if not self._mesh_enabled:
            return 0
        widths = [self._mesh[ei] for ei in range(self.n_executors)
                  if self._mesh_ok[ei] and ei not in self._blacklist]
        return min(widths) if widths else 0

    def _make_stage_specs(self, st: _ShuffleState) -> list:
        """Task specs for one map stage. On the unified plane, a
        hash-partitioned stage's splits are grouped into mesh tasks of up
        to the local mesh width — one task drives M lanes on one
        executor's chips, with inter-executor movement still riding the
        TCP shuffle. Everything else (single/round-robin partitioners,
        mesh plane off or fully degraded) keeps the per-split shape."""
        from spark_rapids_tpu.shuffle import partitioning as SP
        width = self._mesh_group_width()
        if (width < 2 or len(st.splits) < 2
                or not isinstance(st.partitioner, SP.HashPartitioner)):
            return [self._make_map_spec(st, s, i)
                    for i, s in enumerate(st.splits)]
        splits = st.splits
        if st.mode == "pinned":
            # two-level: order a consumer stage's reduce-id splits by the
            # upstream ownership assignment, so each mesh group's lanes
            # share ONE owner and the whole group can be placed there
            owners = None
            for src in _collect_sources(st.subtree, []):
                up = self._tracker.state(src.shuffle_id)
                if up is not None and up.owners is not None:
                    owners = up.owners
                    break
            if owners is not None:
                splits = sorted(splits,
                                key=lambda s: (owners[s]
                                               if 0 <= s < len(owners)
                                               else -1, s))
        specs = []
        for gi in range(0, len(splits), width):
            group = splits[gi:gi + width]
            if len(group) == 1:
                specs.append(self._make_map_spec(st, group[0],
                                                 idx=("m", gi)))
            else:
                lanes = [(s, s if st.mode == "pinned" else None)
                         for s in group]
                specs.append(_TaskSpec(("m", gi), "map", st.subtree, None,
                                       group[0],
                                       shuffle_id=st.shuffle_id,
                                       partitioner=st.partitioner,
                                       lanes=lanes))
        return specs

    def _stage_shape(self, subtree):
        """Task shape covering every partition of `subtree`.
        Co-partitioned shuffle inputs → one pinned task per reduce id;
        everything else → one task per partition of the subtree (a UNION of
        a scan leaf with a shuffle source spreads its leaf splits and reduce
        partitions across executors instead of serializing in one task)."""
        sources = _collect_sources(subtree, [])
        if sources and not _has_non_source_leaves(subtree) and \
                len({s.n_parts for s in sources}) == 1:
            return "pinned", list(range(sources[0].n_parts))
        return "plain", list(range(subtree.num_partitions))

    def _run_result_stage(self, plan) -> pa.Table:
        from spark_rapids_tpu import types as T
        mode, splits = self._stage_shape(plan)
        specs = [_TaskSpec(i, "result", plan,
                           s if mode == "pinned" else None, s)
                 for i, s in enumerate(splits)]
        replies = self._run_tasks(specs)
        tables = []
        for i in range(len(specs)):
            t = pa.ipc.open_stream(replies[i]["ipc"]).read_all()
            if t.num_rows:
                tables.append(t)
        if not tables:
            # derive the empty-result schema from the plan's DECLARED
            # output instead of trusting the first (possibly schema-less)
            # empty reply: an all-empty multi-executor result must not
            # concat mismatched tables
            return pa.Table.from_arrays(
                [pa.array([], T.to_arrow_type(f.data_type))
                 for f in plan.output],
                names=[f.name for f in plan.output])
        return pa.concat_tables(tables)

    def _cleanup_shuffles(self, sids):
        """Best-effort: drop a finished query's shuffle blocks from every
        executor store (they are never read again; leaving them would grow
        executor memory query over query)."""
        for ei in range(self.n_executors):
            try:
                for sid in sids:
                    self._conns[ei].send({"op": "drop_shuffle",
                                          "shuffle_id": sid})
                    self._conns[ei].recv()
            except (BrokenPipeError, EOFError, OSError):
                pass

    def shutdown(self):
        for c in self._conns:
            if c is None:
                continue
            try:
                c.send({"op": "stop"})
                if c.poll(5):
                    c.recv()
            except (BrokenPipeError, EOFError, OSError):
                pass
        for p in self._procs:
            if p is None:
                continue
            p.join(timeout=10)
            if p.is_alive():
                p.terminate()
                p.join(timeout=5)
            if p.is_alive():
                # terminate() can be ignored by a wedged child; escalate so
                # chaos tests never leak zombie processes
                p.kill()
                p.join(timeout=5)
        for c in self._conns:
            if c is None:
                continue
            try:
                c.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False
