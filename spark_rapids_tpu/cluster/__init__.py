from spark_rapids_tpu.cluster.minicluster import MiniCluster  # noqa: F401
