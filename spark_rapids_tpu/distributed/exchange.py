"""MeshExchangeExec — the shuffle exchange as ONE jitted SPMD program over ICI.

Reference mapping: in the reference the exchange IS the distributed engine —
GpuShuffleExchangeExec.scala:80-167 partitions batches on device and the UCX
transport (shuffle-plugin, UCXShuffleTransport.scala) moves blocks peer-to-peer;
joins (GpuShuffledHashJoinBase.scala:97) and sorts ride co-partitioned exchanges.

On a TPU slice the idiomatic data plane is not peer-to-peer RPC but an XLA
`all_to_all` collective over the mesh ("data" axis, ICI links): every device
computes Spark-exact partition ids for its rows, compacts rows per destination,
and one collective moves every row-group in a single step — no host hops. This
exec keeps ShuffleExchangeExec's external contract (child partitions in, one
output partition per device out) so HashJoinExec / HashAggregateExec / SortExec
compose with it unchanged: the planner routes exchanges here when
`spark.rapids.tpu.mesh.enabled` is set.

Supported partitionings: hash (Spark murmur3, bit-exact — strings hash their
UTF-8 bytes via the mesh-global dictionary so both join sides agree), range
(host-sampled bounds compared in mesh-global code space; global dictionaries
are sorted, so code order == lexicographic order), and round-robin
(axis_index-offset deal)."""

from __future__ import annotations

import threading

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_tpu import config as C
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.vector import TpuColumnVector, bucket_capacity
from spark_rapids_tpu.distributed.mesh import encode_shards, put_stacked_shards
from spark_rapids_tpu.exec.base import TpuExec, TaskContext
from spark_rapids_tpu.expr.core import Col, EvalContext
from spark_rapids_tpu.ops import hashing as H
from spark_rapids_tpu.ops.filtering import compact_cols
from spark_rapids_tpu.ops.hashing import pack_utf8_words
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.shuffle.partitioning import (
    HashPartitioner, Partitioner, RangePartitioner, RoundRobinPartitioner,
    murmur3_row_hash, range_part_ids)


def mesh_devices(conf) -> list:
    """Devices forming the execution mesh per conf (0 = all visible)."""
    want = conf.get(C.MESH_DEVICES)
    devs = jax.devices()
    return list(devs if want <= 0 else devs[:want])


def _string_dict_words(col: Col):
    """(words, lens) device packing of a Col's dictionary (trace-time constant:
    the dictionary is static metadata, only the codes are traced)."""
    strs = col.dictionary.to_pylist() if col.dictionary is not None else []
    words, lens = pack_utf8_words(strs)
    if words.shape[0] == 0:
        words = np.zeros((1, 1), dtype=np.int32)
        lens = np.zeros(1, dtype=np.int32)
    return jnp.asarray(words), jnp.asarray(lens)


def row_exchange(cols, n_rows, pids, n_dev: int, cap: int):
    """The generic ICI row exchange, called inside shard_map: compact this
    shard's rows per destination device, all_to_all the stacked groups over the
    "data" axis, and re-pack received rows to the front. Returns
    (merged_cols with (n_dev*cap,) arrays, m_rows device scalar)."""
    live = jnp.arange(cap, dtype=jnp.int32) < n_rows
    sends_v, sends_m, sends_n = [], [], []
    for p in range(n_dev):
        mask = live & (pids == p)
        pc, pn = compact_cols(cols, mask)
        sends_v.append([c.values for c in pc])
        sends_m.append([c.validity for c in pc])
        sends_n.append(pn)
    ncols = len(cols)
    stacked_v = [jnp.stack([sends_v[p][c] for p in range(n_dev)])
                 for c in range(ncols)]
    stacked_m = [jnp.stack([sends_m[p][c] for p in range(n_dev)])
                 for c in range(ncols)]
    sn = jnp.stack(sends_n)
    recv_v = [jax.lax.all_to_all(a, "data", 0, 0) for a in stacked_v]
    recv_m = [jax.lax.all_to_all(a, "data", 0, 0) for a in stacked_m]
    rn = jax.lax.all_to_all(sn, "data", 0, 0)

    mcap = n_dev * cap
    slot = jnp.arange(mcap, dtype=jnp.int32) % cap
    rlive = slot < jnp.repeat(rn, cap)
    rcols = []
    for c in range(ncols):
        v = recv_v[c].reshape(mcap)
        m = recv_m[c].reshape(mcap)
        proto = cols[c]
        default = jnp.asarray(proto.dtype.default_value(), dtype=v.dtype)
        rcols.append(Col(jnp.where(m & rlive, v, default), m & rlive,
                         proto.dtype, proto.dictionary))
    # pack present rows (null-valued rows included — presence is rlive, not
    # value validity) to the front
    merged, m_rows = compact_cols(rcols, rlive)
    return merged, m_rows


class MeshExchangeExec(TpuExec):
    """Mesh-backed drop-in for ShuffleExchangeExec: num_partitions == number of
    mesh devices; reduce partition d is whatever the all_to_all delivered to
    device d."""

    def __init__(self, partitioner: Partitioner, child: TpuExec, conf=None,
                 devices=None):
        super().__init__(child, conf=conf)
        devs = devices if devices is not None else mesh_devices(self.conf)
        self.n = len(devs)
        if partitioner.num_partitions != self.n:
            raise ValueError(
                f"mesh exchange needs num_partitions == n_devices "
                f"({partitioner.num_partitions} != {self.n})")
        self.mesh = Mesh(np.array(devs), ("data",))
        self.partitioner = partitioner.bind(child.output)
        self._done = threading.Event()
        self._lock = threading.Lock()
        self._shard_out: list | None = None
        self._error = None
        self._partition_time = self.metrics.metric(M.PARTITION_TIME, M.MODERATE)

    @property
    def output(self):
        return self.child.output

    @property
    def num_partitions(self):
        return self.n

    # -- partition-id programs (run inside shard_map, trace-time specialized) --
    def _pids_fn(self, cap: int):
        part = self.partitioner
        if isinstance(part, HashPartitioner):
            key_exprs = part.key_exprs
            n = self.n

            def hash_pids(cols, n_rows):
                ctx = EvalContext(cols, n_rows, cap)
                keys = [e.eval(ctx) for e in key_exprs]
                dict_words = {i: _string_dict_words(k)
                              for i, k in enumerate(keys) if k.is_string}
                h = murmur3_row_hash(keys, cap, dict_words=dict_words)
                return H.pmod(h, n)
            return hash_pids
        if isinstance(part, RangePartitioner):
            sort_exprs, orders, bounds = part.sort_exprs, part.orders, part._bounds

            def range_pids(cols, n_rows):
                if bounds is None:
                    return jnp.zeros((cap,), jnp.int32)
                ctx = EvalContext(cols, n_rows, cap)
                keys = [e.eval(ctx) for e in sort_exprs]
                return range_part_ids(keys, bounds, orders, cap)
            return range_pids
        if isinstance(part, RoundRobinPartitioner):
            n = self.n

            def rr_pids(cols, n_rows):
                start = jax.lax.axis_index("data").astype(jnp.int32)
                return (jnp.arange(cap, dtype=jnp.int32) + start) % n
            return rr_pids
        raise ValueError(
            f"mesh exchange does not support {type(part).__name__}")

    # -- the SPMD exchange program --------------------------------------------
    def _build_program(self, schema, cap, dicts):
        n_dev = self.n
        n_cols = len(schema.fields)
        pids_fn = self._pids_fn(cap)

        def shard_step(*flat):
            vals = flat[:n_cols]
            masks = flat[n_cols:2 * n_cols]
            n_rows = flat[2 * n_cols][0]
            # re-attach the mesh-global dictionaries (static metadata): string
            # keys must hash/compare their actual UTF-8 bytes, not bare codes
            cols = [Col(v[0], m[0], f.data_type, dicts.get(ci))
                    for ci, (v, m, f) in enumerate(
                        zip(vals, masks, schema.fields))]
            pids = pids_fn(cols, n_rows)
            merged, m_rows = row_exchange(cols, n_rows, pids, n_dev, cap)
            return (tuple(c.values[None] for c in merged)
                    + tuple(c.validity[None] for c in merged)
                    + (m_rows[None],))

        spec = P("data", None)
        shard_map = getattr(jax, "shard_map", None)
        if shard_map is None:  # older jax
            from jax.experimental.shard_map import shard_map
        return jax.jit(shard_map(
            shard_step, mesh=self.mesh,
            in_specs=tuple([spec] * (2 * n_cols) + [P("data")]),
            out_specs=tuple([spec] * (2 * n_cols) + [P("data")])))

    # -- execution -------------------------------------------------------------
    def _collect_shard_tables(self):
        """Drain child partitions on host (thread-pool map side, same as
        ShuffleExchangeExec), dealing them round-robin onto the mesh devices."""
        import pyarrow as pa
        from concurrent.futures import ThreadPoolExecutor
        per_dev: list[list] = [[] for _ in range(self.n)]
        lock = threading.Lock()

        def map_task(split):
            with TaskContext():
                got = [b.to_arrow() for b in self.child.execute_partition(split)
                       if b.num_rows]
            with lock:
                per_dev[split % self.n].extend(got)

        nparts = self.child.num_partitions
        nthreads = max(1, min(self.conf.get(C.NUM_LOCAL_TASKS), nparts))
        if nparts == 1:
            map_task(0)
        else:
            with ThreadPoolExecutor(max_workers=nthreads) as pool:
                list(pool.map(map_task, range(nparts)))
        empty = self._empty_table()
        return [pa.concat_tables(ts) if ts else empty for ts in per_dev]

    def _empty_table(self):
        import pyarrow as pa
        return pa.table({f.name: pa.array([], T.to_arrow_type(f.data_type))
                         for f in self.output})

    def _run_exchange(self):
        schema = self.output
        tables = self._collect_shard_tables()
        shards, cap, global_dicts = encode_shards(tables, schema, self.n)
        if isinstance(self.partitioner, RangePartitioner):
            # bounds from a host-side sample of the ENCODED shards so string
            # bounds live in the mesh-global (sorted) dictionary space
            sample = [ColumnarBatch([c.to_vector() for c in cols], nr, schema)
                      for cols, nr in shards if nr > 0]
            if sample:
                self.partitioner.set_bounds_from_sample(sample)

        with self._partition_time.timed():
            step = self._build_program(schema, cap, global_dicts)
            vals, masks, nrows = put_stacked_shards(self.mesh, shards)
            out = step(*vals, *masks, nrows)

        n_out = len(schema.fields)
        out_v, out_m, m_rows = out[:n_out], out[n_out:2 * n_out], out[-1]
        counts = np.asarray(m_rows)  # ONE host sync at the stage boundary
        dicts = global_dicts
        batches = []
        for d in range(self.n):
            n = int(counts[d])
            pcap = min(bucket_capacity(max(n, 1)), self.n * cap)
            cvs = []
            for ci, f in enumerate(schema.fields):
                v = out_v[ci][d][:pcap]
                m = out_m[ci][d][:pcap] & (jnp.arange(pcap) < n)
                cvs.append(TpuColumnVector(f.data_type, v, m, dicts.get(ci)))
            batches.append(ColumnarBatch(cvs, n, schema))
        self._shard_out = batches

    def _ensure_exchange(self):
        if not self._done.is_set():
            with self._lock:
                if not self._done.is_set():
                    try:
                        self._run_exchange()
                    except BaseException as e:
                        self._error = e
                    finally:
                        self._done.set()
        if self._error is not None:
            raise RuntimeError("mesh exchange failed") from self._error

    def execute_partition(self, split):
        # release this task's permit before blocking on the collective map
        # stage (same deadlock-avoidance as ShuffleExchangeExec)
        from spark_rapids_tpu.exec.base import current_task_id
        from spark_rapids_tpu.runtime.semaphore import TpuSemaphore
        TpuSemaphore.get().release_if_necessary(current_task_id())
        self._ensure_exchange()

        def it():
            b = self._shard_out[split]
            if b.num_rows:
                yield b
        return self.wrap_output(it())

    def args_string(self):
        return (f"{type(self.partitioner).__name__}({self.n}) "
                f"mesh={self.n}dev")
