"""Distributed execution over a device mesh (SURVEY.md §5 comm backend).

The reference's distributed story is Spark tasks + the UCX shuffle; ours is
two-tier: the TCP transport (shuffle/transport.py) for cross-host DCN, and THIS
package for intra-slice execution — whole query stages jitted over a
jax.sharding.Mesh with XLA collectives (all_to_all) riding ICI."""

from spark_rapids_tpu.distributed.mesh import (  # noqa: F401
    LocalMesh, MeshDegradedError, MeshExecutor, encode_shards,
    put_stacked_shards)
from spark_rapids_tpu.distributed.exchange import (  # noqa: F401
    MeshExchangeExec, mesh_devices, row_exchange)
