"""MeshExecutor — SPMD aggregate execution over a TPU mesh in ONE jit.

Reference analogy: a Spark stage = map tasks → UCX shuffle → reduce tasks
(RapidsShuffleInternalManagerBase + GpuShuffleExchangeExec). On a TPU slice the
idiomatic equivalent is a single compiled SPMD program: every chip holds one
data shard; the "shuffle" is an XLA all_to_all over ICI inside the same program
(no host hops, no per-block RPC). This module generalizes
__graft_entry__.dryrun_multichip into a product executor:

    shard-local: filter → project keys/values → sort-based partial aggregate
    exchange:    hash-partition partial rows → lax.all_to_all over axis "data"
    shard-local: merge-aggregate received partials → evaluate finals

Strings participate via a mesh-global dictionary built on host at ingest (codes
are ints on device). The exchange hash is mesh-internal (chained murmur3 over
key carriers) — it only balances partials, it is NOT the Spark-compatible
partitioning (that lives in shuffle/partitioning.py for the Spark shuffle path).

Scaling note: per-shard capacity is static, so compile once and stream any
number of row-chunks through; DCN-spanning jobs compose this with the TCP
transport between slices (SURVEY.md §5 distributed backend mapping)."""

from __future__ import annotations

import threading

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.vector import bucket_capacity
from spark_rapids_tpu.expr.core import Alias, Col, EvalContext, bind_references
from spark_rapids_tpu.expr.aggregates import AggregateFunction
from spark_rapids_tpu.ops import grouping as G
from spark_rapids_tpu.ops import hashing as H
from spark_rapids_tpu.ops.filtering import compact_cols, gather_cols, selection_mask


def _unalias(e):
    return e.child if isinstance(e, Alias) else e


def _mesh_hash(cols, capacity: int):
    """Deterministic per-row hash for the internal exchange (chained murmur3
    over value carriers; string codes hash as ints — mesh-internal only)."""
    h = jnp.full((capacity,), jnp.int32(42))
    for c in cols:
        if c.values.dtype == jnp.int64:
            nh = H.hash_long(c.values, h)
        elif c.values.dtype == jnp.float64:
            nh = H.hash_double(c.values, h)
        else:
            nh = H.hash_int(c.values.astype(jnp.int32), h)
        h = jnp.where(c.validity, nh, h)
    return h


def encode_shards(tables, schema: T.StructType, n: int):
    """Host-side mesh ingest shared by MeshExecutor and MeshExchangeExec: pad
    each shard to one common capacity; string columns are re-coded against a
    mesh-GLOBAL sorted dictionary (codes then compare/exchange as ints on
    device, and code order == lexicographic order). Returns
    (shards [(cols, n_rows)] * n, cap, global_dicts {ordinal: pa.Array})."""
    import pyarrow as pa
    from spark_rapids_tpu.columnar.arrow import table_to_device
    from spark_rapids_tpu.ops.filtering import slice_to_capacity
    if len(tables) > n:
        raise ValueError(
            f"{len(tables)} input shards > {n} mesh devices; "
            "merge shards before calling the mesh executor")
    cap = bucket_capacity(max((t.num_rows for t in tables), default=1))
    global_dicts = {}
    for i, f in enumerate(schema):
        if isinstance(f.data_type, T.StringType):
            union = pa.concat_arrays(
                [t.column(i).combine_chunks().cast(pa.string()).unique()
                 for t in tables]).unique().sort()
            global_dicts[i] = union
    shards = []
    for t in tables:
        batch = table_to_device(t, schema=schema)
        cols = []
        for i, cv in enumerate(batch.columns):
            c = Col.from_vector(cv)
            if i in global_dicts and c.dictionary is not None:
                remap = {v: j for j, v in
                         enumerate(global_dicts[i].to_pylist())}
                m = np.array([remap[v] for v in
                              c.dictionary.to_pylist()] or [0], np.int32)
                c = Col(jnp.asarray(m)[c.values], c.validity, c.dtype,
                        global_dicts[i])
            cols.append(c)
        # re-pad to the common mesh capacity
        cols = slice_to_capacity(cols, t.num_rows, cap)
        shards.append((cols, t.num_rows))
    while len(shards) < n:  # fewer shards than chips: empty pads
        cols = [Col(jnp.full((cap,), f.data_type.default_value(),
                             dtype=f.data_type.jnp_dtype),
                    jnp.zeros((cap,), jnp.bool_), f.data_type,
                    global_dicts.get(i))
                for i, f in enumerate(schema)]
        shards.append((cols, 0))
    return shards, cap, global_dicts


def put_stacked_shards(mesh: Mesh, shards):
    """device_put every field of `shards` ([(cols, n_rows)] with one entry
    per mesh device) stacked over the mesh's "data" axis. Returns
    (vals, masks, nrows) ready to feed a shard_map program — the ingest
    step shared by MeshExecutor.aggregate, MeshExchangeExec._run_exchange
    and LocalMesh.partition_wave."""
    sharding = NamedSharding(mesh, P("data", None))
    vals, masks = [], []
    for ci in range(len(shards[0][0])):
        vals.append(jax.device_put(
            jnp.stack([s[0][ci].values for s in shards]), sharding))
        masks.append(jax.device_put(
            jnp.stack([s[0][ci].validity for s in shards]), sharding))
    nrows = jax.device_put(
        jnp.asarray([s[1] for s in shards], jnp.int32),
        NamedSharding(mesh, P("data")))
    return vals, masks, nrows


class MeshDegradedError(RuntimeError):
    """The executor's local mesh is unavailable (fewer than 2 devices),
    narrower than the task group being dispatched (mesh shrank), or failed
    inside its collective region. The cluster driver treats a reply
    carrying this as DEGRADATION, not task failure: the mesh task's splits
    are transparently re-planned onto the per-split TCP-shuffle path under
    a bumped map-output epoch — no task-attempt strike, bit-identical
    results (cluster/minicluster.py)."""


class LocalMesh:
    """One MiniCluster executor's device mesh — the intra-process half of
    the unified mesh-cluster plane (ROADMAP item 4: N processes x M chips).

    A mesh map task carries up to `n` lanes (one map split each); per
    partition wave, the Spark-exact murmur3 partition ids of EVERY lane's
    current batch are computed in ONE jitted shard_map dispatch (lane =
    shard), and the wave's per-reduce-partition row counts are all-reduced
    over ICI with `lax.psum` — the map-output-statistics exchange.

    TWO-LEVEL EXCHANGE (docs/cluster.md): block content for reduce
    partitions OWNED by this host (the driver's ownership assignment, i.e.
    the partitions whose consumer will be placed here) rides
    `exchange_wave` — every fixed-width column moves lane→lane with ONE
    `lax.all_to_all` per carrier over ICI, and the receiving lane writes
    the shards into the local block store under the SAME (map_split, seq)
    keys the per-batch path would have used, so `iter_union_blocks`'
    canonical-key merge keeps bit-identity with the TCP plane by
    construction. Only partitions owned by OTHER hosts are sliced with the
    per-batch path (shuffle.partitioning.slice_into_partitions) and parked
    for the TCP fetch. Waves whose schema carries variable-width columns
    (strings, lists, maps, structs) fall back to slice-and-park for the
    whole wave without breaking the mesh group, and any failure inside the
    collective degrades the task to per-split TCP execution — which is
    what makes the transparent mesh→TCP degraded fallback sound."""

    _instance: "LocalMesh | None" = None
    _ilock = threading.Lock()

    def __init__(self, n_devices: int = 0):
        devs = jax.devices()
        n = len(devs) if n_devices <= 0 else min(n_devices, len(devs))
        if n < 2:
            raise MeshDegradedError(
                f"local mesh unavailable: {len(devs)} visible device(s), "
                f"{n_devices} requested")
        self.n = n
        self.mesh = Mesh(np.array(devs[:n]), ("data",))
        self._steps: dict = {}

    @classmethod
    def get(cls, n_devices: int = 0) -> "LocalMesh":
        with cls._ilock:
            if cls._instance is None:
                cls._instance = LocalMesh(n_devices)
            return cls._instance

    @classmethod
    def reset(cls):
        with cls._ilock:
            cls._instance = None

    def _pid_step(self, dtypes, cap: int, n_out: int):
        """Jitted shard_map program keyed by (key dtypes, capacity, reduce
        fan-out): per shard, murmur3 partition ids masked to a sentinel on
        padding rows, plus the psum-reduced live row count per partition."""
        key = (tuple(type(dt).__name__ for dt in dtypes), cap, n_out)
        step = self._steps.get(key)
        if step is not None:
            return step
        from spark_rapids_tpu.ops import hashing as H
        from spark_rapids_tpu.shuffle.partitioning import murmur3_row_hash
        nk = len(dtypes)

        def shard_step(*flat):
            vals = flat[:nk]
            masks = flat[nk:2 * nk]
            n_rows = flat[2 * nk][0]
            cols = [Col(v[0], m[0], dt)
                    for v, m, dt in zip(vals, masks, dtypes)]
            h = murmur3_row_hash(cols, cap)
            pids = H.pmod(h, n_out)
            live = jnp.arange(cap, dtype=jnp.int32) < n_rows
            pids = jnp.where(live, pids, jnp.int32(n_out))
            counts = jnp.bincount(pids, length=n_out + 1)[:n_out]
            return pids[None], jax.lax.psum(counts, "data")

        spec = P("data", None)
        shard_map = getattr(jax, "shard_map", None)
        if shard_map is None:  # older jax
            from jax.experimental.shard_map import shard_map
        step = jax.jit(shard_map(
            shard_step, mesh=self.mesh,
            in_specs=tuple([spec] * (2 * nk) + [P("data")]),
            out_specs=(spec, P())))
        self._steps[key] = step
        return step

    @staticmethod
    def _pad_col(col: Col, cap: int) -> Col:
        n = col.values.shape[0]
        if n >= cap:
            return col
        default = jnp.asarray(col.dtype.default_value(),
                              dtype=col.values.dtype)
        return Col(jnp.concatenate([col.values,
                                    jnp.full((cap - n,), default)]),
                   jnp.concatenate([col.validity,
                                    jnp.zeros((cap - n,), jnp.bool_)]),
                   col.dtype)

    def partition_wave(self, batches: list, partitioner):
        """One wave: `batches` holds each live lane's current batch (≤ n).
        Returns ([pids per batch, each sliced to that batch's capacity],
        wave_counts) where wave_counts is the psum-reduced live-row count
        per reduce partition (None on the per-batch string fallback).
        Lanes whose keys include string columns fall back to the per-batch
        pid path: per-lane dictionaries cannot be trace-time constants of
        one stacked program (docs/cluster.md)."""
        if len(batches) > self.n:
            raise MeshDegradedError(
                f"mesh shrank: {self.n} device(s) < {len(batches)} lanes")
        n_out = partitioner.num_partitions
        keys_per_lane = []
        for b in batches:
            ctx = EvalContext.from_batch(b)
            keys_per_lane.append([e.eval(ctx)
                                  for e in partitioner.key_exprs])
        if any(k.is_string for k in keys_per_lane[0]):
            return [partitioner.part_ids(b) for b in batches], None
        cap = max(b.capacity for b in batches)
        dtypes = [k.dtype for k in keys_per_lane[0]]
        shards = [([self._pad_col(k, cap) for k in keys], b.num_rows)
                  for keys, b in zip(keys_per_lane, batches)]
        while len(shards) < self.n:    # idle lanes: empty pad shards
            shards.append((
                [Col(jnp.full((cap,), dt.default_value(),
                              dtype=dt.jnp_dtype),
                     jnp.zeros((cap,), jnp.bool_), dt) for dt in dtypes],
                0))
        vals, masks, nrows = put_stacked_shards(self.mesh, shards)
        pids, counts = self._pid_step(dtypes, cap, n_out)(
            *vals, *masks, nrows)
        counts = np.asarray(counts)
        # movement ledger, ICI edge: the program's only collective is the
        # psum of per-partition live-row counts — metered as the ACTUAL
        # per-lane operand bytes (every device contributes one n_out count
        # vector of the psum operand's real dtype)
        from spark_rapids_tpu.runtime import movement as MV
        op_bytes = int(counts.dtype.itemsize) * n_out * self.n
        MV.record("ici.collective", op_bytes, link="ici",
                  site="mesh.partition_wave", payload_bytes=op_bytes)
        return ([pids[d][:b.capacity] for d, b in enumerate(batches)],
                counts)

    # -- two-level content exchange -----------------------------------------
    @staticmethod
    def exchangeable_schema(schema) -> bool:
        """Whether a batch schema can ride the ICI content exchange: every
        column must be fixed-width on device. Variable-width carriers
        (strings with per-batch dictionaries, lists, maps, structs) fall
        back to the per-batch slice-and-park path for the whole wave."""
        return all(not isinstance(f.data_type,
                                  (T.StringType, T.ArrayType, T.MapType,
                                   T.StructDataType, T.NullType))
                   for f in schema)

    def _exchange_step(self, dtypes, cap: int, cap_ex: int, n_out: int):
        """Jitted shard_map program keyed by (column dtypes, input
        capacity, exchange-block capacity, fan-out): per lane, rows whose
        reduce partition is owned by THIS host are compacted per
        destination lane and every column carrier (values, validity, pid)
        moves lane→lane with one `lax.all_to_all` over ICI. Returns the
        received shards still stacked per (dest lane, source lane) with
        the received pids sentinel-masked past each source's live count."""
        key = ("exchange", tuple(type(dt).__name__ for dt in dtypes),
               cap, cap_ex, n_out)
        step = self._steps.get(key)
        if step is not None:
            return step
        from spark_rapids_tpu.ops.filtering import compact_cols
        nc = len(dtypes)
        n_dev = self.n

        def shard_step(*flat):
            vals = flat[:nc]
            masks = flat[nc:2 * nc]
            pids = flat[2 * nc][0]          # (cap,) sentinel n_out on pads
            dest_map = flat[2 * nc + 1]     # (n_out+1,) lane or -1
            dest = dest_map[pids]
            cols = [Col(v[0], m[0], dt)
                    for v, m, dt in zip(vals, masks, dtypes)]
            idcol = Col(pids, jnp.ones((cap,), jnp.bool_), T.IntegerType())
            sv, sm, sp, sn = [], [], [], []
            for d in range(n_dev):
                keep = dest == jnp.int32(d)
                cc, cn = compact_cols(cols + [idcol], keep)
                sv.append([c.values[:cap_ex] for c in cc[:-1]])
                sm.append([c.validity[:cap_ex] for c in cc[:-1]])
                sp.append(cc[-1].values[:cap_ex])
                sn.append(jnp.minimum(cn, jnp.int32(cap_ex)))
            stacked_v = [jnp.stack([sv[d][c] for d in range(n_dev)])
                         for c in range(nc)]
            stacked_m = [jnp.stack([sm[d][c] for d in range(n_dev)])
                         for c in range(nc)]
            spids = jnp.stack(sp)
            scnt = jnp.stack(sn).astype(jnp.int32)
            rv = [jax.lax.all_to_all(a, "data", 0, 0) for a in stacked_v]
            rm = [jax.lax.all_to_all(a, "data", 0, 0) for a in stacked_m]
            rp = jax.lax.all_to_all(spids, "data", 0, 0)
            rn = jax.lax.all_to_all(scnt, "data", 0, 0)
            # sentinel-mask the received pids past each source's live count
            # so the host-side per-pid slicing sinks padding rows
            live = jnp.arange(cap_ex, dtype=jnp.int32)[None, :] < rn[:, None]
            rp = jnp.where(live, rp, jnp.int32(n_out))
            return (tuple(v[None] for v in rv) + tuple(m[None] for m in rm)
                    + (rp[None], rn[None]))

        spec = P("data", None)
        shard_map = getattr(jax, "shard_map", None)
        if shard_map is None:  # older jax
            from jax.experimental.shard_map import shard_map
        step = jax.jit(shard_map(
            shard_step, mesh=self.mesh,
            in_specs=tuple([spec] * (2 * nc) + [spec, P()]),
            out_specs=tuple([P("data", None, None)] * (2 * nc + 1)
                            + [spec])))
        self._steps[key] = step
        return step

    def exchange_wave(self, batches: list, pids_list: list, dest_map,
                      n_out: int):
        """Move one wave's intra-host reduce-partition CONTENT over ICI:
        `dest_map` maps pid → receiving lane for partitions owned by this
        host (-1 for cross-host pids, which stay on the slice-and-park
        path). Returns (recv_vals, recv_masks, recv_pids, recv_counts)
        where recv_vals[c][dest][src] is source lane `src`'s rows for the
        partitions assigned to lane `dest`, in source batch order — the
        receiving lane reconstructs per-(map_split, pid) blocks from them
        bit-identically to the per-batch path. The movement ledger meters
        the ACTUAL per-lane all_to_all operand bytes on the ici edge, with
        the live-row content bytes as the payload unit."""
        if len(batches) > self.n:
            raise MeshDegradedError(
                f"mesh shrank: {self.n} device(s) < {len(batches)} lanes")
        cap = max(b.capacity for b in batches)
        cols_per_lane = [[Col.from_vector(c) for c in b.columns]
                         for b in batches]
        dtypes = [c.dtype for c in cols_per_lane[0]]
        # dest_map indexed by pid; slot n_out is the pad-row sentinel and
        # always routes off-mesh (-1)
        dm = np.full((n_out + 1,), -1, np.int32)
        dm[:n_out] = np.asarray(dest_map, np.int32)[:n_out]
        # host-side per-(lane, dest) live-row counts size the exchange
        # block (one d2h sync of the wave's pid vectors, same sync the
        # per-batch slice path pays for its bincount)
        row_bytes = sum(np.dtype(dt.jnp_dtype).itemsize + 1
                        for dt in dtypes) + 4
        live_rows = 0
        max_cnt = 1
        for b, pids in zip(batches, pids_list):
            p = np.asarray(pids)[:b.num_rows]
            d = dm[p]
            d = d[d >= 0]
            live_rows += int(d.size)
            if d.size:
                max_cnt = max(max_cnt, int(np.bincount(d).max()))
        cap_ex = min(bucket_capacity(max_cnt), cap)
        shards = []
        pid_rows = []
        for b, cols, pids in zip(batches, cols_per_lane, pids_list):
            shards.append(([self._pad_col(c, cap) for c in cols],
                           b.num_rows))
            p = jnp.asarray(pids, jnp.int32)
            if p.shape[0] < cap:
                p = jnp.concatenate(
                    [p, jnp.full((cap - p.shape[0],), jnp.int32(n_out))])
            pid_rows.append(p)
        while len(shards) < self.n:    # idle lanes: empty pad shards
            shards.append((
                [Col(jnp.full((cap,), dt.default_value(),
                              dtype=dt.jnp_dtype),
                     jnp.zeros((cap,), jnp.bool_), dt) for dt in dtypes],
                0))
            pid_rows.append(jnp.full((cap,), jnp.int32(n_out)))
        vals, masks, _nrows = put_stacked_shards(self.mesh, shards)
        sharding = NamedSharding(self.mesh, P("data", None))
        pids_stacked = jax.device_put(jnp.stack(pid_rows), sharding)
        dm_dev = jax.device_put(jnp.asarray(dm),
                                NamedSharding(self.mesh, P()))
        step = self._exchange_step(dtypes, cap, cap_ex, n_out)
        out = step(*vals, *masks, pids_stacked, dm_dev)
        nc = len(dtypes)
        rv, rm = list(out[:nc]), list(out[nc:2 * nc])
        rp, rn = out[2 * nc], out[2 * nc + 1]
        rn = np.asarray(rn)             # sync: collective errors surface HERE
        # movement ledger, ICI edge: the REAL all_to_all operand bytes
        # (per-lane (n, cap_ex) carriers for every value/validity/pid
        # column plus the count vector, summed over lanes), dual-unit with
        # the wave's live-row content bytes as the payload column
        from spark_rapids_tpu.runtime import movement as MV
        per_lane = (sum(self.n * cap_ex * np.dtype(dt.jnp_dtype).itemsize
                        for dt in dtypes)
                    + nc * self.n * cap_ex          # validity carriers
                    + self.n * cap_ex * 4           # pid carrier
                    + self.n * 4)                   # count vector
        MV.record("ici.collective", per_lane * self.n, link="ici",
                  site="mesh.exchange_wave",
                  payload_bytes=live_rows * row_bytes)
        return rv, rm, rp, rn


class MeshExecutor:
    """Compile + run grouped aggregation across an n-device mesh."""

    def __init__(self, n_devices: int | None = None, devices=None):
        devs = (list(devices) if devices is not None
                else jax.devices()[:n_devices or len(jax.devices())])
        self.n = len(devs)
        self.mesh = Mesh(np.array(devs), ("data",))

    # -- host-side ingest ----------------------------------------------------
    def _encode_shards(self, tables, schema: T.StructType):
        return encode_shards(tables, schema, self.n)

    # -- the SPMD program ----------------------------------------------------
    def _build_step(self, schema, group_exprs, agg_exprs, filter_expr, cap):
        n_dev = self.n
        group_b = [bind_references(e, schema) for e in group_exprs]
        aggs = [(_unalias(bind_references(e, schema))) for e in agg_exprs]
        assert all(isinstance(a, AggregateFunction) for a in aggs)
        filt_b = (bind_references(filter_expr, schema)
                  if filter_expr is not None else None)
        state_counts = [len(a.state_types) for a in aggs]

        def local_partial(cols, n_rows):
            ctx = EvalContext(cols, n_rows, cap)
            if filt_b is not None:
                pred = filt_b.eval(ctx)
                keep = selection_mask(pred, n_rows, cap)
                cols, n_rows = compact_cols(cols, keep)
                ctx = EvalContext(cols, n_rows, cap)
            keys = [e.eval(ctx) for e in group_b]
            perm, seg_ids, boundary, live = G.group_segments(keys, n_rows, cap)
            skeys = gather_cols(keys, perm, live)
            segctx = G.segment_structure(seg_ids, cap)
            states = []
            for a in aggs:
                in_col = (gather_cols([a.child.eval(ctx)], perm, live)[0]
                          if a.children else
                          Col(jnp.zeros((cap,), jnp.int8), live, T.NULL))
                states.extend(a.update(in_col, segctx))  # per-row states
            out, n_groups = compact_cols(skeys + states, boundary)
            return out, n_groups

        def shard_step(*flat):
            nk = len(group_b)
            n_state = sum(state_counts)
            n_cols = len(schema.fields)
            vals = flat[:n_cols]
            vlds = flat[n_cols:2 * n_cols]
            n_rows = flat[2 * n_cols][0]
            cols = [Col(v[0], m[0], f.data_type)
                    for v, m, f in zip(vals, vlds, schema.fields)]

            partial, n_groups = local_partial(cols, n_rows)

            # exchange: hash-partition partial rows over the mesh
            pids = H.pmod(_mesh_hash(partial[:nk], cap), n_dev)
            live = jnp.arange(cap, dtype=jnp.int32) < n_groups
            sends_v, sends_m, sends_n = [], [], []
            for p in range(n_dev):
                mask = live & (pids == p)
                pc, pn = compact_cols(partial, mask)
                sends_v.append([c.values for c in pc])
                sends_m.append([c.validity for c in pc])
                sends_n.append(pn)
            ncols_p = nk + n_state
            stacked_v = [jnp.stack([sends_v[p][c] for p in range(n_dev)])
                         for c in range(ncols_p)]
            stacked_m = [jnp.stack([sends_m[p][c] for p in range(n_dev)])
                         for c in range(ncols_p)]
            sn = jnp.stack(sends_n)
            recv_v = [jax.lax.all_to_all(a, "data", 0, 0) for a in stacked_v]
            recv_m = [jax.lax.all_to_all(a, "data", 0, 0) for a in stacked_m]
            rn = jax.lax.all_to_all(sn, "data", 0, 0)

            # merge received partials
            mcap = n_dev * cap
            slot = jnp.arange(mcap, dtype=jnp.int32) % cap
            rlive = slot < jnp.repeat(rn, cap)
            rcols = []
            src = partial  # dtype templates
            for c in range(ncols_p):
                v = recv_v[c].reshape(mcap)
                m = recv_m[c].reshape(mcap) & rlive
                proto = src[c]
                default = jnp.asarray(proto.dtype.default_value(),
                                      dtype=v.dtype)
                rcols.append(Col(jnp.where(m, v, default), m, proto.dtype,
                                 proto.dictionary))
            # key validity defines row presence only together with rlive;
            # null-keyed rows are real rows — track presence separately
            present = rlive
            (packed, m_rows) = compact_cols(
                rcols + [Col(jnp.zeros((mcap,), jnp.int8), present, T.NULL)],
                present)
            packed = packed[:-1]
            keys2 = packed[:nk]
            perm, seg_ids, boundary, live2 = G.group_segments(
                keys2, m_rows, mcap)
            skeys2 = gather_cols(keys2, perm, live2)
            segctx2 = G.segment_structure(seg_ids, mcap)
            out_states = []
            si = nk
            for a, nst in zip(aggs, state_counts):
                sts = gather_cols(packed[si:si + nst], perm, live2)
                out_states.extend(a.merge(sts, segctx2))  # per-row states
                si += nst
            out, out_groups = compact_cols(skeys2 + out_states, boundary)

            # finals
            finals = out[:nk]
            si = nk
            for a, nst in zip(aggs, state_counts):
                finals.append(a.evaluate(out[si:si + nst]))
                si += nst
            ret_v = tuple(c.values[None] for c in finals)
            ret_m = tuple(c.validity[None] for c in finals)
            return ret_v + ret_m + (out_groups[None],)

        spec2 = P("data", None)
        n_out = len(group_b) + len(aggs)
        shard_map = getattr(jax, "shard_map", None)
        if shard_map is None:
            from jax.experimental.shard_map import shard_map
        n_in = len(schema.fields)
        step = jax.jit(shard_map(
            shard_step, mesh=self.mesh,
            in_specs=tuple([spec2] * (2 * n_in) + [P("data")]),
            out_specs=tuple([spec2] * (2 * n_out) + [P("data")])))
        return step

    # -- public API ----------------------------------------------------------
    def aggregate(self, tables: list, group_exprs: list, agg_exprs: list,
                  filter_expr=None, schema: T.StructType | None = None):
        """tables: one pyarrow Table per shard (≤ n_devices). Returns one
        pyarrow Table of grouped results."""
        import pyarrow as pa
        if schema is None:
            schema = T.StructType.from_arrow(tables[0].schema)
        shards, cap, _dicts = self._encode_shards(tables, schema)
        step = self._build_step(schema, group_exprs, agg_exprs, filter_expr,
                                cap)
        vals, masks, nrows = put_stacked_shards(self.mesh, shards)
        group_b = [bind_references(e, schema) for e in group_exprs]
        aggs = [_unalias(bind_references(e, schema)) for e in agg_exprs]
        # movement ledger, ICI edge: the exchange inside the program is one
        # lax.all_to_all per partial-aggregate carrier — metered as the
        # ACTUAL operand bytes: every device contributes a (n_dev, cap)
        # values + validity pair per key/state column plus its per-dest
        # count vector (the partials ride at full capacity; the live-row
        # subset is not knowable host-side without a d2h sync)
        from spark_rapids_tpu.runtime import movement as MV
        part_dtypes = ([g.dtype for g in group_b]
                       + [st for a in aggs for st in a.state_types])
        n = self.n
        op_bytes = n * n * cap * sum(
            np.dtype(dt.jnp_dtype).itemsize + 1 for dt in part_dtypes)
        op_bytes += n * n * 4  # per-dest count vectors
        MV.record("ici.collective", op_bytes, link="ici",
                  site="mesh.aggregate", payload_bytes=op_bytes)
        out = step(*vals, *masks, nrows)

        n_out = len(group_b) + len(aggs)
        out_v, out_m, groups = out[:n_out], out[n_out:2 * n_out], out[-1]
        counts = np.asarray(groups)

        names = []
        dtypes = []
        for i, e in enumerate(group_exprs):
            names.append(e.name if isinstance(e, Alias) else
                         getattr(e, "name", f"k{i}"))
            dtypes.append(group_b[i].dtype)
        for i, e in enumerate(agg_exprs):
            names.append(e.name if isinstance(e, Alias) else f"agg{i}")
            dtypes.append(aggs[i].dtype)

        # keep per-key dictionaries for decode
        key_dicts = [shards[0][0][_key_ordinal(group_b[i], schema)].dictionary
                     if isinstance(dtypes[i], T.StringType) else None
                     for i in range(len(group_b))] + [None] * len(aggs)

        rows = {n: [] for n in names}
        for d in range(len(counts)):
            n_g = int(counts[d])
            if n_g == 0:
                continue
            for ci, name in enumerate(names):
                v = np.asarray(out_v[ci][d][:n_g])
                m = np.asarray(out_m[ci][d][:n_g])
                dt = dtypes[ci]
                for j in range(n_g):
                    if not m[j]:
                        rows[name].append(None)
                    elif key_dicts[ci] is not None:
                        rows[name].append(
                            key_dicts[ci][int(v[j])].as_py())
                    else:
                        rows[name].append(_pyval(v[j], dt))
        return pa.table({n: pa.array(rows[n], T.to_arrow_type(dt))
                         for n, dt in zip(names, dtypes)})


def _key_ordinal(expr, schema) -> int:
    from spark_rapids_tpu.expr.core import BoundReference
    if isinstance(expr, BoundReference):
        return expr.ordinal
    return 0


def _pyval(v, dt: T.DataType):
    if isinstance(dt, T.BooleanType):
        return bool(v)
    if isinstance(dt, (T.FloatType, T.DoubleType)):
        return float(v)
    return int(v)
