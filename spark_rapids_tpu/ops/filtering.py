"""Filter/compaction and gather kernels.

Reference: cudf apply_boolean_mask via GpuFilterExec (basicPhysicalOperators.scala:181).
cudf compacts to a new smaller column; XLA needs static shapes, so we compact IN PLACE
within the padded capacity: surviving rows are moved to the front (stable), the live
row count becomes a device scalar, and the tail is marked invalid. The whole thing is
a fused sort-by-flag — no host sync, so filters chain inside one XLA program."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu.expr.core import Col


def selection_mask(pred: Col, num_rows, capacity: int):
    """Rows kept by a filter: predicate true AND valid AND a live (non-pad) row."""
    live = jnp.arange(capacity) < num_rows
    return pred.values & pred.validity & live


def compact_cols(cols, keep_mask):
    """Stable-move surviving rows to the front. Returns (new_cols, new_count).

    Backend-split formulation (same contract, different hardware optimum):

    - TPU: the j-th kept row's source index is recovered by binary search over
      the running kept-count (one cumsum + one searchsorted) — gathers
      vectorize on the TPU while scatters serialize (the same reason
      ops/grouping.py uses scan-based segment reductions).
    - CPU: ONE scatter-with-drop builds the front-compaction permutation,
      then every column rides cheap gathers. XLA:CPU's scatter costs ~50 ms
      per array at 1M rows while a gather is ~8 ms, so paying the scatter
      once instead of twice per column is ~3x at two columns and grows with
      width; searchsorted lowers to ~log2(cap) gather sweeps and measured
      ~8x slower still (docs/perf_notes.md round-4)."""
    capacity = keep_mask.shape[0]
    running = jnp.cumsum(keep_mask.astype(jnp.int32))
    count = running[-1]
    j = jnp.arange(capacity, dtype=jnp.int32)
    live = j < count
    out = []
    from spark_rapids_tpu.runtime.hw import scatters_cheap
    if scatters_cheap():
        dest = jnp.where(keep_mask, running - 1, capacity)
        perm = jnp.zeros((capacity,), jnp.int32).at[dest].set(
            j, mode="drop")
        for c in cols:
            vals = c.values[perm]
            validity = c.validity[perm] & live
            default = jnp.asarray(c.dtype.default_value(),
                                  dtype=vals.dtype)
            out.append(Col(jnp.where(validity, vals, default), validity,
                           c.dtype, c.dictionary))
        return out, count
    perm = jnp.clip(jnp.searchsorted(running, j + 1, side="left"), 0,
                    capacity - 1).astype(jnp.int32)
    for c in cols:
        vals = c.values[perm]
        validity = c.validity[perm] & live
        default = jnp.asarray(c.dtype.default_value(), dtype=vals.dtype)
        out.append(Col(jnp.where(validity, vals, default), validity, c.dtype,
                       c.dictionary))
    return out, count


def gather_cols(cols, indices, valid_out):
    """Gather rows by index (join/sort output). valid_out masks output slots."""
    out = []
    for c in cols:
        vals = c.values[indices]
        validity = c.validity[indices] & valid_out
        default = jnp.asarray(c.dtype.default_value(), dtype=vals.dtype)
        out.append(Col(jnp.where(validity, vals, default), validity, c.dtype,
                       c.dictionary))
    return out


def host_compact_cols(cols, keep_mask, min_shrink: int = 4):
    """Host-indexed stage-boundary compaction: sync the keep mask, gather the
    survivors into a RIGHT-SIZED capacity bucket.

    The in-program `compact_cols` pays a capacity-wide scatter + per-column
    gathers (~53 ms at 1M rows on XLA:CPU) and keeps the output at the INPUT
    capacity — a high-reduction stage (HAVING over a group-by, a selective
    filter) then drags that stale capacity through every downstream operator.
    One host round-trip (mask sync + np.nonzero, ~1 ms at 1M rows) instead
    yields the survivor indices, and a tiny gather program lands the output
    at bucket_capacity(count): the 3-row result of a 1M-capacity stage flows
    on at capacity 8 (measured ~50x on the compaction itself, and every
    downstream per-batch program shrinks with it — docs/perf_notes.md r7).

    Returns (new_cols, count) or None when the output would not shrink by at
    least `min_shrink` (caller falls back to the in-program compact — for
    low-reduction stages the device path is the right one, and the sync
    would only serialize the pipeline)."""
    import numpy as np
    from spark_rapids_tpu.columnar.vector import bucket_capacity
    from spark_rapids_tpu.runtime import fuse

    keep = np.asarray(keep_mask)
    capacity = int(keep.shape[0])
    idx = np.nonzero(keep)[0]
    count = int(idx.size)
    out_cap = bucket_capacity(count)
    if out_cap * min_shrink > capacity:
        return None
    pad = np.zeros(out_cap, dtype=np.int32)
    pad[:count] = idx.astype(np.int32)
    idx_dev = jnp.asarray(pad)
    n_t = jnp.asarray(count, jnp.int32)
    key = ("host_compact", capacity, out_cap,
           tuple((c.dtype, str(c.values.dtype)) for c in cols))

    def build():
        def kernel(cols, indices, n):
            valid_out = jnp.arange(out_cap, dtype=jnp.int32) < n
            return gather_cols(cols, indices, valid_out)
        return kernel

    out = fuse.call_fused(key, "host_compact", build, (cols, idx_dev, n_t),
                          lambda: build()(cols, idx_dev, n_t))
    return out, count


def maybe_host_resize(cols, count, min_shrink: int = 4):
    """Re-land FRONT-COMPACTED columns (survivors first, tail invalid — the
    compact_cols output contract) at bucket_capacity(count): one host sync of
    the live count, then a tiny fused slice program. Returns (cols, n) with a
    HOST int count, or None when the input capacity is small or the shrink is
    under `min_shrink` (the sync would serialize the pipeline for nothing).

    This is the stage-boundary half of the host-compaction design
    (docs/perf_notes.md r7): a high-reduction operator output stops dragging
    its stale input capacity through every downstream per-batch program."""
    from spark_rapids_tpu.columnar.vector import bucket_capacity
    from spark_rapids_tpu.runtime import fuse

    capacity = int(cols[0].values.shape[0])
    if capacity < (1 << 16):
        return None
    n = int(count)
    out_cap = bucket_capacity(n)
    if out_cap * min_shrink > capacity:
        return None
    key = ("cap_slice", capacity, out_cap,
           tuple((c.dtype, str(c.values.dtype)) for c in cols))

    def build():
        def kernel(cols):
            return slice_to_capacity(cols, None, out_cap)
        return kernel

    out = fuse.call_fused(key, "cap_slice", build, (cols,),
                          lambda: slice_to_capacity(cols, n, out_cap))
    return out, n


def fused_compact_cols(cols, keep_mask):
    """compact_cols as its own fused program (device fallback for epilogues
    whose host-compaction path declined — see host_compact_cols)."""
    from spark_rapids_tpu.runtime import fuse
    capacity = int(keep_mask.shape[0])
    key = ("mask_compact", capacity,
           tuple((c.dtype, str(c.values.dtype)) for c in cols))

    def build():
        def kernel(cols, keep):
            return compact_cols(cols, keep)
        return kernel

    return fuse.call_fused(key, "mask_compact", build, (cols, keep_mask),
                           lambda: compact_cols(cols, keep_mask))


def slice_to_capacity(cols, count, new_capacity: int):
    """Shrink/grow the padded capacity (host-known count required)."""
    out = []
    for c in cols:
        if new_capacity <= c.values.shape[0]:
            vals = c.values[:new_capacity]
            validity = c.validity[:new_capacity]
        else:
            pad = new_capacity - c.values.shape[0]
            default = jnp.asarray(c.dtype.default_value(), dtype=c.values.dtype)
            vals = jnp.concatenate([c.values, jnp.full((pad,), default)])
            validity = jnp.concatenate([c.validity, jnp.zeros((pad,), jnp.bool_)])
        out.append(Col(vals, validity, c.dtype, c.dictionary))
    return out
