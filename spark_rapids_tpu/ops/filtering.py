"""Filter/compaction and gather kernels.

Reference: cudf apply_boolean_mask via GpuFilterExec (basicPhysicalOperators.scala:181).
cudf compacts to a new smaller column; XLA needs static shapes, so we compact IN PLACE
within the padded capacity: surviving rows are moved to the front (stable), the live
row count becomes a device scalar, and the tail is marked invalid. The whole thing is
a fused sort-by-flag — no host sync, so filters chain inside one XLA program."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu.expr.core import Col


def selection_mask(pred: Col, num_rows, capacity: int):
    """Rows kept by a filter: predicate true AND valid AND a live (non-pad) row."""
    live = jnp.arange(capacity) < num_rows
    return pred.values & pred.validity & live


def compact_cols(cols, keep_mask):
    """Stable-move surviving rows to the front. Returns (new_cols, new_count).

    Backend-split formulation (same contract, different hardware optimum):

    - TPU: the j-th kept row's source index is recovered by binary search over
      the running kept-count (one cumsum + one searchsorted) — gathers
      vectorize on the TPU while scatters serialize (the same reason
      ops/grouping.py uses scan-based segment reductions).
    - CPU: ONE scatter-with-drop builds the front-compaction permutation,
      then every column rides cheap gathers. XLA:CPU's scatter costs ~50 ms
      per array at 1M rows while a gather is ~8 ms, so paying the scatter
      once instead of twice per column is ~3x at two columns and grows with
      width; searchsorted lowers to ~log2(cap) gather sweeps and measured
      ~8x slower still (docs/perf_notes.md round-4)."""
    capacity = keep_mask.shape[0]
    running = jnp.cumsum(keep_mask.astype(jnp.int32))
    count = running[-1]
    j = jnp.arange(capacity, dtype=jnp.int32)
    live = j < count
    out = []
    from spark_rapids_tpu.runtime.hw import scatters_cheap
    if scatters_cheap():
        dest = jnp.where(keep_mask, running - 1, capacity)
        perm = jnp.zeros((capacity,), jnp.int32).at[dest].set(
            j, mode="drop")
        for c in cols:
            vals = c.values[perm]
            validity = c.validity[perm] & live
            default = jnp.asarray(c.dtype.default_value(),
                                  dtype=vals.dtype)
            out.append(Col(jnp.where(validity, vals, default), validity,
                           c.dtype, c.dictionary))
        return out, count
    perm = jnp.clip(jnp.searchsorted(running, j + 1, side="left"), 0,
                    capacity - 1).astype(jnp.int32)
    for c in cols:
        vals = c.values[perm]
        validity = c.validity[perm] & live
        default = jnp.asarray(c.dtype.default_value(), dtype=vals.dtype)
        out.append(Col(jnp.where(validity, vals, default), validity, c.dtype,
                       c.dictionary))
    return out, count


def gather_cols(cols, indices, valid_out):
    """Gather rows by index (join/sort output). valid_out masks output slots."""
    out = []
    for c in cols:
        vals = c.values[indices]
        validity = c.validity[indices] & valid_out
        default = jnp.asarray(c.dtype.default_value(), dtype=vals.dtype)
        out.append(Col(jnp.where(validity, vals, default), validity, c.dtype,
                       c.dictionary))
    return out


def slice_to_capacity(cols, count, new_capacity: int):
    """Shrink/grow the padded capacity (host-known count required)."""
    out = []
    for c in cols:
        if new_capacity <= c.values.shape[0]:
            vals = c.values[:new_capacity]
            validity = c.validity[:new_capacity]
        else:
            pad = new_capacity - c.values.shape[0]
            default = jnp.asarray(c.dtype.default_value(), dtype=c.values.dtype)
            vals = jnp.concatenate([c.values, jnp.full((pad,), default)])
            validity = jnp.concatenate([c.validity, jnp.zeros((pad,), jnp.bool_)])
        out.append(Col(vals, validity, c.dtype, c.dictionary))
    return out
