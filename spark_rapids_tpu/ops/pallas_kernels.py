"""Hand-written Pallas TPU kernels for the irregular hot ops.

SURVEY.md §7 design stance: XLA fuses the dense columnar math; Pallas covers
the parts XLA lowers poorly on TPU — byte-level bit twiddling with per-row
data-dependent control (string murmur3) and bit-packed decode (parquet
RLE_DICTIONARY indices). Reference analogs: cudf's murmur3 device hash
(GpuHashPartitioning.scala:92 depends on it) and libcudf's parquet index
decoder (GpuParquetScan.scala:1235 `Table.readParquet`).

Both kernels are lane-static reformulations — no dynamic gathers, which
Mosaic lowers badly:

* ``murmur3_words``: rows tile over the grid; the word loop and the
  per-row tail-byte selection unroll over static columns with vector
  selects, so each (TILE, W) block is pure VPU work.
* ``bitunpack128``: 128 consecutive bit-packed values of width ``bw``
  occupy exactly ``4*bw`` 32-bit words, so value lane j always reads word
  ``(j*bw)>>5`` — a static column index. The unpack becomes a per-lane
  shift/mask over statically-selected columns: zero gathers.
* ``radix_ranks``: stable counting-sort ranks over a small partition domain
  as dense (BK, DP) one-hot cumsums, with the sequential TPU grid carrying
  the per-partition running count between row tiles. Backs both the
  exchange partition step (GpuPartitioning.sliceInternalOnGpu analog) and
  the hash-table build.
* ``hash_join_build``/``hash_join_probe``: the cudf innerJoinGatherMaps
  analog (GpuHashJoin.scala:289) for unique fixed-point keys — an open
  (H, HJ_SLOTS) hash table whose build is a radix partition by Fibonacci
  hash bucket and whose probe unrolls the slot loop statically over a
  VMEM-resident table.

Dispatch: compiled on TPU; ``interpret=True`` elsewhere (tests force the
CPU platform). The jnp reference implementations in ops/hashing.py and
ops/parquet_decode.py remain the oracle and the fallback.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

_C1 = np.int32(np.uint32(0xCC9E2D51))
_C2 = np.int32(np.uint32(0x1B873593))
_M5 = np.int32(np.uint32(0xE6546B64))
_FX1 = np.int32(np.uint32(0x85EBCA6B))
_FX2 = np.int32(np.uint32(0xC2B2AE35))


# dispatch switch: None = auto (compiled kernels on TPU, jnp reference
# elsewhere); True forces the kernels (interpret-mode off-TPU — tests);
# False forces the jnp paths (spark.rapids.tpu.sql.pallas.enabled=false)
_FORCE: bool | None = None
_TPU_PROBE: dict | None = None  # per-kernel latched compile-probe results


def set_mode(force: bool | None) -> None:
    global _FORCE
    _FORCE = force


def _probe_tpu(kernel: str) -> bool:
    """Compile a tiny instance of `kernel` once on the TPU backend. A
    Mosaic lowering failure inside an enclosing jit would surface as an
    opaque engine error at compile time; probing here instead latches the
    dispatch off so the jnp formulations keep the engine correct. Latches
    are PER KERNEL: a lowering failure in one (e.g. a newly added kernel
    that has never met real hardware) must not disable the proven ones."""
    global _TPU_PROBE
    if _TPU_PROBE is None:
        _TPU_PROBE = {}
    if kernel not in _TPU_PROBE:
        try:
            if kernel == "murmur3":
                w = jnp.zeros((8, 2), jnp.int32)
                l = jnp.full((8,), 5, jnp.int32)
                jax.block_until_ready(murmur3_words(w, l, 42))
            elif kernel == "bitunpack":
                jax.block_until_ready(
                    bitunpack128(jnp.zeros((32,), jnp.int32), 8, 100, 128))
            elif kernel == "onehot":
                jax.block_until_ready(
                    onehot_sum_f32(jnp.ones((256,), jnp.float32),
                                   jnp.zeros((256,), jnp.int32), 140))
            elif kernel == "radix":
                ids = jnp.asarray([1, 0, 2, 1, 0, 3, 3, 0], jnp.int32)
                jax.block_until_ready(radix_partition_permutation(ids, 4))
            elif kernel == "hashjoin":
                keys = jnp.arange(16, dtype=jnp.int64)
                elig = jnp.ones((16,), jnp.bool_)
                tk, tr, ok = hash_join_build(keys, elig, 128)
                jax.block_until_ready(
                    hash_join_probe(tk, tr, keys[:8], 128))
            else:
                raise ValueError(f"unknown pallas kernel {kernel!r}")
            _TPU_PROBE[kernel] = True
        except Exception:  # noqa: BLE001 — any lowering failure latches off
            _TPU_PROBE[kernel] = False
    return _TPU_PROBE[kernel]


def should_use(kernel: str = "murmur3") -> bool:
    """Does the engine route `kernel`'s op here on this backend?"""
    if _FORCE is not None:
        return _FORCE
    return jax.default_backend() == "tpu" and _probe_tpu(kernel)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _rotl(x, n):
    return lax.shift_left(x, jnp.int32(n)) | lax.shift_right_logical(
        x, jnp.int32(32 - n))


def _mix_k1(k1):
    return _rotl(k1 * _C1, 15) * _C2


def _mix_h1(h1, k1):
    return _rotl(h1 ^ k1, 13) * jnp.int32(5) + _M5


def _fmix(h1, length):
    h1 = h1 ^ length
    h1 = h1 ^ lax.shift_right_logical(h1, jnp.int32(16))
    h1 = h1 * _FX1
    h1 = h1 ^ lax.shift_right_logical(h1, jnp.int32(13))
    h1 = h1 * _FX2
    return h1 ^ lax.shift_right_logical(h1, jnp.int32(16))


# ---------------------------------------------------------------------------
# murmur3 string hash
# ---------------------------------------------------------------------------

_HASH_TILE = 256


def _murmur3_kernel(words_ref, len_ref, seed_ref, out_ref, *, W: int):
    words = words_ref[:]                      # (T, W) int32
    lens = len_ref[:]                         # (T, 1) int32
    h1 = seed_ref[:]                          # (T, 1) int32 running hash
    n_words = lens // 4
    n_tail = lens % 4
    # whole-word rounds, statically unrolled; rows shorter than column i
    # keep their running hash through a vector select
    for i in range(W):
        k = words[:, i:i + 1]
        h1 = jnp.where(i < n_words, _mix_h1(h1, _mix_k1(k)), h1)
    # the tail word (index n_words, per row) via static-column selects —
    # a dynamic per-row gather would not vectorize on the VPU
    tail_word = jnp.zeros_like(lens)
    for i in range(W):
        tail_word = jnp.where(n_words == i, words[:, i:i + 1], tail_word)
    for t in range(3):
        byte = lax.shift_right_logical(tail_word,
                                       jnp.int32(8 * t)) & jnp.int32(0xFF)
        sbyte = jnp.where(byte >= 128, byte - 256, byte)
        h1 = jnp.where(t < n_tail, _mix_h1(h1, _mix_k1(sbyte)), h1)
    out_ref[:] = _fmix(h1, lens)


def murmur3_words(words, lengths, seed) -> jnp.ndarray:
    """Spark Murmur3_x86_32.hashUnsafeBytes over packed word rows, as a
    Pallas kernel. Same contract as ops.hashing.hash_string_words:
    words (n, W) int32 little-endian UTF-8, lengths (n,) int32 → (n,) int32.
    `seed` may be a scalar or a per-row (n,) running hash (the partitioner
    chains column hashes, so the seed is usually row-varying).
    """
    n, W = words.shape
    tile = min(_HASH_TILE, max(8, n))
    n_pad = -(-n // tile) * tile
    words_p = jnp.zeros((n_pad, W), jnp.int32).at[:n].set(
        words.astype(jnp.int32))
    lens_p = jnp.zeros((n_pad, 1), jnp.int32).at[:n, 0].set(
        lengths.astype(jnp.int32))
    seed_rows = jnp.broadcast_to(jnp.asarray(seed, jnp.int32), (n,))
    seed_p = jnp.zeros((n_pad, 1), jnp.int32).at[:n, 0].set(seed_rows)
    out = pl.pallas_call(
        functools.partial(_murmur3_kernel, W=W),
        out_shape=jax.ShapeDtypeStruct((n_pad, 1), jnp.int32),
        grid=(n_pad // tile,),
        in_specs=[
            pl.BlockSpec((tile, W), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((tile, 1), lambda i: (i, 0)),
        interpret=_interpret(),
    )(words_p, lens_p, seed_p)
    return out[:n, 0]


# ---------------------------------------------------------------------------
# parquet bit-unpack
# ---------------------------------------------------------------------------

_UNPACK_TILE = 64  # rows of 128 values → 8192 values per grid step


def _bitunpack_kernel(w_ref, out_ref, *, bw: int):
    w = w_ref[:]                              # (T, 4*bw) int32 words
    mask = jnp.int32((1 << bw) - 1) if bw < 32 else jnp.int32(-1)
    cols = []
    for j in range(128):
        off = j * bw
        w0, sh = off >> 5, off & 31
        v = lax.shift_right_logical(w[:, w0:w0 + 1], jnp.int32(sh))
        if sh + bw > 32:                      # value spans two words
            v = v | lax.shift_left(w[:, w0 + 1:w0 + 2], jnp.int32(32 - sh))
        cols.append(v & mask)
    out_ref[:] = jnp.concatenate(cols, axis=1)


def bitunpack128(words_u32, bit_width: int, n: int, capacity: int):
    """Unpack `n` bit-packed values of `bit_width` bits from 32-bit words
    into (capacity,) int32. 128 values of width bw span exactly 4*bw words,
    so the kernel reads only statically-indexed columns.

    words_u32: (ceil(n/128)*4*bw,) int32 — packed little-endian words.
    """
    if not 1 <= bit_width <= 32:
        raise ValueError(f"bit width {bit_width} out of range")
    bw = bit_width
    n128 = max(1, -(-n // 128))
    tile = min(_UNPACK_TILE, n128)
    rows = -(-n128 // tile) * tile
    need = rows * 4 * bw
    # a legal parquet chunk's final bit-packed run may declare more 8-value
    # groups than remaining values — the packed buffer can be LONGER than
    # `need`; truncate before writing into the padded buffer
    k = min(words_u32.shape[0], need)
    w = jnp.zeros((need,), jnp.int32).at[:k].set(
        words_u32[:k].astype(jnp.int32)).reshape(rows, 4 * bw)
    out = pl.pallas_call(
        functools.partial(_bitunpack_kernel, bw=bw),
        out_shape=jax.ShapeDtypeStruct((rows, 128), jnp.int32),
        grid=(rows // tile,),
        in_specs=[pl.BlockSpec((tile, 4 * bw), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((tile, 128), lambda i: (i, 0)),
        interpret=_interpret(),
    )(w)
    flat = out.reshape(-1)
    idx = jnp.arange(capacity, dtype=jnp.int32)
    safe = jnp.clip(idx, 0, flat.shape[0] - 1)
    return jnp.where(idx < n, flat[safe], 0)


def bytes_to_words_u32(packed: np.ndarray) -> np.ndarray:
    """Host prep: pad a uint8 byte buffer to 4-byte alignment and view as
    little-endian int32 words for bitunpack128."""
    nb = len(packed)
    pad = -nb % 4
    if pad:
        packed = np.concatenate([packed, np.zeros(pad, np.uint8)])
    return packed.view("<i4").astype(np.int32)


# ---------------------------------------------------------------------------
# blocked one-hot matmul (medium-domain dense group-by / histogram)
# ---------------------------------------------------------------------------

_OH_BK = 1024    # row-block (codes/values) per grid step
_OH_BD = 128     # domain lanes per grid step (one MXU/VPU lane tile)


def _onehot_kernel(codes_ref, vals_ref, out_ref, *, bk: int):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    d0 = pl.program_id(0) * _OH_BD
    codes = codes_ref[0, :]                    # (bk,) int32
    vals = vals_ref[0, :]                      # (bk,) f32
    lanes = d0 + lax.broadcasted_iota(jnp.int32, (bk, _OH_BD), 1)
    onehot = (codes[:, None] == lanes).astype(jnp.float32)
    out_ref[0, :] += jnp.dot(vals, onehot,
                             preferred_element_type=jnp.float32)


def onehot_sum_f32(vals, codes, n_domain: int):
    """(n_domain,) f32 bucket sums of `vals` over int32 `codes` — the
    generalized one-hot-matmul group-by (VERDICT r4 next #7; reference
    analog: cudf's hash groupby behind aggregate.scala:706).

    The jnp formulation in ops/grouping.dense_group_sum materializes the
    (cap, D) one-hot in HBM — fine at D<=128, ruinous at medium domains.
    This kernel generates each (BK, 128) one-hot tile on the fly in VMEM
    and feeds the MXU, cutting HBM traffic from O(cap*D) one-hot elements
    to O(cap * D/128) input re-streams (rows stream once per 128-lane
    domain block) + O(D) output; nothing is scattered (the round-2 wedge
    lesson), and every shape is static.

    Exactness: f32 accumulation — callers use it for 0/1 histograms and
    per-batch counts (exact below 2^24) and f32 sums; f64 sums stay on the
    jnp path."""
    cap = vals.shape[0]
    # lane-aligned row block: Mosaic wants multiples of 128 (the probe's
    # aligned instance would not catch a misaligned caller)
    bk = min(_OH_BK, -(-max(cap, 128) // 128) * 128)
    capp = -(-cap // bk) * bk
    dp = -(-n_domain // _OH_BD) * _OH_BD
    codes2 = jnp.full((1, capp), -1, jnp.int32).at[0, :cap].set(
        codes.astype(jnp.int32))
    vals2 = jnp.zeros((1, capp), jnp.float32).at[0, :cap].set(
        vals.astype(jnp.float32))
    out = pl.pallas_call(
        functools.partial(_onehot_kernel, bk=bk),
        out_shape=jax.ShapeDtypeStruct((1, dp), jnp.float32),
        grid=(dp // _OH_BD, capp // bk),
        in_specs=[pl.BlockSpec((1, bk), lambda i, k: (0, k)),
                  pl.BlockSpec((1, bk), lambda i, k: (0, k))],
        out_specs=pl.BlockSpec((1, _OH_BD), lambda i, k: (0, i)),
        interpret=_interpret(),
    )(codes2, vals2)
    return out[0, :n_domain]


# ---------------------------------------------------------------------------
# radix partition (stable counting-sort ranks over small partition domains)
# ---------------------------------------------------------------------------

_RP_BK = 256            # max rows per grid step
_RP_TILE_BUDGET = 1 << 19  # one-hot tile elements (2 MB i32): bk*dp bound
RADIX_MAX_PARTS = 4096  # lane cap (hash_join_buckets tops out here)


def _radix_kernel(ids_ref, rank_ref, counts_ref, *, bk: int, dp: int):
    """One grid step over a row tile. The per-partition running count
    (`counts_ref`, one block revisited every step — the sequential TPU grid
    is the carry chain) turns per-tile exclusive one-hot cumsums into global
    stable ranks: rank(row) = rows with the same id in earlier tiles +
    same-id rows above it in this tile. All dense (BK, DP) VPU work — the
    scatter that cudf's radix partition would do stays outside the kernel."""
    k = pl.program_id(0)

    @pl.when(k == 0)
    def _init():
        counts_ref[...] = jnp.zeros_like(counts_ref)

    ids = ids_ref[0, :]                                   # (bk,) int32
    lanes = lax.broadcasted_iota(jnp.int32, (bk, dp), 1)
    onehot = (ids[:, None] == lanes).astype(jnp.int32)    # out-of-range → 0s
    carry = counts_ref[0, :]                              # (dp,) prior tiles
    incl = jnp.cumsum(onehot, axis=0)
    rank_ref[0, :] = jnp.sum(onehot * (incl - onehot + carry[None, :]),
                             axis=1, dtype=jnp.int32)
    counts_ref[0, :] = carry + incl[-1, :]


def radix_ranks(ids, num_lanes: int):
    """Stable radix ranks: for int32 `ids` in [0, num_lanes), returns
    (ranks, counts) where ranks[i] = #{j < i : ids[j] == ids[i]} and
    counts[l] = #{ids == l}. Ids outside [0, num_lanes) (padding sentinel)
    get rank 0 and are not counted."""
    cap = ids.shape[0]
    dp = -(-max(num_lanes, 1) // 128) * 128
    if dp > RADIX_MAX_PARTS:
        raise ValueError(f"radix domain {num_lanes} exceeds {RADIX_MAX_PARTS}")
    bk = min(_RP_BK, max(8, cap), max(8, _RP_TILE_BUDGET // dp))
    n_pad = -(-cap // bk) * bk
    # out-of-range ids (incl. callers' padding sentinels) map to id=dp —
    # no lane match, so zero rank and zero count; dp-pad lanes beyond
    # num_lanes must not silently rank rows either
    ids = ids.astype(jnp.int32)
    ids = jnp.where((ids >= 0) & (ids < num_lanes), ids, jnp.int32(dp))
    ids_p = jnp.full((1, n_pad), dp, jnp.int32).at[0, :cap].set(ids)
    ranks, counts = pl.pallas_call(
        functools.partial(_radix_kernel, bk=bk, dp=dp),
        out_shape=[jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
                   jax.ShapeDtypeStruct((1, dp), jnp.int32)],
        grid=(n_pad // bk,),
        in_specs=[pl.BlockSpec((1, bk), lambda i: (0, i))],
        out_specs=[pl.BlockSpec((1, bk), lambda i: (0, i)),
                   pl.BlockSpec((1, dp), lambda i: (0, 0))],
        interpret=_interpret(),
    )(ids_p)
    return ranks[0, :cap], counts[0, :num_lanes]


def radix_partition_permutation(ids, num_lanes: int):
    """Stable permutation grouping rows by id (== argsort(ids, stable) for
    ids in [0, num_lanes)) via the radix-rank kernel plus one 1:1 scatter —
    the GpuPartitioning.sliceInternalOnGpu radix analog, replacing the
    comparator `lax.sort` the partition step otherwise pays."""
    cap = ids.shape[0]
    ranks, counts = radix_ranks(ids, num_lanes)
    offsets = jnp.cumsum(counts) - counts                 # exclusive
    dest = offsets[jnp.clip(ids, 0, num_lanes - 1)] + ranks
    return jnp.zeros((cap,), jnp.int32).at[dest].set(
        jnp.arange(cap, dtype=jnp.int32), mode="drop")


# ---------------------------------------------------------------------------
# VMEM hash-table join build + probe (unique fixed-point keys)
# ---------------------------------------------------------------------------

HJ_SLOTS = 8            # bucket capacity; build falls back above this load
_HJ_TILE = 8192         # stream rows per grid step: big tiles keep the grid
#                         short (interpret mode pays per-step overhead; the
#                         (tile, HJ_SLOTS) gather is ~512 KB in VMEM)
_HJ_EMPTY = np.int64(np.iinfo(np.int64).min)  # slot sentinel (engage gate
#                                               requires vmin > int64 min)
# Fibonacci multiplicative constant 0x9E3779B97F4A7C15 as a signed int64
_HJ_MULT = np.int64(np.uint64(0x9E3779B97F4A7C15).astype(np.int64))


def _hj_bucket(vals_i64, h_bits: int):
    h = vals_i64 * _HJ_MULT
    return lax.shift_right_logical(h, jnp.int64(64 - h_bits)).astype(jnp.int32)


def hash_join_build(keys_i64, eligible, num_buckets: int):
    """Build the (num_buckets, HJ_SLOTS) open hash table over unique int64
    keys: bucket = Fibonacci hash of the key, slot = the key's stable radix
    rank within its bucket (the radix kernel again — build IS a radix
    partition by hash bucket). Returns (table_keys, table_rows, ok) flat
    (H*S,) arrays + a device scalar; ok=False means a bucket overflowed
    HJ_SLOTS and the table must be discarded (caller falls back to the
    searchsorted probe). cudf's innerJoinGatherMaps builds the same shape
    with atomics (GpuHashJoin.scala:289); here the bucket ranks come from
    the sequential-grid carry chain instead."""
    if num_buckets & (num_buckets - 1) or num_buckets < 128:
        raise ValueError(f"num_buckets {num_buckets}: need a power of two >= 128")
    h_bits = num_buckets.bit_length() - 1
    cap = keys_i64.shape[0]
    bucket = jnp.where(eligible, _hj_bucket(keys_i64, h_bits),
                       jnp.int32(num_buckets))            # sentinel lane
    ranks, counts = radix_ranks(bucket, num_buckets)
    ok = jnp.max(counts) <= HJ_SLOTS
    slot = bucket * HJ_SLOTS + jnp.minimum(ranks, HJ_SLOTS - 1)
    slot = jnp.where(eligible, slot, jnp.int32(num_buckets * HJ_SLOTS))
    table_keys = jnp.full((num_buckets * HJ_SLOTS,), _HJ_EMPTY,
                          jnp.int64).at[slot].set(keys_i64, mode="drop")
    table_rows = jnp.full((num_buckets * HJ_SLOTS,), -1,
                          jnp.int32).at[slot].set(
        jnp.arange(cap, dtype=jnp.int32), mode="drop")
    # duplicate keys land in one bucket (same hash) with distinct ranks: the
    # unique-keys probe contract would silently under-count them, so the
    # build refuses — S*(S-1)/2 static column compares over the table
    t2 = table_keys.reshape(num_buckets, HJ_SLOTS)
    dup = jnp.zeros((), jnp.bool_)
    for s in range(HJ_SLOTS):
        for t in range(s + 1, HJ_SLOTS):
            dup = dup | jnp.any((t2[:, s] == t2[:, t])
                                & (t2[:, s] != _HJ_EMPTY))
    return table_keys, table_rows, ok & ~dup


def _hash_probe_kernel(sk_ref, tk_ref, tr_ref, pos_ref, found_ref,
                       *, h_bits: int):
    """Probe one stream tile against the whole table (resident in VMEM —
    both table blocks map to (0, 0) every grid step). The slot loop unrolls
    statically; the only dynamic access is the per-row bucket gather, the
    same class as the engine's dictionary-decode gathers."""
    svals = sk_ref[0, :]                                  # (T,) int64
    base = _hj_bucket(svals, h_bits) * HJ_SLOTS
    tk = tk_ref[0, :]
    tr = tr_ref[0, :]
    pos = jnp.full(svals.shape, -1, jnp.int32)
    found = jnp.zeros(svals.shape, jnp.bool_)
    for s in range(HJ_SLOTS):
        cand = tk[base + s]
        hit = cand == svals                               # EMPTY never matches
        pos = jnp.where(hit, tr[base + s], pos)
        found = found | hit
    pos_ref[0, :] = pos
    found_ref[0, :] = found.astype(jnp.int32)


def hash_join_probe(table_keys, table_rows, stream_i64, num_buckets: int):
    """(build_row, found) per stream key — the innerJoinGatherMaps probe.
    Unique-keys contract: at most one slot matches. Validity/liveness
    masking is the caller's job (hash of an invalid row's value is
    harmless; its hit is masked off outside)."""
    h_bits = num_buckets.bit_length() - 1
    n = stream_i64.shape[0]
    tile = min(_HJ_TILE, max(8, n))
    n_pad = -(-n // tile) * tile
    hs = num_buckets * HJ_SLOTS
    sp = jnp.zeros((1, n_pad), jnp.int64).at[0, :n].set(stream_i64)
    pos, found = pl.pallas_call(
        functools.partial(_hash_probe_kernel, h_bits=h_bits),
        out_shape=[jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
                   jax.ShapeDtypeStruct((1, n_pad), jnp.int32)],
        grid=(n_pad // tile,),
        in_specs=[
            pl.BlockSpec((1, tile), lambda i: (0, i)),
            pl.BlockSpec((1, hs), lambda i: (0, 0)),
            pl.BlockSpec((1, hs), lambda i: (0, 0)),
        ],
        out_specs=[pl.BlockSpec((1, tile), lambda i: (0, i)),
                   pl.BlockSpec((1, tile), lambda i: (0, i))],
        interpret=_interpret(),
    )(sp, table_keys.reshape(1, hs), table_rows.reshape(1, hs))
    return pos[0, :n], found[0, :n].astype(jnp.bool_)


def hash_join_buckets(n_build: int) -> int:
    """Bucket count for a build of `n_build` rows: ~0.25 load factor over
    HJ_SLOTS-deep buckets, clamped to the VMEM table budget. Returns 0 when
    the build cannot meet the load factor (too big — caller falls back)."""
    want = 128
    while want * HJ_SLOTS < 4 * max(n_build, 1) and want < 4096:
        want *= 2
    if want * HJ_SLOTS < 2 * n_build:   # >0.5 load: overflow too likely
        return 0
    return want
