"""Equi-join gather-map kernels under XLA's static-shape regime.

Reference (SURVEY.md component #16): GpuHashJoin.scala:289 calls cudf
`innerJoinGatherMaps` / `leftJoinGatherMaps` etc — hash-table probes producing
data-dependent-size gather maps, iterated out-of-core by JoinGatherer.scala.

TPU-native design (no hash tables — irregular memory access is hostile to the MXU/VPU;
sorts and searches are XLA-native):

1. **Dense ranks**: concatenate build+stream key rows and run ONE fused multi-key sort
   (ops.grouping.group_segments); equal key tuples — with Spark's NaN==NaN and
   null-grouping semantics — get equal dense ranks. Rank equality IS key-tuple
   equality (collision-free, unlike hashing).
2. **Range probe**: sort build ranks once; per stream row `searchsorted` left/right
   gives its contiguous match range [lo, hi) — the "gather map" is implicit.
3. **Bounded expansion**: pair j maps to stream row i = searchsorted(cumsum(counts), j)
   and build slot lo[i] + (j - start[i]); expansion is chunked to a fixed output
   capacity so one compiled program serves any join size (the JoinGatherer analog).

Join-type semantics (Spark):
- nulls in keys never match (EqualTo); NaN matches NaN; -0.0 == 0.0 (canonicalized);
- LeftOuter emits unmatched stream rows null-extended; FullOuter additionally emits
  unmatched build rows (computed by the symmetric probe, no scatter);
- LeftSemi emits each matching stream row once; LeftAnti the non-matching ones.
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Col
from spark_rapids_tpu.ops.grouping import group_segments

INNER = "inner"
LEFT_OUTER = "leftouter"
RIGHT_OUTER = "rightouter"
FULL_OUTER = "fullouter"
LEFT_SEMI = "leftsemi"
LEFT_ANTI = "leftanti"
CROSS = "cross"

# plain ints (weak-typed under jnp ops): creating jnp scalars at import time
# would initialize the default jax backend before a process has a chance to
# select its platform (MiniCluster executors force CPU after import)
_BUILD_NULL_RANK = -2
_STREAM_NULL_RANK = -1
_PAD_RANK = 2**31 - 1


def _concat_key_cols(build_keys, stream_keys):
    out = []
    for b, s in zip(build_keys, stream_keys):
        vals = jnp.concatenate([b.values, s.values])
        valid = jnp.concatenate([b.validity, s.validity])
        out.append(Col(vals, valid, b.dtype, b.dictionary))
    return out


def join_ranks(build_keys, n_build, build_cap, stream_keys, n_stream, stream_cap):
    """Dense ranks for both sides such that rank equality == key-tuple equality.
    Null-keyed rows get side-specific sentinel ranks so they never match; padding
    gets +inf rank. Returns (build_ranks, stream_ranks) int32 arrays."""
    total_cap = build_cap + stream_cap
    both = _concat_key_cols(build_keys, stream_keys)
    # live across the concatenated array: build rows [0,n_build), stream rows
    # [build_cap, build_cap+n_stream)
    idx = jnp.arange(total_cap, dtype=jnp.int32)
    live = jnp.where(idx < build_cap, idx < n_build, (idx - build_cap) < n_stream)
    # group_segments sorts with padding sunk by its own live test (arange < num_rows),
    # so feed it a permutation-friendly row count: instead we sort all rows and mask
    # afterwards — pass num_rows=total_cap and handle liveness via rank sentinels.
    perm, seg_ids, boundary, _ = group_segments(both, jnp.int32(total_cap), total_cap)
    ranks = jnp.zeros((total_cap,), jnp.int32).at[perm].set(seg_ids)
    any_null = jnp.zeros((total_cap,), jnp.bool_)
    for c in both:
        any_null = any_null | ~c.validity
    is_build = idx < build_cap
    ranks = jnp.where(any_null, jnp.where(is_build, _BUILD_NULL_RANK,
                                          _STREAM_NULL_RANK), ranks)
    ranks = jnp.where(live, ranks, _PAD_RANK)
    return ranks[:build_cap], ranks[build_cap:]


def probe(build_ranks, stream_ranks):
    """Sorted-build probe. Returns (build_perm, lo, hi) with lo/hi per stream row."""
    build_perm = jnp.argsort(build_ranks, stable=True)
    sorted_build = build_ranks[build_perm]
    lo = jnp.searchsorted(sorted_build, stream_ranks, side="left")
    hi = jnp.searchsorted(sorted_build, stream_ranks, side="right")
    # null/pad sentinels never match: stream sentinel ranks are negative/huge and
    # distinct from build sentinels, but guard explicitly for safety
    bad = (stream_ranks == _STREAM_NULL_RANK) | (stream_ranks == _PAD_RANK)
    hi = jnp.where(bad, lo, hi)
    return build_perm, lo, hi


def pair_counts(lo, hi, n_stream, stream_cap, join_type):
    """Per-stream-row emitted pair count for the join type."""
    live = jnp.arange(stream_cap, dtype=jnp.int32) < n_stream
    matches = (hi - lo).astype(jnp.int32)
    if join_type in (INNER,):
        counts = matches
    elif join_type in (LEFT_OUTER, FULL_OUTER):
        counts = jnp.maximum(matches, 1)
    elif join_type == LEFT_SEMI:
        counts = jnp.minimum(matches, 1)
    elif join_type == LEFT_ANTI:
        counts = (matches == 0).astype(jnp.int32)
    else:
        raise ValueError(f"unsupported join type for pair_counts: {join_type}")
    return jnp.where(live, counts, 0)


def expand_pairs(build_perm, lo, hi, counts, start_pair: int, out_cap: int):
    """Materialize pairs [start_pair, start_pair+out_cap) as
    (stream_idx, build_idx, build_matched, pair_live).

    build_matched=False marks null-extension slots of outer joins. One compiled
    program serves every chunk (static out_cap) — the JoinGatherer iteration."""
    offsets = jnp.cumsum(counts)  # inclusive
    total = offsets[-1]
    j = jnp.arange(out_cap, dtype=jnp.int32) + jnp.int32(start_pair)
    stream_idx = jnp.searchsorted(offsets, j, side="right").astype(jnp.int32)
    stream_idx_c = jnp.clip(stream_idx, 0, counts.shape[0] - 1)
    starts = offsets - counts
    within = j - starts[stream_idx_c]
    n_matches = (hi - lo)[stream_idx_c]
    build_matched = within < n_matches
    b_pos = jnp.clip(lo[stream_idx_c] + jnp.minimum(within, n_matches - 1), 0,
                     build_perm.shape[0] - 1)
    build_idx = build_perm[b_pos]
    pair_live = j < total
    return stream_idx_c, build_idx, build_matched & pair_live, pair_live


def total_pairs(counts):
    return jnp.sum(counts)
