"""Multi-key sort with Spark ordering semantics.

Reference: GpuSortExec.scala:56 + SortUtils.scala over cudf Table.orderBy. Spark
ordering rules implemented here (the reference encodes the same in cudf flags):
- per-key ASC/DESC with explicit NULLS FIRST/LAST;
- floats: NaN is greater than every value (incl. +inf), NaN == NaN, -0.0 == 0.0;
- strings sort by dictionary code (dictionary is sorted, so code order == UTF-8
  lexicographic — actually python str order; matches Spark's UTF8String binary order
  for the ASCII range).

TPU-first notes: lax.sort is a single fused XLA sort over multiple key operands; no
f64→i64 bitcast (unsupported under the TPU x64 rewrite), so float keys stay float with
NaN lifted into a separate int8 key; padding rows carry a leading pad-rank key so they
always sink to the end regardless of key direction.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Col


@dataclasses.dataclass(frozen=True)
class SortOrder:
    ascending: bool = True
    nulls_first: bool = None  # default: first when asc, last when desc (Spark)

    @property
    def resolved_nulls_first(self):
        return self.ascending if self.nulls_first is None else self.nulls_first


def _key_arrays(c: Col, order: SortOrder):
    """Key operands for one sort column, in significance order."""
    keys = []
    nf = order.resolved_nulls_first
    null_rank = jnp.where(c.validity, jnp.int8(1 if nf else 0),
                          jnp.int8(0 if nf else 1))
    keys.append(null_rank)
    vals = c.values
    if isinstance(c.dtype, T.FractionalType):
        nan = jnp.isnan(vals)
        # NaN largest: rank 1 after all finite for asc; first (rank 0) for desc
        nan_rank = jnp.where(nan, jnp.int8(1), jnp.int8(0))
        if not order.ascending:
            nan_rank = jnp.int8(1) - nan_rank
        keys.append(nan_rank)
        vals = jnp.where(nan, jnp.zeros_like(vals), vals)
        vals = jnp.where(vals == 0, jnp.zeros_like(vals), vals)  # -0.0 → 0.0
        if not order.ascending:
            vals = -vals
    elif isinstance(c.dtype, T.BooleanType):
        v8 = vals.astype(jnp.int8)
        vals = v8 if order.ascending else (jnp.int8(1) - v8)
    else:
        if not order.ascending:
            vals = ~vals  # order-reversing, overflow-free for ints
    keys.append(vals)
    return keys


def _key_bits(c: Col) -> int | None:
    """Static bit-width of one key column's order-preserving unsigned image,
    or None if it cannot be packed (wide ints, floats)."""
    if c.is_string and c.dictionary is not None:
        d = max(len(c.dictionary), 1)
        return max(d - 1, 1).bit_length()
    if isinstance(c.dtype, T.BooleanType):
        return 1
    if isinstance(c.dtype, T.IntegralType) or isinstance(c.dtype, T.DateType):
        w = jnp.iinfo(c.values.dtype).bits
        return w + 1 if w <= 32 else None  # +1: bias to unsigned
    return None


def _packed_key(key_cols, orders, num_rows, capacity: int,
                range_hint=None):
    """Pack (pad-rank, per-key null-rank + value image, row index) into ONE
    int64 sort operand. lax.sort cost grows steeply with operand count
    (~4x from 1 to 4 operands at 256k rows on both CPU and TPU backends), so
    a single packed operand with the row index in the low bits — uniqueness
    makes stability free — is the fast path whenever the static widths fit.
    Returns None when the keys cannot be packed order-faithfully.

    `range_hint=(vmin, vmax_minus_vmin_fits)` (single int key only) lets a
    caller that already paid a range reduction + host sync (the join-build
    pattern, exec/aggregate.py) pack a statically-too-wide int64 key as
    `value - vmin`: vmin rides in as a TRACED scalar so one compiled
    program serves every in-range batch."""
    iota_bits = max((capacity - 1).bit_length(), 1)
    if (range_hint is not None and len(key_cols) == 1
            and isinstance(key_cols[0].dtype,
                           (T.IntegralType, T.DateType, T.TimestampType))
            and not isinstance(key_cols[0].dtype, T.BooleanType)):
        vmin, fits = range_hint
        if fits:
            c, o = key_cols[0], orders[0]
            w = 62 - iota_bits - 1      # value bits left beside the ranks
            nf = o.resolved_nulls_first
            acc = (jnp.arange(capacity, dtype=jnp.int32)
                   >= num_rows).astype(jnp.int64)
            null_rank = jnp.where(c.validity, jnp.int64(1 if nf else 0),
                                  jnp.int64(0 if nf else 1))
            acc = (acc << 1) | null_rank
            u = c.values.astype(jnp.int64) - vmin
            u = jnp.clip(u, 0, (1 << w) - 1)
            u = jnp.where(c.validity, u, 0)
            if not o.ascending:
                u = ((1 << w) - 1) - u
            acc = (acc << w) | u
            return ((acc << iota_bits)
                    | jnp.arange(capacity, dtype=jnp.int64)), iota_bits
    total = 1 + iota_bits  # pad rank + tiebreaker
    widths = []
    for c in key_cols:
        w = _key_bits(c)
        if w is None:
            return None
        widths.append(w)
        total += 1 + w  # null rank + value image
    if total > 63:
        return None
    acc = (jnp.arange(capacity, dtype=jnp.int32) >= num_rows).astype(jnp.int64)
    for c, o, w in zip(key_cols, orders, widths):
        nf = o.resolved_nulls_first
        # nulls-first → nulls rank 0 (before valid rows), else after
        null_rank = jnp.where(c.validity, jnp.int64(1 if nf else 0),
                              jnp.int64(0 if nf else 1))
        acc = (acc << 1) | null_rank
        if isinstance(c.dtype, T.BooleanType):
            u = c.values.astype(jnp.int64)
        elif c.is_string:
            u = c.values.astype(jnp.int64)
        else:
            u = c.values.astype(jnp.int64) + (1 << (w - 1))
        u = jnp.clip(u, 0, (1 << w) - 1)
        u = jnp.where(c.validity, u, 0)
        if not o.ascending:
            u = ((1 << w) - 1) - u
        acc = (acc << w) | u
    return (acc << iota_bits) | jnp.arange(capacity, dtype=jnp.int64), iota_bits


def _wide_single_key(key_cols, orders, num_rows, capacity: int):
    """Single int key too wide for the packed operand (int64/timestamp):
    TWO int64 operands instead of the 4-operand stable comparator sort
    (~2.6x cheaper at 1M rows). Operand 1 is the order image with null/pad
    rows forced to the extremes; operand 2 carries (rank, row-index) so
    rank ties between a real extreme value, a null, and padding resolve
    correctly and the unique index makes stability free."""
    if len(key_cols) != 1:
        return None
    c, o = key_cols[0], orders[0]
    if (not isinstance(c.dtype, (T.IntegralType, T.DateType,
                                 T.TimestampType))
            or isinstance(c.dtype, T.BooleanType)):
        return None
    if _key_bits(c) is not None:
        return None   # narrow enough for the packed path
    big = jnp.iinfo(jnp.int64).max
    small = jnp.iinfo(jnp.int64).min
    v = c.values.astype(jnp.int64)
    if not o.ascending:
        v = ~v        # order-reversing, overflow-free
    nf = o.resolved_nulls_first
    v = jnp.where(c.validity, v, small if nf else big)
    live = jnp.arange(capacity, dtype=jnp.int32) < num_rows
    v = jnp.where(live, v, big)
    # rank: valid 1; nulls 0 (first) or 2 (last); padding 3 — dominates
    # operand-1 ties against real extreme values
    rank = jnp.where(c.validity, jnp.int64(1),
                     jnp.int64(0 if nf else 2))
    rank = jnp.where(live, rank, jnp.int64(3))
    iota_bits = max((capacity - 1).bit_length(), 1)
    op2 = (rank << iota_bits) | jnp.arange(capacity, dtype=jnp.int64)
    _, s2 = lax.sort((v, op2), num_keys=2, is_stable=False)
    return (s2 & ((1 << iota_bits) - 1)).astype(jnp.int32)


def sort_permutation(key_cols, orders, num_rows, capacity: int,
                     range_hint=None):
    """Stable permutation sorting live rows by keys; padding sinks to the end."""
    packed = _packed_key(key_cols, orders, num_rows, capacity,
                         range_hint=range_hint)
    if packed is not None:
        key, iota_bits = packed
        (s,) = lax.sort((key,), num_keys=1, is_stable=False)
        return (s & ((1 << iota_bits) - 1)).astype(jnp.int32)
    wide = _wide_single_key(key_cols, orders, num_rows, capacity)
    if wide is not None:
        return wide
    pad_rank = (jnp.arange(capacity, dtype=jnp.int32) >= num_rows).astype(jnp.int8)
    operands = [pad_rank]
    for c, o in zip(key_cols, orders):
        operands.extend(_key_arrays(c, o))
    iota = jnp.arange(capacity, dtype=jnp.int32)
    res = lax.sort(tuple(operands) + (iota,), num_keys=len(operands), is_stable=True)
    return res[-1]


def sort_cols(cols, key_indices, orders, num_rows, capacity):
    from spark_rapids_tpu.ops.filtering import gather_cols
    perm = sort_permutation([cols[i] for i in key_indices], orders, num_rows, capacity)
    live = jnp.arange(capacity, dtype=jnp.int32) < num_rows
    return gather_cols(cols, perm, live)


def partition_permutation(part_ids, num_partitions: int, num_rows,
                          capacity: int):
    """Stable permutation grouping live rows by partition id with padding
    sunk to the end — the exchange partition step. Ids are a tiny dense
    domain, so a comparator sort is overkill: when the radix latch is up
    the Pallas counting-rank kernel (pallas_kernels.radix_partition_permutation)
    produces the permutation from one-hot cumsums; otherwise the stable
    argsort stands in."""
    from spark_rapids_tpu.ops import pallas_kernels as PK
    live = jnp.arange(capacity, dtype=jnp.int32) < num_rows
    ids = jnp.where(live, part_ids.astype(jnp.int32),
                    jnp.int32(num_partitions))
    if (num_partitions + 1 <= PK.RADIX_MAX_PARTS
            and PK.should_use("radix")):
        return PK.radix_partition_permutation(ids, num_partitions + 1)
    return jnp.argsort(ids, stable=True)
