"""Multi-key sort with Spark ordering semantics.

Reference: GpuSortExec.scala:56 + SortUtils.scala over cudf Table.orderBy. Spark
ordering rules implemented here (the reference encodes the same in cudf flags):
- per-key ASC/DESC with explicit NULLS FIRST/LAST;
- floats: NaN is greater than every value (incl. +inf), NaN == NaN, -0.0 == 0.0;
- strings sort by dictionary code (dictionary is sorted, so code order == UTF-8
  lexicographic — actually python str order; matches Spark's UTF8String binary order
  for the ASCII range).

TPU-first notes: lax.sort is a single fused XLA sort over multiple key operands; no
f64→i64 bitcast (unsupported under the TPU x64 rewrite), so float keys stay float with
NaN lifted into a separate int8 key; padding rows carry a leading pad-rank key so they
always sink to the end regardless of key direction.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Col


@dataclasses.dataclass(frozen=True)
class SortOrder:
    ascending: bool = True
    nulls_first: bool = None  # default: first when asc, last when desc (Spark)

    @property
    def resolved_nulls_first(self):
        return self.ascending if self.nulls_first is None else self.nulls_first


def _key_arrays(c: Col, order: SortOrder):
    """Key operands for one sort column, in significance order."""
    keys = []
    nf = order.resolved_nulls_first
    null_rank = jnp.where(c.validity, jnp.int8(1 if nf else 0),
                          jnp.int8(0 if nf else 1))
    keys.append(null_rank)
    vals = c.values
    if isinstance(c.dtype, T.FractionalType):
        nan = jnp.isnan(vals)
        # NaN largest: rank 1 after all finite for asc; first (rank 0) for desc
        nan_rank = jnp.where(nan, jnp.int8(1), jnp.int8(0))
        if not order.ascending:
            nan_rank = jnp.int8(1) - nan_rank
        keys.append(nan_rank)
        vals = jnp.where(nan, jnp.zeros_like(vals), vals)
        vals = jnp.where(vals == 0, jnp.zeros_like(vals), vals)  # -0.0 → 0.0
        if not order.ascending:
            vals = -vals
    elif isinstance(c.dtype, T.BooleanType):
        v8 = vals.astype(jnp.int8)
        vals = v8 if order.ascending else (jnp.int8(1) - v8)
    else:
        if not order.ascending:
            vals = ~vals  # order-reversing, overflow-free for ints
    keys.append(vals)
    return keys


def _key_bits(c: Col) -> int | None:
    """Static bit-width of one key column's order-preserving unsigned image,
    or None if it cannot be packed (wide ints, floats)."""
    if c.is_string and c.dictionary is not None:
        d = max(len(c.dictionary), 1)
        return max(d - 1, 1).bit_length()
    if isinstance(c.dtype, T.BooleanType):
        return 1
    if isinstance(c.dtype, T.IntegralType) or isinstance(c.dtype, T.DateType):
        w = jnp.iinfo(c.values.dtype).bits
        return w + 1 if w <= 32 else None  # +1: bias to unsigned
    return None


def _packed_key(key_cols, orders, num_rows, capacity: int):
    """Pack (pad-rank, per-key null-rank + value image, row index) into ONE
    int64 sort operand. lax.sort cost grows steeply with operand count
    (~4x from 1 to 4 operands at 256k rows on both CPU and TPU backends), so
    a single packed operand with the row index in the low bits — uniqueness
    makes stability free — is the fast path whenever the static widths fit.
    Returns None when the keys cannot be packed order-faithfully."""
    iota_bits = max((capacity - 1).bit_length(), 1)
    total = 1 + iota_bits  # pad rank + tiebreaker
    widths = []
    for c in key_cols:
        w = _key_bits(c)
        if w is None:
            return None
        widths.append(w)
        total += 1 + w  # null rank + value image
    if total > 63:
        return None
    acc = (jnp.arange(capacity, dtype=jnp.int32) >= num_rows).astype(jnp.int64)
    for c, o, w in zip(key_cols, orders, widths):
        nf = o.resolved_nulls_first
        # nulls-first → nulls rank 0 (before valid rows), else after
        null_rank = jnp.where(c.validity, jnp.int64(1 if nf else 0),
                              jnp.int64(0 if nf else 1))
        acc = (acc << 1) | null_rank
        if isinstance(c.dtype, T.BooleanType):
            u = c.values.astype(jnp.int64)
        elif c.is_string:
            u = c.values.astype(jnp.int64)
        else:
            u = c.values.astype(jnp.int64) + (1 << (w - 1))
        u = jnp.clip(u, 0, (1 << w) - 1)
        u = jnp.where(c.validity, u, 0)
        if not o.ascending:
            u = ((1 << w) - 1) - u
        acc = (acc << w) | u
    return (acc << iota_bits) | jnp.arange(capacity, dtype=jnp.int64), iota_bits


def sort_permutation(key_cols, orders, num_rows, capacity: int):
    """Stable permutation sorting live rows by keys; padding sinks to the end."""
    packed = _packed_key(key_cols, orders, num_rows, capacity)
    if packed is not None:
        key, iota_bits = packed
        (s,) = lax.sort((key,), num_keys=1, is_stable=False)
        return (s & ((1 << iota_bits) - 1)).astype(jnp.int32)
    pad_rank = (jnp.arange(capacity, dtype=jnp.int32) >= num_rows).astype(jnp.int8)
    operands = [pad_rank]
    for c, o in zip(key_cols, orders):
        operands.extend(_key_arrays(c, o))
    iota = jnp.arange(capacity, dtype=jnp.int32)
    res = lax.sort(tuple(operands) + (iota,), num_keys=len(operands), is_stable=True)
    return res[-1]


def sort_cols(cols, key_indices, orders, num_rows, capacity):
    from spark_rapids_tpu.ops.filtering import gather_cols
    perm = sort_permutation([cols[i] for i in key_indices], orders, num_rows, capacity)
    live = jnp.arange(capacity, dtype=jnp.int32) < num_rows
    return gather_cols(cols, perm, live)
