"""Device ORC decode kernels — MSB bit-unpack + zigzag as one jit.

Reference: GpuOrcScan.scala:375 copies stripe bytes and hands them to
libcudf's ORC decoder. TPU stage one (same split as ops/parquet_decode.py):
the RLEv2 run STRUCTURE is host metadata (io/orc_native.py); the packed
payload bits decode here. ORC packs values MSB-first (big-endian bit
order, unlike parquet's LSB-first), and widths vary per run, so the
kernel takes per-value bit offsets and widths: an 8-byte big-endian
window per value, one logical shift, one mask — pure vector ops.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def unpack_msb_device(packed: jnp.ndarray, bit_offsets: jnp.ndarray,
                      widths: jnp.ndarray, capacity: int) -> jnp.ndarray:
    """(bytes,) uint8 + per-value bit offsets/widths (MSB-first packing) →
    (capacity,) int64 raw (pre-zigzag) values. Widths must be ≤ 56 so the
    8-byte window always covers offset%8 + width bits."""
    nbytes = packed.shape[0]
    b0 = (bit_offsets >> 3).astype(jnp.int32)
    sh = (bit_offsets & 7).astype(jnp.int64)
    window = jnp.zeros((capacity,), jnp.int64)
    for k in range(8):
        byte = packed[jnp.clip(b0 + k, 0, nbytes - 1)].astype(jnp.int64)
        window = window | lax.shift_left(byte, jnp.int64(8 * (7 - k)))
    w = widths.astype(jnp.int64)
    shifted = lax.shift_right_logical(window, jnp.int64(64) - sh - w)
    mask = jnp.where(w >= 64, jnp.int64(-1),
                     lax.shift_left(jnp.int64(1), w) - 1)
    return shifted & mask


def zigzag_decode(v: jnp.ndarray) -> jnp.ndarray:
    return lax.shift_right_logical(v, jnp.int64(1)) ^ -(v & jnp.int64(1))


def decode_intv2_device(packed: jnp.ndarray, bit_offsets, widths,
                        const_mask, const_vals, signed: bool,
                        capacity: int) -> jnp.ndarray:
    """Merge device-unpacked DIRECT runs with host-decoded constant runs:
    positions with const_mask take const_vals; the rest unpack+zigzag."""
    raw = unpack_msb_device(packed, bit_offsets, widths, capacity)
    vals = zigzag_decode(raw) if signed else raw
    return jnp.where(const_mask, const_vals, vals)
