"""Window kernels — segmented scans over sorted partitions, all inside one XLA
program.

Reference: cudf rolling/window aggregation driven by GpuWindowExpression
(`.overWindow`:295, `windowAggregation`:847). cudf materializes per-row gather
windows; the TPU-native design instead sorts once and computes SEGMENTED SCANS.

Implementation note: jax.lax.associative_scan with a tuple carrier compiles
pathologically on the TPU toolchain here, so scans use (a) the native cumsum for
sums and (b) explicit Hillis-Steele log-step doubling (12 static steps at 4k
capacity: roll + where, all plain XLA ops) for max/min — O(n log n) work, tiny
programs, no data-dependent shapes:

  - unbounded-preceding → current (ROWS): segmented inclusive scan
  - RANGE ...→ current with ties: gather the scan value at each tie-group end
  - unbounded both: segment totals broadcast
  - sliding ROWS [p, f]: prefix-sum differences (sum/count/avg)
  - ranking: positions vs segment starts / tie-group starts
  - lead/lag: shifted gathers masked by partition membership
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T


def _doubling_scan(values, mask_fn, combine):
    """Inclusive scan by log-step doubling: out[i] = combine over the allowed
    prefix. mask_fn(idx, s) says whether out[i-s] may fold into out[i]."""
    cap = values.shape[0]
    idx = jnp.arange(cap, dtype=jnp.int32)
    out = values
    s = 1
    while s < cap:
        prev = jnp.roll(out, s)   # out[i-s]; head rows are masked off below
        out = jnp.where(mask_fn(idx, s), combine(prev, out), out)
        s <<= 1
    return out


def seg_starts(boundary):
    """Index of the segment start for every row."""
    idx = jnp.arange(boundary.shape[0], dtype=jnp.int32)
    marked = jnp.where(boundary, idx, jnp.int32(0))
    return _doubling_scan(marked, lambda i, s: i >= s, jnp.maximum)


def segmented_scan(values, boundary, combine):
    """Inclusive scan of `values` restarting where boundary=True."""
    start = seg_starts(boundary)
    return _doubling_scan(values, lambda i, s: (i - s) >= start, combine)


def seg_cumsum(values, boundary):
    """Segmented cumulative sum via ONE native cumsum + per-segment rebase
    (cheaper than doubling for the common sum/count scans)."""
    cs = jnp.cumsum(values, axis=0)
    start = seg_starts(boundary)
    base = jnp.where(start > 0, cs[jnp.maximum(start - 1, 0)],
                     jnp.zeros_like(cs[0]))
    return cs - base


def seg_cummax(values, boundary):
    return segmented_scan(values, boundary, jnp.maximum)


def seg_cummin(values, boundary):
    return segmented_scan(values, boundary, jnp.minimum)


def tie_group_ends(order_boundary, part_boundary):
    """For RANGE frames: last index of each row's order-key tie group within its
    partition (rows with equal order keys share the frame end — Spark RANGE
    CURRENT ROW includes ties)."""
    n = order_boundary.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    rev = lambda x: jnp.flip(x, 0)
    # a tie group ends where the NEXT row starts a new tie group (or at n-1)
    next_is_boundary = jnp.concatenate(
        [order_boundary[1:], jnp.ones((1,), jnp.bool_)])
    end_idx = jnp.where(next_is_boundary, idx, jnp.int32(0))
    # propagate each end backwards across its tie group: reversed segmented scan
    ends = rev(seg_cummax(rev(end_idx), rev(next_is_boundary)))
    return ends


def row_number(part_boundary, capacity):
    idx = jnp.arange(capacity, dtype=jnp.int32)
    return idx - seg_starts(part_boundary) + 1


def dense_rank(order_boundary, part_boundary):
    newgrp = order_boundary & ~part_boundary
    return seg_cumsum(newgrp.astype(jnp.int32), part_boundary) + 1


def rank(order_boundary, part_boundary, capacity):
    idx = jnp.arange(capacity, dtype=jnp.int32)
    start = seg_starts(part_boundary)
    tie_start = seg_cummax(jnp.where(order_boundary, idx, jnp.int32(0)),
                           part_boundary)
    return tie_start - start + 1


def shift_within_partition(values, validity, seg_ids, offset: int, capacity: int,
                           fill_value, fill_valid):
    """lead (offset>0) / lag (offset<0) with partition-membership masking."""
    idx = jnp.arange(capacity, dtype=jnp.int32)
    src = idx + offset
    in_range = (src >= 0) & (src < capacity)
    src_c = jnp.clip(src, 0, capacity - 1)
    same_part = in_range & (seg_ids[src_c] == seg_ids)
    vals = jnp.where(same_part, values[src_c], fill_value)
    valid = jnp.where(same_part, validity[src_c], fill_valid)
    return vals, valid
