"""Window kernels — segmented scans over sorted partitions, all inside one XLA
program.

Reference: cudf rolling/window aggregation driven by GpuWindowExpression
(`.overWindow`:295, `windowAggregation`:847). cudf materializes per-row gather
windows; the TPU-native design instead sorts once and computes SEGMENTED SCANS.

Implementation note: jax.lax.associative_scan with a tuple carrier compiles
pathologically on the TPU toolchain here, so scans use (a) the native cumsum for
sums and (b) explicit Hillis-Steele log-step doubling (12 static steps at 4k
capacity: roll + where, all plain XLA ops) for max/min — O(n log n) work, tiny
programs, no data-dependent shapes:

  - unbounded-preceding → current (ROWS): segmented inclusive scan
  - RANGE ...→ current with ties: gather the scan value at each tie-group end
  - unbounded both: segment totals broadcast
  - sliding ROWS [p, f]: prefix-sum differences (sum/count/avg)
  - ranking: positions vs segment starts / tie-group starts
  - lead/lag: shifted gathers masked by partition membership
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T


def _doubling_scan(values, mask_fn, combine):
    """Inclusive scan by log-step doubling: out[i] = combine over the allowed
    prefix. mask_fn(idx, s) says whether out[i-s] may fold into out[i]."""
    cap = values.shape[0]
    idx = jnp.arange(cap, dtype=jnp.int32)
    out = values
    s = 1
    while s < cap:
        prev = jnp.roll(out, s)   # out[i-s]; head rows are masked off below
        out = jnp.where(mask_fn(idx, s), combine(prev, out), out)
        s <<= 1
    return out


def seg_starts(boundary):
    """Index of the segment start for every row: the most recent boundary at
    or before the row. Marked indices are prefix-monotone (earlier segments
    start earlier), so one NATIVE global cummax is exact — no cross-segment
    contamination and ~30x cheaper than the log-step doubling scan."""
    idx = jnp.arange(boundary.shape[0], dtype=jnp.int32)
    marked = jnp.where(boundary, idx, jnp.int32(0))
    return jax.lax.cummax(marked)


def seg_ends(boundary):
    """Index of the segment end for every row: the next boundary (exclusive)
    minus one. Suffix-monotone, so one native reversed cummin is exact."""
    cap = boundary.shape[0]
    idx = jnp.arange(cap, dtype=jnp.int32)
    next_b = jnp.concatenate([boundary[1:], jnp.ones((1,), jnp.bool_)])
    marked = jnp.where(next_b, idx, jnp.int32(2**31 - 1))
    return jax.lax.cummin(marked, reverse=True)


def segmented_scan(values, boundary, combine):
    """Inclusive scan of `values` restarting where boundary=True."""
    start = seg_starts(boundary)
    return _doubling_scan(values, lambda i, s: (i - s) >= start, combine)


def seg_cumsum(values, boundary):
    """Segmented cumulative sum via ONE native cumsum + per-segment rebase
    (cheaper than doubling for the common sum/count scans)."""
    cs = jnp.cumsum(values, axis=0)
    start = seg_starts(boundary)
    base = jnp.where(start > 0, cs[jnp.maximum(start - 1, 0)],
                     jnp.zeros_like(cs[0]))
    return cs - base


def seg_cummax(values, boundary):
    return segmented_scan(values, boundary, jnp.maximum)


def tie_group_ends(order_boundary, part_boundary):
    """For RANGE frames: last index of each row's order-key tie group within its
    partition (rows with equal order keys share the frame end — Spark RANGE
    CURRENT ROW includes ties)."""
    n = order_boundary.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    rev = lambda x: jnp.flip(x, 0)
    # a tie group ends where the NEXT row starts a new tie group (or at n-1)
    next_is_boundary = jnp.concatenate(
        [order_boundary[1:], jnp.ones((1,), jnp.bool_)])
    end_idx = jnp.where(next_is_boundary, idx, jnp.int32(0))
    # propagate each end backwards across its tie group: reversed segmented scan
    ends = rev(seg_cummax(rev(end_idx), rev(next_is_boundary)))
    return ends


def row_number(part_boundary, capacity):
    idx = jnp.arange(capacity, dtype=jnp.int32)
    return idx - seg_starts(part_boundary) + 1


def dense_rank(order_boundary, part_boundary):
    newgrp = order_boundary & ~part_boundary
    return seg_cumsum(newgrp.astype(jnp.int32), part_boundary) + 1


def rank(order_boundary, part_boundary, capacity):
    idx = jnp.arange(capacity, dtype=jnp.int32)
    start = seg_starts(part_boundary)
    tie_start = seg_cummax(jnp.where(order_boundary, idx, jnp.int32(0)),
                           part_boundary)
    return tie_start - start + 1


def shift_within_partition(values, validity, seg_ids, offset: int, capacity: int,
                           fill_value, fill_valid):
    """lead (offset>0) / lag (offset<0) with partition-membership masking."""
    idx = jnp.arange(capacity, dtype=jnp.int32)
    src = idx + offset
    in_range = (src >= 0) & (src < capacity)
    src_c = jnp.clip(src, 0, capacity - 1)
    same_part = in_range & (seg_ids[src_c] == seg_ids)
    vals = jnp.where(same_part, values[src_c], fill_value)
    valid = jnp.where(same_part, validity[src_c], fill_valid)
    return vals, valid


# ---- variable-bound frames: [lo, hi] per row ------------------------------
#
# Sliding min/max and bounded RANGE frames reduce every frame shape to an
# inclusive per-row index window [lo, hi]. min/max answer range queries with a
# sparse table (log-levels of power-of-2 span minima — the TPU-native stand-in
# for cudf's per-row rolling gather, reference GpuWindowExpression.scala:847);
# sums/counts difference one global cumsum. All static shapes, O(n log n).

def sparse_table(values, combine, sentinel):
    """(L, n) table: t[k][i] = combine over values[i : i+2^k] (clamped).
    Entries whose span crosses n are padded with `sentinel`; queries built by
    `range_query` never read a padded slot for in-bounds [lo, hi]."""
    n = values.shape[0]
    levels = [values]
    k = 0
    while (1 << (k + 1)) <= n:
        prev = levels[-1]
        s = 1 << k
        shifted = jnp.concatenate(
            [prev[s:], jnp.full((s,), sentinel, prev.dtype)])
        levels.append(combine(prev, shifted))
        k += 1
    return jnp.stack(levels)


def range_query(table, combine, lo, hi):
    """combine over [lo, hi] inclusive per row (requires hi >= lo; callers mask
    empty frames separately). Two overlapping power-of-2 spans."""
    L = table.shape[0]
    w = hi - lo + 1
    k = jnp.zeros_like(w)
    for j in range(1, L):
        k = k + (w >= (1 << j)).astype(k.dtype)
    span = jnp.left_shift(jnp.ones_like(k), k)
    a = table[k, lo]
    b = table[k, hi - span + 1]
    return combine(a, b)


def searchsorted_lex(seg, rank, val, q_seg, q_rank, q_val, side: str):
    """Vectorized first index j with (seg[j], rank[j], val[j]) >= (or > for
    side='right') the per-row query triple, by branchless binary search —
    log2(n) rounds of gathers, no data-dependent control flow. The arrays must
    be lexicographically sorted (they are: rows sort by partition, then
    null-rank, then order value)."""
    n = seg.shape[0]
    lo = jnp.zeros_like(q_seg, shape=q_seg.shape).astype(jnp.int32)
    hi = jnp.full(q_seg.shape, n, jnp.int32)
    steps = max(1, n.bit_length())
    for _ in range(steps):
        mid = (lo + hi) >> 1
        m = jnp.clip(mid, 0, n - 1)
        sj, rj, vj = seg[m], rank[m], val[m]
        if side == "left":
            vcmp = vj >= q_val
        else:
            vcmp = vj > q_val
        ge = (sj > q_seg) | ((sj == q_seg) &
                             ((rj > q_rank) | ((rj == q_rank) & vcmp)))
        ge = ge & (mid < n)
        hi = jnp.where(ge, mid, hi)
        lo = jnp.where(ge, lo, jnp.minimum(mid + 1, n))
    return lo


def range_frame_bounds(order_col_values, order_validity, seg_ids, ascending,
                       preceding, following, pstart, pend):
    """Per-row [lo, hi] for a bounded RANGE frame over ONE numeric order key.

    Sort-space transform: desc negates (bitwise-not for ints so INT_MIN is
    safe), so the search is always ascending. Rows sort (within a partition)
    as null-first-group < values < NaN-group < null-last-group — encoded in a
    rank lane so null/NaN current rows resolve to their PEER GROUP on bounded
    sides (Spark RangeBoundOrdering: null±offset is null, which compares equal
    to nulls only; NaN is its own largest peer class).
    """
    v = order_col_values
    is_float = jnp.issubdtype(v.dtype, jnp.floating)
    if is_float:
        s = jnp.where(jnp.isnan(v), jnp.float64(0), v.astype(jnp.float64))
        s = s if ascending else -s
        nan_rank_pos = jnp.isnan(v)
        q_lo_sent = jnp.float64(-jnp.inf)
        q_hi_sent = jnp.float64(jnp.inf)
        pre = None if preceding is None else jnp.float64(preceding)
        fol = None if following is None else jnp.float64(following)
    else:
        s = v.astype(jnp.int64)
        s = s if ascending else ~s
        nan_rank_pos = jnp.zeros(v.shape, jnp.bool_)
        q_lo_sent = jnp.int64(jnp.iinfo(jnp.int64).min)
        q_hi_sent = jnp.int64(jnp.iinfo(jnp.int64).max)
        pre = None if preceding is None else jnp.int64(preceding)
        fol = None if following is None else jnp.int64(following)

    # rank within partition: nulls keep their sorted side, NaN sorts as the
    # largest value class (asc) / smallest (desc negation puts it first, but
    # the sort itself put NaN where 'NaN is largest' dictates — derive the
    # rank from the OBSERVED layout by giving NaN the rank matching direction)
    nan_rank = jnp.int32(2) if ascending else jnp.int32(-1)
    rank = jnp.where(order_validity,
                     jnp.where(nan_rank_pos, nan_rank, jnp.int32(1)),
                     jnp.int32(0))
    # null rows sort first or last depending on nulls_first: infer from layout
    # (a null row at pstart ⇒ nulls-first). Both cases keep nulls one block.
    null_first_here = ~order_validity[pstart]
    rank = jnp.where(order_validity, rank,
                     jnp.where(null_first_here, jnp.int32(-2), jnp.int32(3)))

    s = jnp.where(order_validity & ~nan_rank_pos, s,
                  jnp.zeros_like(s))  # peers distinguished by rank lane only
    own_rank = rank
    peer_only = ~order_validity | nan_rank_pos

    if pre is None:
        lo = pstart
    else:
        q_val = jnp.where(peer_only, q_lo_sent, s - pre)
        lo = searchsorted_lex(seg_ids, rank, s, seg_ids, own_rank, q_val,
                              side="left")
    if fol is None:
        hi = pend
    else:
        q_val = jnp.where(peer_only, q_hi_sent, s + fol)
        hi = searchsorted_lex(seg_ids, rank, s, seg_ids, own_rank, q_val,
                              side="right") - 1
    return jnp.maximum(lo, pstart).astype(jnp.int32), \
        jnp.minimum(hi, pend).astype(jnp.int32)
