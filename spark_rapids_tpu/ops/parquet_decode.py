"""Device parquet decode kernels — bit-unpack + dictionary gather in one jit.

Reference: GpuParquetScan.scala:1235 (`Table.readParquet` decodes raw chunk
bytes on the GPU). TPU stage one (SURVEY.md §7): the bulk bytes of a
dictionary-encoded column are bit-packed indices; one jitted program unpacks
bits with shifts/masks (VPU-friendly, no scalar loops) and gathers dictionary
values, then scatters present values over the null layout via a rank gather.
Static shapes throughout: byte buffers pad to the capacity bucket.
"""

from __future__ import annotations

import typing

import numpy as np
import jax.numpy as jnp


class EncodedPageSpec(typing.NamedTuple):
    """Static shape/type facts of one encoded data page — everything the
    traceable decode prologue (`decode_page_cols`) closes over. Hashable, so
    it rides fuse-cache keys and pytree aux data directly; two pages with the
    same spec share one compiled program regardless of their byte content."""
    bit_width: int
    pcap: int          # present-value capacity bucket
    bcap: int          # packed-byte capacity bucket (0 under pallas words)
    capacity: int      # output row capacity bucket
    want: str          # decoded value dtype name (int32 codes for strings)
    is_string: bool
    default: object    # canonical fill for invalid slots
    use_pallas: bool
    n_present: int     # static present count (pallas tile shapes need it)


def unpack_bits_device(packed: jnp.ndarray, bit_width: int, n: int,
                       capacity: int) -> jnp.ndarray:
    """(bytes,) uint8 → (capacity,) int32 of `n` bit-packed values.

    value i occupies bits [i*bw, (i+1)*bw): gather the (up to) 5 covering
    bytes, combine little-endian into an int64 window, shift and mask —
    pure vector ops, one fused XLA kernel."""
    idx = jnp.arange(capacity, dtype=jnp.int32)
    bit0 = idx * bit_width
    byte0 = bit0 >> 3
    shift = (bit0 & 7).astype(jnp.int64)
    nbytes = packed.shape[0]
    window = jnp.zeros((capacity,), jnp.int64)
    # a bw-bit value starting at any bit offset 0..7 spans ceil((bw+7)/8)
    # bytes — at most 5 for bw<=32
    for k in range((bit_width + 14) // 8):
        b = packed[jnp.clip(byte0 + k, 0, nbytes - 1)].astype(jnp.int64)
        window = window | (b << (8 * k))
    mask = jnp.int64((1 << bit_width) - 1)
    vals = (window >> shift) & mask
    return jnp.where(idx < n, vals.astype(jnp.int32), 0)


def expand_present_to_rows(present_vals: jnp.ndarray,
                           def_levels: jnp.ndarray,
                           capacity: int):
    """Parquet stores values only for non-null slots; spread them over the
    full row layout: row j takes present value rank(j) where rank is the
    prefix count of set definition levels (a gather, not a scatter)."""
    ranks = jnp.cumsum(def_levels.astype(jnp.int32)) - 1
    safe = jnp.clip(ranks, 0, capacity - 1)
    vals = present_vals[safe]
    valid = def_levels.astype(jnp.bool_)
    return vals, valid


def decode_page_cols(spec: EncodedPageSpec, packed_d, dict_d, dl_d,
                     n_present_t, n_t):
    """TRACEABLE single-page decode: bit-unpack → dictionary gather →
    definition-level spread → canonical nulls, returning (values, validity)
    at spec.capacity. This is the single source of truth for page expansion —
    the standalone fused decode kernel (io/parquet_native.py) and the
    encoded-upload consumers (columnar/encoded.py, exec/aggregate.py) all
    trace THIS body, so encoded-vs-dense results are bit-identical by
    construction. Device args: packed bytes (or pallas words), the device
    dictionary, def-levels as bool (capacity,), and int32 scalars for the
    present/live counts."""
    want = jnp.dtype(spec.want)
    if spec.use_pallas:
        from spark_rapids_tpu.ops import pallas_kernels as PK
        # pallas tile shapes need the STATIC present count (part of the spec,
        # hence part of every cache key that embeds the spec)
        idx = PK.bitunpack128(packed_d, spec.bit_width, spec.n_present,
                              spec.pcap)
    else:
        idx = unpack_bits_device(packed_d, spec.bit_width, n_present_t,
                                 spec.pcap)
    nd = dict_d.shape[0]
    # an all-null page may carry an EMPTY dictionary: nothing to gather
    present = (dict_d[jnp.clip(idx, 0, max(nd - 1, 0))] if nd
               else jnp.zeros((spec.pcap,), dict_d.dtype))
    cap = spec.capacity
    present_padded = jnp.zeros((cap,), present.dtype
                               ).at[:min(spec.pcap, cap)].set(present[:cap])
    vals, valid = expand_present_to_rows(present_padded, dl_d, cap)
    live = jnp.arange(cap, dtype=jnp.int32) < n_t
    m = valid & live
    v = jnp.where(m, vals.astype(want), jnp.asarray(spec.default, want))
    return v, m


def decode_dictionary_page(packed_bytes: np.ndarray, bit_width: int,
                           n_present: int, def_levels: np.ndarray,
                           dict_values: jnp.ndarray, capacity: int):
    """One data page → (values, validity) padded to capacity. The packed
    index bytes and the dictionary live on device; run structure was already
    validated host-side (single bit-packed region — parse_rle_hybrid)."""
    from spark_rapids_tpu.columnar.vector import bucket_capacity
    from spark_rapids_tpu.ops import pallas_kernels as PK
    pcap = max(bucket_capacity(n_present), 8)
    if PK.should_use("bitunpack"):
        words = PK.bytes_to_words_u32(np.asarray(packed_bytes, np.uint8))
        idx = PK.bitunpack128(jnp.asarray(words), bit_width, n_present, pcap)
    else:
        packed_d = jnp.zeros((max(len(packed_bytes), 1),), jnp.uint8
                             ).at[:len(packed_bytes)].set(
            jnp.asarray(packed_bytes, dtype=jnp.uint8))
        idx = unpack_bits_device(packed_d, bit_width, n_present, pcap)
    nd = dict_values.shape[0]
    present = dict_values[jnp.clip(idx, 0, max(nd - 1, 0))]
    dl = jnp.zeros((capacity,), jnp.bool_).at[:len(def_levels)].set(
        jnp.asarray(def_levels.astype(bool)))
    # pad present values out to capacity before the rank gather (pcap <=
    # capacity: n_present <= num_values and capacity is the row bucket)
    present_padded = jnp.zeros((capacity,), present.dtype
                               ).at[:pcap].set(present)
    vals, valid = expand_present_to_rows(present_padded, dl, capacity)
    return vals, valid
