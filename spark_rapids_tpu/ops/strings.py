"""String kernels over dictionary-encoded columns.

Reference: sql-plugin/.../org/apache/spark/sql/rapids/stringFunctions.scala (897 LoC)
runs byte-level CUDA kernels via cudf strings. TPU-first design is different: device
string columns are int32 codes into a small host-side SORTED dictionary, so

- any *scalar* string function (upper, substring, length, contains, format…) is
  computed ONCE PER DISTINCT VALUE on the host dictionary, then applied to millions of
  rows as a single device gather — O(|dict|) host work + O(n) device work, instead of
  the reference's O(total bytes) GPU work;
- comparisons/joins/group-bys between two string columns first remap both onto a
  sorted union dictionary (order-preserving), after which every device op is plain
  int32 arithmetic;
- functions needing byte-level device work with chained state (murmur3 with a running
  seed) use the packed word matrix from TpuColumnVector.dictionary_words().

Exactness: the host functions implement Spark/Java semantics directly (UTF-16-aware
lengths, Java substring indexing), which is the same bit-identical bar the reference
meets with custom CUDA code.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pyarrow as pa
import pyarrow.compute as pc

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Col, valid_and


def _empty_dict():
    return pa.array([], type=pa.string())


def dict_transform_to_string(c: Col, fn) -> Col:
    """Apply a python str→str (or None) function per dictionary entry; result is a new
    string Col. The new dictionary is re-sorted/deduped to keep the order-preserving
    invariant; row codes are remapped by a device gather."""
    entries = c.dictionary.to_pylist() if c.dictionary is not None else []
    outs = [fn(e) for e in entries]
    uniq = sorted(set(o for o in outs if o is not None))
    index = {v: i for i, v in enumerate(uniq)}
    code_map = np.array([index.get(o, 0) for o in outs], dtype=np.int32)
    null_map = np.array([o is None for o in outs], dtype=bool)
    if len(code_map) == 0:
        code_map = np.zeros(1, np.int32)
        null_map = np.zeros(1, bool)
    new_codes = jnp.asarray(code_map)[c.values]
    entry_null = jnp.asarray(null_map)[c.values]
    validity = c.validity & ~entry_null
    new_codes = jnp.where(validity, new_codes, 0)
    return Col(new_codes, validity, T.STRING, pa.array(uniq, type=pa.string()))


def dict_transform_to_values(c: Col, fn, out_dtype: T.DataType) -> Col:
    """Apply a python str→value (or None) function per dictionary entry; result is a
    fixed-width Col via device gather (e.g. length, string→int cast, LIKE)."""
    entries = c.dictionary.to_pylist() if c.dictionary is not None else []
    outs = [fn(e) for e in entries]
    np_dt = T.to_numpy_dtype(out_dtype)
    vals = np.array([o if o is not None else out_dtype.default_value() for o in outs],
                    dtype=np_dt)
    nulls = np.array([o is None for o in outs], dtype=bool)
    if len(vals) == 0:
        vals = np.zeros(1, np_dt)
        nulls = np.zeros(1, bool)
    new_vals = jnp.asarray(vals)[c.values]
    entry_null = jnp.asarray(nulls)[c.values]
    validity = c.validity & ~entry_null
    default = jnp.asarray(out_dtype.default_value(), dtype=out_dtype.jnp_dtype)
    return Col(jnp.where(validity, new_vals, default), validity, out_dtype)


def union_dictionaries(l: Col, r: Col):
    """Remap two string Cols onto one sorted union dictionary (host union + device
    gathers). Needed before any cross-column string comparison/join/group."""
    dl = l.dictionary if l.dictionary is not None else _empty_dict()
    dr = r.dictionary if r.dictionary is not None else _empty_dict()
    if dl.equals(dr):
        return l, r
    union = pa.concat_arrays([dl, dr]).unique().sort()
    idx = {v: i for i, v in enumerate(union.to_pylist())}
    map_l = np.array([idx[v] for v in dl.to_pylist()] or [0], dtype=np.int32)
    map_r = np.array([idx[v] for v in dr.to_pylist()] or [0], dtype=np.int32)
    lv = jnp.asarray(map_l)[l.values]
    rv = jnp.asarray(map_r)[r.values]
    return (Col(jnp.where(l.validity, lv, 0), l.validity, T.STRING, union),
            Col(jnp.where(r.validity, rv, 0), r.validity, T.STRING, union))


def align_many(cols):
    """Remap a list of string Cols onto one shared sorted union dictionary."""
    dicts = [c.dictionary if c.dictionary is not None else _empty_dict() for c in cols]
    if all(d.equals(dicts[0]) for d in dicts[1:]):
        return list(cols)
    union = pa.concat_arrays([d.combine_chunks() if isinstance(d, pa.ChunkedArray)
                              else d for d in dicts]).unique().sort()
    idx = {v: i for i, v in enumerate(union.to_pylist())}
    out = []
    for c, d in zip(cols, dicts):
        m = np.array([idx[v] for v in d.to_pylist()] or [0], dtype=np.int32)
        vals = jnp.asarray(m)[c.values]
        out.append(Col(jnp.where(c.validity, vals, 0), c.validity, T.STRING, union))
    return out


def coalesce_strings(cols):
    cols = align_many(cols)
    vals = cols[-1].values
    validity = cols[-1].validity
    for c in reversed(cols[:-1]):
        vals = jnp.where(c.validity, c.values, vals)
        validity = c.validity | validity
    return Col(jnp.where(validity, vals, 0), validity, T.STRING, cols[0].dictionary)


def if_strings(pred: Col, a: Col, b: Col):
    a, b = union_dictionaries(a, b)
    take_a = pred.values & pred.validity
    vals = jnp.where(take_a, a.values, b.values)
    validity = jnp.where(take_a, a.validity, b.validity)
    return Col(jnp.where(validity, vals, 0), validity, T.STRING, a.dictionary)


_CONCAT_CROSS_LIMIT = 1 << 20


def concat_cols(l: Col, r: Col):
    """concat(a, b) for two string columns. Small dictionaries: build the full
    |L|x|R| pair dictionary on host, keep everything on device via a 2-D gather.
    Large cross products: sync the observed code pairs to host and build only those
    (one device→host round trip, O(observed pairs) host work)."""
    dl = l.dictionary.to_pylist() if l.dictionary is not None else []
    dr = r.dictionary.to_pylist() if r.dictionary is not None else []
    nl, nr = max(len(dl), 1), max(len(dr), 1)
    validity = valid_and(l.validity, r.validity)
    if nl * nr <= _CONCAT_CROSS_LIMIT:
        pair_strings = [a + b for a in (dl or [""]) for b in (dr or [""])]
        uniq = sorted(set(pair_strings))
        index = {v: i for i, v in enumerate(uniq)}
        pair_map = np.array([index[s] for s in pair_strings],
                            dtype=np.int32).reshape(nl, nr)
        codes = jnp.asarray(pair_map)[l.values, r.values]
        return Col(jnp.where(validity, codes, 0), validity, T.STRING,
                   pa.array(uniq, type=pa.string()))
    # observed-pairs path
    lc = np.asarray(l.values)
    rc = np.asarray(r.values)
    pair_keys = lc.astype(np.int64) * nr + rc
    uniq_keys, inv = np.unique(pair_keys, return_inverse=True)
    dl_arr = dl or [""]
    dr_arr = dr or [""]
    strs = [dl_arr[int(k // nr)] + dr_arr[int(k % nr)] for k in uniq_keys]
    uniq = sorted(set(strs))
    index = {v: i for i, v in enumerate(uniq)}
    code_of_pair = np.array([index[s] for s in strs], dtype=np.int32)
    codes = jnp.asarray(code_of_pair[inv])
    return Col(jnp.where(validity, codes, 0), validity, T.STRING,
               pa.array(uniq, type=pa.string()))


# ---------------------------------------------------------------------------
# Spark/Java string semantics helpers (UTF-16 code-unit based, like UTF8String)
# ---------------------------------------------------------------------------

def java_length(s: str) -> int:
    """Spark length() counts characters (code points for UTF8String)."""
    return len(s)


def java_substring(s: str, pos: int, length: int | None) -> str:
    """Spark substring: 1-based, negative pos counts from end, 0 treated as 1."""
    n = len(s)
    if pos > 0:
        start = pos - 1
    elif pos < 0:
        start = max(n + pos, 0)
    else:
        start = 0
    if start >= n:
        return ""
    end = n if length is None else min(start + max(length, 0), n)
    if length is not None and length <= 0:
        return ""
    return s[start:end]


def like_to_regex(pattern: str, escape: str = "\\") -> str:
    """Translate SQL LIKE pattern to an anchored python regex (Spark StringUtils)."""
    import re as _re
    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == escape and i + 1 < len(pattern):
            out.append(_re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(_re.escape(ch))
        i += 1
    return "^" + "".join(out) + "$"


def value_transform_to_string(c: Col, fmt) -> Col:
    """Fixed-width values → string Col via a host-built dictionary over the
    distinct values actually present (one device→host sync; the from_unixtime/
    date_format path — the reference formats on device via cudf strings)."""
    import numpy as np
    vals = np.asarray(c.values)
    valid = np.asarray(c.validity)
    uv, inv = np.unique(vals, return_inverse=True)
    strs = [fmt(v) for v in uv]
    null_of_uv = np.array([s is None for s in strs], dtype=bool)
    uniq = sorted(set(s for s in strs if s is not None))
    index = {s: i for i, s in enumerate(uniq)}
    code_of_uv = np.array([index.get(s, 0) for s in strs], dtype=np.int32)
    codes = code_of_uv[inv.reshape(-1)]
    nulls = null_of_uv[inv.reshape(-1)]
    codes[~valid | nulls] = 0
    validity = c.validity & ~jnp.asarray(nulls)
    return Col(jnp.asarray(codes), validity, T.STRING,
               pa.array(uniq or [""], type=pa.string()))


def value_transform_to_values(c: Col, fn, out_dtype: T.DataType) -> Col:
    """Fixed-width values → fixed-width values via a host-built map over the
    distinct values present (string-parse path, e.g. unix_timestamp(str))."""
    import numpy as np
    vals = np.asarray(c.values)
    uv, inv = np.unique(vals, return_inverse=True)
    np_dt = T.to_numpy_dtype(out_dtype)
    outs = [fn(v) for v in uv]
    null_of_uv = np.array([o is None for o in outs], dtype=bool)
    val_of_uv = np.array([0 if o is None else o for o in outs], dtype=np_dt)
    nulls = jnp.asarray(null_of_uv[inv.reshape(-1)])
    out_vals = jnp.asarray(val_of_uv[inv.reshape(-1)])
    validity = c.validity & ~nulls
    return Col(jnp.where(validity, out_vals,
                         jnp.asarray(out_dtype.default_value(), np_dt)),
               validity, out_dtype)


def sorted_dict_and_rank(entries):
    """File-order dictionary entries → (sorted pa dictionary, rank array
    mapping file-order index → sorted code). Shared by the parquet and ORC
    device decoders (their on-disk dictionaries map 1:1 onto the engine's
    sorted string dictionary)."""
    import pyarrow.compute as pc
    dict_arr = pa.array(entries, pa.string())
    order = pc.array_sort_indices(dict_arr)
    sorted_dict = dict_arr.take(order)
    n = len(dict_arr)
    rank = np.empty(max(n, 1), dtype=np.int32)
    rank[order.to_numpy(zero_copy_only=False)] = np.arange(n, dtype=np.int32)
    return sorted_dict, rank
