"""Spark-exact Murmur3_x86_32 on device.

Reference: GpuHashPartitioning (com/nvidia/spark/rapids/GpuHashPartitioning.scala:92)
relies on cudf's murmur3 matching Spark's `Murmur3Hash(exprs, 42)` bit-for-bit so GPU
and CPU shuffles land rows in the same partitions. Here the same algorithm is written
as jax int32 ops (wrapping two's-complement arithmetic + logical shifts), seed-chained
across columns exactly like Spark's HashExpression.eval:

    h = seed(42); for col in cols: if row not null in col: h = hash_col(value, h)
    partition = pmod(fmix-free h? no — Spark applies fmix inside each column hash)

Column rules (Spark Murmur3Hash / XxHash64 semantics, see also reference
TypeChecks CastChecks for which types may feed a hash):
  bool→hashInt(0/1), byte/short/int/date→hashInt, long/timestamp/decimal64→hashLong,
  float→hashInt(floatToIntBits(x)) with -0.0→0.0, double→hashLong(doubleToLongBits),
  string→hashUnsafeBytes over UTF-8, 4-byte little-endian words then signed tail bytes.
Null values leave the running hash unchanged.
"""

from __future__ import annotations

import struct

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

_C1 = np.int32(np.uint32(0xcc9e2d51))
_C2 = np.int32(np.uint32(0x1b873593))
_M5 = np.int32(np.uint32(0xe6546b64))
_FX1 = np.int32(np.uint32(0x85ebca6b))
_FX2 = np.int32(np.uint32(0xc2b2ae35))


def _i32(x):
    return x.astype(jnp.int32)


def _rotl(x, n):
    return lax.shift_left(x, jnp.int32(n)) | lax.shift_right_logical(x, jnp.int32(32 - n))


def _mix_k1(k1):
    k1 = k1 * _C1
    k1 = _rotl(k1, 15)
    return k1 * _C2


def _mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl(h1, 13)
    return h1 * jnp.int32(5) + _M5


def _fmix(h1, length):
    h1 = h1 ^ jnp.int32(length)
    h1 = h1 ^ lax.shift_right_logical(h1, jnp.int32(16))
    h1 = h1 * _FX1
    h1 = h1 ^ lax.shift_right_logical(h1, jnp.int32(13))
    h1 = h1 * _FX2
    h1 = h1 ^ lax.shift_right_logical(h1, jnp.int32(16))
    return h1


def hash_int(value_i32, seed_i32):
    """Spark Murmur3_x86_32.hashInt, vectorized."""
    h1 = _mix_h1(seed_i32, _mix_k1(value_i32))
    return _fmix(h1, 4)


def hash_long(value_i64, seed_i32):
    """Spark Murmur3_x86_32.hashLong: low word then high word."""
    low = _i32(value_i64)
    high = _i32(lax.shift_right_logical(value_i64, jnp.int64(32)))
    h1 = _mix_h1(seed_i32, _mix_k1(low))
    h1 = _mix_h1(h1, _mix_k1(high))
    return _fmix(h1, 8)


def hash_float(value_f32, seed_i32):
    """Spark hashes floatToIntBits (canonical NaN 0x7fc00000, -0.0 normalized)."""
    v = value_f32.astype(jnp.float32)
    v = jnp.where(v == jnp.float32(-0.0), jnp.float32(0.0), v)
    bits = lax.bitcast_convert_type(v, jnp.int32)
    bits = jnp.where(jnp.isnan(v), jnp.int32(0x7fc00000), bits)
    return hash_int(bits, seed_i32)


def double_to_long_bits(v):
    """Java Double.doubleToLongBits without an f64<->i64 bitcast (unsupported under
    the TPU x64-emulation rewrite): reconstruct the IEEE-754 layout arithmetically
    from frexp. Canonical NaN (0x7ff8000000000000) like Java."""
    m, e = jnp.frexp(jnp.abs(v))  # abs(v) = m * 2^e, m in [0.5, 1)
    biased = e.astype(jnp.int64) + 1022
    normal = biased >= 1
    norm_mant = ((m * 2.0 - 1.0) * (2.0 ** 52)).astype(jnp.int64)
    # Subnormals hash as ±0: XLA's CPU and TPU backends run DAZ/FTZ — even
    # frexp and multiplication see a subnormal operand as zero, so the true
    # mantissa is unrecoverable inside a jitted program. DOCUMENTED
    # divergence from CPU Spark (docs/compatibility.md), matching the
    # reference's own GPU float caveats.
    mant = jnp.where(normal, norm_mant, 0)
    expf = jnp.where(normal, biased, 0)
    bits = lax.shift_left(expf, jnp.int64(52)) | mant
    bits = jnp.where(jnp.isinf(v), jnp.int64(0x7ff0000000000000), bits)
    bits = jnp.where(v == 0, jnp.int64(0), bits)
    sign = jnp.signbit(v).astype(jnp.int64)
    bits = bits | lax.shift_left(sign, jnp.int64(63))
    bits = jnp.where(jnp.isnan(v), jnp.int64(0x7ff8000000000000), bits)
    return bits


def hash_double(value_f64, seed_i32):
    """Spark hashes doubleToLongBits (canonical NaN, -0.0 normalized)."""
    v = value_f64.astype(jnp.float64)
    v = jnp.where(v == jnp.float64(-0.0), jnp.float64(0.0), v)
    return hash_long(double_to_long_bits(v), seed_i32)


def hash_string_words(words, lengths, seed_i32):
    """hashUnsafeBytes over rows of 4-byte little-endian words.

    words: (n, W) int32 — UTF-8 bytes packed little-endian, zero-padded.
    lengths: (n,) int32 byte lengths. Whole words first, then each tail byte is its own
    mix round using the SIGNED byte value, exactly like Spark's hashUnsafeBytes.

    On TPU this dispatches to the Pallas kernel (ops/pallas_kernels.py);
    the jnp formulation below is the off-TPU path and the test oracle.
    """
    from spark_rapids_tpu.ops import pallas_kernels as PK
    if PK.should_use("murmur3"):
        return PK.murmur3_words(words, lengths, seed_i32)
    n, W = words.shape
    n_words = lengths // 4
    n_tail = lengths % 4

    def word_round(i, h1):
        k = words[:, i]
        use = i < n_words
        return jnp.where(use, _mix_h1(h1, _mix_k1(k)), h1)

    # seed the carry with a data-dependent zero: under shard_map the loop body
    # mixes in per-device data, so the carry must be device-varying from the
    # start or the scan rejects the (unvarying-in, varying-out) carry types
    h0 = (jnp.broadcast_to(seed_i32, (n,)).astype(jnp.int32)
          + (lengths * 0).astype(jnp.int32))
    h1 = lax.fori_loop(0, W, word_round, h0)

    # tail bytes: extract byte (n_words*4 + t) for t in 0..2, sign-extended
    for t in range(3):
        word = jnp.take_along_axis(words, n_words[:, None].astype(jnp.int32),
                                   axis=1)[:, 0]
        byte = lax.shift_right_logical(word, (jnp.int32(8) * t)) & jnp.int32(0xFF)
        sbyte = jnp.where(byte >= 128, byte - 256, byte)  # signed java byte
        use = t < n_tail
        h1 = jnp.where(use, _mix_h1(h1, _mix_k1(sbyte)), h1)
    return _fmix(h1, lengths)


def pmod(hash_i32, divisor: int):
    """Spark Pmod(hash, n): non-negative modulo."""
    r = hash_i32 % jnp.int32(divisor)
    return jnp.where(r < 0, r + jnp.int32(divisor), r)


# ---------------------------------------------------------------------------
# host-side reference (dictionary prep + tests)
# ---------------------------------------------------------------------------

def _hm_mix_k1(k1):
    k1 = (k1 * 0xcc9e2d51) & 0xFFFFFFFF
    k1 = ((k1 << 15) | (k1 >> 17)) & 0xFFFFFFFF
    return (k1 * 0x1b873593) & 0xFFFFFFFF


def _hm_mix_h1(h1, k1):
    h1 ^= k1
    h1 = ((h1 << 13) | (h1 >> 19)) & 0xFFFFFFFF
    return (h1 * 5 + 0xe6546b64) & 0xFFFFFFFF


def _hm_fmix(h1, length):
    h1 ^= length
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85ebca6b) & 0xFFFFFFFF
    h1 ^= h1 >> 13
    h1 = (h1 * 0xc2b2ae35) & 0xFFFFFFFF
    h1 ^= h1 >> 16
    return h1


def _to_signed(u):
    return u - 0x100000000 if u >= 0x80000000 else u


def murmur3_bytes_host(data: bytes, seed: int) -> int:
    """Spark Murmur3_x86_32.hashUnsafeBytes on host (signed int32 result)."""
    h1 = seed & 0xFFFFFFFF
    n = len(data)
    aligned = n - n % 4
    for i in range(0, aligned, 4):
        (k1,) = struct.unpack_from("<i", data, i)
        h1 = _hm_mix_h1(h1, _hm_mix_k1(k1 & 0xFFFFFFFF))
    for i in range(aligned, n):
        b = data[i]
        sb = b - 256 if b >= 128 else b
        h1 = _hm_mix_h1(h1, _hm_mix_k1(sb & 0xFFFFFFFF))
    return _to_signed(_hm_fmix(h1, n))


def murmur3_int_host(v: int, seed: int) -> int:
    h1 = _hm_mix_h1(seed & 0xFFFFFFFF, _hm_mix_k1(v & 0xFFFFFFFF))
    return _to_signed(_hm_fmix(h1, 4))


def murmur3_long_host(v: int, seed: int) -> int:
    v &= 0xFFFFFFFFFFFFFFFF
    h1 = _hm_mix_h1(seed & 0xFFFFFFFF, _hm_mix_k1(v & 0xFFFFFFFF))
    h1 = _hm_mix_h1(h1, _hm_mix_k1((v >> 32) & 0xFFFFFFFF))
    return _to_signed(_hm_fmix(h1, 8))


def pack_utf8_words(strings, max_bytes: int | None = None):
    """Pack a list of strings into (words int32 (n,W), lengths int32 (n,)) for
    hash_string_words. Used once per string dictionary."""
    bs = [s.encode("utf-8") if s is not None else b"" for s in strings]
    max_b = max([len(b) for b in bs], default=0)
    if max_bytes is not None:
        max_b = max(max_b, max_bytes)
    W = max(1, (max_b + 3) // 4)
    raw = np.zeros((len(bs), W * 4), dtype=np.uint8)
    lens = np.zeros(len(bs), dtype=np.int32)
    for i, b in enumerate(bs):
        raw[i, :len(b)] = np.frombuffer(b, dtype=np.uint8)
        lens[i] = len(b)
    words = raw.view("<i4").astype(np.int32)
    return words, lens
