"""Device CSV field parsing — digits to numbers as one fused XLA program.

Reference: the reference decodes CSV on the GPU via cudf's CSV parser
(GpuBatchScanExec.scala / CSVPartitionReader, SURVEY.md #25), gated per
type by spark.rapids.sql.csv.read.*.enabled because device parsing is
more lenient than Spark's. TPU stage one: the host computes field
boundaries with vectorized numpy (io/csv_native.py — bytes→offsets is
metadata, same split as the parquet stage-one design) and the device
turns digit bytes into values: a gather of (row, char) byte matrices,
then a static-K horner scan — no scalar loops, one jitted program per
column batch.

Unlike cudf's lenient parser, malformed fields here parse to NULL (closer
to Spark); doubles divide by a power of ten at the end, which can differ
from Spark's strtod by 1 ulp on long fractions — hence the off-by-default
conf for floating point, mirroring the reference's gating."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

MAX_INT_CHARS = 20    # -9223372036854775808
MAX_DBL_CHARS = 26


def _gather_chars(data: jnp.ndarray, starts: jnp.ndarray, K: int):
    """(n,) starts into (n, K) byte matrix (uint8), clipped gather."""
    idx = starts[:, None] + jnp.arange(K, dtype=jnp.int32)[None, :]
    return data[jnp.clip(idx, 0, data.shape[0] - 1)]


def parse_int64(data: jnp.ndarray, starts: jnp.ndarray, lens: jnp.ndarray,
                capacity: int):
    """Parse int64 fields. Empty or malformed → null. data: (bytes,) uint8
    on device; starts/lens: (capacity,) int32 (padded rows have len<0)."""
    chars = _gather_chars(data, starts, MAX_INT_CHARS)  # (n, K)
    j = jnp.arange(MAX_INT_CHARS, dtype=jnp.int32)[None, :]
    in_field = j < lens[:, None]
    neg = chars[:, 0] == ord("-")
    signed = neg | (chars[:, 0] == ord("+"))
    digit_pos = in_field & (j >= signed[:, None].astype(jnp.int32))
    d = chars.astype(jnp.int32) - ord("0")
    is_digit = (d >= 0) & (d <= 9)
    ok = jnp.all(~digit_pos | is_digit, axis=1)
    # at least one digit, sign alone is malformed, over-long fields null
    # (a valid long is at most sign + 19 digits)
    ndigits = jnp.sum(digit_pos, axis=1)
    ok = ok & (ndigits > 0) & (lens >= 1) & (lens <= MAX_INT_CHARS)
    # horner over static columns; accumulate NEGATIVE to hold Long.MIN,
    # detecting wrap like Long.parseLong: val*10 - d < MIN ⇒ overflow
    LIM = jnp.int64(-922337203685477580)  # MIN // 10 (toward zero)
    val = jnp.zeros(chars.shape[0], jnp.int64)
    overflow = jnp.zeros(chars.shape[0], jnp.bool_)
    for col in range(MAX_INT_CHARS):
        take = digit_pos[:, col]
        dj = d[:, col].astype(jnp.int64)
        overflow = overflow | (take & ((val < LIM) | ((val == LIM) & (dj > 8))))
        val = jnp.where(take, val * 10 - dj, val)
    # positive Long.MAX+1 case: -val wraps back to MIN
    overflow = overflow | (~neg & (val == jnp.iinfo(jnp.int64).min))
    val = jnp.where(neg, val, -val)
    valid = ok & ~overflow & (lens >= 0)
    empty = lens == 0          # Spark: empty field → null
    valid = valid & ~empty
    return jnp.where(valid, val, 0), valid


def parse_float64(data: jnp.ndarray, starts: jnp.ndarray, lens: jnp.ndarray,
                  capacity: int):
    """Parse plain-decimal doubles (no exponent/inf/nan — those columns stay
    on host; see io/csv_native.py scoping). 1-ulp divergence possible."""
    chars = _gather_chars(data, starts, MAX_DBL_CHARS)
    j = jnp.arange(MAX_DBL_CHARS, dtype=jnp.int32)[None, :]
    in_field = j < lens[:, None]
    neg = chars[:, 0] == ord("-")
    signed = neg | (chars[:, 0] == ord("+"))
    d = chars.astype(jnp.int32) - ord("0")
    is_digit = (d >= 0) & (d <= 9)
    is_dot = chars == ord(".")
    body = in_field & (j >= signed[:, None].astype(jnp.int32))
    ok = jnp.all(~body | is_digit | is_dot, axis=1)
    ok = ok & (jnp.sum(body & is_dot, axis=1) <= 1)
    ok = ok & (jnp.sum(body & is_digit, axis=1) > 0)
    ok = ok & (lens <= MAX_DBL_CHARS)   # no silent truncation: null instead
    mant = jnp.zeros(chars.shape[0], jnp.float64)
    frac_digits = jnp.zeros(chars.shape[0], jnp.int32)
    seen_dot = jnp.zeros(chars.shape[0], jnp.bool_)
    for col in range(MAX_DBL_CHARS):
        active = body[:, col]
        dig = active & is_digit[:, col]
        mant = jnp.where(dig, mant * 10.0 + d[:, col], mant)
        frac_digits = jnp.where(dig & seen_dot, frac_digits + 1, frac_digits)
        seen_dot = seen_dot | (active & is_dot[:, col])
    val = mant / jnp.power(jnp.float64(10.0), frac_digits.astype(jnp.float64))
    val = jnp.where(neg, -val, val)
    valid = ok & (lens > 0)
    return jnp.where(valid, val, 0.0), valid


def parse_int32(data, starts, lens, capacity):
    v, m = parse_int64(data, starts, lens, capacity)
    in_range = (v >= -(2 ** 31)) & (v < 2 ** 31)
    return v.astype(jnp.int32), m & in_range
