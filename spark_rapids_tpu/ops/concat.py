"""Batch concatenation on device (reference: cudf Table.concatenate driven by
GpuCoalesceBatches / ConcatAndConsumeAll). Implemented as dynamic_update_slice into a
fresh padded buffer so it fuses and works with device-scalar row counts."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.vector import TpuColumnVector, bucket_capacity
from spark_rapids_tpu.expr.core import Col


def concat_cols(per_col, counts_v, cap: int, caps: tuple):
    """TRACEABLE pad-concat body: for each column (a list of per-batch Cols),
    write every batch's full capacity window into a fresh work buffer with
    ordered dynamic_update_slice at the traced cumsum offsets, slice to the
    static output bucket `cap`, and mask validity beyond the live total.
    Shared verbatim by concat_batches and the chained group-by
    (exec/aggregate._chain_step), so chained-vs-unchained concat results are
    bit-identical by construction.

    Ordered dus writes: batch i+1's window starts at off_i + count_i,
    overwriting batch i's padding tail — pure copies, no gather-based
    compaction. The work buffer is over-allocated by max(caps) so
    off_i + cap_i can never exceed it (jax clamps out-of-range dus starts,
    which would silently corrupt)."""
    from spark_rapids_tpu.ops.strings import align_many
    offs = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                            jnp.cumsum(counts_v)[:-1].astype(jnp.int32)])
    wcap = cap + max(caps)
    total_t = jnp.sum(counts_v)
    live = jnp.arange(cap, dtype=jnp.int32) < total_t
    out = []
    for cols in per_col:
        if cols[0].is_string:
            cols = align_many(cols)
        v = jnp.zeros((wcap,), cols[0].values.dtype)
        m = jnp.zeros((wcap,), jnp.bool_)
        for i, c in enumerate(cols):
            v = jax.lax.dynamic_update_slice(v, c.values, (offs[i],))
            m = jax.lax.dynamic_update_slice(m, c.validity, (offs[i],))
        # input pad regions hold canonical defaults (zeros), so the only
        # cleanup is masking validity beyond the live total
        out.append(Col(v[:cap], m[:cap] & live, cols[0].dtype,
                       cols[0].dictionary))
    return out


def concat_batches(batches) -> ColumnarBatch:
    """Concatenate batches (host-known row counts) into one device batch.

    One fused XLA program per (k, capacities, schema) signature: pad-concat
    every column, stable-compact live rows to the front (shared permutation),
    slice to the output bucket. Row counts cross as a traced vector so varying
    fill levels replay the same compiled program."""
    from spark_rapids_tpu.runtime import fuse
    batches = list(batches)
    if len(batches) == 1:
        return batches[0]
    schema = batches[0].schema
    counts = [b.num_rows for b in batches]
    total = sum(counts)
    cap = bucket_capacity(total)
    ncols = batches[0].num_cols
    caps = tuple(b.capacity for b in batches)

    def kernel(per_col, counts_v):
        return concat_cols(per_col, counts_v, cap, caps)

    per_col = [[Col.from_vector(b.column(ci)) for b in batches]
               for ci in range(ncols)]
    key = ("concat", len(batches), caps, cap,
           tuple((f.name, f.data_type) for f in schema) if schema else
           tuple(c[0].dtype for c in per_col))
    counts_v = jnp.asarray(counts, jnp.int32)
    out = fuse.call_fused(key, "concat", lambda: kernel,
                          (per_col, counts_v),
                          lambda: kernel(per_col, counts_v))
    return ColumnarBatch([c.to_vector() for c in out], total, schema)
