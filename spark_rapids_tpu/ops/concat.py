"""Batch concatenation on device (reference: cudf Table.concatenate driven by
GpuCoalesceBatches / ConcatAndConsumeAll). Implemented as dynamic_update_slice into a
fresh padded buffer so it fuses and works with device-scalar row counts."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.vector import TpuColumnVector, bucket_capacity
from spark_rapids_tpu.expr.core import Col


def concat_batches(batches) -> ColumnarBatch:
    """Concatenate batches (host-known row counts) into one device batch.

    One fused XLA program per (k, capacities, schema) signature: pad-concat
    every column, stable-compact live rows to the front (shared permutation),
    slice to the output bucket. Row counts cross as a traced vector so varying
    fill levels replay the same compiled program."""
    from spark_rapids_tpu.runtime import fuse
    batches = list(batches)
    if len(batches) == 1:
        return batches[0]
    schema = batches[0].schema
    counts = [b.num_rows for b in batches]
    total = sum(counts)
    cap = bucket_capacity(total)
    ncols = batches[0].num_cols
    caps = tuple(b.capacity for b in batches)

    def kernel(per_col, counts_v):
        from spark_rapids_tpu.ops.strings import align_many
        from spark_rapids_tpu.ops.filtering import compact_cols, slice_to_capacity
        live = jnp.concatenate([
            jnp.arange(c, dtype=jnp.int32) < counts_v[i]
            for i, c in enumerate(caps)])
        merged = []
        for cols in per_col:
            if cols[0].is_string:
                cols = align_many(cols)
            merged.append(Col(
                jnp.concatenate([c.values for c in cols]),
                jnp.concatenate([c.validity for c in cols]),
                cols[0].dtype, cols[0].dictionary))
        compacted, count = compact_cols(merged, live)
        return slice_to_capacity(compacted, count, cap)

    per_col = [[Col.from_vector(b.column(ci)) for b in batches]
               for ci in range(ncols)]
    key = ("concat", len(batches), caps, cap,
           tuple((f.name, f.data_type) for f in schema) if schema else
           tuple(c[0].dtype for c in per_col))
    counts_v = jnp.asarray(counts, jnp.int32)
    out = fuse.call_fused(key, "concat", lambda: kernel,
                          (per_col, counts_v),
                          lambda: kernel(per_col, counts_v))
    return ColumnarBatch([c.to_vector() for c in out], total, schema)
