"""Batch concatenation on device (reference: cudf Table.concatenate driven by
GpuCoalesceBatches / ConcatAndConsumeAll). Implemented as dynamic_update_slice into a
fresh padded buffer so it fuses and works with device-scalar row counts."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.vector import TpuColumnVector, bucket_capacity
from spark_rapids_tpu.expr.core import Col


def concat_batches(batches) -> ColumnarBatch:
    """Concatenate batches (host-known row counts) into one device batch."""
    batches = list(batches)
    if len(batches) == 1:
        return batches[0]
    schema = batches[0].schema
    total = sum(b.num_rows for b in batches)
    cap = bucket_capacity(total)
    ncols = batches[0].num_cols

    # align string dictionaries per column across batches
    from spark_rapids_tpu.ops.strings import align_many
    per_col = []
    for ci in range(ncols):
        cols = [Col.from_vector(b.column(ci)) for b in batches]
        if cols[0].is_string:
            cols = align_many(cols)
        per_col.append(cols)

    out_cols = []
    for ci in range(ncols):
        cols = per_col[ci]
        dt = cols[0].dtype
        vals = jnp.full((cap,), dt.default_value(), dtype=cols[0].values.dtype)
        valid = jnp.zeros((cap,), jnp.bool_)
        off = 0
        for b, c in zip(batches, cols):
            n = b.num_rows
            if n == 0:
                continue
            vals = jax.lax.dynamic_update_slice(vals, c.values[:n], (off,))
            valid = jax.lax.dynamic_update_slice(valid, c.validity[:n], (off,))
            off += n
        out_cols.append(TpuColumnVector(dt, vals, valid,
                                        cols[0].dictionary))
    return ColumnarBatch(out_cols, total, schema)
