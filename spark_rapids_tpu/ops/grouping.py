"""Sort-based group-by — the cudf groupby analog under XLA's static-shape regime.

Reference: GpuHashAggregateExec (aggregate.scala:240) calls cudf hash groupby, whose
output size is data-dependent. XLA cannot produce data-dependent shapes, so the
TPU-native design is a FUSED sort-based pipeline within the padded capacity:

    sort rows by keys → flag group boundaries → segment-reduce values
    → compact one row per group to the front → group count as a device scalar

Everything is one XLA program; the number of groups never exceeds the number of
live rows, so the input capacity bounds the output. Null keys form their own
group (Spark GROUP BY semantics); null aggregation semantics (sum ignores nulls,
null iff no non-null input, NaN handling in min/max) live in expr/aggregates.py
which drives these primitives.

Segment reductions are SCAN-based, never scatter-based: TPU scatters at large
segment counts are catastrophically slow (measured: jax.ops.segment_sum with
4M segments does not finish in minutes on v5e, while the whole sort is ~7 ms).
Sums difference one global cumsum at segment edges (exact for ints even across
wrap; f64 cancellation error is ~ulp(prefix) — negligible at analytic scales);
min/max/first/last ride segmented doubling scans (ops/windowing.py) gathered at
per-row segment ends. Results are PER-ROW (row i holds the aggregate of row i's
whole segment), so callers compact boundary rows to get one row per group.
"""

from __future__ import annotations

import typing

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Col
from spark_rapids_tpu.ops import windowing as W
from spark_rapids_tpu.ops.sorting import sort_permutation, SortOrder
from spark_rapids_tpu.ops.filtering import gather_cols, compact_cols


class SegCtx(typing.NamedTuple):
    """Shared segment structure for one sorted group-by batch."""
    seg_ids: jnp.ndarray    # group index per sorted row (pad → capacity-1)
    boundary: jnp.ndarray   # True at the first row of each segment
    seg_start: jnp.ndarray  # index of the first row of the row's segment
    seg_end: jnp.ndarray    # index of the last row of the row's segment
    capacity: int


def compact_key_codes(key_cols, max_domain: int = 1 << 20):
    """(codes int32, strides) for keys whose domains are STATICALLY known
    (dictionary-coded strings, booleans); nulls get each key's top code
    (Spark groups nulls together). None when unknown/overflowing."""
    if not key_cols:
        return None
    strides = []
    K = 1
    for c in key_cols:
        if c.is_string and c.dictionary is not None:
            d = len(c.dictionary) + 1
        elif isinstance(c.dtype, T.BooleanType):
            d = 3
        else:
            return None
        strides.append(d)
        K *= d
        if K > max_domain:
            return None
    combined = None
    for c, d in zip(key_cols, strides):
        code = c.values.astype(jnp.int32)
        code = jnp.where(c.validity, code, jnp.int32(d - 1))
        combined = code if combined is None else combined * d + code
    return combined, strides


def combine_compact_keys(key_cols):
    """Fuse group keys with STATICALLY-known small domains (dictionary-coded
    strings, booleans) into one int32 code column: sorts and boundary checks
    then touch a single operand instead of one per key (~6x cheaper multi-key
    group-by). Nulls get their own code (Spark groups nulls together).
    Returns None when any key's domain is unknown or the product overflows."""
    if len(key_cols) < 2:
        return None  # single key is already one operand
    ks = compact_key_codes(key_cols)
    if ks is None:
        return None
    combined, _ = ks
    return Col(combined, jnp.ones_like(combined, dtype=jnp.bool_), T.INT)


def dense_group_sum(vals, mask, codes, n_domain: int, use_matmul: bool,
                    count_like: bool = False):
    """(n_domain,) per-group totals of `vals` over UNSORTED small-domain
    codes — no sort, no segment structure. CPU: D-bucket scatter-add. TPU:
    one-hot matmul (MXU-shaped; a cap-length scatter would serialize there,
    the round-2 wedge lesson).

    `count_like` marks 0/1-valued inputs (histograms, per-batch count
    updates): those are EXACT in f32 below 2^24 rows, so on TPU they ride
    the blocked Pallas one-hot kernel (pallas_kernels.onehot_sum_f32) which
    never materializes the (cap, D) one-hot in HBM — the medium-domain
    MXU-shaped path. Everything else keeps the jnp one-hot (f64 for exact
    integer sums), which bounds the practical domain."""
    v = jnp.where(mask, vals, jnp.zeros_like(vals))
    if use_matmul:
        want = v.dtype
        if count_like and v.shape[0] < (1 << 24):
            # the f32 2^24 exactness bound: a batch cap at/above it could
            # put >2^24 ones in one bucket — exact f64 path instead
            from spark_rapids_tpu.ops import pallas_kernels as PK
            if PK.should_use("onehot"):
                out = PK.onehot_sum_f32(v.astype(jnp.float32), codes,
                                        n_domain)
                return out.astype(want)
        if jnp.issubdtype(want, jnp.integer):
            # integer matmul is not an MXU op; f64 (emulated ~49-bit
            # mantissa on TPU) sums counts exactly to ~5e14
            v = v.astype(jnp.float64)
        onehot = (codes[:, None] == jnp.arange(n_domain, dtype=jnp.int32)
                  [None, :]).astype(v.dtype)
        out = v @ onehot
        return out.astype(want) if out.dtype != want else out
    out = jnp.zeros((n_domain + 1,), v.dtype)
    return out.at[jnp.clip(codes, 0, n_domain)].add(v,
                                                    mode="drop")[:n_domain]


_STACK_MAX_DOMAIN = 64   # per-domain masked matvecs unroll D times


def resolve_dense_group_sums(reqs, codes, n_domain: int, live):
    """CPU batch executor for a batch's dense_group_sum requests
    (`reqs` = [(vals, mask, acc_dtype, count_like), ...]) → results in
    request order. At small domains, requests whose accumulator is
    f64-exact — float sums (native f64) and count-likes (0/1 inputs: any
    count ≤ capacity is exact in a 53-bit mantissa) — stack into one
    (A, cap) f64 matrix reduced by D masked matvecs (V @ (codes == d)):
    XLA:CPU's scatter-add costs ~50 ms per column at 1M rows, the shared
    masked reduction ~6 ms — and unlike a materialized (cap, D) one-hot
    GEMM it never allocates O(cap*D). Wide integer value sums and big
    domains keep the exact per-column scatter path."""
    outs: list = [None] * len(reqs)
    stack = [i for i, (v, m, acc, cl) in enumerate(reqs)
             if cl or jnp.issubdtype(jnp.dtype(acc), jnp.floating)]
    if len(stack) >= 2 and n_domain <= _STACK_MAX_DOMAIN:
        # identity-dedup: sum(x)/avg(x)/count(x) share memoized input arrays
        # (exec/aggregate.py eval_child), so equal requests reduce once
        row_of: dict = {}
        rows = []
        for i in stack:
            v, m, _, _ = reqs[i]
            kk = (id(v), id(m))
            if kk not in row_of:
                row_of[kk] = len(rows)
                rows.append(jnp.where(m & live, v.astype(jnp.float64), 0.0))
        V = jnp.stack(rows)
        sums = jnp.stack(
            [V @ (codes == d).astype(jnp.float64)
             for d in range(n_domain)], axis=1)   # (A, D)
        for i in stack:
            v, m, acc, _ = reqs[i]
            outs[i] = sums[row_of[(id(v), id(m))]].astype(acc)
    for i, (v, m, acc, cl) in enumerate(reqs):
        if outs[i] is None:
            outs[i] = dense_group_sum(v.astype(acc), m & live, codes,
                                      n_domain, False, count_like=cl)
    return outs


def group_segments(key_cols, num_rows, capacity: int, range_hint=None,
                   presorted: bool = False):
    """Sort by keys and compute segment structure.

    Returns (perm, seg_ids, boundary, live) where perm is the sorting permutation,
    seg_ids[i] is the group index of sorted row i (padding rows get group capacity-1
    overflow bucket that is later discarded), boundary marks first row of each group.
    `range_hint` forwards a caller's key-range probe to the packed sort
    (ops/sorting._packed_key) for single statically-wide int keys.
    `presorted=True` asserts the caller PROVED the live rows already arrive
    key-sorted (exec/aggregate's per-batch key-stats probe): the sort and the
    key gather vanish — equal keys are contiguous by hypothesis, so segment
    detection runs directly over the input order (the sorted-input group-by,
    Spark's sort-aware aggregate analog).
    """
    live = jnp.arange(capacity, dtype=jnp.int32) < num_rows
    if presorted:
        perm = jnp.arange(capacity, dtype=jnp.int32)
        sorted_keys = [Col(c.values, c.validity & live, c.dtype, c.dictionary)
                       for c in key_cols]
    else:
        orders = [SortOrder() for _ in key_cols]
        perm = sort_permutation(key_cols, orders, num_rows, capacity,
                                range_hint=range_hint)
        sorted_keys = gather_cols(key_cols, perm, live)

    neq = jnp.zeros((capacity,), jnp.bool_)
    for c in sorted_keys:
        prev_vals = jnp.roll(c.values, 1)
        prev_valid = jnp.roll(c.validity, 1)
        if isinstance(c.dtype, T.FractionalType):
            # NaN == NaN for grouping (Spark), -0.0 == 0.0 (canonicalized already)
            a, b = c.values, prev_vals
            both_nan = jnp.isnan(a) & jnp.isnan(b)
            differs = ~both_nan & ~(a == b)
        else:
            differs = c.values != prev_vals
        neq = neq | differs | (c.validity != prev_valid)
    first_live = jnp.arange(capacity) == 0
    boundary = (first_live | neq) & live
    seg_ids = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    seg_ids = jnp.where(live, seg_ids, capacity - 1)
    seg_ids = jnp.clip(seg_ids, 0, capacity - 1)
    return perm, seg_ids, boundary, live


def segment_structure(seg_ids, capacity: int) -> SegCtx:
    """Per-row segment start/end from sorted seg_ids (two NATIVE cumulative
    ops — see windowing.seg_starts/seg_ends — shared by every aggregate in
    the batch)."""
    idx = jnp.arange(capacity, dtype=jnp.int32)
    prev = jnp.roll(seg_ids, 1)
    boundary = (idx == 0) | (seg_ids != prev)
    seg_start = W.seg_starts(boundary)
    seg_end = W.seg_ends(boundary)
    return SegCtx(seg_ids, boundary, seg_start, seg_end, capacity)


def _edge_sum(data, ctx: SegCtx):
    """Per-row segment total of `data` via one global cumsum differenced at the
    row's segment edges. Exact for ints (wrap cancels); f64 error ~ulp(prefix)."""
    cs = jnp.cumsum(data, axis=0)
    csz = jnp.concatenate([jnp.zeros((1,), cs.dtype), cs])
    return csz[ctx.seg_end + 1] - csz[ctx.seg_start]


def _seg_scan(data, ctx: SegCtx, combine):
    """Segmented inclusive scan reusing the PRECOMPUTED ctx.seg_start (the
    generic windowing.segmented_scan would re-derive it per call)."""
    from spark_rapids_tpu.ops.windowing import _doubling_scan
    return _doubling_scan(data, lambda i, s: (i - s) >= ctx.seg_start, combine)


def _seg_sum_tree(data, ctx: SegCtx):
    """Per-segment float total via a range-sum tree (sparse-table query).

    Level k holds sums of aligned 2^k-blocks (built by pairwise halving — ~2x
    the data in total traffic). Each row's [seg_start, seg_end] range is
    decomposed into <= 2*log2(cap) disjoint aligned blocks and ADDED — no
    prefix subtraction at all, so segment totals never cancel against foreign
    segment prefixes (the flaw of cumsum edge-differencing), and the pairwise
    build gives better-than-sequential float error. Cost: log2(cap) masked
    gathers from geometrically shrinking levels vs log2(cap) full-width
    combine passes for the doubling scan (~20x cheaper at 256k rows)."""
    cap = ctx.capacity
    levels = [data]
    while levels[-1].shape[0] > 1:
        x = levels[-1]
        if x.shape[0] % 2:    # non-power-of-two capacity: zero-pad the level
            x = jnp.concatenate([x, jnp.zeros((1,), x.dtype)])
        levels.append(x.reshape(-1, 2).sum(axis=1))

    lo = ctx.seg_start
    hi = ctx.seg_end + 1
    out = jnp.zeros_like(data)
    for k in range(len(levels)):
        blk = jnp.int32(1 << k)
        # consume a 2^k block at the front if lo is 2^k-aligned-odd
        take_lo = ((lo & blk) != 0) & (lo + blk <= hi)
        contrib = levels[k][jnp.clip(lo >> k, 0, levels[k].shape[0] - 1)]
        out = out + jnp.where(take_lo, contrib, jnp.zeros_like(out))
        lo = jnp.where(take_lo, lo + blk, lo)
        # and one at the back if hi has bit k set
        take_hi = ((hi & blk) != 0) & (hi - blk >= lo)
        contrib = levels[k][jnp.clip((hi - blk) >> k, 0, levels[k].shape[0] - 1)]
        out = out + jnp.where(take_hi, contrib, jnp.zeros_like(out))
        hi = jnp.where(take_hi, hi - blk, hi)
    return out


def _seg_extreme(data, ctx: SegCtx, largest: bool):
    """Per-segment min/max by re-sorting (seg_id, value) pairs — seg_ids are
    already sorted, so the 2-key native sort only reorders within segments and
    the extreme lands on the segment's first/last row. One native sort
    (~log n comparator passes fused by XLA) instead of a log-step doubling
    scan over full-width data."""
    _, sorted_vals = jax.lax.sort([ctx.seg_ids, data], num_keys=2)
    pos = ctx.seg_end if largest else ctx.seg_start
    return sorted_vals[pos]


def segment_count(validity, ctx: SegCtx):
    """Per-row count of valid rows in the row's segment."""
    return _edge_sum(validity.astype(jnp.int64), ctx)


def segment_sum(values, validity, ctx: SegCtx):
    data = jnp.where(validity, values, jnp.zeros_like(values))
    if jnp.issubdtype(data.dtype, jnp.floating):
        # floats: range-sum tree — additions of disjoint aligned blocks only,
        # no cancellation against foreign segment prefixes
        s = _seg_sum_tree(data, ctx)[ctx.seg_end]
    else:
        s = _edge_sum(data, ctx)  # ints: exact even across wrap
    return s, segment_count(validity, ctx)


def segment_min(values, validity, ctx: SegCtx, dtype: T.DataType):
    if isinstance(dtype, T.FractionalType):
        sentinel = jnp.asarray(jnp.inf, values.dtype)
        nan = jnp.isnan(values)
        data = jnp.where(validity & ~nan, values, sentinel)
        m = _seg_extreme(data, ctx, largest=False)
        # all-NaN group: min is NaN (Spark: NaN is largest; min picks non-NaN if any)
        has_non_nan = _edge_sum((validity & ~nan).astype(jnp.int32), ctx)
        has_nan = _edge_sum((validity & nan).astype(jnp.int32), ctx)
        return jnp.where((has_non_nan == 0) & (has_nan > 0), jnp.nan, m)
    if values.dtype == jnp.bool_:
        data = jnp.where(validity, values, True).astype(jnp.int8)
        return _seg_extreme(data, ctx, largest=False).astype(jnp.bool_)
    info = jnp.iinfo(values.dtype)
    data = jnp.where(validity, values, jnp.asarray(info.max, values.dtype))
    return _seg_extreme(data, ctx, largest=False)


def segment_max(values, validity, ctx: SegCtx, dtype: T.DataType):
    if isinstance(dtype, T.FractionalType):
        nan = jnp.isnan(values)
        sentinel = jnp.asarray(-jnp.inf, values.dtype)
        data = jnp.where(validity & ~nan, values, sentinel)
        m = _seg_extreme(data, ctx, largest=True)
        has_nan = _edge_sum((validity & nan).astype(jnp.int32), ctx)
        # any NaN in group → max is NaN (NaN is largest)
        return jnp.where(has_nan > 0, jnp.nan, m)
    if values.dtype == jnp.bool_:
        data = jnp.where(validity, values, False).astype(jnp.int8)
        return _seg_extreme(data, ctx, largest=True).astype(jnp.bool_)
    info = jnp.iinfo(values.dtype)
    data = jnp.where(validity, values, jnp.asarray(info.min, values.dtype))
    return _seg_extreme(data, ctx, largest=True)


def segment_first(values, validity, ctx: SegCtx, ignore_nulls: bool):
    """First (by sorted order) value per group; Spark First(ignoreNulls)."""
    idx = jnp.arange(ctx.capacity, dtype=jnp.int32)
    big = jnp.int32(ctx.capacity)
    eligible = validity if ignore_nulls else jnp.ones_like(validity)
    cand = jnp.where(eligible, idx, big)
    pos = _seg_extreme(cand, ctx, largest=False)
    pos_clamped = jnp.clip(pos, 0, ctx.capacity - 1)
    vals = values[pos_clamped]
    valid = (pos < big) & validity[pos_clamped]
    return vals, valid


def segment_last(values, validity, ctx: SegCtx, ignore_nulls: bool):
    """Last (by sorted order) value per group; Spark Last(ignoreNulls)."""
    idx = jnp.arange(ctx.capacity, dtype=jnp.int32)
    small = jnp.int32(-1)
    eligible = validity if ignore_nulls else jnp.ones_like(validity)
    cand = jnp.where(eligible, idx, small)
    pos = _seg_extreme(cand, ctx, largest=True)
    pos_clamped = jnp.clip(pos, 0, ctx.capacity - 1)
    vals = values[pos_clamped]
    valid = (pos > small) & validity[pos_clamped]
    return vals, valid
