"""Sort-based group-by — the cudf groupby analog under XLA's static-shape regime.

Reference: GpuHashAggregateExec (aggregate.scala:240) calls cudf hash groupby, whose
output size is data-dependent. XLA cannot produce data-dependent shapes, so the
TPU-native design is a FUSED sort-based pipeline within the padded capacity:

    sort rows by keys → flag group boundaries → segment-reduce values
    → compact one row per group to the front → group count as a device scalar

Everything is one XLA program (sort + cumsum + segment ops + gather); the number of
groups never exceeds the number of live rows, so the input capacity bounds the output.
Null keys form their own group (Spark GROUP BY semantics); null aggregation semantics
(sum ignores nulls, null iff no non-null input, NaN handling in min/max) live in
expr/aggregates.py which drives these primitives.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Col
from spark_rapids_tpu.ops.sorting import sort_permutation, SortOrder
from spark_rapids_tpu.ops.filtering import gather_cols, compact_cols


def group_segments(key_cols, num_rows, capacity: int):
    """Sort by keys and compute segment structure.

    Returns (perm, seg_ids, boundary, live) where perm is the sorting permutation,
    seg_ids[i] is the group index of sorted row i (padding rows get group capacity-1
    overflow bucket that is later discarded), boundary marks first row of each group.
    """
    orders = [SortOrder() for _ in key_cols]
    perm = sort_permutation(key_cols, orders, num_rows, capacity)
    live = jnp.arange(capacity, dtype=jnp.int32) < num_rows
    sorted_keys = gather_cols(key_cols, perm, live)

    neq = jnp.zeros((capacity,), jnp.bool_)
    for c in sorted_keys:
        prev_vals = jnp.roll(c.values, 1)
        prev_valid = jnp.roll(c.validity, 1)
        if isinstance(c.dtype, T.FractionalType):
            # NaN == NaN for grouping (Spark), -0.0 == 0.0 (canonicalized already)
            a, b = c.values, prev_vals
            both_nan = jnp.isnan(a) & jnp.isnan(b)
            differs = ~both_nan & ~(a == b)
        else:
            differs = c.values != prev_vals
        neq = neq | differs | (c.validity != prev_valid)
    first_live = jnp.arange(capacity) == 0
    boundary = (first_live | neq) & live
    seg_ids = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    seg_ids = jnp.where(live, seg_ids, capacity - 1)
    seg_ids = jnp.clip(seg_ids, 0, capacity - 1)
    return perm, seg_ids, boundary, live


def segment_sum(values, validity, seg_ids, capacity):
    data = jnp.where(validity, values, jnp.zeros_like(values))
    s = jax.ops.segment_sum(data, seg_ids, num_segments=capacity)
    cnt = jax.ops.segment_sum(validity.astype(jnp.int64), seg_ids,
                              num_segments=capacity)
    return s, cnt


def segment_min(values, validity, seg_ids, capacity, dtype: T.DataType):
    if isinstance(dtype, T.FractionalType):
        sentinel = jnp.asarray(jnp.inf, values.dtype)
        nan = jnp.isnan(values)
        data = jnp.where(validity & ~nan, values, sentinel)
        m = jax.ops.segment_min(data, seg_ids, num_segments=capacity)
        # all-NaN group: min is NaN (Spark: NaN is largest; min picks non-NaN if any)
        has_non_nan = jax.ops.segment_max((validity & ~nan).astype(jnp.int32),
                                          seg_ids, num_segments=capacity)
        has_nan = jax.ops.segment_max((validity & nan).astype(jnp.int32), seg_ids,
                                      num_segments=capacity)
        m = jnp.where((has_non_nan == 0) & (has_nan > 0), jnp.nan, m)
        return m
    info = jnp.iinfo(values.dtype) if values.dtype != jnp.bool_ else None
    if values.dtype == jnp.bool_:
        data = jnp.where(validity, values, True)
        return jax.ops.segment_min(data.astype(jnp.int8), seg_ids,
                                   num_segments=capacity).astype(jnp.bool_)
    data = jnp.where(validity, values, jnp.asarray(info.max, values.dtype))
    return jax.ops.segment_min(data, seg_ids, num_segments=capacity)


def segment_max(values, validity, seg_ids, capacity, dtype: T.DataType):
    if isinstance(dtype, T.FractionalType):
        nan = jnp.isnan(values)
        sentinel = jnp.asarray(-jnp.inf, values.dtype)
        data = jnp.where(validity & ~nan, values, sentinel)
        m = jax.ops.segment_max(data, seg_ids, num_segments=capacity)
        has_nan = jax.ops.segment_max((validity & nan).astype(jnp.int32), seg_ids,
                                      num_segments=capacity)
        # any NaN in group → max is NaN (NaN is largest)
        m = jnp.where(has_nan > 0, jnp.nan, m)
        return m
    if values.dtype == jnp.bool_:
        data = jnp.where(validity, values, False)
        return jax.ops.segment_max(data.astype(jnp.int8), seg_ids,
                                   num_segments=capacity).astype(jnp.bool_)
    info = jnp.iinfo(values.dtype)
    data = jnp.where(validity, values, jnp.asarray(info.min, values.dtype))
    return jax.ops.segment_max(data, seg_ids, num_segments=capacity)


def segment_first(values, validity, seg_ids, capacity, ignore_nulls: bool):
    """First (by sorted order) value per group; Spark First(ignoreNulls)."""
    idx = jnp.arange(capacity, dtype=jnp.int32)
    big = jnp.int32(capacity)
    eligible = validity if ignore_nulls else jnp.ones_like(validity)
    cand = jnp.where(eligible, idx, big)
    pos = jax.ops.segment_min(cand, seg_ids, num_segments=capacity)
    pos_clamped = jnp.clip(pos, 0, capacity - 1)
    vals = values[pos_clamped]
    valid = (pos < big) & validity[pos_clamped]
    return vals, valid


def segment_last(values, validity, seg_ids, capacity, ignore_nulls: bool):
    """Last (by sorted order) value per group; Spark Last(ignoreNulls)."""
    idx = jnp.arange(capacity, dtype=jnp.int32)
    small = jnp.int32(-1)
    eligible = validity if ignore_nulls else jnp.ones_like(validity)
    cand = jnp.where(eligible, idx, small)
    pos = jax.ops.segment_max(cand, seg_ids, num_segments=capacity)
    pos_clamped = jnp.clip(pos, 0, capacity - 1)
    vals = values[pos_clamped]
    valid = (pos > small) & validity[pos_clamped]
    return vals, valid
