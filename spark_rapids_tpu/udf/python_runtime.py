"""Python UDF runtime: worker-process execution with Arrow exchange.

Reference (SURVEY.md #40): GpuArrowEvalPythonExec ships device batches to
separate Python worker processes over Arrow IPC (BatchQueue:187,
GpuArrowPythonRunner:336, python/rapids daemon/worker), throttled by
PythonWorkerSemaphore (separate from the device semaphore). Here the workers are
a process pool fed cloudpickled functions and Arrow IPC payloads; device batches
hop D2H → worker → H2D with a bounded prefetch pipeline standing in for the
BatchQueue."""

from __future__ import annotations

import concurrent.futures as futures
import io
import threading

import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import TpuExec, acquire_semaphore
from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime.tracing import trace_range


def _worker_eval(payload: bytes, ipc: bytes, vectorized: bool,
                 ret_arrow: bytes) -> bytes:
    """Runs inside a worker process: unpickle fn, eval over the arrow batch."""
    import cloudpickle
    import pyarrow as pa_w
    fn = cloudpickle.loads(payload)
    tbl = pa_w.ipc.open_stream(ipc).read_all()
    cols = [tbl.column(i).to_pandas() for i in range(tbl.num_columns)]
    ret_type = pa_w.ipc.open_stream(ret_arrow).read_all().schema.field(0).type
    if vectorized:
        out = fn(*cols)
        arr = pa_w.Array.from_pandas(out, type=ret_type)
    else:
        # scalar UDF: one python call per row; nulls arrive as None and the
        # function decides (Spark scalar-UDF semantics)
        lists = [tbl.column(i).to_pylist() for i in range(tbl.num_columns)]
        vals = [fn(*args) for args in zip(*lists)] if lists else []
        arr = pa_w.array(vals, type=ret_type)
    sink = pa_w.BufferOutputStream()
    out_t = pa_w.table({"r": arr})
    with pa_w.ipc.new_stream(sink, out_t.schema) as w:
        w.write_table(out_t)
    return sink.getvalue().to_pybytes()


class PythonWorkerSemaphore:
    """Bound concurrent python workers (reference PythonWorkerSemaphore.scala:41
    — deliberately separate from the device semaphore)."""

    _sem = threading.Semaphore(4)

    @classmethod
    def initialize(cls, n: int):
        cls._sem = threading.Semaphore(n)


class PythonWorkerPool:
    _instance = None
    _lock = threading.Lock()

    def __init__(self, max_workers: int = 4):
        import multiprocessing as mp
        # spawn, never fork: the parent runs multithreaded JAX, and forking
        # a threaded process intermittently dies with "Fatal Python error"
        # (the reference sidesteps this the same way — its python workers
        # are daemon-spawned fresh interpreters, python/rapids/daemon.py)
        self.pool = futures.ProcessPoolExecutor(
            max_workers=max_workers, mp_context=mp.get_context("spawn"))

    @classmethod
    def get(cls) -> "PythonWorkerPool":
        with cls._lock:
            if cls._instance is None:
                cls._instance = PythonWorkerPool()
            return cls._instance

    @classmethod
    def shutdown(cls):
        with cls._lock:
            if cls._instance is not None:
                cls._instance.pool.shutdown(wait=False)
                cls._instance = None


def _to_ipc(tbl: pa.Table) -> bytes:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, tbl.schema) as w:
        w.write_table(tbl)
    return sink.getvalue().to_pybytes()


def _ret_schema_ipc(ret_type: T.DataType) -> bytes:
    t = pa.table({"r": pa.array([], T.to_arrow_type(ret_type))})
    return _to_ipc(t)


class PythonUDF(Expression):
    """A UDF that could not be compiled to device expressions; the planner tags
    its exec host-side, and host evaluation runs through the worker pool
    (reference GpuUserDefinedFunction fallback contract)."""

    def __init__(self, fn, children: list, return_type: T.DataType,
                 vectorized: bool = False):
        self.fn = fn
        self.children = list(children)
        self.return_type = return_type
        self.vectorized = vectorized

    @property
    def dtype(self):
        return self.return_type

    @property
    def nullable(self):
        return True

    def with_children(self, children):
        return PythonUDF(self.fn, children, self.return_type, self.vectorized)

    def eval(self, ctx):
        raise RuntimeError("PythonUDF cannot run inside a device kernel; the "
                           "planner must route it through ArrowEvalPythonExec")

    def eval_arrow(self, tbl: pa.Table) -> pa.Array:
        """Evaluate over a host arrow table of the child columns."""
        import cloudpickle
        payload = cloudpickle.dumps(self.fn)
        with PythonWorkerSemaphore._sem:
            fut = PythonWorkerPool.get().pool.submit(
                _worker_eval, payload, _to_ipc(tbl), self.vectorized,
                _ret_schema_ipc(self.return_type))
            out_ipc = fut.result()
        return pa.ipc.open_stream(out_ipc).read_all().column(0)

    def __repr__(self):
        name = getattr(self.fn, "__name__", "fn")
        return f"python_udf:{name}({', '.join(map(repr, self.children))})"


class ArrowEvalPythonExec(TpuExec):
    """Device exec evaluating PythonUDF projections: D2H → worker → H2D with a
    bounded prefetch pipeline (reference GpuArrowEvalPythonExec + BatchQueue)."""

    def __init__(self, project_list: list, child: TpuExec, conf=None,
                 prefetch: int = 2):
        from spark_rapids_tpu.expr.core import bind_references
        super().__init__(child, conf=conf)
        self.project_list = [bind_references(e, child.output)
                             for e in project_list]
        self.prefetch = prefetch
        self._udf_time = self.metrics.metric(M.OP_TIME, M.MODERATE)

    @property
    def output(self):
        from spark_rapids_tpu.expr.core import (Alias, AttributeReference,
                                                BoundReference)
        fields = []
        for i, e in enumerate(self.project_list):
            name = (e.name if isinstance(e, (Alias, AttributeReference,
                                             BoundReference)) else f"c{i}")
            fields.append(T.StructField(name, e.dtype, e.nullable))
        return T.StructType(fields)

    def execute_partition(self, split):
        from spark_rapids_tpu.expr.core import Alias, EvalContext

        def eval_batch(batch):
            with trace_range("ArrowEvalPython", self._udf_time):
                host = batch.to_arrow()
                cols = {}
                for i, e in enumerate(self.project_list):
                    inner = e.child if isinstance(e, Alias) else e
                    fname = self.output.fields[i].name
                    if isinstance(inner, PythonUDF):
                        child_tbl = pa.Table.from_arrays(
                            [_host_eval_col(c, host)
                             for c in inner.children],
                            names=[f"a{j}"
                                   for j in range(len(inner.children))])
                        cols[fname] = inner.eval_arrow(child_tbl)
                    else:
                        cols[fname] = _host_eval_col(inner, host)
                out = pa.table(cols)
                return ColumnarBatch.from_arrow(out, self.output)

        def it():
            # prefetch threads re-enter the query scope so any event they
            # fire (spill during H2D, etc.) attributes to this query/node
            collector = M.current_collector()

            def eval_in_scope(batch):
                with M.collector_context(collector), \
                        M.node_frame(self._node_id, None):
                    return eval_batch(batch)

            pending = []
            pool = futures.ThreadPoolExecutor(max_workers=self.prefetch)
            try:
                for batch in self.child.execute_partition(split):
                    acquire_semaphore(self.metrics)
                    pending.append(pool.submit(eval_in_scope, batch))
                    while len(pending) > self.prefetch:
                        yield pending.pop(0).result()
                for f in pending:
                    yield f.result()
            finally:
                pool.shutdown(wait=False)
        return self.wrap_output(it())


def _host_eval_col(expr, tbl: pa.Table) -> pa.Array:
    from spark_rapids_tpu.plan.host_eval import eval_host
    hc = eval_host(expr, tbl)
    return pa.array(hc.data, T.to_arrow_type(hc.dtype))
