"""Pandas-UDF exec family: mapInPandas, grouped applyInPandas, grouped
aggregate, and cogrouped applyInPandas.

Reference (SURVEY.md #40): sql-plugin/src/main/scala/org/apache/spark/sql/
rapids/execution/python/ — GpuMapInPandasExec.scala, GpuFlatMapGroupsInPandas
Exec.scala, GpuAggregateInPandasExec.scala, GpuFlatMapCoGroupsInPandasExec
.scala: device batches hop to python workers over Arrow, the GPU side handles
batching/partitioning, the pandas side runs the user function.

TPU realization: the engine keeps scan→exchange on device; each PARTITION
crosses to a spawned worker as one multi-batch Arrow IPC stream (preserving
Spark's iterator-of-batches contract for mapInPandas — a stateful user fn
sees the whole partition), the worker groups/applies in pandas, and results
ride Arrow back and device_put as columnar batches. Grouped shapes require a
hash exchange on the keys first (the planner inserts it, like Spark's
required-distribution for FlatMapGroupsInPandas).
"""

from __future__ import annotations

import concurrent.futures as futures

import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import TpuExec, acquire_semaphore
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime.tracing import trace_range
from spark_rapids_tpu.udf.python_runtime import (PythonWorkerPool,
                                                 PythonWorkerSemaphore,
                                                 _to_ipc)


def _schema_ipc(schema: T.StructType) -> bytes:
    return _to_ipc(schema.to_arrow().empty_table())


def _read_schema(schema_ipc: bytes):
    import pyarrow as pa_w
    return pa_w.ipc.open_stream(schema_ipc).read_all().schema


def _stream_ipc(tables) -> bytes:
    """Serialize a sequence of same-schema tables as one multi-batch stream."""
    sink = pa.BufferOutputStream()
    writer = None
    for t in tables:
        if writer is None:
            writer = pa.ipc.new_stream(sink, t.schema)
        for b in t.to_batches():
            writer.write_batch(b)
    if writer is None:
        return b""
    writer.close()
    return sink.getvalue().to_pybytes()


def _df_to_table(df, schema):
    import pyarrow as pa_w
    cols = []
    for f in schema:
        col = pa_w.Array.from_pandas(df[f.name], type=f.type)
        cols.append(col)
    return pa_w.Table.from_arrays(cols, schema=schema)


# ---------------------------------------------------------------------------
# worker-side functions (run in spawned processes; import only stdlib + arrow
# + pandas + cloudpickle)

def _worker_map_partition(payload: bytes, ipc: bytes,
                          schema_ipc: bytes) -> bytes:
    """mapInPandas: fn(iterator[DataFrame]) -> iterator[DataFrame]."""
    import cloudpickle
    import pyarrow as pa_w
    fn = cloudpickle.loads(payload)
    schema = _read_schema(schema_ipc)
    if ipc:
        reader = pa_w.ipc.open_stream(ipc)
        dfs = (pa_w.Table.from_batches([b]).to_pandas() for b in reader)
    else:
        dfs = iter(())
    sink = pa_w.BufferOutputStream()
    writer = pa_w.ipc.new_stream(sink, schema)
    for out_df in fn(dfs):
        writer.write_table(_df_to_table(out_df, schema))
    writer.close()
    return sink.getvalue().to_pybytes()


def _worker_grouped_apply(payload: bytes, ipc: bytes, schema_ipc: bytes,
                          key_names: tuple) -> bytes:
    """applyInPandas: fn(group DataFrame incl. key columns) -> DataFrame."""
    import cloudpickle
    import pyarrow as pa_w
    fn = cloudpickle.loads(payload)
    schema = _read_schema(schema_ipc)
    sink = pa_w.BufferOutputStream()
    writer = pa_w.ipc.new_stream(sink, schema)
    if ipc:
        df = pa_w.ipc.open_stream(ipc).read_all().to_pandas()
        if len(df):
            for _, g in df.groupby(list(key_names), dropna=False, sort=False):
                out_df = fn(g.reset_index(drop=True))
                writer.write_table(_df_to_table(out_df, schema))
    writer.close()
    return sink.getvalue().to_pybytes()


def _norm_key(vals):
    """Hashable, NaN-stable group key (NaN groups with NaN, Spark/pandas
    dropna=False semantics)."""
    out = []
    for v in vals:
        if isinstance(v, float) and v != v:
            out.append("__nan__")
        else:
            out.append(v)
    return tuple(out)


def _worker_cogrouped_apply(payload: bytes, l_ipc: bytes, r_ipc: bytes,
                            schema_ipc: bytes, l_keys: tuple, r_keys: tuple,
                            l_schema_ipc: bytes, r_schema_ipc: bytes) -> bytes:
    """cogroup applyInPandas: fn(left_group_df, right_group_df) -> DataFrame.
    Keys present on either side produce a call; the absent side gets an
    empty frame with its full schema (Spark FlatMapCoGroupsInPandas)."""
    import cloudpickle
    import pyarrow as pa_w
    fn = cloudpickle.loads(payload)
    schema = _read_schema(schema_ipc)

    def side(ipc, sch_ipc):
        if ipc:
            return pa_w.ipc.open_stream(ipc).read_all().to_pandas()
        return _read_schema(sch_ipc).empty_table().to_pandas()

    ldf = side(l_ipc, l_schema_ipc)
    rdf = side(r_ipc, r_schema_ipc)

    def groups(df, keys):
        if not len(df):
            return {}, []
        order, out = [], {}
        for key, g in df.groupby(list(keys), dropna=False, sort=False):
            k = _norm_key(key if isinstance(key, tuple) else (key,))
            out[k] = g.reset_index(drop=True)
            order.append(k)
        return out, order

    lg, lorder = groups(ldf, l_keys)
    rg, rorder = groups(rdf, r_keys)
    keys = lorder + [k for k in rorder if k not in lg]
    sink = pa_w.BufferOutputStream()
    writer = pa_w.ipc.new_stream(sink, schema)
    for k in keys:
        out_df = fn(lg.get(k, ldf.iloc[0:0]), rg.get(k, rdf.iloc[0:0]))
        writer.write_table(_df_to_table(out_df, schema))
    writer.close()
    return sink.getvalue().to_pybytes()


def _worker_agg_pandas(payloads: list, ipc: bytes, schema_ipc: bytes,
                       key_names: tuple, input_cols: tuple) -> bytes:
    """Grouped aggregate pandas UDFs: one scalar per (group, udf).
    payloads[i] aggregates over the series named in input_cols[i]."""
    import cloudpickle
    import pyarrow as pa_w
    fns = [cloudpickle.loads(p) for p in payloads]
    schema = _read_schema(schema_ipc)
    rows = {f.name: [] for f in schema}
    nkeys = len(key_names)
    if ipc:
        df = pa_w.ipc.open_stream(ipc).read_all().to_pandas()
        if len(df):
            for key, g in df.groupby(list(key_names), dropna=False,
                                     sort=False):
                key = key if isinstance(key, tuple) else (key,)
                for i, name in enumerate(key_names):
                    v = key[i]
                    # pandas surfaces a null int64 key as float NaN
                    if isinstance(v, float) and v != v:
                        v = None
                    rows[schema.field(i).name].append(v)
                for i, fn in enumerate(fns):
                    args = [g[c].reset_index(drop=True)
                            for c in input_cols[i]]
                    rows[schema.field(nkeys + i).name].append(fn(*args))
    cols = [pa_w.array(rows[f.name], type=f.type) for f in schema]
    out = pa_w.Table.from_arrays(cols, schema=schema)
    return _to_ipc(out)


# ---------------------------------------------------------------------------
# expression marker for grouped aggregate pandas UDFs

from spark_rapids_tpu.expr.core import Expression as _Expression


class PandasAggUDF(_Expression):
    """F.pandas_agg_udf(fn, return_type)(col...) — the GROUPED_AGG flavor of
    Spark's pandas_udf: fn(Series...) -> scalar per group (reference
    GpuAggregateInPandasExec's udf payloads). Only valid inside
    group_by().agg(); the session layer routes it to AggregateInPandasNode."""

    def __init__(self, fn, return_type: T.DataType, input_cols: list):
        self.fn = fn
        self.return_type = return_type
        self.input_cols = list(input_cols)
        self.children = []

    def eval(self, ctx):
        raise RuntimeError(
            "pandas aggregate UDFs only run inside group_by().agg()")

    def alias(self, name: str):
        from spark_rapids_tpu.expr.core import Alias
        return Alias(self, name)

    @property
    def name(self):
        return getattr(self.fn, "__name__", "pandas_agg")

    @property
    def dtype(self):
        return self.return_type

    @property
    def nullable(self):
        return True

    @property
    def child(self):
        return None

    def __repr__(self):
        return f"pandas_agg:{self.name}({', '.join(self.input_cols)})"


# ---------------------------------------------------------------------------
# exec side

def _submit(worker_fn, *args) -> bytes:
    with PythonWorkerSemaphore._sem:
        fut = PythonWorkerPool.get().pool.submit(worker_fn, *args)
        return fut.result()


def _yield_ipc_batches(out_ipc: bytes, schema: T.StructType):
    if not out_ipc:
        return
    reader = pa.ipc.open_stream(out_ipc)
    for b in reader:
        if b.num_rows:
            yield ColumnarBatch.from_arrow(pa.Table.from_batches([b]), schema)


class _PandasExecBase(TpuExec):
    def __init__(self, fn, out_schema: T.StructType, *children, conf=None):
        super().__init__(*children, conf=conf)
        self.fn = fn
        self.out_schema = out_schema
        self._udf_time = self.metrics.metric(M.OP_TIME, M.MODERATE)

    @property
    def output(self):
        return self.out_schema

    def _partition_ipc(self, child, split) -> bytes:
        tables = []
        for batch in child.execute_partition(split):
            acquire_semaphore(self.metrics)
            tables.append(batch.to_arrow())
        return _stream_ipc(tables)

    def _payload(self):
        import cloudpickle
        return cloudpickle.dumps(self.fn)


class MapInPandasExec(_PandasExecBase):
    """df.mapInPandas(fn, schema) — reference GpuMapInPandasExec.scala:
    the user fn sees the partition as an iterator of pandas DataFrames."""

    def execute_partition(self, split):
        def it():
            with trace_range("MapInPandas", self._udf_time):
                ipc = self._partition_ipc(self.child, split)
                out = _submit(_worker_map_partition, self._payload(), ipc,
                              _schema_ipc(self.out_schema))
            yield from _yield_ipc_batches(out, self.out_schema)
        return self.wrap_output(it())

    def args_string(self):
        return f"fn={getattr(self.fn, '__name__', 'fn')}"


class GroupedMapInPandasExec(_PandasExecBase):
    """groupBy(keys).applyInPandas(fn, schema) — reference
    GpuFlatMapGroupsInPandasExec.scala. The planner hash-exchanges the child
    on the keys first, so every group is entirely within one partition."""

    def __init__(self, key_names: list, fn, out_schema, child, conf=None):
        super().__init__(fn, out_schema, child, conf=conf)
        self.key_names = list(key_names)

    def execute_partition(self, split):
        def it():
            with trace_range("GroupedMapInPandas", self._udf_time):
                ipc = self._partition_ipc(self.child, split)
                out = _submit(_worker_grouped_apply, self._payload(), ipc,
                              _schema_ipc(self.out_schema),
                              tuple(self.key_names))
            yield from _yield_ipc_batches(out, self.out_schema)
        return self.wrap_output(it())

    def args_string(self):
        return f"keys={self.key_names} fn={getattr(self.fn, '__name__', 'fn')}"


class CoGroupedMapInPandasExec(_PandasExecBase):
    """cogroup(left, right).applyInPandas — reference
    GpuFlatMapCoGroupsInPandasExec.scala. Both children are hash-exchanged
    on their keys with the SAME partition count, so matching groups meet in
    the same split."""

    def __init__(self, left_keys: list, right_keys: list, fn, out_schema,
                 left, right, conf=None):
        super().__init__(fn, out_schema, left, right, conf=conf)
        self.left_key_names = list(left_keys)
        self.right_key_names = list(right_keys)

    @property
    def num_partitions(self):
        return self.children[0].num_partitions

    def execute_partition(self, split):
        def it():
            with trace_range("CoGroupedMapInPandas", self._udf_time):
                l_ipc = self._partition_ipc(self.children[0], split)
                r_ipc = self._partition_ipc(self.children[1], split)
                out = _submit(_worker_cogrouped_apply, self._payload(), l_ipc,
                              r_ipc, _schema_ipc(self.out_schema),
                              tuple(self.left_key_names),
                              tuple(self.right_key_names),
                              _schema_ipc(self.children[0].output),
                              _schema_ipc(self.children[1].output))
            yield from _yield_ipc_batches(out, self.out_schema)
        return self.wrap_output(it())

    def args_string(self):
        return (f"lkeys={self.left_key_names} rkeys={self.right_key_names} "
                f"fn={getattr(self.fn, '__name__', 'fn')}")


class AggregateInPandasExec(_PandasExecBase):
    """groupBy(keys).agg(pandas_agg_udf(...)) — reference
    GpuAggregateInPandasExec.scala: each UDF reduces its input series to one
    scalar per group."""

    def __init__(self, key_names: list, udfs: list, out_schema, child,
                 conf=None):
        """udfs: list of (fn, [input column names])."""
        super().__init__(None, out_schema, child, conf=conf)
        self.key_names = list(key_names)
        self.udfs = list(udfs)

    def execute_partition(self, split):
        import cloudpickle

        def it():
            with trace_range("AggregateInPandas", self._udf_time):
                ipc = self._partition_ipc(self.child, split)
                payloads = [cloudpickle.dumps(fn) for fn, _ in self.udfs]
                out = _submit(_worker_agg_pandas, payloads, ipc,
                              _schema_ipc(self.out_schema),
                              tuple(self.key_names),
                              tuple(tuple(cols) for _, cols in self.udfs))
            yield from _yield_ipc_batches(out, self.out_schema)
        return self.wrap_output(it())

    def args_string(self):
        return f"keys={self.key_names} udfs={len(self.udfs)}"
