"""L7 UDF layer: bytecode compiler + Python worker runtime (SURVEY.md #38-40)."""

from spark_rapids_tpu.udf.compiler import compile_udf, udf  # noqa: F401
from spark_rapids_tpu.udf.python_runtime import PythonUDF  # noqa: F401
