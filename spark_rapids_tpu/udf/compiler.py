"""UDF compiler: Python bytecode → engine expression trees.

Reference (SURVEY.md #38): the udf-compiler module JIT-translates Scala/Java
bytecode into Catalyst expressions via javassist CFG extraction + abstract
interpretation of JVM opcodes (CFG.scala:329, Instruction.scala:953,
CatalystExpressionBuilder.scala:430). Same design against CPython bytecode: a
symbolic stack machine interprets the instruction stream; conditional jumps fork
execution and merge as If(cond, then, else); the result is a bound Expression
that runs fused on the device instead of a per-row Python call.

Coverage: arithmetic/comparison/boolean operators, constants, arguments,
ternaries and nested conditionals, `and`/`or` short-circuits, math.* calls,
abs(), str methods (upper/lower/strip), len(). Both CPython bytecode dialects
in the support window are handled: 3.10's specialized opcodes
(BINARY_ADD/..., CALL_FUNCTION/CALL_METHOD, JUMP_IF_{TRUE,FALSE}_OR_POP,
JUMP_ABSOLUTE) and 3.11+'s unified forms (BINARY_OP, CALL + PUSH_NULL,
COPY/SWAP; 3.12 emits COPY + POP_JUMP + POP_TOP for short-circuits — the
fork at the jump reconverges as If). Anything else returns None and the
caller falls back to the Python-worker runtime (#40), exactly the
compiled-else-fallback contract of the reference's Plugin.scala:28."""

from __future__ import annotations

import dis
import math
import types as pytypes

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import arithmetic as A
from spark_rapids_tpu.expr import conditional as C
from spark_rapids_tpu.expr import mathexprs as M
from spark_rapids_tpu.expr import predicates as P
from spark_rapids_tpu.expr import strings as S
from spark_rapids_tpu.expr.core import Expression, Literal, _infer_literal_type


class _CannotCompile(Exception):
    pass


# BINARY_OP argument → expression class (CPython 3.11+ oparg values)
_BINOPS = {
    "+": A.Add, "-": A.Subtract, "*": A.Multiply, "/": A.Divide,
    "%": A.Remainder, "//": A.IntegralDivide, "**": M.Pow,
}

# pre-3.11 specialized binary opcodes (one opcode per operator)
_BINOP_NAMES = {
    "BINARY_ADD": A.Add, "BINARY_SUBTRACT": A.Subtract,
    "BINARY_MULTIPLY": A.Multiply, "BINARY_TRUE_DIVIDE": A.Divide,
    "BINARY_MODULO": A.Remainder, "BINARY_FLOOR_DIVIDE": A.IntegralDivide,
    "BINARY_POWER": M.Pow,
}

_CMPOPS = {
    "<": P.LessThan, "<=": P.LessThanOrEqual, ">": P.GreaterThan,
    ">=": P.GreaterThanOrEqual, "==": P.EqualTo, "!=": P.NotEqual,
}

_MATH_CALLS = {
    ("math", "sqrt"): M.Sqrt, ("math", "exp"): M.Exp, ("math", "sin"): M.Sin,
    ("math", "cos"): M.Cos, ("math", "tan"): M.Tan, ("math", "asin"): M.Asin,
    ("math", "acos"): M.Acos, ("math", "atan"): M.Atan,
    ("math", "log"): M.Log, ("math", "log2"): M.Log2,
    ("math", "log10"): M.Log10, ("math", "log1p"): M.Log1p,
    ("math", "floor"): M.Floor, ("math", "ceil"): M.Ceil,
}

_STR_METHODS = {
    "upper": S.Upper, "lower": S.Lower, "strip": S.Trim, "lstrip": S.LTrim,
    "rstrip": S.RTrim,
}


class _Marker:
    """Non-expression stack values (modules, bound methods, NULL)."""

    def __init__(self, kind, payload=None):
        self.kind = kind
        self.payload = payload


def _lit(v) -> Expression:
    if isinstance(v, Expression):
        return v
    return Literal(v, _infer_literal_type(v))


class _Compiler:
    def __init__(self, fn, arg_exprs):
        self.fn = fn
        code = fn.__code__
        if code.co_argcount != len(arg_exprs):
            raise _CannotCompile("arity mismatch")
        if code.co_flags & 0x08 or code.co_flags & 0x04:  # *args / **kwargs
            raise _CannotCompile("varargs not supported")
        if fn.__closure__:
            self.cells = {name: cell.cell_contents for name, cell in
                          zip(code.co_freevars, fn.__closure__)}
        else:
            self.cells = {}
        self.args = {code.co_varnames[i]: arg_exprs[i]
                     for i in range(code.co_argcount)}
        self.instrs = list(dis.get_instructions(fn))
        self.by_offset = {ins.offset: i for i, ins in enumerate(self.instrs)}
        self.globals = fn.__globals__

    def run(self) -> Expression:
        # absolute backstop so pathologically branchy UDFs cannot stall the
        # planner (each conditional forks both arms; cost can grow with 2^depth)
        self._steps = 0
        return self._exec(0, [])

    def _exec(self, idx: int, stack: list, depth: int = 0) -> Expression:
        """Symbolically execute from instruction idx; returns the expression
        produced at RETURN. Forks at conditional jumps (bounded depth). Loops
        cannot become expressions: an unconditional loop (`while True`) is a
        JUMP_BACKWARD revisiting an offset within one linear walk → detected
        below; a conditional loop re-forks each iteration → depth bound."""
        if depth > 40:
            raise _CannotCompile("too many branches")
        stack = list(stack)
        seen = set()  # instruction indices executed in this linear walk
        while idx < len(self.instrs):
            self._steps += 1
            if self._steps > 1_000_000:
                raise _CannotCompile("UDF too complex to compile")
            seen.add(idx)
            ins = self.instrs[idx]
            op = ins.opname
            if op in ("RESUME", "NOP", "CACHE", "PRECALL",
                      "COPY_FREE_VARS", "MAKE_CELL"):
                idx += 1
            elif op == "LOAD_FAST":
                if ins.argval not in self.args:
                    raise _CannotCompile(f"unknown local {ins.argval}")
                stack.append(self.args[ins.argval])
                idx += 1
            elif op == "LOAD_CONST":
                stack.append(_lit(ins.argval) if not isinstance(
                    ins.argval, (tuple, frozenset, pytypes.CodeType))
                    else _Marker("const", ins.argval))
                idx += 1
            elif op == "LOAD_DEREF":
                if ins.argval not in self.cells:
                    raise _CannotCompile(f"unknown closure var {ins.argval}")
                v = self.cells[ins.argval]
                if not isinstance(v, (int, float, str, bool, type(None))):
                    raise _CannotCompile("non-scalar closure capture")
                stack.append(_lit(v))
                idx += 1
            elif op == "LOAD_GLOBAL":
                name = ins.argval
                import builtins
                v = self.globals.get(name, getattr(builtins, name, None))
                if v is math:
                    stack.append(_Marker("module", "math"))
                elif v is abs:
                    stack.append(_Marker("builtin", "abs"))
                elif v is len:
                    stack.append(_Marker("builtin", "len"))
                elif isinstance(v, (int, float, str, bool)):
                    stack.append(_lit(v))
                else:
                    raise _CannotCompile(f"unsupported global {name}")
                idx += 1
            elif op == "LOAD_ATTR":
                recv = stack.pop()
                if isinstance(recv, _Marker) and recv.kind == "module":
                    key = (recv.payload, ins.argval)
                    if key not in _MATH_CALLS:
                        raise _CannotCompile(f"unsupported call {key}")
                    stack.append(_Marker("mathfn", _MATH_CALLS[key]))
                elif isinstance(recv, Expression):
                    # method load on a column (3.12 encodes method bit in arg)
                    if ins.argval not in _STR_METHODS:
                        raise _CannotCompile(
                            f"unsupported method {ins.argval}")
                    stack.append(_Marker("strmethod",
                                         (_STR_METHODS[ins.argval], recv)))
                else:
                    raise _CannotCompile("bad LOAD_ATTR receiver")
                idx += 1
            elif op == "LOAD_METHOD":
                recv = stack.pop()
                if isinstance(recv, _Marker) and recv.kind == "module":
                    # pre-3.11 method load on a module (math.sqrt etc. —
                    # 3.12 routes these through LOAD_ATTR instead)
                    key = (recv.payload, ins.argval)
                    if key not in _MATH_CALLS:
                        raise _CannotCompile(f"unsupported call {key}")
                    stack.append(_Marker("mathfn", _MATH_CALLS[key]))
                elif isinstance(recv, Expression) and \
                        ins.argval in _STR_METHODS:
                    stack.append(_Marker("strmethod",
                                         (_STR_METHODS[ins.argval], recv)))
                else:
                    raise _CannotCompile(f"unsupported method {ins.argval}")
                idx += 1
            elif op in ("CALL", "CALL_FUNCTION", "CALL_METHOD"):
                # 3.11+ unified CALL; pre-3.11 CALL_FUNCTION/CALL_METHOD
                # (the symbolic stack holds ONE marker per callee either way)
                nargs = ins.arg
                cargs = [stack.pop() for _ in range(nargs)][::-1]
                callee = stack.pop()
                if isinstance(callee, _Marker) and callee.kind == "null":
                    callee = stack.pop()  # NULL | callable layout
                stack.append(self._call(callee, cargs))
                idx += 1
            elif op == "PUSH_NULL":
                stack.append(_Marker("null"))
                idx += 1
            elif op == "BINARY_OP":
                r, l = stack.pop(), stack.pop()
                sym = ins.argrepr.rstrip("=")
                if sym not in _BINOPS:
                    raise _CannotCompile(f"unsupported binop {ins.argrepr}")
                stack.append(_BINOPS[sym](self._expr(l), self._expr(r)))
                idx += 1
            elif op in _BINOP_NAMES:
                r, l = stack.pop(), stack.pop()
                stack.append(_BINOP_NAMES[op](self._expr(l), self._expr(r)))
                idx += 1
            elif op == "COMPARE_OP":
                r, l = stack.pop(), stack.pop()
                sym = ins.argrepr.strip()
                if sym not in _CMPOPS:
                    raise _CannotCompile(f"unsupported compare {sym}")
                stack.append(_CMPOPS[sym](self._expr(l), self._expr(r)))
                idx += 1
            elif op == "UNARY_NEGATIVE":
                stack.append(A.UnaryMinus(self._expr(stack.pop())))
                idx += 1
            elif op == "UNARY_NOT":
                stack.append(P.Not(self._expr(stack.pop())))
                idx += 1
            elif op in ("POP_JUMP_IF_FALSE", "POP_JUMP_IF_TRUE"):
                cond = self._expr(stack.pop())
                target = self.by_offset[ins.argval]
                if op == "POP_JUMP_IF_TRUE":
                    cond = P.Not(cond)
                then_e = self._exec(idx + 1, stack, depth + 1)
                else_e = self._exec(target, stack, depth + 1)
                return C.If(cond, then_e, else_e)
            elif op in ("JUMP_IF_FALSE_OR_POP", "JUMP_IF_TRUE_OR_POP"):
                # pre-3.11 and/or short-circuit: the jumping arm KEEPS the
                # condition value on the stack, the falling-through arm pops
                # it — fork both and reconverge as If
                cond = self._expr(stack[-1])
                target = self.by_offset[ins.argval]
                keep = self._exec(target, stack, depth + 1)
                drop = self._exec(idx + 1, stack[:-1], depth + 1)
                if op == "JUMP_IF_FALSE_OR_POP":
                    return C.If(cond, drop, keep)   # true → evaluate rest
                return C.If(cond, keep, drop)       # true → keep cond
            elif op == "COPY":
                stack.append(stack[-ins.arg])
                idx += 1
            elif op == "DUP_TOP":
                stack.append(stack[-1])
                idx += 1
            elif op == "POP_TOP":
                stack.pop()
                idx += 1
            elif op == "SWAP":
                stack[-1], stack[-ins.arg] = stack[-ins.arg], stack[-1]
                idx += 1
            elif op == "ROT_TWO":
                stack[-1], stack[-2] = stack[-2], stack[-1]
                idx += 1
            elif op in ("JUMP_FORWARD", "JUMP_BACKWARD", "JUMP_ABSOLUTE"):
                target = self.by_offset[ins.argval]
                if target in seen:
                    raise _CannotCompile("loop in UDF bytecode")
                idx = target
            elif op == "RETURN_VALUE":
                return self._expr(stack.pop())
            elif op == "RETURN_CONST":
                return _lit(ins.argval)
            else:
                raise _CannotCompile(f"unsupported opcode {op}")
        raise _CannotCompile("fell off the end")

    def _expr(self, v) -> Expression:
        if isinstance(v, Expression):
            return v
        raise _CannotCompile(f"expected expression, got {v}")

    def _call(self, callee, cargs) -> Expression:
        if isinstance(callee, _Marker) and callee.kind == "mathfn":
            if len(cargs) == 1:
                from spark_rapids_tpu.expr.cast import Cast
                return callee.payload(Cast(self._expr(cargs[0]), T.DOUBLE))
            if len(cargs) == 2 and callee.payload is M.Pow:
                return M.Pow(self._expr(cargs[0]), self._expr(cargs[1]))
            raise _CannotCompile("bad math arity")
        if isinstance(callee, _Marker) and callee.kind == "builtin":
            if callee.payload == "abs" and len(cargs) == 1:
                return A.Abs(self._expr(cargs[0]))
            if callee.payload == "len" and len(cargs) == 1:
                return S.Length(self._expr(cargs[0]))
            raise _CannotCompile(f"unsupported builtin {callee.payload}")
        if isinstance(callee, _Marker) and callee.kind == "strmethod":
            cls, recv = callee.payload
            if cargs:
                raise _CannotCompile("string method args not supported")
            return cls(recv)
        raise _CannotCompile("unsupported callee")


def compile_udf(fn, arg_exprs: list) -> Expression | None:
    """Compile `fn(args…)` into an Expression over `arg_exprs`, or None when the
    bytecode uses unsupported features (caller falls back to the Python-worker
    runtime)."""
    try:
        return _Compiler(fn, list(arg_exprs)).run()
    except _CannotCompile:
        return None


def udf(fn, return_type: T.DataType | None = None):
    """Decorator/factory: `udf(lambda x: x * 2)(F.col('a'))` — compiled to a
    device expression when possible, else a PythonUDF running in worker
    processes (reference GpuScalaUDF + fallback, SURVEY.md #38/#39)."""

    def build(*cols):
        from spark_rapids_tpu.session import _to_expr
        args = [_to_expr(c) for c in cols]
        compiled = compile_udf(fn, args)
        if compiled is not None:
            return compiled
        from spark_rapids_tpu.udf.python_runtime import PythonUDF
        if return_type is None:
            raise ValueError(
                "UDF could not be compiled to device expressions; the Python "
                "worker fallback needs an explicit return_type")
        return PythonUDF(fn, args, return_type)

    return build
