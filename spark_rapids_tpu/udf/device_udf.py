"""Accelerated user UDFs: jax functions that run INSIDE the engine's device
programs.

Reference: RapidsUDF (sql-plugin/src/main/java/com/nvidia/spark/RapidsUDF.java
— users implement `evaluateColumnar` with a cudf implementation of their UDF,
and GpuUserDefinedFunction.scala routes the expression to it instead of the
row-by-row JVM fallback). TPU analog: the user supplies a jnp->jnp function;
the expression evaluates it on the padded column values inside whatever jitted
program the surrounding exec builds, so a jax UDF fuses with the rest of the
stage exactly like a built-in expression.

Two contracts (both batch-columnar, never per-row):

- simple (default): ``fn(*value_arrays) -> value_array``. Null semantics are
  Spark's UDF default: the result is null where ANY input is null, and fn
  never sees which rows those are (inputs hold the type's canonical default
  in null slots).
- null-aware: ``fn(*(values, validity) pairs) -> (values, validity)`` for
  UDFs that want to produce or consume nulls themselves.

The jax-compiled UDF path is the preferred ladder rung above the bytecode
compiler (udf/compiler.py) and the arrow worker pool (udf/python_runtime.py):
    jax_udf (device, fused) > compiled bytecode (device exprs) > python pool.
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Col, Expression


class JaxUDF(Expression):
    """User-provided device function evaluated columnar-batch-at-a-time."""

    def __init__(self, fn, children: list, return_type: T.DataType,
                 null_aware: bool = False, name: str | None = None):
        self.fn = fn
        self.children = list(children)
        self.return_type = return_type
        self.null_aware = null_aware
        self.udf_name = name or getattr(fn, "__name__", "jax_udf")

    @property
    def dtype(self):
        return self.return_type

    @property
    def nullable(self):
        return True

    def with_children(self, children):
        return JaxUDF(self.fn, children, self.return_type, self.null_aware,
                      self.udf_name)

    def eval(self, ctx):
        cols = [c.eval(ctx) for c in self.children]
        if self.null_aware:
            out = self.fn(*((c.values, c.validity) for c in cols))
            try:
                vals, valid = out
            except (TypeError, ValueError):
                raise TypeError(
                    f"null-aware jax UDF {self.udf_name} must return "
                    "(values, validity)") from None
        else:
            vals = self.fn(*(c.values for c in cols))
            valid = jnp.ones((ctx.capacity,), jnp.bool_)
            for c in cols:
                valid = valid & c.validity
        vals = jnp.asarray(vals)
        if vals.shape != (ctx.capacity,):
            raise ValueError(
                f"jax UDF {self.udf_name} returned shape {vals.shape}, expected "
                f"({ctx.capacity},) — UDFs must be elementwise over the "
                "padded batch")
        want = self.return_type.jnp_dtype
        if want is not None and vals.dtype != jnp.dtype(want):
            vals = vals.astype(want)
        default = jnp.asarray(self.return_type.default_value(), vals.dtype)
        vals = jnp.where(valid, vals, default)  # canonicalize null slots
        return Col(vals, valid, self.return_type)

    def __repr__(self):
        return f"jax_udf:{self.udf_name}({', '.join(map(repr, self.children))})"


def jax_udf(fn, return_type: T.DataType, null_aware: bool = False):
    """Wrap a jax function as a device UDF: ``F.jax_udf(fn, T.DOUBLE)(col)``.
    The function must be jit-traceable (no data-dependent Python control
    flow) and elementwise over 1-D arrays."""
    from spark_rapids_tpu.expr.core import _auto_lit, Expression as _E

    def build(*cols):
        kids = [c if isinstance(c, _E) else _auto_lit(c) for c in cols]
        return JaxUDF(fn, kids, return_type, null_aware)

    return build
