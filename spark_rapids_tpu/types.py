"""Spark SQL data types, mapped to TPU-resident representations.

Mirrors the type surface the reference supports on GPU (reference TypeChecks.scala:129
`TypeSig`, GpuColumnVector.java `getNonNestedRapidsType`): BOOLEAN, BYTE, SHORT, INT,
LONG, FLOAT, DOUBLE, DATE, TIMESTAMP, STRING, DECIMAL(<=18), NULL, plus nested
ARRAY/STRUCT/MAP (later rounds).

Device representation (TPU-first, not a cudf translation):
- fixed-width types: one padded jax array + bool validity mask.
- DateType: int32 days since epoch. TimestampType: int64 microseconds since epoch (UTC),
  matching Spark's internal representation.
- DecimalType(p<=18): scaled int64 (reference supports the same bound via DECIMAL64,
  GpuOverrides.scala DecimalType checks).
- StringType: dictionary-encoded — int32 codes on device + a host-side sorted dictionary
  (pyarrow), so comparisons/sorts/joins/group-bys run entirely on-device over codes; a
  byte-mode (int32 offsets + uint8 data on device) is used by byte-level kernels.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np
import pyarrow as pa


class DataType:
    """Base of the Spark SQL type hierarchy."""

    #: jnp dtype of the device value array (None for types with no single array, e.g. NULL)
    jnp_dtype = None
    #: canonical Spark SQL name
    sql_name = "unknown"

    def __repr__(self):
        return self.sql_name

    def __eq__(self, other):
        return type(self) is type(other)

    def __hash__(self):
        return hash(type(self))

    @property
    def is_numeric(self):
        return isinstance(self, NumericType)

    @property
    def is_fixed_width(self):
        return self.jnp_dtype is not None

    def default_value(self):
        """Canonical value stored in invalid (null) slots so padded garbage never leaks
        into hashes/sorts (reference keeps nulls arbitrary and relies on cudf null
        masks; on TPU we canonicalize instead)."""
        return 0


class NumericType(DataType):
    pass


class IntegralType(NumericType):
    pass


class FractionalType(NumericType):
    pass


class BooleanType(DataType):
    jnp_dtype = jnp.bool_
    sql_name = "boolean"

    def default_value(self):
        return False


class ByteType(IntegralType):
    jnp_dtype = jnp.int8
    sql_name = "tinyint"


class ShortType(IntegralType):
    jnp_dtype = jnp.int16
    sql_name = "smallint"


class IntegerType(IntegralType):
    jnp_dtype = jnp.int32
    sql_name = "int"


class LongType(IntegralType):
    jnp_dtype = jnp.int64
    sql_name = "bigint"


class FloatType(FractionalType):
    jnp_dtype = jnp.float32
    sql_name = "float"

    def default_value(self):
        return 0.0


class DoubleType(FractionalType):
    jnp_dtype = jnp.float64
    sql_name = "double"

    def default_value(self):
        return 0.0


class StringType(DataType):
    # device codes are int32 into a host dictionary; byte-mode uses offsets+uint8 data
    jnp_dtype = jnp.int32
    sql_name = "string"


class DateType(DataType):
    """Days since 1970-01-01, matching Spark's internal int32 representation."""
    jnp_dtype = jnp.int32
    sql_name = "date"


class TimestampType(DataType):
    """Microseconds since epoch UTC, matching Spark's internal int64 representation."""
    jnp_dtype = jnp.int64
    sql_name = "timestamp"


@dataclasses.dataclass(frozen=True)
class DecimalType(NumericType):
    """Decimal with precision<=18 carried as scaled int64 (DECIMAL64, the same bound the
    reference enforces in GpuOverrides tagging for cudf DType.DECIMAL64)."""
    precision: int = 10
    scale: int = 0
    jnp_dtype = jnp.int64

    MAX_PRECISION = 18

    def __post_init__(self):
        if self.precision > self.MAX_PRECISION:
            raise ValueError(
                f"DecimalType precision {self.precision} > {self.MAX_PRECISION} not "
                f"supported on device (reference has the same DECIMAL64 bound)")

    @property
    def sql_name(self):  # type: ignore[override]
        return f"decimal({self.precision},{self.scale})"

    def __repr__(self):
        return self.sql_name

    def __eq__(self, other):
        return (isinstance(other, DecimalType) and other.precision == self.precision
                and other.scale == self.scale)

    def __hash__(self):
        return hash(("decimal", self.precision, self.scale))


class NullType(DataType):
    jnp_dtype = jnp.int8  # carrier; every slot is invalid
    sql_name = "void"


class ArrayType(DataType):
    """Spark ArrayType. Host-side (interpreter/IO) representation is an arrow list
    column; there is no flat device representation yet, so TypeSig keeps array
    columns on the host (reference supports nested types in a limited op subset,
    TypeChecks.scala TypeSig.ARRAY)."""

    jnp_dtype = None
    sql_name = "array"

    def __init__(self, element_type: DataType, contains_null: bool = True):
        self.element_type = element_type
        self.contains_null = contains_null

    def default_value(self):
        return None

    def __eq__(self, other):
        return (isinstance(other, ArrayType)
                and other.element_type == self.element_type)

    def __hash__(self):
        return hash(("array", self.element_type))

    def __repr__(self):
        return f"ArrayType({self.element_type!r})"


# ---------------------------------------------------------------------------
# singletons (Spark-style)
# ---------------------------------------------------------------------------
BOOLEAN = BooleanType()
BYTE = ByteType()
SHORT = ShortType()
INT = IntegerType()
LONG = LongType()
FLOAT = FloatType()
DOUBLE = DoubleType()
STRING = StringType()
DATE = DateType()
TIMESTAMP = TimestampType()
NULL = NullType()


_ARROW_TO_SPARK = {
    pa.bool_(): BOOLEAN,
    pa.int8(): BYTE,
    pa.int16(): SHORT,
    pa.int32(): INT,
    pa.int64(): LONG,
    pa.float32(): FLOAT,
    pa.float64(): DOUBLE,
    pa.string(): STRING,
    pa.large_string(): STRING,
    pa.string_view(): STRING,
    pa.date32(): DATE,
    pa.null(): NULL,
}


def from_arrow_type(at: pa.DataType) -> DataType:
    """Map an Arrow type to the Spark SQL type the engine executes with."""
    if at in _ARROW_TO_SPARK:
        return _ARROW_TO_SPARK[at]
    if pa.types.is_timestamp(at):
        return TIMESTAMP
    if pa.types.is_decimal(at):
        return DecimalType(at.precision, at.scale)
    if pa.types.is_dictionary(at):
        return from_arrow_type(at.value_type)
    if pa.types.is_list(at) or pa.types.is_large_list(at):
        return ArrayType(from_arrow_type(at.value_type))
    if pa.types.is_struct(at):
        return StructDataType([at.field(i).name for i in range(at.num_fields)],
                              [from_arrow_type(at.field(i).type)
                               for i in range(at.num_fields)])
    raise TypeError(f"unsupported arrow type {at}")


def to_arrow_type(dt: DataType) -> pa.DataType:
    if isinstance(dt, ArrayType):
        return pa.list_(to_arrow_type(dt.element_type))
    if isinstance(dt, MapType):
        return pa.map_(to_arrow_type(dt.key_type),
                       to_arrow_type(dt.value_type))
    if isinstance(dt, StructDataType):
        return pa.struct([pa.field(n, to_arrow_type(t))
                          for n, t in zip(dt.names, dt.types)])
    if isinstance(dt, DecimalType):
        return pa.decimal128(dt.precision, dt.scale)
    if isinstance(dt, TimestampType):
        return pa.timestamp("us", tz="UTC")
    for a, s in _ARROW_TO_SPARK.items():
        if s == dt and a not in (pa.large_string(), pa.string_view()):
            return a
    raise TypeError(f"unsupported spark type {dt}")


def to_numpy_dtype(dt: DataType):
    return np.dtype(jnp.dtype(dt.jnp_dtype).name)


@dataclasses.dataclass(frozen=True)
class StructField:
    name: str
    data_type: DataType
    nullable: bool = True


class StructDataType(DataType):
    """Spark's StructType used as a COLUMN data type (struct<...> values).
    Like ArrayType there is no flat device representation; device support is
    limited to fused create+extract expression pairs (expr/complexexprs.py),
    everything else stays on host (reference TypeChecks TypeSig.STRUCT)."""

    jnp_dtype = None
    sql_name = "struct"

    def __init__(self, names: list, types: list):
        self.names = list(names)
        self.types = list(types)

    def default_value(self):
        return None

    def __eq__(self, other):
        return (isinstance(other, StructDataType)
                and other.names == self.names and other.types == self.types)

    def __hash__(self):
        return hash(("struct", tuple(self.names)))

    def __repr__(self):
        inner = ", ".join(f"{n}: {t!r}" for n, t in
                          zip(self.names, self.types))
        return f"StructDataType({inner})"


class StructType:
    """Schema of a batch/plan output (Spark StructType analog)."""
    fields: tuple

    def __init__(self, fields):
        object.__setattr__(self, "fields", tuple(fields))

    @property
    def names(self):
        return [f.name for f in self.fields]

    def __len__(self):
        return len(self.fields)

    def __getitem__(self, i):
        if isinstance(i, str):
            for f in self.fields:
                if f.name == i:
                    return f
            raise KeyError(i)
        return self.fields[i]

    def index_of(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)

    def __iter__(self):
        return iter(self.fields)

    def to_arrow(self) -> pa.Schema:
        return pa.schema([pa.field(f.name, to_arrow_type(f.data_type), f.nullable)
                          for f in self.fields])

    @staticmethod
    def from_arrow(schema: pa.Schema) -> "StructType":
        return StructType([StructField(f.name, from_arrow_type(f.type), f.nullable)
                           for f in schema])

    def to_json(self):
        """JSON-able schema (Spark StructType.json analog, used by the shuffle frame)."""
        return [{"name": f.name, "type": _type_to_json(f.data_type),
                 "nullable": f.nullable} for f in self.fields]

    @staticmethod
    def from_json(obj) -> "StructType":
        return StructType([StructField(f["name"], _type_from_json(f["type"]),
                                       f["nullable"]) for f in obj])


# -- compact type codes for the shuffle/spill wire format ----------------------
_CODE_TO_TYPE = {
    1: BOOLEAN, 2: BYTE, 3: SHORT, 4: INT, 5: LONG, 6: FLOAT, 7: DOUBLE,
    8: STRING, 9: DATE, 10: TIMESTAMP, 11: NULL,
}
_TYPE_TO_CODE = {type(v): k for k, v in _CODE_TO_TYPE.items()}
_DECIMAL_CODE = 12


def type_code(dt: DataType) -> int:
    if isinstance(dt, DecimalType):
        # precision/scale <= 38 each fit a byte-pair packed above the code space
        return _DECIMAL_CODE + (dt.precision << 8) + (dt.scale << 16)
    return _TYPE_TO_CODE[type(dt)]


def type_from_code(code: int) -> DataType:
    if code & 0xFF == _DECIMAL_CODE:
        return DecimalType((code >> 8) & 0xFF, (code >> 16) & 0xFF)
    return _CODE_TO_TYPE[code]


def _type_to_json(dt: DataType):
    if isinstance(dt, DecimalType):
        return {"decimal": [dt.precision, dt.scale]}
    return dt.sql_name


def _type_from_json(obj) -> DataType:
    if isinstance(obj, dict):
        p, s = obj["decimal"]
        return DecimalType(p, s)
    for t in _CODE_TO_TYPE.values():
        if t.sql_name == obj:
            return t
    raise ValueError(f"unknown type json {obj!r}")


class MapType(DataType):
    """Spark MapType. Like ArrayType/StructDataType there is no flat device
    representation; device support is the fused CreateMap+GetMapValue pair
    (expr/complexexprs.py), everything else stays on host (reference
    TypeChecks TypeSig.MAP)."""

    jnp_dtype = None
    sql_name = "map"

    def __init__(self, key_type: DataType, value_type: DataType,
                 value_contains_null: bool = True):
        self.key_type = key_type
        self.value_type = value_type
        self.value_contains_null = value_contains_null

    def __eq__(self, other):
        return (isinstance(other, MapType)
                and other.key_type == self.key_type
                and other.value_type == self.value_type)

    def __hash__(self):
        return hash(("map", self.key_type, self.value_type))

    def __repr__(self):
        return f"map<{self.key_type!r},{self.value_type!r}>"
