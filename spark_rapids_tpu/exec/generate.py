"""Generate (explode) exec — device expansion of list columns.

Reference: GpuGenerateExec.scala (explode/posexplode over cudf LIST columns,
493 LoC). TPU-native design: the list column arrives from the arrow bridge as
a ListVector (flat padded element vector on device + host row offsets,
columnar/vector.py); the exec computes the explode mapping as ONE jitted
gather program — per-output-row source indices come from a searchsorted over
the cumulative length prefix, so the MXU-facing data path never sees variable
shapes. Output capacity is the bucketed total element count (host-known from
offsets metadata, no device sync).

explode_outer keeps null/empty-list rows as one output row with a null
element (effective length max(len, 1); the element slot is invalid when the
position is past the true length).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.vector import (ListVector, TpuColumnVector,
                                              bucket_capacity)
from spark_rapids_tpu.exec.base import TpuExec, acquire_semaphore
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime.tracing import trace_range


class GenerateExec(TpuExec):
    def __init__(self, generator_col: str, child: TpuExec, outer: bool = False,
                 element_type: T.DataType | None = None, pos: bool = False,
                 conf=None):
        super().__init__(child, conf=conf)
        self.generator_col = generator_col
        self.outer = outer
        self.pos = pos  # posexplode: also emit the element position
        self.element_type = element_type or T.LONG

    @property
    def output(self):
        fields = [f for f in self.child.output
                  if f.name != self.generator_col]
        if self.pos:
            fields.append(T.StructField("pos", T.INT, self.outer))
        fields.append(T.StructField("col", self.element_type, True))
        return T.StructType(fields)

    def execute_partition(self, split):
        def it():
            for batch in self.child.execute_partition(split):
                acquire_semaphore(self.metrics)
                with trace_range("GenerateExec", self._op_time):
                    out = self._generate(batch)
                if out is not None:
                    yield out
        return self.wrap_output(it())

    def _generate(self, batch: ColumnarBatch) -> ColumnarBatch | None:
        names = batch.schema.names
        gi = names.index(self.generator_col)
        lv = batch.columns[gi]
        assert isinstance(lv, ListVector), \
            "planner must feed GenerateExec a bridge-produced list column"
        n = batch.num_rows
        lengths = np.diff(lv.offsets)[:n]
        # outer: null and empty lists still emit one (null-element) row
        eff = np.maximum(lengths, 1) if self.outer else lengths
        total = int(eff.sum())
        if total == 0:
            return None
        out_cap = bucket_capacity(total)

        # device mapping: out position -> (source row, element index)
        eff_d = jnp.zeros((batch.capacity,), jnp.int32).at[:n].set(
            jnp.asarray(eff.astype(np.int32)))
        cum = jnp.cumsum(eff_d)
        pos = jnp.arange(out_cap, dtype=jnp.int32)
        src = jnp.searchsorted(cum, pos, side="right").astype(jnp.int32)
        src_c = jnp.clip(src, 0, batch.capacity - 1)
        base = jnp.where(src_c > 0, cum[jnp.maximum(src_c - 1, 0)], 0)
        elem_idx = pos - base
        live = pos < total

        # element column: gather from the flat vector
        off_d = jnp.asarray(lv.offsets[:n].astype(np.int64))
        off_pad = jnp.zeros((batch.capacity,), jnp.int64).at[:n].set(off_d)
        flat_pos = off_pad[src_c] + elem_idx
        flat_cap = lv.flat.capacity
        flat_pos_c = jnp.clip(flat_pos, 0, flat_cap - 1)
        real_elem = elem_idx < lv.data[src_c]  # past-length slots (outer pad)
        evals = lv.flat.data[flat_pos_c]
        evalid = lv.flat.validity[flat_pos_c] & real_elem & live
        evals = jnp.where(evalid, evals, jnp.asarray(
            lv.element_dtype.default_value(), evals.dtype))

        out_cols = []
        for name, col in zip(names, batch.columns):
            if name == self.generator_col:
                continue
            vals = col.data[src_c]
            valid = col.validity[src_c] & live
            out_cols.append(TpuColumnVector(col.dtype, vals, valid,
                                            col.dictionary))
        if self.pos:
            # posexplode_outer pads null/empty rows with a NULL position
            pos_valid = real_elem & live
            out_cols.append(TpuColumnVector(
                T.INT, jnp.where(pos_valid, elem_idx, 0), pos_valid))
        out_cols.append(TpuColumnVector(self.element_type, evals, evalid,
                                        lv.flat.dictionary))
        return ColumnarBatch(out_cols, total, self.output)

    def args_string(self):
        kind = "posexplode" if self.pos else "explode"
        return f"{kind}({self.generator_col}), outer={self.outer}"
