"""Expand exec — each input row emits one row per projection (rollup/cube/
grouping-sets building block).

Reference: GpuExpandExec.scala (194 LoC): evaluates k projections per batch and
interleaves them. TPU-native: evaluate all k projections at the padded capacity,
stack to (cap, k) and reshape row-major — one fused XLA program, and the
interleaved layout (r0p0, r0p1, …) matches Spark's output order exactly."""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.vector import bucket_capacity
from spark_rapids_tpu.exec.base import TpuExec, acquire_semaphore
from spark_rapids_tpu.expr.core import Col, EvalContext, bind_references
from spark_rapids_tpu.ops.filtering import slice_to_capacity
from spark_rapids_tpu.ops.strings import align_many
from spark_rapids_tpu.runtime.tracing import trace_range


class ExpandExec(TpuExec):
    def __init__(self, projections: list, out_schema: T.StructType,
                 child: TpuExec, conf=None):
        super().__init__(child, conf=conf)
        self.projections = [[bind_references(e, child.output) for e in proj]
                            for proj in projections]
        k = len(self.projections)
        assert k >= 1 and all(len(p) == len(out_schema) for p in self.projections)
        self._out = out_schema

    @property
    def output(self):
        return self._out

    def execute_partition(self, split):
        k = len(self.projections)

        def it():
            for batch in self.child.execute_partition(split):
                acquire_semaphore(self.metrics)
                with trace_range("ExpandExec", self._op_time):
                    yield self._expand(batch, k)
        return self.wrap_output(it())

    def _expand(self, batch: ColumnarBatch, k: int) -> ColumnarBatch:
        from spark_rapids_tpu.expr.misc import CONTEXT_SENSITIVE
        from spark_rapids_tpu.runtime import fuse
        n_rows = batch.lazy_num_rows
        out_rows = n_rows * k
        # static output capacity: the host-known bucket when the row count is
        # known, else the padded worst case — either way a STATIC shape, so
        # the whole expand (k evals + interleave + re-bucket) traces as one
        # fused program keyed on it
        target = bucket_capacity(out_rows if isinstance(out_rows, int)
                                 else batch.capacity * k)
        ctx_sensitive = any(
            e.collect(lambda x: isinstance(x, CONTEXT_SENSITIVE))
            for proj in self.projections for e in proj)
        if batch.columns and not ctx_sensitive:
            key = ("expand", fuse.schema_key(self.child.output),
                   tuple(tuple(fuse.expr_key(e) for e in proj)
                         for proj in self.projections), target)

            def build():
                def kernel(cols, num_rows):
                    ctx = EvalContext(cols, num_rows,
                                      cols[0].values.shape[0])
                    return self._expand_kernel(ctx, k, target)
                return kernel

            in_cols = [Col.from_vector(c) for c in batch.columns]
            nr = jnp.asarray(n_rows, jnp.int32)
            out_cols = fuse.call_fused(
                key, "ExpandExec", build, (in_cols, nr),
                lambda: self._expand_kernel(EvalContext.from_batch(batch),
                                            k, target))
        else:
            out_cols = self._expand_kernel(EvalContext.from_batch(batch),
                                           k, target)
        return ColumnarBatch([c.to_vector() for c in out_cols], out_rows,
                             self._out)

    def _expand_kernel(self, ctx: EvalContext, k: int, target: int):
        """Pure per-batch expand body (traceable): k projection evals, the
        row-major interleave, and the re-land at the static `target`
        capacity (downstream kernels assume power-of-two buckets)."""
        cap = ctx.capacity
        per_proj = [[e.eval(ctx) for e in proj] for proj in self.projections]
        out_rows = ctx.num_rows * k
        out_cap = cap * k
        out_cols = []
        for ci, field in enumerate(self._out):
            cols = [per_proj[p][ci] for p in range(k)]
            if any(c.is_string for c in cols):
                cols = align_many(cols)  # shared dictionary across projections
            vals = jnp.stack([c.values for c in cols], axis=1).reshape(out_cap)
            valid = jnp.stack([c.validity for c in cols],
                              axis=1).reshape(out_cap)
            live = jnp.arange(out_cap, dtype=jnp.int64) < out_rows
            out_cols.append(Col(vals, valid & live, field.data_type,
                                cols[0].dictionary))
        if target != out_cap:
            out_cols = slice_to_capacity(out_cols, None, target)
        return out_cols

    def args_string(self):
        return f"{len(self.projections)} projections"
