"""Hash aggregate exec — Spark's two-phase aggregation on TPU.

Reference: aggregate.scala GpuHashAggregateExec:240 with the update→concat→merge loop
at 282-420 and computeAggregate:706: batches are aggregated incrementally (update
aggregation per batch, then merge-aggregation of partials) so memory stays bounded;
modes Partial/Final/Complete mirror Spark's AggregateMode.

TPU-native realization (see ops/grouping.py): each batch goes through one fused XLA
program — sort by keys, segment-reduce, compact one row per group. Partial results
accumulate; when more than one partial batch exists they are concatenated and
merge-aggregated (the same incremental loop as the reference). The group count stays
a device scalar until a downstream sync."""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import TpuExec, acquire_semaphore
from spark_rapids_tpu.expr.core import Alias, Col, EvalContext, bind_references
from spark_rapids_tpu.expr.aggregates import AggregateFunction
from spark_rapids_tpu.ops import grouping as G
from spark_rapids_tpu.ops.concat import concat_batches
from spark_rapids_tpu.ops.filtering import compact_cols, gather_cols
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import retry as R
from spark_rapids_tpu.runtime.tracing import trace_range

PARTIAL = "partial"
FINAL = "final"
COMPLETE = "complete"

# smallest batch capacity the group-by chain will fuse (see _chain_step)
_CHAIN_MIN_CAPACITY = 1024

# the partial→merge contract per aggregate op: which op folds two PARTIAL
# states of the named op into one (sums and counts re-SUM; min/max are
# idempotent under themselves). This is the same algebra the FINAL-mode
# merge below implements batch-to-batch; streaming/coordinator.py reuses it
# epoch-to-epoch — incremental streaming state IS a parked partial batch,
# and any consumer that parks partials across queries must merge with
# exactly these ops or double-count
AGG_MERGE_OPS = {"sum": "sum", "count": "sum", "min": "min", "max": "max"}


def _agg_fn(e) -> AggregateFunction:
    f = e.child if isinstance(e, Alias) else e
    assert isinstance(f, AggregateFunction), f
    return f


class HashAggregateExec(TpuExec):
    """group_exprs: grouping expressions; agg_exprs: Alias(AggregateFunction).

    mode=COMPLETE: update + evaluate in one exec (single stage);
    mode=PARTIAL: emits keys + state columns (pre-shuffle);
    mode=FINAL: child output is PARTIAL layout; merges states and evaluates.
    """

    def __init__(self, group_exprs: list, agg_exprs: list, child: TpuExec,
                 mode: str = COMPLETE, conf=None, prefilter=None,
                 preproject=None, prefilter_on_projected: bool = False):
        super().__init__(child, conf=conf)
        self.mode = mode
        # whole-stage fusion (planner hoists child Filter/Project execs):
        # `preproject` exprs re-derive the aggregation input inside the
        # kernel; `prefilter` masks rows there (dense path) or compacts
        # in-program (segment path) — no separate dispatches, no full-width
        # intermediate batches. The reference gets this from whole-stage
        # codegen feeding GpuHashAggregateExec; the fuse layer plays that
        # role here. With preproject set, group/agg exprs must arrive BOUND
        # against the hoisted project's output (the planner's logical nodes
        # bind eagerly, so this holds by construction).
        self.preproject = list(preproject) if preproject is not None else None
        self.prefilter_on_projected = prefilter_on_projected
        if mode == FINAL:
            # keys are the first child columns; aggs reference state columns
            self.group_exprs = [bind_references(e, child.output)
                                for e in group_exprs]
            self.agg_exprs = list(agg_exprs)
        elif self.preproject is not None:
            self.group_exprs = list(group_exprs)
            self.agg_exprs = list(agg_exprs)
        else:
            self.group_exprs = [bind_references(e, child.output)
                                for e in group_exprs]
            self.agg_exprs = [bind_references(e, child.output) for e in agg_exprs]
        bind_to = child.output if not prefilter_on_projected else None
        self.prefilter = (prefilter if prefilter is None or bind_to is None
                          else bind_references(prefilter, bind_to))
        # HAVING fusion: a Filter directly ABOVE this aggregate folded into
        # the finalize kernel (fuse_having, planner-only). Evaluated against
        # self.output after f.evaluate; surviving groups compact in the same
        # program (or via the host-indexed epilogue) — the separate FilterExec
        # dispatch and its full-width capacity disappear.
        self.postfilter = None
        self._agg_time = self.metrics.metric(M.AGG_TIME, M.MODERATE)
        self._concat_time = self.metrics.metric(M.CONCAT_TIME, M.MODERATE)
        # observed input cardinality (stats plane): with output rows this
        # gives the aggregation's reduction factor per node
        self._in_rows = self.metrics.metric(M.NUM_INPUT_ROWS, M.ESSENTIAL)

    @property
    def output(self):
        fields = [T.StructField(e.name, e.dtype, True) for e in self.group_exprs]
        if self.mode == PARTIAL:
            for e in self.agg_exprs:
                f = _agg_fn(e)
                for i, st in enumerate(f.state_types):
                    fields.append(T.StructField(f"{e.name}#state{i}", st, True))
        else:
            for e in self.agg_exprs:
                fields.append(T.StructField(e.name, _agg_fn(e).dtype, True))
        return T.StructType(fields)

    def fuse_having(self, condition):
        """Fold a HAVING predicate into finalization (plan/overrides
        conv_filter). The condition must reference only this aggregate's
        OUTPUT columns; COMPLETE/FINAL modes only (PARTIAL output is
        state-typed and the filter must see evaluated aggregates)."""
        assert self.mode != PARTIAL
        from spark_rapids_tpu.expr import predicates as P
        cond = bind_references(condition, self.output)
        self.postfilter = (cond if self.postfilter is None
                           else P.And(self.postfilter, cond))

    def _partial_schema(self):
        fields = [T.StructField(e.name, e.dtype, True) for e in self.group_exprs]
        for e in self.agg_exprs:
            f = _agg_fn(e)
            for i, st in enumerate(f.state_types):
                fields.append(T.StructField(f"{e.name}#state{i}", st, True))
        return T.StructType(fields)

    # ------------------------------------------------------------------
    def _aggregate_batch(self, batch: ColumnarBatch, merge: bool) -> ColumnarBatch:
        """One fused update-or-merge aggregation, jit-compiled per shape bucket
        (runtime/fuse.py). In merge mode the batch is in keys+state layout; in
        update mode it is raw child output. Returns a batch in keys+state
        layout with one row per group."""
        from spark_rapids_tpu.columnar.encoded import (EncodedColumnVector,
                                                       densify_cols)
        from spark_rapids_tpu.expr.core import Col
        from spark_rapids_tpu.expr.misc import CONTEXT_SENSITIVE
        from spark_rapids_tpu.runtime import fuse
        pre = self.prefilter if not merge else None
        prep = self.preproject if not merge else None
        ctx_sensitive = any(
            e.collect(lambda x: isinstance(x, CONTEXT_SENSITIVE))
            for e in (*self.group_exprs, *self.agg_exprs,
                      *([pre] if pre is not None else []),
                      *(prep or [])))
        if batch.columns and not ctx_sensitive:
            # scan-side chain: still-encoded scan columns enter the kernel AS
            # ENCODED PAGES and expand inside this fused program (late
            # materialization) — the standalone decode dispatch and its dense
            # H2D column never exist. from_vector on anything else (including
            # an already-forced encoded vector) yields the usual dense Col.
            use_enc = not merge and self.conf.scan_fusion_enabled
            in_cols = []
            for c in batch.columns:
                enc = (c.encoded if use_enc
                       and isinstance(c, EncodedColumnVector) else None)
                in_cols.append(enc if enc is not None else Col.from_vector(c))
            nr = jnp.asarray(batch.lazy_num_rows, jnp.int32)
            vmin_t, has_hint, presorted = self._key_range_hint(
                batch, in_cols, nr, merge)
            key = ("agg", merge, fuse.schema_key(
                self._partial_schema() if merge else self.child.output),
                tuple(fuse.expr_key(e) for e in self.group_exprs),
                tuple(fuse.expr_key(e) for e in self.agg_exprs),
                fuse.expr_key(pre) if pre is not None else None,
                tuple(fuse.expr_key(e) for e in prep) if prep is not None
                else None, self.prefilter_on_projected, has_hint, presorted)

            def build():
                def kernel(cols, num_rows, vmin):
                    cols = densify_cols(cols)
                    ctx = EvalContext(cols, num_rows, cols[0].values.shape[0])
                    return self._agg_kernel(
                        ctx, merge,
                        range_hint=(vmin, True) if has_hint else None,
                        presorted=presorted)
                return kernel

            compacted, n_groups = fuse.call_fused(
                key, "HashAggregateExec", build, (in_cols, nr, vmin_t),
                lambda: self._agg_kernel(EvalContext.from_batch(batch), merge))
            # stage-boundary right-sizing: a high-reduction aggregation at a
            # big capacity stops dragging that capacity into downstream
            # programs (merge/finalize/join build) — one count sync, one tiny
            # slice program (ops/filtering.maybe_host_resize)
            if compacted and self.conf.stage_fusion_enabled:
                from spark_rapids_tpu.ops.filtering import maybe_host_resize
                resized = maybe_host_resize(compacted, n_groups)
                if resized is not None:
                    compacted, n_groups = resized
        else:
            compacted, n_groups = self._agg_kernel(
                EvalContext.from_batch(batch), merge)
        cols = [c.to_vector() for c in compacted]
        return ColumnarBatch(cols, n_groups, self._partial_schema())

    def _chain_step(self, acc: ColumnarBatch, batch: ColumnarBatch,
                    A: int, pred_P: int):
        """One fused update→concat→merge step of the group-by chain: aggregate
        the incoming batch, pad-concat the partial onto the accumulated
        partials, and merge-aggregate — ONE program per batch, like
        exec/joins.py chains probes. The unchained loop pays three host syncs
        per batch (key-stats probe, concat's num_rows, right-sizing count);
        the chain pays exactly one (the status readback below) and its output
        capacity is PREDICTED from the caller's host-side group counts
        (``bucket_capacity(A + pred_P)``), so no device count ever gates a
        shape. The update, concat, and merge bodies are the SAME traced
        functions the unchained path runs (``_agg_kernel``, ``concat_cols``),
        and the result is accepted only when the predicted concat bucket
        matches the one the unchained loop would have used — chained-vs-
        unchained results are bit-identical; on any non-chainable shape or
        mispredict the caller redoes the batch unchained (degraded, never
        wrong).

        Returns ``(accepted, merged_batch, merged_groups, update_groups)``
        or None when the shape cannot chain at all.
        """
        from spark_rapids_tpu.columnar.encoded import (EncodedColumnVector,
                                                       densify_cols)
        from spark_rapids_tpu.columnar.vector import bucket_capacity
        from spark_rapids_tpu.expr.misc import CONTEXT_SENSITIVE
        from spark_rapids_tpu.ops.concat import concat_cols
        from spark_rapids_tpu.runtime import fuse
        import numpy as np
        if not (batch.columns and acc.columns):
            return None
        # chaining only pays when its one-off trace+compile can amortize over
        # real batches: the syncs it removes cost microseconds, the fused
        # program costs seconds to compile, and a cluster executor compiling
        # it mid-task under an armed task deadline can be killed for it —
        # tiny batches (toy partitions, interactive map tasks) go unchained
        if batch.capacity < _CHAIN_MIN_CAPACITY:
            return None
        pre = self.prefilter
        prep = self.preproject
        ctx_sensitive = any(
            e.collect(lambda x: isinstance(x, CONTEXT_SENSITIVE))
            for e in (*self.group_exprs, *self.agg_exprs,
                      *([pre] if pre is not None else []),
                      *(prep or [])))
        if ctx_sensitive:
            return None
        acc_cap = acc.capacity
        bcap = batch.capacity
        Cc = bucket_capacity(max(A + pred_P, 1))
        use_enc = self.conf.scan_fusion_enabled
        in_cols = []
        for c in batch.columns:
            enc = (c.encoded if use_enc
                   and isinstance(c, EncodedColumnVector) else None)
            in_cols.append(enc if enc is not None else Col.from_vector(c))
        acc_cols = [Col.from_vector(c) for c in acc.columns]
        key = ("agg_chain", fuse.schema_key(self.child.output),
               fuse.schema_key(self._partial_schema()), acc_cap, bcap, Cc,
               tuple(fuse.expr_key(e) for e in self.group_exprs),
               tuple(fuse.expr_key(e) for e in self.agg_exprs),
               fuse.expr_key(pre) if pre is not None else None,
               tuple(fuse.expr_key(e) for e in prep) if prep is not None
               else None, self.prefilter_on_projected)

        def build():
            def kernel(a_cols, b_cols, acc_n, nr):
                b_cols = densify_cols(b_cols)
                uctx = EvalContext(b_cols, nr, bcap)
                # no key-stats probe: skipping the range hint / presorted
                # strategies is value-neutral (every sort embeds the row
                # index, so all strategies produce the same total order)
                upd_cols, upd_n = self._agg_kernel(uctx, merge=False)
                counts_v = jnp.stack([acc_n, upd_n.astype(jnp.int32)])
                per_col = [[a, u] for a, u in zip(a_cols, upd_cols)]
                cat = concat_cols(per_col, counts_v, Cc, (acc_cap, bcap))
                mctx = EvalContext(cat, acc_n + upd_n, Cc)
                mg_cols, mg_n = self._agg_kernel(mctx, merge=True)
                status = jnp.stack([jnp.asarray(mg_n, jnp.int32),
                                    jnp.asarray(upd_n, jnp.int32)])
                return mg_cols, status
            return kernel

        acc_n_t = jnp.asarray(acc.lazy_num_rows, jnp.int32)
        nr_t = jnp.asarray(batch.lazy_num_rows, jnp.int32)
        out = fuse.call_fused(key, "HashAggregateExec.chain", build,
                              (acc_cols, in_cols, acc_n_t, nr_t),
                              lambda: None)
        if out is None:
            return None   # uncacheable key or trace fallback → go unchained
        mg_cols, status = out
        st = np.asarray(status)   # the ONE host sync of the chained step
        mg_n, upd_n = int(st[0]), int(st[1])
        # accept only when the concat ran at the bucket the unchained loop's
        # concat_batches would have picked (bucket of the TRUE total): the
        # merge's f64 reduction order is capacity-sensitive, so an equal
        # bucket is exactly the bit-identity condition
        accepted = bucket_capacity(max(A + upd_n, 1)) == Cc
        if accepted and self.conf.stage_fusion_enabled:
            # same stage-boundary right-sizing the unchained merge applies —
            # mg_n is already a host int, so this syncs nothing extra
            from spark_rapids_tpu.ops.filtering import maybe_host_resize
            resized = maybe_host_resize(mg_cols, mg_n)
            if resized is not None:
                mg_cols, mg_n = resized
        merged = ColumnarBatch([c.to_vector() for c in mg_cols], mg_n,
                               self._partial_schema())
        return accepted, merged, mg_n, upd_n

    def _key_range_hint(self, batch, in_cols, nr, merge: bool):
        """(vmin_traced, has_hint, presorted) for the single-wide-int-key
        group-by: one cheap reduction + ONE host sync per batch decides
        whether the key range fits the packed single-operand sort (the
        join-build strategy-pick pattern, exec/joins._prep_fast_build) — a
        statically 64-bit key (LONG/TIMESTAMP) otherwise forces the 2-operand
        wide sort, ~3x the packed cost at 1M rows (docs/perf_notes.md). The
        same probe now also checks whether the live rows already ARRIVE
        key-sorted with no nulls (clustered fact tables — TPC-H lineitem is
        physically ordered by l_orderkey): then the sort vanishes entirely
        and the segment path runs over the input order (the sorted-input
        group-by; `presorted` wins over the hint). Gated to big capacities
        (below, the comparator fallback is already cheap), keys with no
        hoisted preprojection (the probe reads the raw batch), and int
        dtypes too wide to pack statically."""
        from spark_rapids_tpu.runtime import fuse
        zero = jnp.zeros((), jnp.int64)
        cap = batch.capacity
        if (len(self.group_exprs) != 1 or cap < (1 << 17)
                or (not merge and self.preproject is not None)):
            return zero, False, False
        e = self.group_exprs[0]
        try:
            kdt = e.dtype
        except Exception:  # noqa: BLE001 — unresolvable dtype: no hint
            return zero, False, False
        if (not isinstance(kdt, (T.IntegralType, T.TimestampType))
                or isinstance(kdt, T.BooleanType)
                or jnp.iinfo(kdt.jnp_dtype).bits <= 32):
            return zero, False, False   # narrow keys already pack statically
        skey = ("agg_key_stats", merge, fuse.schema_key(
            self._partial_schema() if merge else self.child.output),
            fuse.expr_key(e))

        def build():
            def kernel(cols, num_rows):
                from spark_rapids_tpu.columnar.encoded import densify_cols
                cols = densify_cols(cols)
                cap_ = cols[0].values.shape[0]
                ctx = EvalContext(cols, num_rows, cap_)
                k = ctx.cols[0] if merge else e.eval(ctx)
                vals = k.values.astype(jnp.int64)
                live = jnp.arange(cap_, dtype=jnp.int32) < num_rows
                eligible = k.validity & live
                vmin = jnp.min(jnp.where(eligible, vals,
                                         jnp.iinfo(jnp.int64).max))
                vmax = jnp.max(jnp.where(eligible, vals,
                                         jnp.iinfo(jnp.int64).min))
                # sorted = every live row valid AND values nondecreasing over
                # the live prefix (all-valid means validity boundaries cannot
                # reorder groups, so input order == sorted group order)
                all_valid = jnp.all(k.validity | ~live)
                nondec = jnp.all(jnp.where(live[1:],
                                           vals[1:] >= vals[:-1], True))
                return vmin, vmax, all_valid & nondec
            return kernel

        vmin_t, vmax_t, sorted_t = fuse.call_fused(
            skey, "HashAggregateExec.key_stats", build, (in_cols, nr),
            lambda: build()(in_cols, nr))
        vmin, vmax = int(vmin_t), int(vmax_t)
        presorted = bool(sorted_t) and self.conf.stage_fusion_enabled
        w = 62 - max((cap - 1).bit_length(), 1) - 1
        fits = vmax >= vmin and (vmax - vmin) < (1 << w) and not presorted
        return jnp.asarray(vmin if fits else 0, jnp.int64), fits, presorted

    def _agg_kernel(self, ctx: EvalContext, merge: bool, range_hint=None,
                    presorted: bool = False):
        """Pure per-batch aggregation body (traceable). `presorted` asserts
        the per-batch probe (_key_range_hint) PROVED the single key column
        arrives sorted and null-free: the segment sort AND every row gather
        collapse to identity."""
        cap = ctx.capacity
        keep = None

        def eval_keep(c):
            from spark_rapids_tpu.ops.filtering import selection_mask
            return selection_mask(self.prefilter.eval(c), c.num_rows, cap)

        if not merge:
            if self.prefilter is not None and not self.prefilter_on_projected:
                keep = eval_keep(ctx)
            if self.preproject is not None:
                cols = [e.eval(ctx) for e in self.preproject]
                ctx = EvalContext(cols, ctx.num_rows, cap)
            if self.prefilter is not None and self.prefilter_on_projected:
                keep = eval_keep(ctx)
        nkeys = len(self.group_exprs)
        if nkeys:
            if merge:
                key_cols = [ctx.cols[i] for i in range(nkeys)]
            else:
                key_cols = [e.eval(ctx) for e in self.group_exprs]
            dense = self._agg_dense(ctx, merge, key_cols, live_mask=keep)
            if dense is not None:
                return dense
            if keep is not None:
                # segment path sorts by key — masked rows must become padding,
                # so compact first (still inside this one fused program)
                new_cols, cnt = compact_cols(ctx.cols, keep)
                ctx = EvalContext(new_cols, cnt, cap)
                key_cols = [e.eval(ctx) for e in self.group_exprs]
                keep = None
            combined = G.combine_compact_keys(key_cols)
            presorted = presorted and combined is None and len(key_cols) == 1
            perm, seg_ids, boundary, live = G.group_segments(
                [combined] if combined is not None else key_cols,
                ctx.num_rows, cap,
                range_hint=(range_hint if combined is None
                            and len(key_cols) == 1 else None),
                presorted=presorted)
            sorted_keys = ([Col(c.values, c.validity & live, c.dtype,
                                c.dictionary) for c in key_cols]
                           if presorted else
                           gather_cols(key_cols, perm, live))
        else:
            if keep is not None:
                # segment kernels need contiguous runs — masked rows mid-run
                # would split segment 0; compact inside this same program
                new_cols, cnt = compact_cols(ctx.cols, keep)
                ctx = EvalContext(new_cols, cnt, cap)
                keep = None
            live = jnp.arange(cap) < ctx.num_rows
            perm = jnp.arange(cap, dtype=jnp.int32)
            seg_ids = jnp.where(live, 0, cap - 1).astype(jnp.int32)
            # global agg: always one output row, even on empty input (Spark)
            boundary = jnp.arange(cap, dtype=jnp.int32) == 0
            sorted_keys = []
        segctx = G.segment_structure(seg_ids, cap)

        # aggregate states are PER-ROW (row i = aggregate of its whole
        # segment, ops/grouping.py) — one compaction pulls boundary rows of
        # keys and states together
        state_cols = []
        off = nkeys
        for e in self.agg_exprs:
            f = _agg_fn(e)
            nstates = len(f.state_types)
            if merge:
                ins = [ctx.cols[off + i] for i in range(nstates)]
                ins = ([Col(c.values, c.validity & live, c.dtype,
                            c.dictionary) for c in ins]
                       if presorted else gather_cols(ins, perm, live))
                outs = f.merge(ins, segctx)
            else:
                if f.child is None:
                    in_col = Col(jnp.zeros((cap,), jnp.int8), live, T.BYTE)
                else:
                    in_col = f.child.eval(ctx)
                in_sorted = (Col(in_col.values, in_col.validity & live,
                                 in_col.dtype, in_col.dictionary)
                             if presorted else
                             gather_cols([in_col], perm, live)[0])
                outs = f.update(in_sorted, segctx)
            off += nstates
            state_cols.extend(outs)
        return compact_cols(list(sorted_keys) + state_cols, boundary)

    def _agg_dense(self, ctx: EvalContext, merge: bool, key_cols,
                   live_mask=None):
        """Sort-free small-domain aggregation: keys with statically-known
        compact domains (dict strings / bools) and sum-shaped aggregates
        (Sum/Count/Average) reduce straight into D per-group buckets —
        scatter-add on CPU, one-hot MATMUL on TPU (the MXU-shaped group-by;
        cudf's hash groupby plays this role in the reference,
        aggregate.scala:706). The sorted segment path (q1: ~18 ms sort +
        ~12 ms/column tree per batch) drops to ~1 ms/column.

        Returns (cols, n_groups) or None when ineligible."""
        import jax
        from spark_rapids_tpu.columnar.vector import bucket_capacity
        from spark_rapids_tpu.expr.aggregates import Average, Count, Sum

        on_tpu = jax.devices()[0].platform == "tpu"
        fns = [_agg_fn(e) for e in self.agg_exprs]
        if not all(isinstance(f, (Sum, Count, Average)) for f in fns):
            return None
        # TPU domain bound: the f64 one-hot matmul materializes (cap, D) so
        # D stays small; count-only aggregations (incl. DISTINCT dedup,
        # which has no aggregates) ride the blocked Pallas one-hot kernel
        # in the non-merge phase and stretch to medium domains — only when
        # that kernel actually dispatches (probe latch), else the jnp
        # fallback would materialize the very (cap, D) blowup the 128
        # bound exists to prevent
        count_only = all(isinstance(f, Count) for f in fns)
        if on_tpu:
            from spark_rapids_tpu.ops import pallas_kernels as PK
            # mirror dense_group_sum's f32-exactness cap guard: a batch at
            # or above 2^24 rows would fall through to the jnp one-hot,
            # materializing the (cap, D) blowup the 128 bound prevents
            max_dom = (1024 if count_only and not merge
                       and ctx.capacity < (1 << 24)
                       and PK.should_use("onehot") else 128)
        else:
            max_dom = 4096
        ks = G.compact_key_codes(key_cols, max_domain=max_dom)
        if ks is None:
            return None
        if on_tpu and any(
                not jnp.issubdtype(jnp.dtype(st.jnp_dtype), jnp.floating)
                for f in fns if isinstance(f, (Sum, Average))
                for st in f.state_types[:1]):
            return None   # int64 matmul is not an MXU op
        codes, strides = ks
        D = 1
        for d in strides:
            D *= d
        cap = ctx.capacity
        live = jnp.arange(cap, dtype=jnp.int32) < ctx.num_rows
        if live_mask is not None:
            live = live & live_mask    # fused prefilter (see _agg_kernel)
        codes = jnp.where(live, codes, jnp.int32(D))   # pad bucket, dropped

        # memoized child eval + count images: aggregates sharing a child
        # (sum(x) + avg(x) + count(x)) then feed IDENTICAL arrays to gsum,
        # so the CPU resolve pass dedups their stacked rows by identity
        from spark_rapids_tpu.runtime import fuse as _fuse
        _child_memo: dict = {}
        _cnt_memo: dict = {}

        def eval_child(e):
            k = _fuse.expr_key(e)
            if k not in _child_memo:
                _child_memo[k] = e.eval(ctx)
            return _child_memo[k]

        def cnt_vals(col):
            a = _cnt_memo.get(id(col))
            if a is None:
                a = col.validity.astype(jnp.int64)
                _cnt_memo[id(col)] = a
            return a

        def _state_cols(gsum):
            """One walk over the aggregate list through `gsum`; the CPU path
            runs it twice (record, then replay) so every f64-safe reduction
            lands in one stacked masked-matvec pass
            (G.resolve_dense_group_sums)."""
            rows_per = gsum(jnp.ones((cap,), jnp.int32),
                            jnp.ones((cap,), jnp.bool_), jnp.int32,
                            count_like=True)
            state_cols = []   # (D,)-length states, padded to D_cap below
            off = len(key_cols)
            for e, f in zip(self.agg_exprs, fns):
                nstates = len(f.state_types)
                if merge:
                    ins = [ctx.cols[off + i] for i in range(nstates)]
                elif f.child is None:
                    ins = [Col(jnp.zeros((cap,), jnp.int8), live, T.BYTE)]
                else:
                    ins = [eval_child(f.child)]
                off += nstates
                if isinstance(f, Count):
                    s = gsum(cnt_vals(ins[0])
                             if not merge else ins[0].values,
                             ins[0].validity, jnp.int64,
                             count_like=not merge)   # update inputs are 0/1
                    state_cols.append(Col(s, jnp.ones_like(s, jnp.bool_),
                                          T.LONG))
                    continue
                sum_t = f.state_types[0]
                acc = sum_t.jnp_dtype
                s = gsum(ins[0].values, ins[0].validity, acc)
                cnt = gsum(cnt_vals(ins[0]), ins[0].validity,
                           jnp.int64, count_like=True)   # validity is 0/1
                state_cols.append(Col(s, cnt > 0, sum_t))
                if isinstance(f, Average):
                    if merge:
                        c2 = gsum(ins[1].values, ins[1].validity, jnp.int64)
                    else:
                        c2 = cnt
                    state_cols.append(Col(c2, jnp.ones_like(c2, jnp.bool_),
                                          T.LONG))
            return rows_per, state_cols

        if on_tpu:
            def gsum(vals, mask, acc_dtype, count_like=False):
                return G.dense_group_sum(vals.astype(acc_dtype), mask & live,
                                         codes, D, on_tpu,
                                         count_like=count_like)
            rows_per, state_cols = _state_cols(gsum)
        else:
            # CPU: XLA's scatter-add costs ~50 ms per column at 1M rows
            # (numpy bincount: ~6 ms); batching every f64-safe reduction of
            # the batch into one shared-one-hot GEMM amortizes the one-hot
            # materialization and runs ~6x faster for q1-shaped aggregates.
            # Record pass enumerates the requests (outputs discarded),
            # replay pass rebuilds the states from the batched results.
            reqs = []

            def record(vals, mask, acc_dtype, count_like=False):
                reqs.append((vals, mask, acc_dtype, count_like))
                return jnp.zeros((D,), acc_dtype)
            _state_cols(record)
            results = G.resolve_dense_group_sums(reqs, codes, D, live)
            it = iter(results)

            def replay(vals, mask, acc_dtype, count_like=False):
                return next(it)
            rows_per, state_cols = _state_cols(replay)

        # decode bucket index -> key columns (inverse of the stride mix)
        D_cap = bucket_capacity(D)
        bidx = jnp.arange(D_cap, dtype=jnp.int32)
        key_out = []
        for ki, (c, d) in enumerate(zip(key_cols, strides)):
            tail = 1
            for d2 in strides[ki + 1:]:
                tail *= d2
            part = (bidx // tail) % jnp.int32(d)
            valid = (part != d - 1) & (bidx < D)
            if c.is_string:
                key_out.append(Col(jnp.where(valid, part, 0), valid,
                                   T.STRING, c.dictionary))
            else:   # boolean
                key_out.append(Col(jnp.where(valid, part == 1, False),
                                   valid, T.BOOLEAN))
        present = jnp.zeros((D_cap,), jnp.bool_).at[:D].set(rows_per > 0)

        def pad(col):
            v = jnp.zeros((D_cap,), col.values.dtype).at[:D].set(col.values)
            m = jnp.zeros((D_cap,), jnp.bool_).at[:D].set(col.validity)
            return Col(v, m & present, col.dtype, col.dictionary)

        out = key_out + [pad(c) for c in state_cols]
        return compact_cols(out, present)

    def _finalize(self, partial: ColumnarBatch) -> ColumnarBatch:
        from spark_rapids_tpu.expr.core import Col
        from spark_rapids_tpu.ops.filtering import (fused_compact_cols,
                                                    host_compact_cols,
                                                    selection_mask)
        from spark_rapids_tpu.runtime import fuse

        def body(ctx):
            nkeys = len(self.group_exprs)
            out = [ctx.cols[i] for i in range(nkeys)]
            off = nkeys
            for e in self.agg_exprs:
                f = _agg_fn(e)
                states = [ctx.cols[off + i] for i in range(len(f.state_types))]
                off += len(f.state_types)
                out.append(f.evaluate(states))
            if self.postfilter is None:
                return out, None
            # fused HAVING: the predicate sees the EVALUATED output columns;
            # the keep mask leaves the kernel so the epilogue can choose the
            # host-indexed compaction (right-sized capacity) over the
            # in-program one
            octx = EvalContext(out, ctx.num_rows, ctx.capacity)
            keep = selection_mask(self.postfilter.eval(octx), octx.num_rows,
                                  octx.capacity)
            return out, keep

        if partial.columns:
            key = ("agg_final", fuse.schema_key(self._partial_schema()),
                   tuple(fuse.expr_key(e) for e in self.group_exprs),
                   tuple(fuse.expr_key(e) for e in self.agg_exprs),
                   fuse.expr_key(self.postfilter)
                   if self.postfilter is not None else None)

            def build():
                def kernel(cols, num_rows):
                    return body(EvalContext(cols, num_rows,
                                            cols[0].values.shape[0]))
                return kernel

            in_cols = [Col.from_vector(c) for c in partial.columns]
            nr = jnp.asarray(partial.lazy_num_rows, jnp.int32)
            out, keep = fuse.call_fused(
                key, "HashAggregateExec.finalize", build, (in_cols, nr),
                lambda: body(EvalContext.from_batch(partial)))
        else:
            out, keep = body(EvalContext.from_batch(partial))
        num_rows = partial.lazy_num_rows
        if keep is not None:
            res = host_compact_cols(out, keep)
            if res is None:
                res = fused_compact_cols(out, keep)
            out, num_rows = res
        return ColumnarBatch([c.to_vector() for c in out], num_rows,
                             self.output)

    def execute_partition(self, split):
        def it():
            merge_input = self.mode == FINAL

            def agg_one(b, merge=merge_input):
                with trace_range("HashAggregate.agg", self._agg_time):
                    return self._aggregate_batch(b, merge=merge)

            acc = None
            # group-by chain (host-side predictors): A = accumulated group
            # count, pred_P = predicted partial-group count of the next batch
            # (last observed). Both are plain ints maintained WITHOUT extra
            # syncs on chained iterations.
            chain_ok = (not merge_input and bool(self.group_exprs)
                        and self.conf.groupby_chain_enabled)
            A = pred_P = 0
            for batch in self.child.execute_partition(split):
                self._in_rows.add_lazy(batch.lazy_num_rows)
                # acquire only once data is ready for device work — acquiring before
                # pulling the child would hold a permit across a blocking shuffle map
                # stage and deadlock the semaphore (reference RapidsShuffleIterator
                # acquires on data arrival, RapidsShuffleIterator.scala:300)
                acquire_semaphore(self.metrics)
                if acc is not None and chain_ok:
                    def chain_step(a=acc, b=batch, A=A, P=pred_P):
                        with trace_range("HashAggregate.chain",
                                         self._agg_time):
                            return self._chain_step(a, b, A, P)
                    try:
                        res = R.call_with_retry(chain_step, scope="agg.chain")
                    except R.DeviceOomError:
                        res = None   # fall back to the splittable update loop
                    if res is not None:
                        accepted, merged, mg_n, upd_n = res
                        if accepted:
                            acc, A, pred_P = merged, mg_n, upd_n
                            continue
                        # capacity mispredict: DISCARD the chained result and
                        # redo this batch unchained — never accept a result
                        # whose concat bucket differs from the unchained one
                        # (degraded, never wrong). The observed update count
                        # still improves the next prediction.
                        pred_P = upd_n
                # per-batch update aggregation under the OOM ladder: a split
                # aggregates the halves into two partials, which the merge
                # loop below folds together — exactly the semantics of
                # batches arriving pre-split (reference withRetry around the
                # update aggregation, aggregate.scala:282-420)
                for partial in R.with_retry([batch], agg_one, conf=self.conf,
                                            scope="agg.update"):
                    if acc is None:
                        acc = partial
                        continue

                    # incremental concat+merge loop (reference aggregate.scala:388)
                    def merge_acc(a=acc, p=partial):
                        with trace_range("HashAggregate.concat",
                                         self._concat_time):
                            both = concat_batches([a, p])
                        with trace_range("HashAggregate.merge",
                                         self._agg_time):
                            return self._aggregate_batch(both, merge=True)

                    # the merge needs BOTH partials at once — unsplittable,
                    # so spill-only retry (withRetryNoSplit)
                    acc = R.call_with_retry(merge_acc, scope="agg.merge")
                if chain_ok and acc is not None:
                    # refresh predictors after an unchained batch (first batch
                    # or chain fallback): one count sync — the unchained loop
                    # already syncs counts per merge, so this adds none on the
                    # steady path and the chain adds exactly one per step
                    A = acc.num_rows
                    pred_P = pred_P or A
            if acc is None:
                if self.group_exprs:
                    return  # grouped agg over empty input → no rows (Spark)
                acquire_semaphore(self.metrics)
                empty = ColumnarBatch.empty(
                    self._partial_schema() if merge_input else self.child.output)
                acc = self._aggregate_batch(empty, merge=merge_input)
            if self.mode == PARTIAL:
                yield acc
            else:
                yield self._finalize(acc)
        return self.wrap_output(it())

    def args_string(self):
        return f"keys={self.group_exprs} aggs={self.agg_exprs} mode={self.mode}"
