"""Shuffle exchange exec — partition on device, exchange through the block store.

Reference (SURVEY.md component #30): GpuShuffleExchangeExecBase.scala:80
(`prepareBatchShuffleDependency`:167 partitions + slices on device and hands sliced
batches to the shuffle manager), ShuffledBatchRDD reads one reduce partition.

The map stage runs once, lazily, the first time any reduce partition executes
(Spark's stage barrier stands in as a threading.Event here since scheduling is local;
the distributed Mesh path in distributed/ replaces this with an ICI all_to_all).
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor

from spark_rapids_tpu import config as C
from spark_rapids_tpu.exec.base import TpuExec, TaskContext
from spark_rapids_tpu.exec.coalesce import coalesce_iterator, TargetSize
from spark_rapids_tpu.runtime import eventlog as EL
from spark_rapids_tpu.runtime import faults as F
from spark_rapids_tpu.runtime import memory as mem
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import retry as R
from spark_rapids_tpu.runtime import tracing
from spark_rapids_tpu.shuffle.manager import ShuffleBlockStore
from spark_rapids_tpu.shuffle.partitioning import Partitioner, RangePartitioner


class ShuffleExchangeExec(TpuExec):
    """Reference GpuShuffleExchangeExecBase:80."""

    def __init__(self, partitioner: Partitioner, child: TpuExec, conf=None):
        super().__init__(child, conf=conf)
        self.partitioner = partitioner.bind(child.output)
        self._map_done = threading.Event()
        self._map_lock = threading.Lock()
        self._shuffle_id = None
        self._pending_shuffle_id = None
        self._partition_time = self.metrics.metric(M.PARTITION_TIME, M.MODERATE)
        self._reads_left = self.partitioner.num_partitions
        self._reads_lock = threading.Lock()

    @property
    def output(self):
        return self.child.output

    @property
    def num_partitions(self):
        return self.partitioner.num_partitions

    def _run_map_stage(self):
        store = ShuffleBlockStore.get()
        serialized = not self.conf.get(C.SHUFFLE_MANAGER_ENABLED)
        # write to a PRIVATE shuffle id and publish it only when every block
        # is in the store: a concurrent reader re-resolving self._shuffle_id
        # mid-rebuild (its fetch failure raced this recompute) must never see
        # a half-written shuffle as complete — it sees the stale/None id,
        # gets KeyError, and its own recompute ladder blocks on the barrier
        sid = store.register_shuffle(serialized=serialized)
        self._pending_shuffle_id = sid
        collector = M.current_collector()
        EL.emit("stage.map.start", node=self._node_id,
                shuffle=sid,
                map_partitions=self.child.num_partitions,
                reduce_partitions=self.partitioner.num_partitions)

        if isinstance(self.partitioner, RangePartitioner):
            # driver-side sample pass to pick range bounds (reference
            # GpuRangePartitioner.sketch over a reservoir sample; we sample the
            # first batch of every input partition)
            samples = []
            for split in range(self.child.num_partitions):
                with TaskContext():
                    for b in self.child.execute_partition(split):
                        samples.append(b)
                        break
            if samples:
                self.partitioner.set_bounds_from_sample(samples)

        from spark_rapids_tpu.runtime import pipeline as P
        pipe_on = P.enabled(self.conf)

        def map_task(split):
            # pool thread: re-enter the query scope and open an attribution
            # frame for this exchange so map-side partitioning time lands on
            # this node's selfTime (child operator frames subtract their own)
            with M.collector_context(collector), \
                    M.node_frame(self._node_id, self._self_time), \
                    TaskContext():
                child_it = self.child.execute_partition(split)
                if pipe_on:
                    # map-segment boundary: upstream compute produces on the
                    # stage's worker thread while THIS thread partitions,
                    # serializes and writes the previous batch
                    child_it = P.stage_iterator(
                        child_it, edge="exchange.map", conf=self.conf,
                        registry=self.metrics,
                        node_id=getattr(self.child, "_node_id", None),
                        spillable=True)
                piece_seq = 0
                for batch in child_it:
                    if batch.num_rows == 0:
                        continue

                    def partition_one(b):
                        with self._partition_time.timed():
                            return self.partitioner.partition(b, split)

                    # map-side writer under the OOM ladder: partitioning a
                    # split half writes the same rows to the same reduce ids,
                    # so piece-granularity recovery is transparent downstream
                    for pieces in R.with_retry([batch], partition_one,
                                               conf=self.conf,
                                               scope="exchange.map"):
                        piece_seq += 1
                        for pid, piece in pieces:
                            # per-piece spill-only retry: a failed block
                            # registration rolls back before raising, so the
                            # re-attempt never double-writes. seq pins each
                            # block's position to (map split, piece order):
                            # concurrent map tasks + pipeline stages may
                            # WRITE out of order, but order-sensitive
                            # consumers (first/last) still see a stable
                            # stream per reduce partition
                            R.call_with_retry(
                                lambda p=pid, b=piece, s=piece_seq:
                                    store.write_block(sid, p, b,
                                                      seq=(split, s)),
                                scope="exchange.write")

        nthreads = max(1, min(self.conf.get(C.NUM_LOCAL_TASKS),
                              self.child.num_partitions))
        if self.child.num_partitions == 1:
            map_task(0)
        else:
            with ThreadPoolExecutor(max_workers=nthreads) as pool:
                list(pool.map(map_task, range(self.child.num_partitions)))
        # per-reduce-partition byte sizes: the shuffle-skew input of the
        # stats plane (bounded: one int per reduce partition). Recorded into
        # the query's collector unconditionally so skew survives into
        # plan.stats/history even with the event log off or the map stage run
        # by the mesh plane
        sizes = ShuffleBlockStore.get().partition_sizes(
            sid, self.partitioner.num_partitions)
        collector = M.current_collector()
        if collector is not None:
            collector.record_shuffle_sizes(self._node_id, sid, sizes)
        if EL.enabled():
            EL.emit("stage.map.end", node=self._node_id,
                    shuffle=sid,
                    partition_sizes=[int(s) for s in sizes])
        self._shuffle_id = sid          # publish: the map outputs are complete
        self._pending_shuffle_id = None

    def _ensure_map_stage(self):
        if self._map_done.is_set():
            self._raise_if_failed()
            return
        with self._map_lock:
            if not self._map_done.is_set():
                try:
                    self._run_map_stage()
                except BaseException as e:
                    # don't re-run the map stage per reduce task, and don't strand
                    # the partially written blocks in the catalog (the failed
                    # build wrote to the still-unpublished pending id)
                    self._map_error = e
                    pending = getattr(self, "_pending_shuffle_id", None)
                    if pending is not None:
                        ShuffleBlockStore.get().unregister_shuffle(pending)
                        self._pending_shuffle_id = None
                finally:
                    self._map_done.set()
        self._raise_if_failed()

    def _raise_if_failed(self):
        err = getattr(self, "_map_error", None)
        if err is not None:
            from spark_rapids_tpu.runtime.scheduler import QueryCancelledError
            if isinstance(err, QueryCancelledError):
                # keep the typed cancellation visible at the session so the
                # lifecycle classifies as cancelled/deadline, not query.error
                raise err
            raise RuntimeError("shuffle map stage failed") from err

    def _invalidate_map_stage(self, observed):
        """Forget the map outputs so the next read recomputes them (the
        standalone analog of Spark's FetchFailed → stage retry,
        RapidsShuffleIterator.scala:82,153). `_reads_left` is NOT reset: it
        counts reader completions, and each reduce partition still finishes
        exactly once — the last one out unregisters whatever shuffle id is
        then current.

        `observed` is the shuffle generation the caller's read actually
        failed against. Concurrent reduce readers (pipeline stage threads)
        all race the same invalidation: the first one tears the stale
        generation down and rebuilds; the rest fail against that SAME stale
        id (KeyError/BufferClosedError mid-yank) and must not invalidate the
        freshly rebuilt outputs — they see `_shuffle_id != observed` and
        fall through to `_ensure_map_stage`, which hands them the new
        generation (or blocks on the in-flight rebuild)."""
        with self._map_lock:
            if observed is None or self._shuffle_id != observed:
                # this reader never saw a live generation (it raced the
                # invalidate→republish window) or a newer one exists: either
                # way there is nothing of its own to tear down
                return
            if self._shuffle_id is not None:
                ShuffleBlockStore.get().unregister_shuffle(self._shuffle_id)
                self._shuffle_id = None
            self._map_error = None
            self._map_done.clear()

    def _read_with_recompute(self, split):
        """Stream one reduce partition; a fetch failure detected BEFORE any
        batch was emitted invalidates the map outputs and recomputes them
        (bounded by shuffle.fetch.maxRetries). A mid-stream failure after
        partial emission cannot be retried safely — the consumer already saw
        rows — and surfaces as TransportError (Spark would re-run the reduce
        task there; the local scheduler has no task-level rerun).
        KeyError counts as a fetch failure: a concurrent reader's
        invalidation can yank the shuffle between ensure and read, and
        BufferClosedError the same way when the invalidation lands after
        this reader snapshotted the block list. SpillCorruptionError too:
        a shuffle block whose disk-tier spill payload failed its CRC is a
        lost block — recompute the map outputs rather than decode corrupt
        rows (the Spark shuffle-checksum → FetchFailed contract)."""
        from spark_rapids_tpu.shuffle.transport import TransportError
        from spark_rapids_tpu.runtime import scheduler as SCHED
        store = ShuffleBlockStore.get()
        retries = self.conf.get(C.SHUFFLE_FETCH_MAX_RETRIES)
        for attempt in range(retries + 1):
            # cancellation wins over the stage-retry ladder: a cancelled
            # query must not pay for a map-stage recompute first
            SCHED.check_cancel()
            emitted = False
            # pin the generation this attempt reads: on failure only THIS id
            # may be invalidated (a concurrent reader's recompute may already
            # have published a newer one that must survive)
            sid = self._shuffle_id
            try:
                # fault-injection checkpoint: "transport:fetch:N" chaos specs
                # drop reduce-side fetches here (the stage-retry ladder), the
                # same site name the peer ladder in shuffle/fetch.py checks
                F.maybe_inject("transport", "fetch")
                for b in store.read_partition(sid, split):
                    emitted = True
                    yield b
                return
            except (TransportError, KeyError, mem.BufferClosedError,
                    mem.SpillCorruptionError) as e:
                if emitted or attempt == retries:
                    raise TransportError(
                        f"reduce {split} fetch failed"
                        f"{' after partial read' if emitted else ''}: {e}"
                    ) from e
                M.resilience_add(M.FETCH_RECOMPUTES)
                tracing.span_event("fetch.recompute", split=split,
                                   error=str(e)[:120])
                self._invalidate_map_stage(sid)
                with M.node_frame(self._node_id, None):
                    self._ensure_map_stage()

    def abort_query(self):
        """Query-death cleanup (called by session._run_action on cancel/
        error): when reduce partitions were never all consumed, the
        read-completion countdown can never free the shuffle blocks — a
        cancelled query's unvisited splits have no reader to account them.
        Unregister whatever map outputs are live so the query leaks no
        device buffers. Racing readers (worker threads still draining)
        observe BufferClosedError/KeyError, whose recompute ladder checks
        the cancel token first and drains instead of rebuilding."""
        with self._reads_lock:
            if self._reads_left <= 0:
                return                  # normal completion already freed them
        store = ShuffleBlockStore.get()
        with self._map_lock:
            for sid in (self._shuffle_id, self._pending_shuffle_id):
                if sid is not None:
                    store.unregister_shuffle(sid)
            self._shuffle_id = None
            self._pending_shuffle_id = None

    def account_read_done(self):
        """One reduce partition finished (drained OR abandoned unopened);
        the last one frees the shuffle blocks — the reference keeps them
        until Spark unregisters the shuffle; our local scheduler reads each
        partition exactly once."""
        with self._reads_lock:
            self._reads_left -= 1
            done = self._reads_left == 0
        if done:
            ShuffleBlockStore.get().unregister_shuffle(self._shuffle_id)

    def read_reduce(self, pid):
        """Stream ONE reduce partition with recompute + cleanup accounting;
        shared by the direct reader and AdaptiveShuffleReaderExec. Each pid
        must be consumed (or closed) exactly once across all readers."""
        try:
            yield from self._read_with_recompute(pid)
        finally:
            self.account_read_done()

    def _reader(self, split):
        # post-shuffle coalesce to target batch size (reference
        # GpuShuffleCoalesceExec inserted by GpuTransitionOverrides:57-63)
        goal = TargetSize(self.conf.batch_size_bytes)
        yield from coalesce_iterator(self.read_reduce(split), goal,
                                     self.metrics, conf=self.conf)

    def execute_partition(self, split):
        # drop this task's permit before (possibly) blocking on the map stage —
        # holding it would starve the map tasks and deadlock (the reference
        # releases the semaphore while waiting on shuffle fetches,
        # RapidsShuffleIterator.scala:300)
        from spark_rapids_tpu.exec.base import current_task_id
        from spark_rapids_tpu.runtime.semaphore import TpuSemaphore
        TpuSemaphore.get().release_if_necessary(current_task_id())
        # metric=None frame: waiting for (or inline-running) the map stage is
        # charged by the map tasks' own frames; the parent operator's frame
        # must not double-count the blocked wall time
        with M.node_frame(self._node_id, None):
            self._ensure_map_stage()
        from spark_rapids_tpu.runtime import pipeline as P
        it = self._reader(split)
        if P.enabled(self.conf):
            # reduce-segment boundary: fetch + decompress + coalesce run on
            # the stage's worker thread, overlapping downstream compute
            it = P.stage_iterator(
                it, edge="exchange.reduce", conf=self.conf,
                registry=self.metrics, node_id=self._node_id,
                self_time_metric=self._self_time, spillable=True)
        return self.wrap_output(it)

    def args_string(self):
        return f"{type(self.partitioner).__name__}({self.partitioner.num_partitions})"


class AdaptiveShuffleReaderExec(TpuExec):
    """AQE coalescing shuffle reader (reference GpuCustomShuffleReaderExec +
    Spark's CoalesceShufflePartitions): after the map stage materializes,
    contiguous small reduce partitions merge into reader partitions of
    roughly `adaptive.advisoryPartitionSizeInBytes`, so a skewed or
    over-partitioned shuffle doesn't pay per-partition read overhead.

    The coalescing decision is EXECUTION-time (the AQE stage barrier):
    `num_partitions` stays the exchange's static count so plan conversion
    never triggers the upstream query; splits beyond the merged spec list
    simply come up empty and account for nothing.

    Only planned above exchanges with a single consumer (aggregate/window):
    merging changes the row distribution across splits, which would break
    the co-partitioning contract between the two sides of a shuffled join."""

    def __init__(self, exchange: ShuffleExchangeExec, conf=None):
        super().__init__(exchange, conf=conf)
        self._specs: list | None = None
        self._spec_lock = threading.Lock()

    @property
    def output(self):
        return self.child.output

    @property
    def num_partitions(self):
        # static: asking must NOT run the map stage (the planner asks during
        # conversion); empty tail splits are cheap no-op tasks
        return self.child.num_partitions

    def _ensure_specs(self):
        if self._specs is not None:
            return self._specs
        ex = self.child
        # same no-double-count contract as ShuffleExchangeExec.execute_partition
        with M.node_frame(ex._node_id, None):
            ex._ensure_map_stage()    # own double-checked synchronization
        with self._spec_lock:
            if self._specs is None:
                n = ex.partitioner.num_partitions
                sizes = ShuffleBlockStore.get().partition_sizes(
                    ex._shuffle_id, n)
                target = self.conf.get(C.ADVISORY_PARTITION_BYTES)
                specs, cur, cur_bytes = [], [], 0
                for pid in range(n):
                    if cur and cur_bytes + sizes[pid] > target:
                        specs.append(cur)
                        cur, cur_bytes = [], 0
                    cur.append(pid)
                    cur_bytes += sizes[pid]
                if cur:
                    specs.append(cur)
                self._specs = specs
        return self._specs

    def execute_partition(self, split):
        ex = self.child
        goal = TargetSize(self.conf.batch_size_bytes)

        def it():
            # drop this task's permit before (possibly) blocking on the map
            # stage — holding it would starve the map tasks and deadlock
            # (same guard as ShuffleExchangeExec.execute_partition)
            from spark_rapids_tpu.exec.base import current_task_id
            from spark_rapids_tpu.runtime.semaphore import TpuSemaphore
            TpuSemaphore.get().release_if_necessary(current_task_id())
            specs = self._ensure_specs()
            pids = specs[split] if split < len(specs) else []
            opened = 0
            try:
                for pid in pids:
                    opened += 1
                    yield from ex.read_reduce(pid)   # accounts for itself
            finally:
                # early close mid-spec (limit): the open pid's read_reduce
                # already accounted; the never-opened tail must too, or the
                # shuffle blocks leak
                for _ in pids[opened:]:
                    ex.account_read_done()
        from spark_rapids_tpu.runtime import pipeline as P
        out = coalesce_iterator(it(), goal, self.metrics, conf=self.conf)
        if P.enabled(self.conf):
            # same reduce-segment boundary as the direct reader
            out = P.stage_iterator(
                out, edge="exchange.reduce", conf=self.conf,
                registry=self.metrics, node_id=self._node_id,
                self_time_metric=self._self_time, spillable=True)
        return self.wrap_output(out)

    def args_string(self):
        specs = self._specs
        n = len(specs) if specs is not None else "?"
        return f"coalesced={n}"
